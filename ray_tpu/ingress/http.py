"""The HTTP/ASGI front door: real sockets in, coalesced buckets out.

``serve.serve``'s HTTP server routes ONE request per replica actor
call — the per-request path PR 9 measured an order of magnitude slow.
This module is the internet-facing counterpart of the batched plane: a
single-threaded **asyncio** ingress speaking HTTP/1.1 over real
sockets (and ASGI 3 for external servers), whose only job per request
is admission control + one queue append — all batching intelligence
lives in the :class:`~ray_tpu.ingress.router.CoalescingRouter` behind
it, all compute in the replicas behind THAT.

Protocol (docs/serving.md "the front door"):

- ``POST /v1/policy/<name>/actions`` with
  ``{"obs": [...], "explore": bool?, "deadline_ms": number?}`` →
  ``{"action": ..., "params_version": int, "logp": float?}``;
  429/503 + ``Retry-After`` when admission sheds, 504 when the
  deadline expires (before dispatch — dropped, not computed);
- ``GET /healthz`` → liveness + per-policy router/admission summary;
- ``GET /metrics`` → the process Prometheus exposition
  (``utils.metrics_exporter.format_prometheus``), so one scrape covers
  ingress, router, serve, and device-ledger families — or, when a
  fleet aggregator is installed (``telemetry.fleetview.install``), the
  MERGED fleet exposition with a ``host=`` label on every series.

Deployments resolve through the EXISTING serve machinery:
:meth:`PolicyIngress.serve_deployment` wraps a named
``RunningDeployment``'s replicas behind a router fed by the
controller's membership feed; :meth:`PolicyIngress.add_policy` mounts
any pre-built router (in-process servers for tests/bench, actor
fleets in deployments).
"""

from __future__ import annotations

import asyncio
import json
import threading
import time
import uuid
from typing import Any, Dict, Optional, Tuple

import numpy as np

from ray_tpu.ingress.admission import AdmissionController
from ray_tpu.ingress.router import (
    CoalescingRouter,
    DeadlineExpired,
    NoReplicasAvailable,
)
from ray_tpu.telemetry import metrics as telemetry_metrics
from ray_tpu.util import tracing

# cross-service trace propagation (docs/observability.md "Fleet
# view"): a client may hand us a trace id in this header; when tracing
# is on and none arrives, the ingress mints one. Either way the id is
# echoed in the response and carried through router batch formation to
# the replica, so ingress:request → router:dispatch → serve:batch
# stitch into ONE trace across processes.
TRACE_HEADER = "x-ray-tpu-trace"

_REASONS = {
    200: "OK",
    400: "Bad Request",
    404: "Not Found",
    405: "Method Not Allowed",
    429: "Too Many Requests",
    500: "Internal Server Error",
    503: "Service Unavailable",
    504: "Gateway Timeout",
}

# request-path diet: the response head's fixed parts are serialized
# ONCE per status at import — the per-request work is two int formats
# (length) and a join, not an f-string build + encode of the whole head
_HEAD_PREFIX = {
    status: (
        f"HTTP/1.1 {status} {reason}\r\n"
        "Content-Type: application/json\r\n"
    ).encode("latin1")
    for status, reason in _REASONS.items()
}
_CONN_KEEPALIVE = b"Connection: keep-alive\r\n\r\n"
_CONN_CLOSE = b"Connection: close\r\n\r\n"


def _head_prefix(status: int) -> bytes:
    pre = _HEAD_PREFIX.get(status)
    if pre is None:
        pre = (
            f"HTTP/1.1 {status} Unknown\r\n"
            "Content-Type: application/json\r\n"
        ).encode("latin1")
    return pre

ACTIONS_PREFIX = "/v1/policy/"
ACTIONS_SUFFIX = "/actions"


def _json_row(row: Dict[str, Any]) -> Dict[str, Any]:
    """Normalize a router result row (LocalReplica numpy payloads or
    ActorReplica's already-JSON rows) into the wire shape."""
    action = row.get("action")
    if not isinstance(action, (int, float, list, type(None))):
        action = np.asarray(action).tolist()
    out: Dict[str, Any] = {
        "action": action,
        "params_version": row.get("params_version"),
    }
    if "logp" in row:
        out["logp"] = row["logp"]
    else:
        extra = row.get("extra") or {}
        logp = extra.get("action_logp")
        if logp is not None:
            out["logp"] = float(np.asarray(logp))
    return out


class PolicyIngress:
    """The serving fleet's front door: one asyncio event loop owns
    every socket; routers own batching; admission owns backpressure.

    ``start()`` binds the listener and runs the loop on a dedicated
    thread; ``asgi_app()`` exposes the identical dispatch as an ASGI 3
    application for external servers (uvicorn et al.) — both paths
    share ``_dispatch``, so behavior cannot drift between them.
    """

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        *,
        max_inflight: int = 256,
        shed_queue_wait_s: Optional[float] = None,
        default_timeout_s: float = 60.0,
        notice_host: Optional[str] = None,
        notice_poll_s: float = 2.0,
        quotas: Optional[Dict[str, int]] = None,
        default_quota: Optional[int] = None,
        reuse_port: bool = False,
        listen_sock=None,
    ):
        self.host = host
        self._requested_port = int(port)
        self.port: Optional[int] = None
        self.default_timeout_s = float(default_timeout_s)
        # horizontal scale-out hooks (ingress/supervisor.py): either
        # bind our own SO_REUSEPORT socket so N sibling processes
        # share ONE port (the kernel balances connections), or accept
        # on a pre-bound listener inherited from the supervisor (the
        # fallback where SO_REUSEPORT is unavailable)
        self._reuse_port = bool(reuse_port)
        self._listen_sock = listen_sock
        # provider-notice drain (resilience/provider_notice.py): the
        # ingress is a fleet member like any learner host — on a
        # preemption notice it stops renewing keep-alive connections
        # and answers healthz 503 so load balancers route away before
        # the host dies. notice_host is the identity probed against
        # the per-host notice dir (default: this machine's hostname).
        import socket as _socket

        self.notice_host = notice_host or _socket.gethostname()
        self.notice_poll_s = float(notice_poll_s)
        self._draining = False
        self._notice_grace_s: Optional[float] = None
        self._admission_defaults = dict(
            max_inflight=max_inflight,
            shed_queue_wait_s=shed_queue_wait_s,
        )
        # per-policy quotas only mean anything against ONE shared
        # in-flight budget: with quotas configured, every mounted
        # policy (without an explicit controller) admits through this
        # shared controller, whose wait signal is the WORST signal
        # across all mounted routers
        self._shared_admission: Optional[AdmissionController] = None
        if quotas is not None or default_quota is not None:
            self._shared_admission = AdmissionController(
                wait_signal=self._worst_wait_signal,
                quotas=quotas,
                default_quota=default_quota,
                **self._admission_defaults,
            )
        # name -> (router, admission); mutated only via add/remove
        self._policies: Dict[
            str, Tuple[CoalescingRouter, AdmissionController]
        ] = {}
        self._owned_routers: list = []
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._server: Optional[asyncio.AbstractServer] = None
        self._thread: Optional[threading.Thread] = None
        self._ready = threading.Event()
        self._stop = threading.Event()

    # -- policy registry -------------------------------------------------

    def add_policy(
        self,
        name: str,
        router: CoalescingRouter,
        admission: Optional[AdmissionController] = None,
    ) -> None:
        """Mount ``router`` at ``/v1/policy/<name>/actions``. Without
        an explicit controller, one is built from the ingress defaults
        with the router's ``queue_wait_signal`` as its shed feed (the
        shared ``queue_wait_window`` accessor) — unless this ingress
        was configured with ``quotas``/``default_quota``, in which
        case every defaulted policy admits through the ONE shared,
        quota-aware controller."""
        if admission is None:
            if self._shared_admission is not None:
                admission = self._shared_admission
            else:
                admission = AdmissionController(
                    wait_signal=router.queue_wait_signal,
                    **self._admission_defaults,
                )
        self._policies[name] = (router, admission)

    def _worst_wait_signal(self) -> Optional[float]:
        """Shed feed for the shared (quota) controller: the worst p50
        queue wait across every mounted router."""
        waits = []
        for router, _ in self._policies.values():
            try:
                w = router.queue_wait_signal()
            except Exception:
                w = None
            if w is not None:
                waits.append(w)
        return max(waits) if waits else None

    def serve_deployment(self, name: str, **router_kwargs) -> None:
        """Front a serve-core deployment: resolves the
        ``RunningDeployment`` (``serve.policy_deployment`` → deploy),
        builds a router over its replica membership feed, and mounts
        it. The router keeps following the feed, so autoscaler
        scale-ups and dead-replica replacements flow through without
        re-mounting."""
        from ray_tpu.serve import serve as serve_core

        dep = serve_core.get_running(name)
        if dep is None:
            raise ValueError(f"no running deployment {name!r}")
        feed = serve_core.membership_feed(name)
        _, members = feed.current()
        router = CoalescingRouter(
            name, members, membership=feed, **router_kwargs
        )
        self._owned_routers.append(router)
        self.add_policy(name, router)

    def remove_policy(self, name: str) -> None:
        self._policies.pop(name, None)

    # -- lifecycle -------------------------------------------------------

    def start(self, timeout_s: float = 10.0) -> "PolicyIngress":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(
            target=self._run_loop, daemon=True, name="policy_ingress",
        )
        self._thread.start()
        if not self._ready.wait(timeout_s):
            raise RuntimeError("ingress failed to bind in time")
        return self

    # ray-tpu: thread=ingress-loop
    def _run_loop(self) -> None:
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self._loop = loop
        try:
            loop.run_until_complete(self._serve_forever())
        finally:
            try:
                loop.run_until_complete(loop.shutdown_asyncgens())
            except Exception:
                pass
            loop.close()

    async def _serve_forever(self) -> None:
        if self._listen_sock is not None:
            self._server = await asyncio.start_server(
                self._handle_conn, sock=self._listen_sock
            )
        elif self._reuse_port:
            self._server = await asyncio.start_server(
                self._handle_conn,
                self.host,
                self._requested_port,
                reuse_port=True,
            )
        else:
            self._server = await asyncio.start_server(
                self._handle_conn, self.host, self._requested_port
            )
        self.port = self._server.sockets[0].getsockname()[1]
        self._ready.set()
        watcher = asyncio.ensure_future(self._watch_notice())
        try:
            async with self._server:
                await self._server.serve_forever()
        except asyncio.CancelledError:
            pass
        finally:
            watcher.cancel()

    # ray-tpu: thread=ingress-loop
    async def _watch_notice(self) -> None:
        """Poll the provider-notice source for this host; on a notice,
        flip the ingress into draining mode: live keep-alive
        connections get ``Connection: close`` on their next response,
        ``/healthz`` answers 503 so the balancer stops sending. The
        probe reads env/files only — cheap enough for the loop."""
        from ray_tpu.resilience import provider_notice

        while not self._stop.is_set():
            try:
                grace = provider_notice.probe(self.notice_host)
            except Exception:
                grace = None
            if grace is not None:
                self._draining = True
                self._notice_grace_s = grace
                return
            await asyncio.sleep(self.notice_poll_s)

    def drain(self, grace_s: Optional[float] = None) -> None:
        """Flip this ingress into draining mode NOW (the same state a
        provider notice produces): healthz answers 503, keep-alive
        connections close after their next response. The supervisor
        broadcasts this to every worker of a bank so the whole front
        door drains together."""
        self._notice_grace_s = grace_s
        self._draining = True

    @property
    def draining(self) -> bool:
        return self._draining

    def preemption_notice(self) -> Optional[float]:
        """Grace seconds from the provider notice, or None when no
        notice has been observed."""
        return self._notice_grace_s

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def stop(self, join_timeout: float = 10.0) -> None:
        self._stop.set()
        loop = self._loop
        if loop is not None and loop.is_running():
            def _shutdown():
                for task in asyncio.all_tasks():
                    task.cancel()

            try:
                loop.call_soon_threadsafe(_shutdown)
            except RuntimeError:
                pass
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(timeout=join_timeout)
        self._thread = None
        for router in self._owned_routers:
            router.stop()

    # -- socket path (asyncio HTTP/1.1) ----------------------------------

    # ray-tpu: thread=ingress-loop
    async def _handle_conn(self, reader, writer) -> None:
        """One keep-alive connection: parse → dispatch → respond,
        until the client closes. Requests on DIFFERENT connections
        interleave on the loop; batching happens in the router."""
        # one header dict per CONNECTION, cleared per request — a
        # keep-alive client paying a dict allocation per request adds
        # up at flood rates (the request-path diet)
        hdr_buf: Dict[str, str] = {}
        try:
            while not self._stop.is_set():
                request = await self._read_request(reader, hdr_buf)
                if request is None:
                    break
                method, path, headers, body = request
                status, extra_headers, payload = await self._dispatch(
                    method, path, body, headers=headers
                )
                keep_alive = (
                    headers.get("connection", "").lower() != "close"
                    # draining: answer, then close — keep-alive
                    # connections must not pin requests to a host
                    # about to be preempted
                    and not self._draining
                )
                parts = [
                    _head_prefix(status),
                    b"Content-Length: %d\r\n" % len(payload),
                ]
                for k, v in extra_headers:
                    parts.append(f"{k}: {v}\r\n".encode("latin1"))
                parts.append(
                    _CONN_KEEPALIVE if keep_alive else _CONN_CLOSE
                )
                parts.append(payload)
                writer.write(b"".join(parts))
                await writer.drain()
                if not keep_alive:
                    break
        except (
            asyncio.IncompleteReadError,
            ConnectionError,
            asyncio.CancelledError,
        ):
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass

    @staticmethod
    async def _read_request(reader, hdr_buf: Optional[Dict] = None):
        line = await reader.readline()
        if not line or line in (b"\r\n", b"\n"):
            return None
        try:
            method, path, _version = (
                line.decode("latin1").strip().split(" ", 2)
            )
        except ValueError:
            return None
        # reuse the caller's per-connection buffer when given (the
        # request-path diet); fresh dict otherwise (ASGI adapter &c.)
        if hdr_buf is not None:
            hdr_buf.clear()
            headers = hdr_buf
        else:
            headers = {}
        while True:
            h = await reader.readline()
            if h in (b"\r\n", b"\n", b""):
                break
            key, _, value = h.decode("latin1").partition(":")
            headers[key.strip().lower()] = value.strip()
        length = int(headers.get("content-length", 0) or 0)
        body = await reader.readexactly(length) if length else b""
        return method.upper(), path, headers, body

    # -- shared dispatch (socket server AND the ASGI app) ----------------

    async def _dispatch(
        self,
        method: str,
        path: str,
        body: bytes,
        headers: Optional[Dict[str, str]] = None,
    ):
        """Route one request. Returns ``(status, extra_headers,
        payload_bytes)``; never raises (a handler bug answers 500).
        ``headers`` carries lowercase-keyed request headers (both the
        socket parser and the ASGI adapter normalize to this)."""
        t0 = time.perf_counter()
        route = "other"
        trace_id = (headers or {}).get(TRACE_HEADER) or None
        try:
            if path == "/healthz":
                route = "healthz"
                status, headers, payload = self._healthz()
            elif path == "/metrics":
                route = "metrics"
                status, headers, payload = self._metrics()
            elif path.startswith(ACTIONS_PREFIX) and path.endswith(
                ACTIONS_SUFFIX
            ):
                route = "actions"
                name = path[
                    len(ACTIONS_PREFIX) : -len(ACTIONS_SUFFIX)
                ]
                if method != "POST":
                    status, headers, payload = self._error(
                        405, "POST required"
                    )
                else:
                    if trace_id is None and tracing.is_enabled():
                        trace_id = uuid.uuid4().hex[:16]
                    (
                        status,
                        headers,
                        payload,
                    ) = await self._handle_actions(
                        name, body, trace_id=trace_id
                    )
                    if trace_id is not None:
                        headers = list(headers) + [
                            (TRACE_HEADER, trace_id)
                        ]
            else:
                status, headers, payload = self._error(
                    404, f"no route {path!r}"
                )
        except Exception as e:  # pragma: no cover - defensive
            status, headers, payload = self._error(500, repr(e))
        telemetry_metrics.inc_ingress_request(route, status)
        telemetry_metrics.observe_ingress_latency(
            route, time.perf_counter() - t0
        )
        return status, headers, payload

    async def _handle_actions(
        self,
        name: str,
        body: bytes,
        trace_id: Optional[str] = None,
    ):
        entry = self._policies.get(name)
        if entry is None:
            return self._error(404, f"no policy {name!r}")
        router, admission = entry
        try:
            payload = json.loads(body) if body else {}
            obs = payload["obs"]
        except Exception:
            return self._error(
                400, 'body must be JSON with an "obs" field'
            )
        explore = payload.get("explore")
        deadline_ms = payload.get("deadline_ms")
        deadline_s = (
            float(deadline_ms) / 1e3
            if deadline_ms is not None
            else None
        )
        # one ingress:request span per admitted request, on the
        # client's trace when a header arrived (context_span) — its
        # injected context rides the router request through batch
        # formation so the replica's serve:batch span stitches under it
        ctx = (
            {"trace_id": trace_id, "parent_span_id": None}
            if trace_id is not None
            else None
        )
        t_req = time.perf_counter()
        with tracing.context_span(
            ctx, "ingress:request", policy=name
        ):
            decision = admission.try_admit(deadline_s, policy=name)
            if decision is not None:
                return self._shed_response(decision)
            trace_ctx = tracing.inject_context()
            try:
                fut = router.submit(
                    obs,
                    explore=explore,
                    deadline_s=deadline_s,
                    trace=trace_ctx,
                )
                timeout = (
                    deadline_s
                    if deadline_s is not None
                    else self.default_timeout_s
                )
                row = await asyncio.wait_for(
                    asyncio.wrap_future(fut), timeout=timeout + 0.25
                )
            except DeadlineExpired as e:
                return self._error(504, str(e))
            except asyncio.TimeoutError:
                return self._error(
                    504, "deadline exceeded awaiting result"
                )
            except NoReplicasAvailable as e:
                return (
                    503,
                    [("Retry-After", "1")],
                    json.dumps({"error": str(e)}).encode(),
                )
            except Exception as e:
                return self._error(500, repr(e))
            finally:
                admission.release(policy=name)
            # the overload contract (bench.py --flood): a deadlined
            # request NEVER gets a 200 past its deadline — a result
            # that raced past it while batched is worthless to the
            # client and is reported as the 504 it effectively is
            if (
                deadline_s is not None
                and time.perf_counter() - t_req > deadline_s
            ):
                return self._error(
                    504, "completed past deadline"
                )
        return (
            200,
            [],
            json.dumps(_json_row(row)).encode(),
        )

    def _shed_response(self, decision):
        retry = max(1, int(round(decision.retry_after_s)))
        return (
            decision.status,
            [("Retry-After", str(retry))],
            json.dumps(
                {
                    "error": f"shed: {decision.reason}",
                    "retry_after_s": decision.retry_after_s,
                }
            ).encode(),
        )

    def _healthz(self):
        policies = {}
        for name, (router, admission) in self._policies.items():
            policies[name] = {
                "replicas": router.num_replicas(),
                "dead_replicas": router.num_dead(),
                "queue_depth": router.stats()["queue_depth"],
                "inflight": admission.num_inflight(),
            }
        ok = (
            all(
                p["replicas"] > p["dead_replicas"]
                for p in policies.values()
            )
            and not self._draining
        )
        status = "ok" if ok else "degraded"
        if self._draining:
            status = "draining"
        return (
            200 if ok else 503,
            [],
            json.dumps(
                {
                    "status": status,
                    "policies": policies,
                    "draining": self._draining,
                }
            ).encode(),
        )

    def _metrics(self):
        from ray_tpu.utils.metrics_exporter import format_prometheus

        # a process hosting the fleet aggregator serves the MERGED
        # (host-labeled) fleet exposition from the same scrape route;
        # everyone else serves the process-local one
        text = None
        try:
            from ray_tpu.telemetry import fleetview

            text = fleetview.render_installed()
        except Exception:
            text = None
        if text is None:
            text = format_prometheus()
        return 200, [], text.encode()

    @staticmethod
    def _error(status: int, message: str):
        return (
            status,
            [],
            json.dumps({"error": message}).encode(),
        )

    # -- ASGI ------------------------------------------------------------

    def asgi_app(self):
        """An ASGI 3 application over the same dispatch: mount the
        front door in any external ASGI server without the built-in
        socket listener."""
        ingress = self

        async def app(scope, receive, send):
            if scope["type"] == "lifespan":
                while True:
                    msg = await receive()
                    if msg["type"] == "lifespan.startup":
                        await send(
                            {"type": "lifespan.startup.complete"}
                        )
                    elif msg["type"] == "lifespan.shutdown":
                        await send(
                            {"type": "lifespan.shutdown.complete"}
                        )
                        return
                return
            assert scope["type"] == "http"
            body = b""
            while True:
                msg = await receive()
                body += msg.get("body", b"")
                if not msg.get("more_body"):
                    break
            req_headers = {
                k.decode("latin1").lower(): v.decode("latin1")
                for k, v in scope.get("headers") or ()
            }
            status, extra_headers, payload = await ingress._dispatch(
                scope.get("method", "GET"), scope.get("path", "/"),
                body, headers=req_headers,
            )
            headers = [
                (b"content-type", b"application/json"),
            ] + [
                (k.lower().encode("latin1"), v.encode("latin1"))
                for k, v in extra_headers
            ]
            await send(
                {
                    "type": "http.response.start",
                    "status": status,
                    "headers": headers,
                }
            )
            await send(
                {"type": "http.response.body", "body": payload}
            )

        return app

    # -- aggregate stats -------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        return {
            "url": self.url if self.port else None,
            "policies": {
                name: {
                    "router": router.stats(),
                    "admission": admission.stats(),
                }
                for name, (router, admission) in self._policies.items()
            },
        }
