"""Connectors: composable transforms between envs and policies.

Counterpart of the reference's ``rllib/connectors/connector.py``
(``Connector :78``, ``AgentConnector :126``, ``ActionConnector :235``,
``ConnectorPipeline :273``) and the concrete connectors under
``rllib/connectors/{agent,action}/``: a serializable pipeline of small
transforms applied to observations on the way INTO a policy
(AgentConnector) and to sampled actions on the way OUT
(ActionConnector).

The rollout hot path stays batched and jit-friendly: agent connectors
here operate on numpy observation batches (one call per vector-env
step), not per-agent Python objects — the decomposition the reference's
new stack performs per AgentConnectorDataType collapses into array
ops."""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np


class ConnectorContext:
    """Construction-time info for connectors (reference
    ConnectorContext.from_policy)."""

    def __init__(
        self,
        observation_space=None,
        action_space=None,
        config: Optional[Dict] = None,
    ):
        self.observation_space = observation_space
        self.action_space = action_space
        self.config = config or {}

    @classmethod
    def from_policy(cls, policy) -> "ConnectorContext":
        return cls(
            policy.observation_space,
            policy.action_space,
            policy.config,
        )


class Connector:
    """reference connector.py:78."""

    def __init__(self, ctx: ConnectorContext):
        self.ctx = ctx
        self.is_training = True

    def in_training(self, is_training: bool) -> None:
        self.is_training = is_training

    def __call__(self, data):
        raise NotImplementedError

    def to_config(self) -> Tuple[str, List[Any]]:
        return type(self).__name__, []

    @classmethod
    def from_config(
        cls, ctx: ConnectorContext, params: List[Any]
    ) -> "Connector":
        return cls(ctx, *params)

    def __repr__(self):
        return type(self).__name__


class AgentConnector(Connector):
    """Transforms observation batches env → policy
    (reference connector.py:126)."""


class ActionConnector(Connector):
    """Transforms action batches policy → env
    (reference connector.py:235)."""


class ConnectorPipeline(Connector):
    """Sequential composition (reference connector.py:273); itself a
    connector, so pipelines nest."""

    def __init__(self, ctx: ConnectorContext, connectors: List[Connector]):
        super().__init__(ctx)
        self.connectors = list(connectors)

    def __call__(self, data):
        for c in self.connectors:
            data = c(data)
        return data

    def in_training(self, is_training: bool) -> None:
        for c in self.connectors:
            c.in_training(is_training)

    def append(self, connector: Connector) -> None:
        self.connectors.append(connector)

    def prepend(self, connector: Connector) -> None:
        self.connectors.insert(0, connector)

    def remove(self, name: str) -> None:
        self.connectors = [
            c for c in self.connectors if type(c).__name__ != name
        ]

    def to_config(self) -> Tuple[str, List[Any]]:
        return "ConnectorPipeline", [
            c.to_config() for c in self.connectors
        ]

    @classmethod
    def from_config(
        cls, ctx: ConnectorContext, params: List[Any]
    ) -> "ConnectorPipeline":
        return cls(
            ctx, [restore_connector(ctx, p) for p in params]
        )

    def __repr__(self):
        inner = ", ".join(repr(c) for c in self.connectors)
        return f"ConnectorPipeline[{inner}]"


# -- concrete agent connectors ---------------------------------------------


class ObsPreprocessorConnector(AgentConnector):
    """Applies the catalog preprocessor (one-hot/flatten) — reference
    connectors/agent/obs_preproc.py."""

    def __init__(self, ctx: ConnectorContext):
        super().__init__(ctx)
        from ray_tpu.models.catalog import ModelCatalog

        self._prep = ModelCatalog.get_preprocessor_for_space(
            ctx.observation_space
        )
        self.observation_space = self._prep.observation_space

    def __call__(self, obs):
        return np.stack([self._prep.transform(o) for o in obs])


class FlattenObsConnector(AgentConnector):
    """Flattens trailing obs dims to 1-D per row."""

    def __call__(self, obs):
        obs = np.asarray(obs)
        return obs.reshape(obs.shape[0], -1)


class MeanStdFilterConnector(AgentConnector):
    """Running mean/std normalization (reference
    connectors/agent/mean_std_filter.py); stats update only in
    training mode."""

    def __init__(self, ctx: ConnectorContext, shape=None):
        super().__init__(ctx)
        from ray_tpu.utils.filter import MeanStdFilter

        shape = shape or (
            ctx.observation_space.shape
            if ctx.observation_space is not None
            else None
        )
        self.filter = MeanStdFilter(shape)

    def __call__(self, obs):
        return np.stack(
            [
                self.filter(np.asarray(o), update=self.is_training)
                for o in obs
            ]
        )

    def to_config(self):
        return "MeanStdFilterConnector", [None]


class ClipRewardConnector(AgentConnector):
    """Clips rewards (sign or bound) — reference
    connectors/agent/clip_reward.py. Operates on reward arrays."""

    def __init__(
        self,
        ctx: ConnectorContext,
        sign: bool = False,
        limit: Optional[float] = None,
    ):
        super().__init__(ctx)
        self.sign = sign
        self.limit = limit

    def __call__(self, rewards):
        rewards = np.asarray(rewards, np.float32)
        if self.sign:
            return np.sign(rewards)
        if self.limit is not None:
            return np.clip(rewards, -self.limit, self.limit)
        return rewards

    def to_config(self):
        return "ClipRewardConnector", [self.sign, self.limit]


def LambdaAgentConnector(fn: Callable) -> type:
    """reference connectors/agent/lambdas.py."""

    class _Lambda(AgentConnector):
        def __call__(self, data):
            return fn(data)

    _Lambda.__name__ = f"LambdaAgentConnector({fn.__name__})"
    return _Lambda


# -- concrete action connectors --------------------------------------------


class ClipActionsConnector(ActionConnector):
    """reference connectors/action/clip.py."""

    def __call__(self, actions):
        space = self.ctx.action_space
        import gymnasium as gym

        if isinstance(space, gym.spaces.Box):
            return np.clip(actions, space.low, space.high)
        return actions


class NormalizeActionsConnector(ActionConnector):
    """Maps [-1,1]-normalized actions to the space bounds — reference
    connectors/action/normalize.py."""

    def __call__(self, actions):
        from ray_tpu.evaluation.sampler import unsquash_action

        return np.asarray(
            [
                unsquash_action(a, self.ctx.action_space)
                for a in np.asarray(actions)
            ]
        )


def LambdaActionConnector(fn: Callable) -> type:
    class _Lambda(ActionConnector):
        def __call__(self, data):
            return fn(data)

    _Lambda.__name__ = f"LambdaActionConnector({fn.__name__})"
    return _Lambda


# -- registry / (de)serialization ------------------------------------------

_CONNECTORS: Dict[str, type] = {}


def register_connector(name: str, cls: type) -> None:
    """reference connector.py register_connector."""
    _CONNECTORS[name] = cls


def get_connector(name: str) -> type:
    if name not in _CONNECTORS:
        raise ValueError(
            f"Unknown connector {name!r}; known: {sorted(_CONNECTORS)}"
        )
    return _CONNECTORS[name]


def restore_connector(ctx: ConnectorContext, config: Tuple) -> Connector:
    """Rebuild a connector (or pipeline) from to_config output."""
    name, params = config
    if name == "ConnectorPipeline":
        return ConnectorPipeline.from_config(ctx, params)
    return get_connector(name).from_config(ctx, params)


for _cls in (
    ObsPreprocessorConnector,
    FlattenObsConnector,
    MeanStdFilterConnector,
    ClipRewardConnector,
    ClipActionsConnector,
    NormalizeActionsConnector,
):
    register_connector(_cls.__name__, _cls)
