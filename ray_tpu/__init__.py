"""ray_tpu — a TPU-native distributed RL training framework.

A from-scratch reimplementation of the capabilities of the Ray + RLlib reference
(charlesjsun/ray, surveyed in SURVEY.md), designed TPU-first: CPU actor fleets run
environment rollout while the policy-gradient learner loop runs as jit-compiled JAX
sharded across a TPU mesh.

Public surface (mirrors the reference's ``ray`` top-level API,
``python/ray/_private/worker.py:984,2086``):

    import ray_tpu as ray
    ray.init()
    @ray.remote
    def f(x): ...
    ref = f.remote(1)
    ray.get(ref)
"""

from ray_tpu.version import __version__
from ray_tpu.core.api import (
    init,
    shutdown,
    is_initialized,
    remote,
    get,
    put,
    wait,
    method,
    get_runtime_context,
    get_actor,
    available_resources,
    cluster_resources,
    nodes,
    timeline,
    kill,
    cancel,
    free,
)
from ray_tpu.core.object_store import ObjectRef

__all__ = [
    "__version__",
    "init",
    "shutdown",
    "is_initialized",
    "remote",
    "get",
    "put",
    "wait",
    "method",
    "kill",
    "free",
    "cancel",
    "get_runtime_context",
    "get_actor",
    "available_resources",
    "cluster_resources",
    "nodes",
    "timeline",
    "ObjectRef",
]
