"""AIR Checkpoint: the shared checkpoint currency across libraries.

Counterpart of the reference's ``python/ray/air/checkpoint.py``: one
object convertible between dict / directory / bytes forms, passed
between Train workers, Tune trials, and user code."""

from __future__ import annotations

import os
import pickle
import shutil
import tempfile
from typing import Any, Dict, Optional


class Checkpoint:
    """reference air/checkpoint.py Checkpoint."""

    def __init__(
        self,
        data: Optional[Dict] = None,
        directory: Optional[str] = None,
    ):
        if (data is None) == (directory is None):
            raise ValueError(
                "exactly one of data/directory must be given"
            )
        self._data = data
        self._directory = directory

    # -- constructors -----------------------------------------------------

    @classmethod
    def from_dict(cls, data: Dict) -> "Checkpoint":
        return cls(data=dict(data))

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(directory=str(path))

    @classmethod
    def from_bytes(cls, blob: bytes) -> "Checkpoint":
        return cls(data=pickle.loads(blob))

    # -- conversions ------------------------------------------------------

    def to_dict(self) -> Dict:
        if self._data is not None:
            return dict(self._data)
        path = os.path.join(self._directory, "checkpoint.pkl")
        if os.path.exists(path):
            with open(path, "rb") as f:
                return pickle.load(f)
        raise ValueError(
            f"directory checkpoint {self._directory} has no "
            "checkpoint.pkl; use to_directory()"
        )

    def to_directory(self, path: Optional[str] = None) -> str:
        path = path or tempfile.mkdtemp(prefix="ray_tpu_ckpt_")
        os.makedirs(path, exist_ok=True)
        if self._directory is not None:
            if os.path.abspath(self._directory) != os.path.abspath(path):
                shutil.copytree(
                    self._directory, path, dirs_exist_ok=True
                )
        else:
            from ray_tpu.util.atomic_io import atomic_write

            atomic_write(
                os.path.join(path, "checkpoint.pkl"),
                lambda f: pickle.dump(self._data, f),
            )
        return path

    def to_bytes(self) -> bytes:
        return pickle.dumps(self.to_dict())

    def __repr__(self):
        kind = "dict" if self._data is not None else "directory"
        return f"Checkpoint({kind})"
