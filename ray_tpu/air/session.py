"""Training session context (reference ``python/ray/air/session.py`` /
``train/_internal/session.py:261`` session.report): inside a Train
worker's train_func, ``session.report(metrics, checkpoint=...)``
streams results to the driver and ``get_world_rank()``/
``get_world_size()`` expose the worker's place in the group."""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

_CTX = threading.local()


class _Session:
    def __init__(self, rank: int, world_size: int, report_fn):
        self.rank = rank
        self.world_size = world_size
        self.report_fn = report_fn
        self.last_checkpoint = None
        self.loaded_checkpoint = None


def _init_session(
    rank: int, world_size: int, report_fn, checkpoint=None
) -> None:
    _CTX.session = _Session(rank, world_size, report_fn)
    _CTX.session.loaded_checkpoint = checkpoint


def _get_session() -> Optional[_Session]:
    return getattr(_CTX, "session", None)


def report(metrics: Dict[str, Any], *, checkpoint=None) -> None:
    """reference session.report :261."""
    s = _get_session()
    if s is None:
        raise RuntimeError(
            "session.report() called outside a Train worker"
        )
    if checkpoint is not None:
        s.last_checkpoint = checkpoint
    s.report_fn(dict(metrics), checkpoint)


def get_world_rank() -> int:
    s = _get_session()
    return 0 if s is None else s.rank


def get_world_size() -> int:
    s = _get_session()
    return 1 if s is None else s.world_size


def get_checkpoint():
    """The checkpoint to resume from (if the Trainer got one)."""
    s = _get_session()
    return None if s is None else s.loaded_checkpoint
