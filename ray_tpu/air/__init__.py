from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air import session

__all__ = ["Checkpoint", "session"]
