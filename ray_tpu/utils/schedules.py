"""Parameter schedules (lr, entropy, epsilon).

Counterpart of the reference's ``rllib/utils/schedules/*.py``. Implemented as
pure functions of a float timestep so they can be evaluated either on host
(python) or inside a jitted learner step (jnp) — every ``value`` method uses
only arithmetic and ``where``-style selection.
"""

from __future__ import annotations

import bisect
from typing import List, Sequence, Tuple, Union

import numpy as np


class Schedule:
    def value(self, t):
        raise NotImplementedError

    def __call__(self, t):
        return self.value(t)


class ConstantSchedule(Schedule):
    def __init__(self, value: float):
        self._v = value

    def value(self, t):
        return self._v


class LinearSchedule(Schedule):
    """Linear interpolation from initial_p to final_p over schedule_timesteps."""

    def __init__(self, schedule_timesteps: int, final_p: float,
                 initial_p: float = 1.0):
        self.schedule_timesteps = schedule_timesteps
        self.final_p = final_p
        self.initial_p = initial_p

    def value(self, t):
        frac = np.minimum(np.asarray(t, dtype=np.float64)
                          / self.schedule_timesteps, 1.0)
        return self.initial_p + frac * (self.final_p - self.initial_p)


class ExponentialSchedule(Schedule):
    def __init__(self, schedule_timesteps: int, initial_p: float = 1.0,
                 decay_rate: float = 0.1):
        self.schedule_timesteps = schedule_timesteps
        self.initial_p = initial_p
        self.decay_rate = decay_rate

    def value(self, t):
        return self.initial_p * np.power(
            self.decay_rate, np.asarray(t, dtype=np.float64)
            / self.schedule_timesteps)


class PiecewiseSchedule(Schedule):
    """Piecewise-linear over (t, value) endpoints
    (reference schedules/piecewise_schedule.py)."""

    def __init__(self, endpoints: Sequence[Tuple[int, float]],
                 outside_value: float | None = None):
        endpoints = sorted(endpoints)
        self.ts = [e[0] for e in endpoints]
        self.vs = [e[1] for e in endpoints]
        self.outside_value = outside_value

    def value(self, t):
        t = float(t)
        if t <= self.ts[0]:
            return self.vs[0]
        if t >= self.ts[-1]:
            return (self.outside_value
                    if self.outside_value is not None else self.vs[-1])
        i = bisect.bisect_right(self.ts, t) - 1
        frac = (t - self.ts[i]) / (self.ts[i + 1] - self.ts[i])
        return self.vs[i] + frac * (self.vs[i + 1] - self.vs[i])


def make_schedule(
    spec: Union[None, float, Schedule, List[List[float]]],
    default: float = 0.0,
) -> Schedule:
    """RLlib-style schedule spec: None | float | [[t, v], ...]."""
    if spec is None:
        return ConstantSchedule(default)
    if isinstance(spec, Schedule):
        return spec
    if isinstance(spec, (int, float)):
        return ConstantSchedule(float(spec))
    return PiecewiseSchedule([(int(t), float(v)) for t, v in spec])
