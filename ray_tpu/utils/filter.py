"""Running-statistics observation filters.

Counterpart of the reference's ``rllib/utils/filter.py`` (``Filter :15``,
``MeanStdFilter :151``). Filters run on CPU rollout actors (numpy); their
stats are synchronized through the same weight-broadcast channel as policy
params. Batched: ``__call__`` accepts (obs_dim,) or (batch, obs_dim).
"""

from __future__ import annotations

import numpy as np


class Filter:
    """No-op base filter (reference filter.py:15)."""

    is_concurrent = False

    def __call__(self, x, update: bool = True):
        return x

    def apply_changes(self, other: "Filter", with_buffer: bool = False):
        pass

    def copy(self) -> "Filter":
        return Filter()

    def sync(self, other: "Filter"):
        pass

    def clear_buffer(self):
        pass

    def as_serializable(self) -> "Filter":
        return self


class NoFilter(Filter):
    def copy(self) -> "NoFilter":
        return NoFilter()


class RunningStat:
    """Welford online mean/var, batched (reference filter.py:61)."""

    def __init__(self, shape=()):
        self.num = 0
        self.mean_ = np.zeros(shape, dtype=np.float64)
        self.s = np.zeros(shape, dtype=np.float64)

    def push_batch(self, x: np.ndarray):
        x = np.asarray(x, dtype=np.float64)
        if x.ndim == len(self.mean_.shape):
            x = x[None]
        n_b = x.shape[0]
        if n_b == 0:
            return
        mean_b = x.mean(axis=0)
        s_b = ((x - mean_b) ** 2).sum(axis=0)
        n_a = self.num
        if n_a == 0:
            self.mean_ = mean_b
            self.s = s_b
        else:
            delta = mean_b - self.mean_
            tot = n_a + n_b
            self.mean_ = self.mean_ + delta * n_b / tot
            self.s = self.s + s_b + delta**2 * n_a * n_b / tot
        self.num += n_b

    def push(self, x):
        self.push_batch(np.asarray(x)[None])

    def update(self, other: "RunningStat"):
        n1, n2 = self.num, other.num
        if n2 == 0:
            return
        if n1 == 0:
            self.num = other.num
            self.mean_ = other.mean_.copy()
            self.s = other.s.copy()
            return
        delta = other.mean_ - self.mean_
        tot = n1 + n2
        self.s = self.s + other.s + delta**2 * n1 * n2 / tot
        self.mean_ = self.mean_ + delta * n2 / tot
        self.num = tot

    @property
    def n(self):
        return self.num

    @property
    def mean(self):
        return self.mean_

    @property
    def var(self):
        return self.s / (self.num - 1) if self.num > 1 else np.square(self.mean_)

    @property
    def std(self):
        return np.sqrt(self.var)

    def copy(self):
        out = RunningStat()
        out.num = self.num
        out.mean_ = self.mean_.copy()
        out.s = self.s.copy()
        return out


class MeanStdFilter(Filter):
    """Normalizes by running mean/std (reference filter.py:151).

    Keeps a ``buffer`` of stats accumulated since the last sync so that a
    central copy can aggregate deltas from many rollout actors
    (``apply_changes``), mirroring the reference's distributed filter sync.
    """

    def __init__(self, shape, demean: bool = True, destd: bool = True,
                 clip: float | None = 10.0):
        self.shape = shape
        self.demean = demean
        self.destd = destd
        self.clip = clip
        self.rs = RunningStat(shape)
        self.buffer = RunningStat(shape)

    def clear_buffer(self):
        self.buffer = RunningStat(self.shape)

    def apply_changes(self, other: "MeanStdFilter", with_buffer: bool = False):
        self.rs.update(other.buffer)
        if with_buffer:
            self.buffer = other.buffer.copy()

    def copy(self) -> "MeanStdFilter":
        out = MeanStdFilter(self.shape, self.demean, self.destd, self.clip)
        out.sync(self)
        return out

    def as_serializable(self) -> "MeanStdFilter":
        return self.copy()

    def sync(self, other: "MeanStdFilter"):
        self.demean = other.demean
        self.destd = other.destd
        self.clip = other.clip
        self.rs = other.rs.copy()
        self.buffer = other.buffer.copy()

    def __call__(self, x, update: bool = True):
        x = np.asarray(x, dtype=np.float64)
        if update:
            self.rs.push_batch(x)
            self.buffer.push_batch(x)
        if self.demean:
            x = x - self.rs.mean
        if self.destd:
            x = x / (self.rs.std + 1e-8)
        if self.clip:
            x = np.clip(x, -self.clip, self.clip)
        return x.astype(np.float32)

    def __repr__(self):
        return f"MeanStdFilter(shape={self.shape}, n={self.rs.n})"


def get_filter(filter_config, shape) -> Filter:
    """Reference filter.py get_filter equivalent."""
    if filter_config in ("MeanStdFilter", "ConcurrentMeanStdFilter"):
        return MeanStdFilter(shape)
    elif filter_config == "NoFilter" or filter_config is None:
        return NoFilter()
    elif callable(filter_config):
        return filter_config(shape)
    raise ValueError(f"Unknown observation_filter: {filter_config}")
