"""Backend platform pinning shared by the CLI entry points.

``RAY_TPU_PLATFORM=cpu`` (or any jax platform name) pins jax before
the backend initializes. Needed because a deployment's sitecustomize
may set ``jax.config.jax_platforms`` directly, which bypasses the
``JAX_PLATFORMS`` env var — e.g. for CPU smoke runs of the train /
evaluate CLIs on a host whose default backend is a tunneled TPU.
"""

from __future__ import annotations

import os


def apply_platform_override() -> None:
    platform = os.environ.get("RAY_TPU_PLATFORM")
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)
