from ray_tpu.utils.filter import Filter, NoFilter, MeanStdFilter, RunningStat
from ray_tpu.utils.schedules import (
    Schedule,
    ConstantSchedule,
    LinearSchedule,
    PiecewiseSchedule,
    ExponentialSchedule,
    make_schedule,
)

__all__ = [
    "Filter",
    "NoFilter",
    "MeanStdFilter",
    "RunningStat",
    "Schedule",
    "ConstantSchedule",
    "LinearSchedule",
    "PiecewiseSchedule",
    "ExponentialSchedule",
    "make_schedule",
]
