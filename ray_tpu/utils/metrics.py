"""User-defined metrics: Counter / Gauge / Histogram.

Counterpart of the reference's ``python/ray/util/metrics.py``
(``Counter :155``, ``Histogram :220``, ``Gauge :288``) and the
OpenCensus→Prometheus export chain (``src/ray/stats/metric.h:102``,
``_private/metrics_agent.py:63``), collapsed to a process-local
registry + a Prometheus-text endpoint (ray_tpu.utils.metrics_exporter).
Tag-based metric series are supported via tag dicts."""

from __future__ import annotations

import bisect
import threading
from typing import Dict, List, Optional, Sequence, Tuple

_REGISTRY_LOCK = threading.Lock()
_REGISTRY: Dict[str, "Metric"] = {}


def _tag_key(tags: Optional[Dict[str, str]]) -> Tuple:
    return tuple(sorted((tags or {}).items()))


class Metric:
    kind = "untyped"

    def __init__(
        self,
        name: str,
        description: str = "",
        tag_keys: Optional[Sequence[str]] = None,
    ):
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys or ())
        self._lock = threading.Lock()
        self._series: Dict[Tuple, float] = {}
        with _REGISTRY_LOCK:
            _REGISTRY[name] = self

    def series(self) -> List[Tuple[Tuple, float]]:
        with self._lock:
            return list(self._series.items())


class Counter(Metric):
    """Monotonic counter (reference metrics.py:155)."""

    kind = "counter"

    def inc(self, value: float = 1.0, tags: Optional[Dict] = None):
        if value < 0:
            raise ValueError("counters only increase")
        k = _tag_key(tags)
        with self._lock:
            self._series[k] = self._series.get(k, 0.0) + value


class Gauge(Metric):
    """Point-in-time value (reference metrics.py:288)."""

    kind = "gauge"

    def set(self, value: float, tags: Optional[Dict] = None):
        with self._lock:
            self._series[_tag_key(tags)] = float(value)


class Histogram(Metric):
    """Bucketed observations (reference metrics.py:220)."""

    kind = "histogram"

    def __init__(
        self,
        name: str,
        description: str = "",
        boundaries: Optional[Sequence[float]] = None,
        tag_keys: Optional[Sequence[str]] = None,
    ):
        super().__init__(name, description, tag_keys)
        self.boundaries = list(
            boundaries or (0.005, 0.05, 0.5, 5.0, 50.0)
        )
        self._buckets: Dict[Tuple, List[float]] = {}
        self._sums: Dict[Tuple, float] = {}
        self._counts: Dict[Tuple, int] = {}

    def observe(self, value: float, tags: Optional[Dict] = None):
        k = _tag_key(tags)
        with self._lock:
            counts = self._buckets.setdefault(
                k, [0.0] * (len(self.boundaries) + 1)
            )
            counts[bisect.bisect_left(self.boundaries, value)] += 1
            self._sums[k] = self._sums.get(k, 0.0) + float(value)
            self._counts[k] = self._counts.get(k, 0) + 1

    def series(self):
        with self._lock:
            return [
                (
                    k,
                    {
                        "buckets": list(self._buckets.get(k, [])),
                        "sum": self._sums.get(k, 0.0),
                        "count": self._counts.get(k, 0),
                    },
                )
                for k in self._counts
            ]


def get_metric(name: str) -> Optional[Metric]:
    with _REGISTRY_LOCK:
        return _REGISTRY.get(name)


# sub-ms..minutes buckets: device transfers sit in the low
# milliseconds, XLA compiles in the seconds-to-minutes range
_TIMER_BOUNDARIES = (
    0.0005, 0.002, 0.01, 0.05, 0.2, 1.0, 5.0, 30.0, 120.0,
)


def timer_histogram(name: str, description: str = "") -> Histogram:
    """Get-or-create a latency Histogram (idempotent accessor for the
    per-stage learner timers: transfer / compile / step — see
    Policy.last_learn_timers and docs/sharding.md)."""
    m = get_metric(name)
    if isinstance(m, Histogram):
        return m
    return Histogram(
        name, description, boundaries=_TIMER_BOUNDARIES
    )


def all_metrics() -> List[Metric]:
    with _REGISTRY_LOCK:
        return list(_REGISTRY.values())


def clear_registry() -> None:
    with _REGISTRY_LOCK:
        _REGISTRY.clear()
