"""Pluggable exploration strategies (reference
``rllib/utils/exploration/exploration.py:23`` and siblings).

TPU-first: the action-selection part of every strategy is a pure traced
function composed into the policy's jitted action program — schedules
enter as traced scalars (no recompiles), stochastic state (OU noise)
flows through the program like RNN state, and intrinsic-reward learners
(Curiosity/RND) train their own nets with jitted updates in
``postprocess_trajectory``.
"""

from ray_tpu.utils.exploration.exploration import (
    Exploration,
    StochasticSampling,
    Random,
    EpsilonGreedy,
    GaussianNoise,
    OrnsteinUhlenbeckNoise,
    ParameterNoise,
    exploration_from_config,
)
from ray_tpu.utils.exploration.curiosity import Curiosity
from ray_tpu.utils.exploration.rnd import RND

__all__ = [
    "Exploration",
    "StochasticSampling",
    "Random",
    "EpsilonGreedy",
    "GaussianNoise",
    "OrnsteinUhlenbeckNoise",
    "ParameterNoise",
    "Curiosity",
    "RND",
    "exploration_from_config",
]
