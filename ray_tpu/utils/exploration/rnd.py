"""Random Network Distillation exploration (reference
``rllib/utils/exploration/random_encoder.py``, after Burda et al. 2018).

A frozen randomly-initialized target encoder f(s) and a trained
predictor f_hat(s); intrinsic reward is the (running-normalized)
prediction error. Predictor update is one jitted adam step per
trajectory in ``postprocess_trajectory``."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.data.sample_batch import SampleBatch
from ray_tpu.utils.exploration.curiosity import _MLP
from ray_tpu.utils.exploration.exploration import (
    StochasticSampling,
    register_exploration,
)


class RND(StochasticSampling):
    def __init__(self, action_space, config, model_config=None):
        super().__init__(action_space, config, model_config)
        cfg = self.config
        self.embed_dim = int(cfg.get("embed_dim", 128))
        self.eta = float(cfg.get("intrinsic_reward_coeff", 0.5))
        self.lr = float(cfg.get("lr", 1e-4))
        hid = tuple(cfg.get("hiddens", (256,)))
        self.target_net = _MLP(self.embed_dim, hid)
        self.predictor_net = _MLP(self.embed_dim, hid)
        self._tx = optax.adam(self.lr)
        self.target_params = None
        self.predictor_params = None
        self.opt_state = None
        self._update_fn = None
        self._rng = jax.random.PRNGKey(int(cfg.get("seed", 0)))
        # Welford running stats for intrinsic-reward normalization.
        self._count = 1e-4
        self._mean = 0.0
        self._m2 = 1.0

    def _init_params(self, obs: np.ndarray) -> None:
        r1, r2, self._rng = jax.random.split(self._rng, 3)
        dummy = jnp.zeros((2,) + obs.shape[1:], jnp.float32)
        self.target_params = self.target_net.init(r1, dummy)
        self.predictor_params = self.predictor_net.init(r2, dummy)
        self.opt_state = self._tx.init(self.predictor_params)

    def _build_update_fn(self):
        target_net, predictor_net = self.target_net, self.predictor_net
        tx = self._tx

        def loss_fn(pred_params, target_params, obs):
            t = jax.lax.stop_gradient(
                target_net.apply(target_params, obs)
            )
            p = predictor_net.apply(pred_params, obs)
            err = jnp.sum(jnp.square(p - t), axis=-1)
            return err.mean(), err

        def update(pred_params, opt_state, target_params, obs):
            (loss, err), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(pred_params, target_params, obs)
            updates, opt_state = tx.update(grads, opt_state, pred_params)
            pred_params = optax.apply_updates(pred_params, updates)
            return pred_params, opt_state, err

        return jax.jit(update)

    def postprocess_trajectory(self, policy, sample_batch):
        obs = np.asarray(sample_batch[SampleBatch.OBS], np.float32)
        if self.target_params is None:
            self._init_params(obs)
        if self._update_fn is None:
            self._update_fn = self._build_update_fn()
        self.predictor_params, self.opt_state, err = self._update_fn(
            self.predictor_params,
            self.opt_state,
            self.target_params,
            obs,
        )
        err = np.asarray(err, np.float64)
        # batched Welford merge
        n, mean, var = err.size, err.mean(), err.var()
        delta = mean - self._mean
        tot = self._count + n
        self._mean += delta * n / tot
        self._m2 += var * n + delta**2 * self._count * n / tot
        self._count = tot
        std = max(np.sqrt(self._m2 / self._count), 1e-8)
        # Scale by running std only (Burda et al. 2018): mean-centering
        # would hand below-average-novelty states a NEGATIVE bonus and
        # zero out the aggregate signal from the very first batch.
        intrinsic = self.eta * err / std
        sample_batch[SampleBatch.REWARDS] = sample_batch[
            SampleBatch.REWARDS
        ] + intrinsic.astype(np.float32)
        return sample_batch

    def get_state(self):
        if self.target_params is None:
            return {}
        return {
            "target_params": jax.device_get(self.target_params),
            "predictor_params": jax.device_get(self.predictor_params),
            "opt_state": jax.device_get(self.opt_state),
            "norm": (self._count, self._mean, self._m2),
        }

    def set_state(self, state):
        if "target_params" in state:
            self.target_params = jax.device_put(state["target_params"])
            self.predictor_params = jax.device_put(
                state["predictor_params"]
            )
            self.opt_state = jax.device_put(state["opt_state"])
            self._count, self._mean, self._m2 = state["norm"]


register_exploration("RND", RND)
