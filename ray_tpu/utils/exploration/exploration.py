"""Exploration strategy API + the distribution/noise-based strategies.

Reference: ``rllib/utils/exploration/exploration.py:23`` (API),
``stochastic_sampling.py``, ``epsilon_greedy.py``, ``random.py``,
``gaussian_noise.py``, ``ornstein_uhlenbeck_noise.py``,
``parameter_noise.py``. The reference dispatches per-framework inside
``get_exploration_action``; here the strategy contributes a pure
``sample_fn`` that the policy traces INTO its jitted action program, so
exploration costs nothing extra at runtime:

- scheduled knobs (epsilon, noise scale) enter as traced f32 scalars via
  the policy's ``coeff_values`` — annealing never recompiles;
- stochastic carried state (the OU process) flows through the program
  as explicit state, like RNN state;
- strategies with their own learners (Curiosity/RND, see siblings) hook
  ``postprocess_trajectory`` instead.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.utils.schedules import PiecewiseSchedule, make_schedule


class Exploration:
    """Base strategy. All hooks are optional; the default is pure
    exploitation of the action distribution."""

    # Set by strategies that read policy._last_obs (ParameterNoise), so
    # the policy doesn't pin an obs device buffer for everyone else.
    needs_last_obs: bool = False

    def __init__(self, action_space, config: Dict, model_config=None):
        self.action_space = action_space
        self.config = dict(config or {})
        self.model_config = dict(model_config or {})

    # -- traced hooks ---------------------------------------------------

    def sample_fn(
        self,
        dist,
        rng: jax.Array,
        explore: bool,
        coeffs: Dict[str, jnp.ndarray],
        state: Tuple,
    ) -> Tuple[jnp.ndarray, jnp.ndarray, Tuple]:
        """Pure function traced inside the policy's jitted action
        program. ``explore`` is a static bool; ``coeffs`` are traced
        scalars; ``state`` is the carried exploration state (a tuple of
        arrays, possibly empty). Returns (actions, logp, new_state)."""
        if explore:
            actions, logp = dist.sampled_action_logp(rng)
        else:
            actions = dist.deterministic_sample()
            logp = dist.logp(actions)
        return actions, logp, state

    def initial_state(self, batch_size: int) -> Tuple:
        """Carried exploration state for a rollout batch (OU noise)."""
        return ()

    # -- host-side hooks ------------------------------------------------

    def init_coeffs(self) -> Dict[str, float]:
        """Scheduled scalars to merge into policy.coeff_values."""
        return {}

    def update_coeffs(self, coeff_values: Dict, timestep: int) -> None:
        """Advance schedules (host side, called per compute_actions)."""

    def params_for_inference(self, policy, explore: bool):
        """Which params the action program should run with (overridden
        by ParameterNoise to substitute perturbed params)."""
        return policy.params

    def on_weights_updated(self, policy) -> None:
        """Called after policy.set_weights (ParameterNoise re-perturbs)."""

    def postprocess_trajectory(self, policy, sample_batch):
        """Intrinsic-reward strategies rewrite the batch here."""
        return sample_batch

    def get_state(self) -> Dict[str, Any]:
        return {}

    def set_state(self, state: Dict[str, Any]) -> None:
        pass


class StochasticSampling(Exploration):
    """Sample from the action distribution when exploring, deterministic
    (mode) otherwise (reference stochastic_sampling.py). This is the
    base-class behavior, named for config symmetry."""


class Random(Exploration):
    """Uniform-random actions while exploring (reference random.py).
    Supports Discrete and Box action spaces."""

    def sample_fn(self, dist, rng, explore, coeffs, state):
        if not explore:
            actions = dist.deterministic_sample()
            return actions, dist.logp(actions), state
        det = dist.deterministic_sample()
        import gymnasium as gym

        if isinstance(self.action_space, gym.spaces.Discrete):
            n = int(self.action_space.n)
            actions = jax.random.randint(rng, det.shape, 0, n)
            logp = jnp.full(det.shape[:1], -jnp.log(float(n)))
        else:
            low = jnp.asarray(self.action_space.low, jnp.float32)
            high = jnp.asarray(self.action_space.high, jnp.float32)
            u = jax.random.uniform(rng, det.shape)
            actions = low + u * (high - low)
            logp = jnp.zeros(det.shape[:1])
        return actions, logp, state


class EpsilonGreedy(Exploration):
    """Epsilon-greedy over a discrete distribution's greedy action with
    an annealed epsilon (reference epsilon_greedy.py). The epsilon knob
    rides ``coeffs["epsilon"]`` so annealing never recompiles."""

    def __init__(self, action_space, config, model_config=None):
        super().__init__(action_space, config, model_config)
        cfg = self.config
        self.schedule = PiecewiseSchedule(
            [
                (0, float(cfg.get("initial_epsilon", 1.0))),
                (
                    int(cfg.get("epsilon_timesteps", 10000)),
                    float(cfg.get("final_epsilon", 0.02)),
                ),
            ]
        )

    def init_coeffs(self):
        return {"epsilon": float(self.schedule(0))}

    def update_coeffs(self, coeff_values, timestep):
        coeff_values["epsilon"] = float(self.schedule(timestep))

    def sample_fn(self, dist, rng, explore, coeffs, state):
        greedy = dist.deterministic_sample()
        if not explore:
            return greedy, dist.logp(greedy), state
        num_actions = dist.inputs.shape[-1]
        rng_u, rng_a = jax.random.split(rng)
        random_actions = jax.random.randint(
            rng_a, greedy.shape, 0, num_actions
        )
        use_random = (
            jax.random.uniform(rng_u, greedy.shape) < coeffs["epsilon"]
        )
        actions = jnp.where(use_random, random_actions, greedy)
        return actions, dist.logp(actions), state


class GaussianNoise(Exploration):
    """Deterministic action + annealed additive Gaussian noise, clipped
    to the action-space bounds (reference gaussian_noise.py; the DDPG/
    TD3 exploration). ``random_timesteps`` of pure-random warmup are
    approximated by the scale schedule's initial value."""

    def __init__(self, action_space, config, model_config=None):
        super().__init__(action_space, config, model_config)
        cfg = self.config
        self.stddev = float(cfg.get("stddev", 0.1))
        self.scale_schedule = make_schedule(
            cfg.get("scale_schedule"),
            float(cfg.get("initial_scale", 1.0)),
        )
        if cfg.get("scale_schedule") is None and cfg.get(
            "scale_timesteps"
        ):
            self.scale_schedule = PiecewiseSchedule(
                [
                    (0, float(cfg.get("initial_scale", 1.0))),
                    (
                        int(cfg["scale_timesteps"]),
                        float(cfg.get("final_scale", 1.0)),
                    ),
                ]
            )
        self.low = np.asarray(action_space.low, np.float32)
        self.high = np.asarray(action_space.high, np.float32)

    def init_coeffs(self):
        return {"noise_scale": float(self.scale_schedule(0))}

    def update_coeffs(self, coeff_values, timestep):
        coeff_values["noise_scale"] = float(self.scale_schedule(timestep))

    def _noise(self, rng, det, state):
        return self.stddev * jax.random.normal(rng, det.shape), state

    def sample_fn(self, dist, rng, explore, coeffs, state):
        det = dist.deterministic_sample()
        logp = jnp.zeros(det.shape[:1])
        if not explore:
            return det, logp, state
        noise, state = self._noise(rng, det, state)
        actions = jnp.clip(
            det + coeffs["noise_scale"] * noise,
            jnp.asarray(self.low),
            jnp.asarray(self.high),
        )
        return actions, logp, state


class OrnsteinUhlenbeckNoise(GaussianNoise):
    """Temporally-correlated OU noise (reference
    ornstein_uhlenbeck_noise.py): ``x += theta*(0-x) + sigma*N(0,1)``
    carried across steps as traced exploration state, matching the
    vector-env batch. State resets to zero whenever the rollout batch
    size changes (approximation of per-episode reset; the OU process
    mean-reverts quickly regardless)."""

    def __init__(self, action_space, config, model_config=None):
        super().__init__(action_space, config, model_config)
        cfg = self.config
        self.theta = float(cfg.get("ou_theta", 0.15))
        self.sigma = float(cfg.get("ou_sigma", 0.2))
        self.base_scale = float(cfg.get("ou_base_scale", 0.1))

    def initial_state(self, batch_size: int) -> Tuple:
        dim = int(np.prod(self.action_space.shape))
        return (jnp.zeros((batch_size, dim), jnp.float32),)

    def _noise(self, rng, det, state):
        (x,) = state
        x = x + self.theta * (0.0 - x) + self.sigma * jax.random.normal(
            rng, x.shape
        )
        return self.base_scale * x.reshape(det.shape), (x,)


class ParameterNoise(Exploration):
    """Adaptive parameter-space noise (reference parameter_noise.py,
    after Plappert et al. 2018): perturb the policy weights with
    N(0, sigma) and act greedily under the perturbed weights; sigma
    adapts so the induced action-space divergence tracks a target.

    Host-side by design: perturbation happens at weight-sync / interval
    boundaries (not per step), so the traced action program just runs
    with substituted params."""

    needs_last_obs = True

    def __init__(self, action_space, config, model_config=None):
        super().__init__(action_space, config, model_config)
        cfg = self.config
        self.initial_stddev = float(cfg.get("initial_stddev", 1.0))
        self.target_stddev = float(cfg.get("target_stddev", 0.01))
        self.adapt_coeff = float(cfg.get("adapt_coeff", 1.01))
        self.perturb_interval = int(cfg.get("perturb_interval", 50))
        self.stddev = self.initial_stddev
        self._perturbed = None
        self._calls = 0
        self._perturb_fn = None

    def _perturb(self, policy):
        policy._rng, rng = jax.random.split(policy._rng)
        if self._perturb_fn is None:

            def fn(params, rng, stddev):
                leaves, treedef = jax.tree_util.tree_flatten(params)
                rngs = jax.random.split(rng, len(leaves))
                out = [
                    p + stddev * jax.random.normal(r, p.shape, p.dtype)
                    if jnp.issubdtype(p.dtype, jnp.floating)
                    else p
                    for p, r in zip(leaves, rngs)
                ]
                return jax.tree_util.tree_unflatten(treedef, out)

            self._perturb_fn = jax.jit(fn)
        self._perturbed = self._perturb_fn(
            policy.params, rng, jnp.asarray(self.stddev, jnp.float32)
        )

    def _adapt(self, policy) -> None:
        """Grow/shrink sigma toward the target divergence, measured as
        the RMS distance between clean and perturbed model outputs on
        the last observed batch (the reference uses action-space KL;
        output-space RMS is the framework-generic analog)."""
        obs = getattr(policy, "_last_obs", None)
        if obs is None or self._perturbed is None:
            return
        try:
            clean, _, _ = policy.model_forward(policy.params, obs)
            pert, _, _ = policy.model_forward(self._perturbed, obs)
            dist = float(
                np.sqrt(
                    np.mean(
                        np.square(
                            np.asarray(clean, np.float32)
                            - np.asarray(pert, np.float32)
                        )
                    )
                )
            )
        except Exception as e:
            if not getattr(self, "_adapt_warned", False):
                self._adapt_warned = True
                import warnings

                warnings.warn(
                    "ParameterNoise sigma adaptation disabled: model "
                    f"forward on the last obs batch failed ({e!r}); "
                    "stddev stays at its current value."
                )
            return
        if dist > self.target_stddev:
            self.stddev /= self.adapt_coeff
        else:
            self.stddev *= self.adapt_coeff

    def params_for_inference(self, policy, explore: bool):
        if not explore:
            return policy.params
        self._calls += 1
        if (
            self._perturbed is None
            or self._calls % self.perturb_interval == 0
        ):
            self._adapt(policy)
            self._perturb(policy)
        return self._perturbed

    def on_weights_updated(self, policy) -> None:
        self._perturbed = None  # re-perturb from the fresh weights

    def get_state(self):
        return {"stddev": self.stddev}

    def set_state(self, state):
        self.stddev = float(state.get("stddev", self.stddev))


_REGISTRY = {
    "StochasticSampling": StochasticSampling,
    "Random": Random,
    "EpsilonGreedy": EpsilonGreedy,
    "GaussianNoise": GaussianNoise,
    "OrnsteinUhlenbeckNoise": OrnsteinUhlenbeckNoise,
    "ParameterNoise": ParameterNoise,
}


def register_exploration(name: str, cls) -> None:
    _REGISTRY[name] = cls


def exploration_from_config(
    config: Dict,
    action_space,
    model_config=None,
    default: str = "StochasticSampling",
) -> Exploration:
    """Build the strategy from ``config["exploration_config"]``
    (reference ``from_config`` on exploration_config dicts)."""
    ec = dict(config.get("exploration_config") or {})
    typ = ec.pop("type", default)
    if isinstance(typ, type):
        return typ(action_space, ec, model_config)
    cls = _REGISTRY.get(typ)
    if cls is None:
        # late registration (Curiosity/RND import cycle)
        from ray_tpu.utils.exploration import curiosity, rnd  # noqa: F401

        cls = _REGISTRY.get(typ)
    if cls is None:
        raise ValueError(
            f"Unknown exploration type {typ!r}; known: {sorted(_REGISTRY)}"
        )
    return cls(action_space, ec, model_config)
