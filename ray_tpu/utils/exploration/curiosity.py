"""Curiosity (ICM) exploration — intrinsic rewards from an Intrinsic
Curiosity Module (reference ``rllib/utils/exploration/curiosity.py``,
after Pathak et al. 2017).

Three small nets over flattened observations: a feature encoder phi, an
inverse model (phi(s), phi(s')) -> action logits, and a forward model
(phi(s), a) -> phi(s'). Intrinsic reward = eta * ||phi_hat(s') -
phi(s')||^2. The whole ICM update — loss, grads, adam — is ONE jitted
program run per trajectory in ``postprocess_trajectory`` (the reference
runs a torch optimizer step there too)."""

from __future__ import annotations

from typing import Dict, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.data.sample_batch import SampleBatch
from ray_tpu.models.base import get_activation
from ray_tpu.utils.exploration.exploration import (
    StochasticSampling,
    register_exploration,
)


class _MLP(nn.Module):
    out: int
    hiddens: Tuple[int, ...] = (256,)
    activation: str = "relu"

    @nn.compact
    def __call__(self, x):
        act = get_activation(self.activation)
        h = x.reshape(x.shape[0], -1).astype(jnp.float32)
        for i, size in enumerate(self.hiddens):
            h = act(nn.Dense(size, name=f"h_{i}")(h))
        return nn.Dense(self.out, name="out")(h)


class Curiosity(StochasticSampling):
    """Underlying action selection is stochastic sampling; the module's
    contribution is the intrinsic reward + ICM learner."""

    def __init__(self, action_space, config, model_config=None):
        super().__init__(action_space, config, model_config)
        cfg = self.config
        self.feature_dim = int(cfg.get("feature_dim", 288))
        self.eta = float(cfg.get("eta", 1.0))
        self.beta = float(cfg.get("beta", 0.2))
        self.lr = float(cfg.get("lr", 1e-3))
        hid = tuple(cfg.get("feature_net_hiddens", (256,)))
        import gymnasium as gym

        if not isinstance(action_space, gym.spaces.Discrete):
            raise ValueError(
                "Curiosity currently supports Discrete action spaces "
                "(reference curiosity.py has the same restriction)"
            )
        self.num_actions = int(action_space.n)
        self.phi = _MLP(self.feature_dim, hid)
        self.inverse = _MLP(
            self.num_actions, tuple(cfg.get("inverse_net_hiddens", (256,)))
        )
        self.forward_m = _MLP(
            self.feature_dim,
            tuple(cfg.get("forward_net_hiddens", (256,))),
        )
        self._tx = optax.adam(self.lr)
        self.params = None
        self.opt_state = None
        self._update_fn = None
        self._rng = jax.random.PRNGKey(int(cfg.get("seed", 0)))

    def _init_params(self, obs: np.ndarray) -> None:
        r1, r2, r3, self._rng = jax.random.split(self._rng, 4)
        dummy = jnp.zeros((2,) + obs.shape[1:], jnp.float32)
        phi_p = self.phi.init(r1, dummy)
        feat = jnp.zeros((2, 2 * self.feature_dim), jnp.float32)
        inv_p = self.inverse.init(r2, feat)
        fwd_in = jnp.zeros(
            (2, self.feature_dim + self.num_actions), jnp.float32
        )
        fwd_p = self.forward_m.init(r3, fwd_in)
        self.params = {"phi": phi_p, "inverse": inv_p, "forward": fwd_p}
        self.opt_state = self._tx.init(self.params)

    def _build_update_fn(self):
        phi, inverse, forward_m = self.phi, self.inverse, self.forward_m
        num_actions, beta, eta = self.num_actions, self.beta, self.eta
        tx = self._tx

        def icm_loss(params, obs, next_obs, actions):
            f = phi.apply(params["phi"], obs)
            f_next = phi.apply(params["phi"], next_obs)
            # inverse: predict a from (phi, phi')
            inv_logits = inverse.apply(
                params["inverse"],
                jnp.concatenate([f, f_next], axis=-1),
            )
            onehot = jax.nn.one_hot(actions, num_actions)
            inv_loss = optax.softmax_cross_entropy(
                inv_logits, onehot
            ).mean()
            # forward: predict phi' from (phi, a)
            f_pred = forward_m.apply(
                params["forward"],
                jnp.concatenate([f, onehot], axis=-1),
            )
            fwd_err = jnp.sum(
                jnp.square(f_pred - jax.lax.stop_gradient(f_next)),
                axis=-1,
            )
            fwd_loss = 0.5 * fwd_err.mean()
            loss = (1.0 - beta) * inv_loss + beta * fwd_loss
            return loss, eta * 0.5 * fwd_err

        def update(params, opt_state, obs, next_obs, actions):
            (loss, intrinsic), grads = jax.value_and_grad(
                icm_loss, has_aux=True
            )(params, obs, next_obs, actions)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss, intrinsic

        return jax.jit(update)

    def postprocess_trajectory(self, policy, sample_batch):
        obs = np.asarray(sample_batch[SampleBatch.OBS], np.float32)
        if SampleBatch.NEXT_OBS in sample_batch:
            next_obs = np.asarray(
                sample_batch[SampleBatch.NEXT_OBS], np.float32
            )
        else:
            next_obs = np.concatenate([obs[1:], obs[-1:]], axis=0)
        actions = np.asarray(sample_batch[SampleBatch.ACTIONS])
        if self.params is None:
            self._init_params(obs)
        if self._update_fn is None:
            self._update_fn = self._build_update_fn()
        self.params, self.opt_state, loss, intrinsic = self._update_fn(
            self.params, self.opt_state, obs, next_obs, actions
        )
        sample_batch[SampleBatch.REWARDS] = sample_batch[
            SampleBatch.REWARDS
        ] + np.asarray(intrinsic, np.float32)
        return sample_batch

    def get_state(self):
        if self.params is None:
            return {}
        return {
            "params": jax.device_get(self.params),
            "opt_state": jax.device_get(self.opt_state),
        }

    def set_state(self, state):
        if "params" in state:
            self.params = jax.device_put(state["params"])
            self.opt_state = jax.device_put(state["opt_state"])


register_exploration("Curiosity", Curiosity)
