"""Prometheus text-format exporter for ray_tpu.utils.metrics.

Counterpart of the reference's per-node metrics agent + exporter
(``_private/metrics_agent.py:63``, ``_private/prometheus_exporter.py``):
an HTTP endpoint serving /metrics in the Prometheus exposition
format."""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from ray_tpu.utils.metrics import Histogram, all_metrics


def _esc(v) -> str:
    """Prometheus label-value escaping: backslash, quote, newline."""
    return (
        str(v)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _fmt_tags(tag_items) -> str:
    if not tag_items:
        return ""
    inner = ",".join(f'{k}="{_esc(v)}"' for k, v in tag_items)
    return "{" + inner + "}"


def format_prometheus() -> str:
    """Render every registered metric in Prometheus text format."""
    lines = []
    for m in all_metrics():
        name = m.name.replace(".", "_")
        if m.description:
            lines.append(f"# HELP {name} {m.description}")
        lines.append(f"# TYPE {name} {m.kind}")
        if isinstance(m, Histogram):
            for tags, data in m.series():
                cum = 0.0
                for b, c in zip(m.boundaries, data["buckets"]):
                    cum += c
                    t = dict(tags)
                    t["le"] = repr(float(b))
                    lines.append(
                        f"{name}_bucket{_fmt_tags(sorted(t.items()))}"
                        f" {cum}"
                    )
                total = sum(data["buckets"])
                t = dict(tags)
                t["le"] = "+Inf"
                lines.append(
                    f"{name}_bucket{_fmt_tags(sorted(t.items()))}"
                    f" {total}"
                )
                # sorted like the _bucket lines above: series keys must
                # be byte-stable across scrapes or Prometheus sees a
                # new series every time tag insertion order shifts
                lines.append(
                    f"{name}_sum{_fmt_tags(sorted(tags))} {data['sum']}"
                )
                lines.append(
                    f"{name}_count{_fmt_tags(sorted(tags))}"
                    f" {data['count']}"
                )
        else:
            for tags, value in m.series():
                lines.append(
                    f"{name}{_fmt_tags(sorted(tags))} {value}"
                )
    return "\n".join(lines) + "\n"


class MetricsServer:
    """Serves /metrics (Prometheus scrape target).

    ``render`` swaps the exposition source: the fleet aggregator
    installs its merged (host-labeled) renderer here so the
    coordinator's existing scrape port serves the whole fleet
    (telemetry/fleetview.py). A renderer that raises falls back to the
    process-local exposition rather than failing the scrape."""

    def __init__(
        self, host: str = "127.0.0.1", port: int = 0, render=None
    ):
        self.render = render

        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def do_GET(self):
                if self.path.rstrip("/") not in ("", "/metrics"):
                    self.send_response(404)
                    self.end_headers()
                    return
                text = None
                if outer.render is not None:
                    try:
                        text = outer.render()
                    except Exception:
                        text = None
                if text is None:
                    text = format_prometheus()
                blob = text.encode()
                self.send_response(200)
                self.send_header(
                    "Content-Type", "text/plain; version=0.0.4"
                )
                self.send_header("Content-Length", str(len(blob)))
                self.end_headers()
                self.wfile.write(blob)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        self._thread.start()

    def shutdown(self) -> None:
        self._server.shutdown()
        self._server.server_close()
