from ray_tpu.ops.gae import (
    discount_cumsum,
    discount_cumsum_np,
    compute_gae,
    compute_gae_np,
)
from ray_tpu.ops.vtrace import vtrace_from_importance_weights, vtrace_from_logits

__all__ = [
    "discount_cumsum",
    "discount_cumsum_np",
    "compute_gae",
    "compute_gae_np",
    "vtrace_from_importance_weights",
    "vtrace_from_logits",
]
