"""Device-side framestack reconstruction (deduplicated obs transfer).

Atari-style training batches are sliding-window framestacks: row n's
observation is frames [f_n .. f_{n+k-1}], so consecutive rows share
k-1 of their k frames and a naively-shipped (N, H, W, k) obs column
carries each frame k times. The reference avoids SOME of this cost
host-side (plasma stores a fragment's arrays once and workers map them
zero-copy — ``src/ray/object_manager/plasma/store.h:55``), but still
moves full stacks over the loader thread to the device
(``rllib/execution/multi_gpu_learner_thread.py``).

Here the dedup crosses the host→device boundary, where it matters most
on TPU (HBM ingest is the learner's bottleneck once compute is one
fused program): the host ships the UNIQUE frame stream plus a per-row
int32 first-frame index (k× fewer obs bytes), and the jitted learn
program rebuilds the (N, H, W, k) stacks with one gather before the
SGD nest. ``JaxPolicy`` recognizes the ``obs_frames``/``obs_frame_idx``
columns automatically (see ``policy/jax_policy.py``).

Sharding note: the frame pool rides replicated while row columns shard
over the data axis, so stacks build locally on every shard from the
shared pool — correct on any mesh, sized for the single-host learner
path where the transfer win lives.
"""

from __future__ import annotations

import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

try:  # Pallas row-copy kernels (gather/scatter lanes); the XLA
    # gather below stays the portable path and the golden reference
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover - minimal jax builds
    pl = None
    pltpu = None

# Batch columns of the deduplicated format.
FRAMES = "obs_frames"
FRAME_IDX = "obs_frame_idx"


# -- Pallas row gather/scatter (docs/data_plane.md "Pallas kernels") ---
#
# The replay sample path, the superstep ring feed and the framestack
# rebuild are all the same access pattern: gather R rows of a (M, D)
# uint32-lane store (uint8 pixels ride packed 4-wide — see
# build_stacks). XLA lowers that to a general gather HLO; the Pallas
# kernel is a scalar-prefetch row copy — the index vector rides SMEM
# ahead of the grid, each grid step DMAs exactly one store row
# HBM→VMEM→HBM. Pure data movement at uint32 lane width, so outputs
# are BITWISE identical to the XLA path (the uint8 unpack around the
# kernel is a bitcast — a layout view, not a copy). ``use_pallas``
# resolves like ops/flash_attention.py: None = auto (Pallas on TPU
# backends where the shape class lowers, XLA elsewhere);
# ``interpret=True`` runs the kernel through the Pallas interpreter on
# any backend (the CPU-client fallback the parity tests exercise).


def _row_copy_kernel(idx_ref, src_ref, out_ref):
    # index plumbing lives entirely in the BlockSpec index_maps; the
    # body is the DMA'd row copy
    out_ref[...] = src_ref[...]


def _row_scatter_kernel(idx_ref, vals_ref, ring_ref, out_ref):
    # ring_ref is the aliased initial output (read untouched); the
    # body overwrites just the block the out index_map routed here
    del ring_ref
    out_ref[...] = vals_ref[...]


def _pallas_rows(src2, flat_idx, out_rows, scatter, interpret):
    """Shared pallas_call for row gather/scatter on a (M, D) array.
    Gather: out[i] = src2[idx[i]]; scatter: out starts as the aliased
    ring and out[idx[i]] = src2[i]."""
    r = flat_idx.shape[0]
    d = src2.shape[1]
    if scatter:
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(r,),
            in_specs=[
                pl.BlockSpec((1, d), lambda i, idx_ref: (i, 0)),
                # the aliased ring: route its block to the same row
                # the output writes so the alias is block-consistent
                pl.BlockSpec(
                    (1, d), lambda i, idx_ref: (idx_ref[i], 0)
                ),
            ],
            out_specs=pl.BlockSpec(
                (1, d), lambda i, idx_ref: (idx_ref[i], 0)
            ),
        )
        # operand indices for aliasing count past the scalar-prefetch
        # operand: 0=idx, 1=vals, 2=ring → output 0. Rows no grid step
        # writes keep the ring's contents (the circular-buffer
        # contract).
        return pl.pallas_call(
            _row_scatter_kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((out_rows, d), src2.dtype),
            input_output_aliases={2: 0},
            interpret=interpret,
        )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(r,),
        in_specs=[
            pl.BlockSpec((1, d), lambda i, idx_ref: (idx_ref[i], 0))
        ],
        out_specs=pl.BlockSpec((1, d), lambda i, idx_ref: (i, 0)),
    )
    return pl.pallas_call(
        _row_copy_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((out_rows, d), src2.dtype),
        interpret=interpret,
    )


@functools.lru_cache(maxsize=None)
def _rows_lower(m, d, dtype_str, scatter):
    """One-time probe per shape class: does the row-copy kernel lower
    on this backend? (Mosaic's envelope shifts between releases; a
    failing class falls back to the XLA gather instead of crashing the
    replay hot loop.)"""
    try:
        src = jnp.zeros((m if scatter else 2, d), dtype_str)
        ring = jnp.zeros((2, d), dtype_str)
        idx = jnp.zeros((m if scatter else 1,), jnp.int32)
        if scatter:
            jax.jit(
                lambda i, s, rg: _pallas_rows(s, i, 2, True, False)(
                    i, s, rg
                )
            ).lower(idx, src, ring).compile()
        else:
            jax.jit(
                lambda i, s: _pallas_rows(s, i, 1, False, False)(i, s)
            ).lower(idx, src).compile()
        return True
    except Exception:  # pragma: no cover - backend-dependent
        return False


def _resolve_use_pallas(use_pallas, interpret, probe):
    if use_pallas is None:
        return interpret or (
            jax.default_backend() == "tpu" and pltpu is not None
            and probe()
        )
    return bool(use_pallas) and pl is not None


def gather_rows(src, idx, *, use_pallas=None, interpret=False):
    """``src[idx]`` over the leading axis — the replay/framestack row
    gather, optionally through the Pallas row-copy kernel. ``src``:
    (M, ...) any dtype; ``idx``: any int shape. Bitwise identical on
    every path (pure data movement)."""
    idx = jnp.asarray(idx)
    inner = src.shape[1:]
    d = int(np.prod(inner)) if inner else 1
    use = _resolve_use_pallas(
        use_pallas,
        interpret,
        lambda: _rows_lower(1, d, str(src.dtype), False),
    )
    if not use:
        return src[idx]
    flat_idx = idx.reshape(-1).astype(jnp.int32)
    src2 = src.reshape(src.shape[0], d)
    out2 = _pallas_rows(
        src2, flat_idx, flat_idx.shape[0], False, interpret
    )(flat_idx, src2)
    return out2.reshape(idx.shape + inner)


def scatter_rows(ring, pos, vals, *, use_pallas=None, interpret=False):
    """``ring.at[pos].set(vals)`` over the leading axis — the replay
    insert's circular scatter, optionally through the Pallas row-copy
    kernel (ring aliased through, so unwritten rows keep their
    contents). ``pos``: (R,) int; ``vals``: (R, ...) matching ring's
    row shape. Bitwise identical on every path."""
    pos = jnp.asarray(pos)
    inner = ring.shape[1:]
    d = int(np.prod(inner)) if inner else 1
    r = int(pos.shape[0])
    use = _resolve_use_pallas(
        use_pallas,
        interpret,
        lambda: _rows_lower(r, d, str(ring.dtype), True),
    )
    if not use:
        return ring.at[pos].set(vals)
    ring2 = ring.reshape(ring.shape[0], d)
    vals2 = vals.reshape(r, d)
    out2 = _pallas_rows(
        vals2, pos.astype(jnp.int32), ring.shape[0], True, interpret
    )(pos.astype(jnp.int32), vals2, ring2)
    return out2.reshape(ring.shape)


def frame_stream_columns(
    frames: np.ndarray, num_rows: int, k: int
) -> Dict[str, np.ndarray]:
    """Columns for a batch whose row n stacks frames [n .. n+k-1] of a
    contiguous stream. ``frames``: (num_rows + k - 1, H, W, 1)."""
    assert frames.shape[0] >= num_rows + k - 1, (
        frames.shape, num_rows, k
    )
    assert frames.shape[-1] == 1, frames.shape
    return {
        FRAMES: np.asarray(frames),
        FRAME_IDX: np.arange(num_rows, dtype=np.int32),
    }


def decompose_stacked_obs(
    obs: np.ndarray,
) -> Tuple[np.ndarray, np.ndarray] | None:
    """Recover (frame_stream, idx) from a stacked (N, H, W, k) obs
    column IF its rows really are a sliding window (consecutive rows
    share k-1 frames); None when they don't. Host-side utility for
    producers that only have stacked observations."""
    n, h, w, k = obs.shape
    if k <= 1 or n < 2:
        return None
    if not np.array_equal(obs[1:, :, :, : k - 1], obs[:-1, :, :, 1:]):
        return None
    stream = np.concatenate(
        [
            np.moveaxis(obs[0], -1, 0)[..., None],  # (k, H, W, 1)
            obs[1:, :, :, -1][..., None],  # (N-1, H, W, 1)
        ],
        axis=0,
    )
    return stream, np.arange(n, dtype=np.int32)


def decompose_segmented_obs(
    obs: np.ndarray, new_segment: np.ndarray
) -> Tuple[np.ndarray, np.ndarray] | None:
    """Generalized :func:`decompose_stacked_obs` for a batch that
    concatenates SEVERAL sliding windows (rollout fragments from
    different envs/episodes back to back, as e2e train batches are).

    ``new_segment``: (N,) bool — True where row i does NOT slide from
    row i-1 (fragment start, episode reset). Row 0 is always a start.
    Rows inside a segment are verified to really be a sliding window
    (vectorized compare); any mismatch returns None so the caller falls
    back to shipping materialized stacks — a wrong boundary mask can
    cost the dedup win but never correctness. Returns ``(stream, idx)``
    where each segment contributes k + (len-1) frames to the stream.
    """
    n, h, w, k = obs.shape
    if k <= 1 or n == 0:
        return None
    new_segment = np.asarray(new_segment, bool).copy()
    new_segment[0] = True
    slide_rows = np.flatnonzero(~new_segment)
    # verify in row chunks: fancy-indexing the whole batch at once
    # would materialize ~2 extra copies of a multi-GB pixel batch on
    # the host right before the transfer this dedup exists to shrink
    for c in range(0, slide_rows.size, 64):
        rows = slide_rows[c : c + 64]
        if not np.array_equal(
            obs[rows, :, :, : k - 1], obs[rows - 1, :, :, 1:]
        ):
            return None
    starts = np.flatnonzero(new_segment)
    bounds = np.append(starts, n)
    idx = np.empty(n, np.int32)
    pieces = []
    off = 0
    for s, e in zip(bounds[:-1], bounds[1:]):
        seg_len = int(e - s)
        # first row contributes its k frames, later rows 1 new frame
        pieces.append(np.moveaxis(obs[s], -1, 0)[..., None])
        if seg_len > 1:
            pieces.append(obs[s + 1 : e, :, :, -1][..., None])
        idx[s:e] = off + np.arange(seg_len, dtype=np.int32)
        off += seg_len + k - 1
    return np.concatenate(pieces, axis=0), idx


def compress_fragment_obs(
    obs: np.ndarray,
    next_obs: np.ndarray,
    dones: np.ndarray,
) -> Tuple[np.ndarray, np.ndarray] | None:
    """Worker-side compression of ONE rollout fragment's observation
    columns into the frame-pool format, taken before the fragment
    ships to the driver — this is where the dedup pays most: a stacked
    (T, H, W, k) OBS plus NEXT_OBS is 2k single frames' worth of bytes
    per step through pickle, the object ring, driver concat and the
    TPU tunnel; the pool is ~1.

    The pool covers NEXT_OBS implicitly: ``next_obs[t]`` is the stack
    at ``idx[t] + 1`` (sliding), so only the fragment's final
    bootstrap frame is appended (the pseudo-row). ``dones`` marks
    in-fragment episode resets (fixed-unroll mode): the obs AFTER a
    done row starts a fresh window. Returns ``(pool, idx)`` with
    ``idx`` of length T (the bootstrap stack lives at ``idx[-1]+1``),
    or None when the rows aren't sliding windows (caller ships stacks
    unchanged)."""
    T = obs.shape[0]
    if T == 0:
        return None
    ext = np.concatenate([obs, next_obs[-1:]], axis=0)
    seg = np.zeros(T + 1, bool)
    seg[0] = True
    if T > 1:
        seg[1:T] = np.asarray(dones[: T - 1], bool)
    dec = decompose_segmented_obs(ext, seg)
    if dec is None:
        return None
    pool, idx = dec
    return pool, idx[:T]


def compress_replay_obs(
    obs: np.ndarray,
    next_obs: np.ndarray,
    dones: np.ndarray,
) -> Tuple[np.ndarray, np.ndarray] | None:
    """Replay-family variant of :func:`compress_fragment_obs`: the
    pool covers OBS **and** NEXT_OBS exactly, including each episode's
    terminal stack. TD losses read ``next_obs`` at every row (the
    bootstrap term is masked at dones, but the bytes still ship and
    replay buffers store them), so unlike the on-policy path the
    terminal observation of every in-fragment episode must survive
    compression: each episode segment contributes one pseudo-row —
    its final ``next_obs`` — to the pooled stream.

    Invariants of the returned ``(pool, idx)`` (idx length T):
    ``obs[t] == stack(idx[t])`` and ``next_obs[t] == stack(idx[t]+1)``
    for ALL t — :func:`materialize_fragment` rebuilds both columns
    byte-identically (its idx+1 clamp is a no-op here because every
    segment ends with the pseudo-row). Returns None when the rows
    aren't sliding windows (caller ships stacks unchanged)."""
    T = obs.shape[0]
    if T == 0:
        return None
    dones = np.asarray(dones[:T], bool)
    # every done row ends a segment; the final row always does
    seg_end = dones.copy()
    seg_end[T - 1] = True
    end_rows = np.flatnonzero(seg_end)
    # ext: obs rows with each segment's terminal next_obs inserted
    # right after its end row (np.insert indices refer to pre-insert
    # positions, hence end_rows + 1)
    ext = np.insert(obs, end_rows + 1, next_obs[end_rows], axis=0)
    n_seg = len(end_rows)
    starts = np.concatenate(([0], end_rows[:-1] + 1))
    new_segment = np.zeros(T + n_seg, bool)
    new_segment[starts + np.arange(n_seg)] = True
    dec = decompose_segmented_obs(ext, new_segment)
    if dec is None:
        return None
    pool, ext_idx = dec
    # obs row t sits at ext position t + (#pseudo-rows inserted
    # before its segment)
    seg_id = np.zeros(T, np.int64)
    seg_id[1:] = np.cumsum(dones[:-1])
    return pool, ext_idx[np.arange(T) + seg_id]


def materialize_stacks_np(
    pool: np.ndarray, idx: np.ndarray, k: int
) -> np.ndarray:
    """Host-side :func:`build_stacks`: (M, H, W, 1) pool + (N,) first-
    frame indices → (N, H, W, k) stacked observations."""
    gathered = pool[idx[:, None] + np.arange(k)[None, :]]
    return np.moveaxis(gathered[..., 0], 1, -1)


def materialize_fragment(batch_cols: Dict, k: int) -> Dict:
    """Undo :func:`compress_fragment_obs` on a batch's columns: rebuild
    OBS exactly, and NEXT_OBS as the ``idx+1`` stacks — exact
    everywhere consumers read it (within segments and the final
    bootstrap row); at interior episode-reset rows the true terminal
    next_obs was not pooled, so those rows get the FOLLOWING row's
    reset obs instead (no trainer reads next_obs at those rows: the
    on-policy family drops the column entirely and the fixed-unroll
    V-trace tree only reads the final bootstrap stack)."""
    cols = dict(batch_cols)
    pool = np.asarray(cols.pop(FRAMES))
    idx = np.asarray(cols.pop(FRAME_IDX), np.int64)
    from ray_tpu.data.sample_batch import SampleBatch

    cols[SampleBatch.OBS] = materialize_stacks_np(pool, idx, k)
    next_idx = np.minimum(idx + 1, len(pool) - k)
    cols[SampleBatch.NEXT_OBS] = materialize_stacks_np(
        pool, next_idx, k
    )
    return cols


def build_stacks(
    frames: jnp.ndarray,
    idx: jnp.ndarray,
    k: int,
    *,
    use_pallas=None,
    interpret=False,
):
    """Device-side: (M, H, W, 1) frame pool + (N,) first-frame indices
    → (N, H, W, k) stacked observations (one gather, XLA-fusable).

    uint8 pools gather through a uint32-lane bitcast view: narrow-
    element gathers are element-width-bound on TPU (~127 GB/s effective
    for uint8 vs ~420 GB/s through uint32 lanes on v5e, measured for
    the minibatch row gather — MFU.md), and the pool gather is the same
    access pattern at 4× fewer, 4× wider elements. Pure data movement:
    the reconstructed stacks are byte-identical. ``use_pallas`` routes
    the gather through the scalar-prefetch row-copy kernel
    (:func:`gather_rows`) with the uint32 unpack fused around it — the
    surrounding bitcasts are layout views, so the Pallas path stays
    bitwise identical too."""
    assert frames.shape[-1] == 1, (
        "frame pools are single-channel (stack depth k comes from the "
        f"index expansion); got channel dim {frames.shape[-1]} — "
        "multi-channel frames would silently train on one channel"
    )
    inner = int(np.prod(frames.shape[1:]))
    if frames.dtype == jnp.uint8 and inner % 4 == 0:
        packed = jax.lax.bitcast_convert_type(
            frames.reshape(frames.shape[0], inner // 4, 4), jnp.uint32
        )
        gathered = gather_rows(
            packed,
            idx[:, None] + jnp.arange(k)[None, :],
            use_pallas=use_pallas,
            interpret=interpret,
        )
        u8 = jax.lax.bitcast_convert_type(gathered, jnp.uint8)
        u8 = u8.reshape((u8.shape[0], k) + frames.shape[1:])
        return jnp.moveaxis(u8[..., 0], 1, -1)
    gathered = gather_rows(
        frames,
        idx[:, None] + jnp.arange(k)[None, :],
        use_pallas=use_pallas,
        interpret=interpret,
    )
    # (N, k, H, W, 1) → (N, H, W, k)
    return jnp.moveaxis(gathered[..., 0], 1, -1)
