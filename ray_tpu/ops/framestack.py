"""Device-side framestack reconstruction (deduplicated obs transfer).

Atari-style training batches are sliding-window framestacks: row n's
observation is frames [f_n .. f_{n+k-1}], so consecutive rows share
k-1 of their k frames and a naively-shipped (N, H, W, k) obs column
carries each frame k times. The reference avoids SOME of this cost
host-side (plasma stores a fragment's arrays once and workers map them
zero-copy — ``src/ray/object_manager/plasma/store.h:55``), but still
moves full stacks over the loader thread to the device
(``rllib/execution/multi_gpu_learner_thread.py``).

Here the dedup crosses the host→device boundary, where it matters most
on TPU (HBM ingest is the learner's bottleneck once compute is one
fused program): the host ships the UNIQUE frame stream plus a per-row
int32 first-frame index (k× fewer obs bytes), and the jitted learn
program rebuilds the (N, H, W, k) stacks with one gather before the
SGD nest. ``JaxPolicy`` recognizes the ``obs_frames``/``obs_frame_idx``
columns automatically (see ``policy/jax_policy.py``).

Sharding note: the frame pool rides replicated while row columns shard
over the data axis, so stacks build locally on every shard from the
shared pool — correct on any mesh, sized for the single-host learner
path where the transfer win lives.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax.numpy as jnp
import numpy as np

# Batch columns of the deduplicated format.
FRAMES = "obs_frames"
FRAME_IDX = "obs_frame_idx"


def frame_stream_columns(
    frames: np.ndarray, num_rows: int, k: int
) -> Dict[str, np.ndarray]:
    """Columns for a batch whose row n stacks frames [n .. n+k-1] of a
    contiguous stream. ``frames``: (num_rows + k - 1, H, W, 1)."""
    assert frames.shape[0] >= num_rows + k - 1, (
        frames.shape, num_rows, k
    )
    assert frames.shape[-1] == 1, frames.shape
    return {
        FRAMES: np.asarray(frames),
        FRAME_IDX: np.arange(num_rows, dtype=np.int32),
    }


def decompose_stacked_obs(
    obs: np.ndarray,
) -> Tuple[np.ndarray, np.ndarray] | None:
    """Recover (frame_stream, idx) from a stacked (N, H, W, k) obs
    column IF its rows really are a sliding window (consecutive rows
    share k-1 frames); None when they don't. Host-side utility for
    producers that only have stacked observations."""
    n, h, w, k = obs.shape
    if k <= 1 or n < 2:
        return None
    if not np.array_equal(obs[1:, :, :, : k - 1], obs[:-1, :, :, 1:]):
        return None
    stream = np.concatenate(
        [
            np.moveaxis(obs[0], -1, 0)[..., None],  # (k, H, W, 1)
            obs[1:, :, :, -1][..., None],  # (N-1, H, W, 1)
        ],
        axis=0,
    )
    return stream, np.arange(n, dtype=np.int32)


def build_stacks(frames: jnp.ndarray, idx: jnp.ndarray, k: int):
    """Device-side: (M, H, W, 1) frame pool + (N,) first-frame indices
    → (N, H, W, k) stacked observations (one gather, XLA-fusable)."""
    assert frames.shape[-1] == 1, (
        "frame pools are single-channel (stack depth k comes from the "
        f"index expansion); got channel dim {frames.shape[-1]} — "
        "multi-channel frames would silently train on one channel"
    )
    gathered = frames[idx[:, None] + jnp.arange(k)[None, :]]
    # (N, k, H, W, 1) → (N, H, W, k)
    return jnp.moveaxis(gathered[..., 0], 1, -1)
