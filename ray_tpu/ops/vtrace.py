"""V-trace off-policy correction (IMPALA) as an XLA associative scan.

TPU-native counterpart of the reference's
``rllib/algorithms/impala/vtrace_torch.py:127`` (multi_from_logits) and
``:251`` (from_importance_weights). The sequential backward recurrence

    acc[t] = delta[t] + discount[t] * c[t] * acc[t+1]

is a first-order linear recurrence, so it is computed with
``lax.associative_scan`` (log-depth) rather than a python/time loop.

All arrays are batch-major (B, T); the reference is time-major (T, B) —
batch-major keeps the layout identical to the rest of the learner pipeline
and lets XLA tile the (B,) dim onto the VPU lanes.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp


class VTraceReturns(NamedTuple):
    vs: jnp.ndarray  # (B, T) v-trace corrected value targets
    pg_advantages: jnp.ndarray  # (B, T) policy-gradient advantages


def _linear_recurrence_reverse(coeffs: jnp.ndarray, deltas: jnp.ndarray):
    """y[t] = deltas[t] + coeffs[t] * y[t+1], scanned along axis -1."""

    def combine(a, b):
        ca, va = a
        cb, vb = b
        return ca * cb, va * cb + vb

    _, y = jax.lax.associative_scan(
        combine, (coeffs, deltas), reverse=True, axis=deltas.ndim - 1
    )
    return y


def vtrace_from_importance_weights(
    log_rhos: jnp.ndarray,
    discounts: jnp.ndarray,
    rewards: jnp.ndarray,
    values: jnp.ndarray,
    bootstrap_value: jnp.ndarray,
    clip_rho_threshold: Optional[float] = 1.0,
    clip_pg_rho_threshold: Optional[float] = 1.0,
) -> VTraceReturns:
    """V-trace from log importance weights (reference vtrace_torch.py:251).

    Args:
        log_rhos: (B, T) log(target_prob / behaviour_prob) per step.
        discounts: (B, T) gamma * (1 - done) per step.
        rewards/values: (B, T).
        bootstrap_value: (B,) value estimate after the last step.
    """
    rhos = jnp.exp(log_rhos)
    if clip_rho_threshold is not None:
        clipped_rhos = jnp.minimum(clip_rho_threshold, rhos)
    else:
        clipped_rhos = rhos
    cs = jnp.minimum(1.0, rhos)

    values_tp1 = jnp.concatenate(
        [values[:, 1:], bootstrap_value[:, None]], axis=1
    )
    deltas = clipped_rhos * (rewards + discounts * values_tp1 - values)

    vs_minus_v_xs = _linear_recurrence_reverse(discounts * cs, deltas)
    vs = vs_minus_v_xs + values

    vs_tp1 = jnp.concatenate([vs[:, 1:], bootstrap_value[:, None]], axis=1)
    if clip_pg_rho_threshold is not None:
        clipped_pg_rhos = jnp.minimum(clip_pg_rho_threshold, rhos)
    else:
        clipped_pg_rhos = rhos
    pg_advantages = clipped_pg_rhos * (
        rewards + discounts * vs_tp1 - values
    )
    return VTraceReturns(
        vs=jax.lax.stop_gradient(vs),
        pg_advantages=jax.lax.stop_gradient(pg_advantages),
    )


def vtrace_from_logits(
    behaviour_action_log_probs: jnp.ndarray,
    target_action_log_probs: jnp.ndarray,
    discounts: jnp.ndarray,
    rewards: jnp.ndarray,
    values: jnp.ndarray,
    bootstrap_value: jnp.ndarray,
    clip_rho_threshold: Optional[float] = 1.0,
    clip_pg_rho_threshold: Optional[float] = 1.0,
) -> VTraceReturns:
    """V-trace from behaviour/target action log-probs
    (reference vtrace_torch.py:127 multi_from_logits)."""
    log_rhos = target_action_log_probs - behaviour_action_log_probs
    return vtrace_from_importance_weights(
        log_rhos=jax.lax.stop_gradient(log_rhos),
        discounts=discounts,
        rewards=rewards,
        values=values,
        bootstrap_value=bootstrap_value,
        clip_rho_threshold=clip_rho_threshold,
        clip_pg_rho_threshold=clip_pg_rho_threshold,
    )
