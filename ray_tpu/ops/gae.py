"""Generalized Advantage Estimation as XLA-friendly scans.

TPU-native counterpart of the reference's numpy GAE
(``rllib/evaluation/postprocessing.py:76`` compute_advantages and the
``discount_cumsum`` helper). The reference runs this per-episode in numpy on
rollout workers; here the fast path is a jit-compiled ``lax.scan`` over fixed
(B, T) fragments inside the learner step, with episode boundaries handled by
``dones`` masks so no dynamic shapes are ever needed.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

try:  # Pallas fragment-scan kernel; associative_scan stays the
    # portable path and the golden reference
    from jax.experimental import pallas as pl
except Exception:  # pragma: no cover - minimal jax builds
    pl = None


def discount_cumsum_np(x: np.ndarray, gamma: float) -> np.ndarray:
    """y[t] = sum_{k>=t} gamma^(k-t) x[k] (host/numpy golden version)."""
    out = np.zeros_like(x, dtype=np.float32)
    run = 0.0
    for t in range(len(x) - 1, -1, -1):
        run = x[t] + gamma * run
        out[t] = run
    return out


def discount_cumsum(x: jnp.ndarray, gamma: float) -> jnp.ndarray:
    """Reverse discounted cumsum along the last axis via associative scan.

    Uses a first-order linear recurrence composed associatively, so XLA can
    parallelize it (log-depth) instead of a sequential loop.
    """

    def combine(a, b):
        # Each element is (coeff, value): y = coeff * y_next + value
        ca, va = a
        cb, vb = b
        return ca * cb, va * cb + vb

    coeffs = jnp.full_like(x, gamma)
    _, y = jax.lax.associative_scan(
        combine, (coeffs, x), reverse=True, axis=x.ndim - 1
    )
    return y


def compute_gae_np(
    rewards: np.ndarray,
    values: np.ndarray,
    dones: np.ndarray,
    bootstrap_value: float,
    gamma: float = 0.99,
    lambda_: float = 1.0,
):
    """Host/numpy GAE over a single trajectory (golden version).

    Matches the semantics of reference ``postprocessing.py:76``: if the
    trajectory was terminated, ``bootstrap_value`` should be 0; if truncated,
    it is V(s_T).
    """
    T = len(rewards)
    values_tp1 = np.append(values[1:], bootstrap_value)
    not_done = 1.0 - dones.astype(np.float32)
    deltas = rewards + gamma * values_tp1 * not_done - values
    adv = np.zeros(T, dtype=np.float32)
    run = 0.0
    for t in range(T - 1, -1, -1):
        run = deltas[t] + gamma * lambda_ * not_done[t] * run
        adv[t] = run
    value_targets = adv + values
    return adv.astype(np.float32), value_targets.astype(np.float32)


def compute_gae(
    rewards: jnp.ndarray,
    values: jnp.ndarray,
    dones: jnp.ndarray,
    bootstrap_value: jnp.ndarray,
    gamma: float = 0.99,
    lambda_: float = 1.0,
):
    """GAE over fixed-shape (B, T) fragments; jit/TPU fast path.

    Args:
        rewards/values/dones: float/bool arrays of shape (B, T). ``dones``
            marks environment termination at step t (no bootstrap across it).
        bootstrap_value: (B,) value estimate of the observation *after* the
            fragment's last step (0 where the last step terminated).

    Returns:
        (advantages, value_targets), both (B, T) float32.

    Episode boundaries inside a fragment are handled by the ``dones`` mask:
    the recurrence resets because (1 - done) zeroes both the bootstrapped
    next-value and the accumulated advantage.
    """
    rewards = rewards.astype(jnp.float32)
    values = values.astype(jnp.float32)
    not_done = 1.0 - dones.astype(jnp.float32)

    values_tp1 = jnp.concatenate(
        [values[:, 1:], bootstrap_value[:, None]], axis=1
    )
    deltas = rewards + gamma * values_tp1 * not_done - values

    # adv[t] = delta[t] + (gamma*lambda*not_done[t]) * adv[t+1]
    coeffs = gamma * lambda_ * not_done

    def combine(a, b):
        ca, va = a
        cb, vb = b
        return ca * cb, va * cb + vb

    _, adv = jax.lax.associative_scan(
        combine, (coeffs, deltas), reverse=True, axis=deltas.ndim - 1
    )
    value_targets = adv + values
    return adv, value_targets


def _gae_scan_kernel(deltas_ref, coeffs_ref, adv_ref, *, t):
    """Reverse first-order recurrence over the time axis for one row
    block: adv[t] = delta[t] + coeff[t] * adv[t+1]. Sequential in T
    (the mathematically exact order — no reassociation), vectorized
    over the row block."""
    # ray-tpu: device-fn
    rows = adv_ref.shape[0]

    def body(i, run):
        col = t - 1 - i
        d = pl.load(deltas_ref, (slice(None), pl.ds(col, 1)))
        c = pl.load(coeffs_ref, (slice(None), pl.ds(col, 1)))
        run = d + c * run
        pl.store(adv_ref, (slice(None), pl.ds(col, 1)), run)
        return run

    jax.lax.fori_loop(
        0, t, body, jnp.zeros((rows, 1), jnp.float32)
    )


def _gae_scan_pallas(deltas, coeffs, interpret):
    b, t = deltas.shape
    bq = min(b, 8) if b % 8 else 8
    pad = (-b) % bq
    if pad:
        deltas = jnp.pad(deltas, ((0, pad), (0, 0)))
        coeffs = jnp.pad(coeffs, ((0, pad), (0, 0)))
    out = pl.pallas_call(
        functools.partial(_gae_scan_kernel, t=t),
        grid=((b + pad) // bq,),
        in_specs=[
            pl.BlockSpec((bq, t), lambda i: (i, 0)),
            pl.BlockSpec((bq, t), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((bq, t), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((b + pad, t), jnp.float32),
        interpret=interpret,
    )(deltas, coeffs)
    return out[:b] if pad else out


@functools.lru_cache(maxsize=None)
def _gae_lowers(b, t):  # pragma: no cover - backend-dependent
    """One-time probe per (B, T) class: does the fragment-scan kernel
    lower on this backend's Mosaic?"""
    try:
        x = jnp.zeros((b, t), jnp.float32)
        jax.jit(
            lambda d, c: _gae_scan_pallas(d, c, False)
        ).lower(x, x).compile()
        return True
    except Exception:
        return False


def compute_gae_fragment(
    rewards: jnp.ndarray,
    values: jnp.ndarray,
    next_values: jnp.ndarray,
    terminateds: jnp.ndarray,
    dones: jnp.ndarray,
    gamma: float = 0.99,
    lambda_: float = 1.0,
    use_pallas=None,
    interpret: bool = False,
):
    """GAE over (B, T) fragments with the HOST lane's truncation
    semantics (``evaluation/postprocessing.py``): bootstrap 0 across a
    *terminated* step, bootstrap ``next_values`` (= V of the final,
    pre-reset observation) across a *truncated* one, and stop the
    advantage accumulation at EVERY episode boundary either way. This
    is the device rollout lane's postprocess
    (``execution/jax_rollout.py``); :func:`compute_gae` above keeps the
    simpler single-mask form for fragments without mid-stream
    truncation.

    Args:
        rewards/values: (B, T) float.
        next_values: (B, T) float — V(NEXT_OBS[t]) per row, i.e. the
            value of the observation AFTER step t *before* any
            auto-reset (for non-terminal steps this equals
            values[t+1]; at a truncation it is the terminal
            observation's value, exactly what the host lane's
            ``value_batch(last_obs)`` bootstrap uses).
        terminateds/dones: (B, T) bool; ``dones = terminateds |
            truncateds``.

    Returns (advantages, value_targets), both (B, T) float32.

    ``use_pallas`` (None = auto, True/False forces) routes the reverse
    recurrence through the Pallas fragment-scan kernel: sequential in
    T per row block — the mathematically exact evaluation order — vs
    the associative scan's log-depth reassociation, so the two paths
    agree to float32 tolerance (~1e-5 rel), not bitwise; see
    docs/data_plane.md. ``interpret=True`` runs the kernel through the
    Pallas interpreter (the CPU parity path)."""
    rewards = rewards.astype(jnp.float32)
    values = values.astype(jnp.float32)
    next_values = next_values.astype(jnp.float32)
    not_term = 1.0 - terminateds.astype(jnp.float32)
    not_done = 1.0 - dones.astype(jnp.float32)

    deltas = rewards + gamma * next_values * not_term - values
    coeffs = gamma * lambda_ * not_done

    if use_pallas is None:
        use_pallas = interpret or (
            jax.default_backend() == "tpu" and pl is not None
            and _gae_lowers(*deltas.shape)
        )
    if use_pallas and pl is not None:
        adv = _gae_scan_pallas(deltas, coeffs, interpret)
        return adv, adv + values

    def combine(a, b):
        ca, va = a
        cb, vb = b
        return ca * cb, va * cb + vb

    _, adv = jax.lax.associative_scan(
        combine, (coeffs, deltas), reverse=True, axis=deltas.ndim - 1
    )
    return adv, adv + values


def standardize(x: jnp.ndarray, eps: float = 1e-4) -> jnp.ndarray:
    """Zero-mean unit-variance normalization (reference ppo.py:415
    standardize_fields)."""
    return (x - x.mean()) / jnp.maximum(x.std(), eps)
