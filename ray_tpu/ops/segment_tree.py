"""Vectorized segment trees for prioritized replay.

Counterpart of the reference's ``rllib/execution/segment_tree.py:172``
(SumSegmentTree/MinSegmentTree). The reference uses per-element python
recursion; here the tree is a flat numpy array with vectorized batch
operations (``set_items``, ``sample_idx`` for a whole batch at once) since
replay sampling happens on the host at batch granularity.
"""

from __future__ import annotations

import numpy as np


class SegmentTree:
    def __init__(self, capacity: int, operation, neutral_element: float):
        assert capacity > 0 and capacity & (capacity - 1) == 0, (
            "capacity must be a positive power of 2"
        )
        self.capacity = capacity
        self.operation = operation
        self.neutral_element = neutral_element
        self.value = np.full(2 * capacity, neutral_element, dtype=np.float64)

    def set_items(self, idx: np.ndarray, val: np.ndarray) -> None:
        idx = np.asarray(idx, dtype=np.int64) + self.capacity
        self.value[idx] = val
        idx //= 2
        while np.any(idx >= 1):
            live = idx[idx >= 1]
            self.value[live] = self.operation(
                self.value[2 * live], self.value[2 * live + 1]
            )
            idx //= 2
            idx = idx[idx >= 1]
            if len(idx) == 0:
                break

    def __setitem__(self, idx, val):
        self.set_items(np.atleast_1d(idx), np.atleast_1d(val))

    def __getitem__(self, idx):
        return self.value[self.capacity + idx]

    def reduce(self, start: int = 0, end: int | None = None) -> float:
        if end is None:
            end = self.capacity
        if end < 0:
            end += self.capacity
        result = self.neutral_element
        start += self.capacity
        end += self.capacity
        while start < end:
            if start & 1:
                result = self.operation(result, self.value[start])
                start += 1
            if end & 1:
                end -= 1
                result = self.operation(result, self.value[end])
            start //= 2
            end //= 2
        return result


class SumSegmentTree(SegmentTree):
    def __init__(self, capacity: int):
        super().__init__(capacity, np.add, 0.0)

    def sum(self, start: int = 0, end: int | None = None) -> float:
        return self.reduce(start, end)

    def find_prefixsum_idx(self, prefixsum: np.ndarray) -> np.ndarray:
        """Vectorized: for each p in prefixsum, find the highest leaf i such
        that sum(leaves[0..i-1]) <= p. Descends all queries in lockstep."""
        p = np.asarray(prefixsum, dtype=np.float64).copy()
        idx = np.ones(len(p), dtype=np.int64)
        while idx[0] < self.capacity:
            left = 2 * idx
            left_vals = self.value[left]
            go_right = p > left_vals
            p = np.where(go_right, p - left_vals, p)
            idx = np.where(go_right, left + 1, left)
        return idx - self.capacity


class MinSegmentTree(SegmentTree):
    def __init__(self, capacity: int):
        super().__init__(capacity, np.minimum, float("inf"))

    def min(self, start: int = 0, end: int | None = None) -> float:
        return self.reduce(start, end)


# -- device-resident tree (docs/data_plane.md "device sum tree") -------
#
# The same trees as float64 mesh arrays, with insert/update/
# prefix-sum-sample as jit'd programs. The determinism contract: given
# the SAME already-alpha-powered leaf stream the host trees receive,
# every device op is an exact-rounding f64 operation (add, sub, div,
# compare, min — all bitwise-reproducible between numpy and XLA on the
# measured backends), so index draws and sampled priorities reproduce
# the host trees bit-exactly. The alpha-power itself is NOT exact
# across backends (libm vs XLA pow differ in the last ulp), which is
# why `_PrioritySampling` keeps that transform on the host for both
# planes and ships powered leaf values here; the IS-weight beta-power
# runs in-program because its f64 last-ulp is absorbed by the f32
# cast the host path applies anyway (parity-suite asserted).


# ray-tpu: device-fn f64
def reduce_range_body(value, size, op, neutral, capacity: int):
    """In-program counterpart of ``SegmentTree.reduce(0, size)`` with a
    FIXED trip count (one executable serves every ``size``): the same
    node decomposition, visited in the same order, accumulated with the
    same f64 ops — bit-exact by construction. ``size`` is a traced
    scalar."""
    import jax.numpy as jnp

    levels = capacity.bit_length()  # log2(capacity) + 1

    s = jnp.int64(capacity)
    e = jnp.int64(capacity) + size
    r = jnp.float64(neutral)
    for _ in range(levels):
        active = s < e
        # host loop body order: the start-side node first, then the
        # end-side node — the f64 accumulation order is part of the
        # bit-exactness contract
        c1 = active & (s % 2 == 1)
        r = jnp.where(c1, op(r, value[s]), r)
        s = jnp.where(c1, s + 1, s)
        c2 = active & (e % 2 == 1)
        e2 = e - 1
        r = jnp.where(c2, op(r, value[e2]), r)
        e = jnp.where(c2, e2, e)
        # monotone: once s >= e, floor-halving keeps s >= e, so the
        # extra fixed-trip iterations are no-ops
        s = s // 2
        e = e // 2
    return r


# ray-tpu: device-fn f64
def find_prefixsum_body(value, prefixsum, capacity: int):
    """In-program ``SumSegmentTree.find_prefixsum_idx``: the lockstep
    root→leaf descent, one comparison + exact f64 subtraction per
    level."""
    import jax.numpy as jnp

    p = prefixsum
    idx = jnp.ones(p.shape, jnp.int64)
    for _ in range(capacity.bit_length() - 1):
        left = 2 * idx
        left_vals = value[left]
        go_right = p > left_vals
        p = jnp.where(go_right, p - left_vals, p)
        idx = jnp.where(go_right, left + 1, left)
    return idx - capacity


# -- Pallas prefix descent (docs/data_plane.md "Pallas kernels") -------
#
# The root→leaf descent as one Pallas kernel: the whole tree rides
# VMEM-resident and each level is a vectorized gather + exact f64
# compare/subtract — the identical op sequence to
# ``find_prefixsum_body``, so draws stay bit-exact vs the host trees.
# The tree is f64 (the determinism contract above), which Mosaic does
# not lower on current TPU releases — so on this container the kernel
# is interpreter-only (``use_pallas="auto"`` resolves to the XLA body
# on TPU via the lowering probe; benchmarks/e2e/pallas_kernels.json
# records the why-not) and exists as the parity-tested template for
# backends that grow f64 VMEM support.


# ray-tpu: device-fn f64
def _descent_kernel(value_ref, p_ref, out_ref, *, levels, capacity):
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    p = p_ref[...]
    idx = jnp.ones(p.shape, jnp.int32)
    for _ in range(levels):
        left = 2 * idx
        left_vals = pl.load(value_ref, (left,))
        go_right = p > left_vals
        p = jnp.where(go_right, p - left_vals, p)
        idx = jnp.where(go_right, left + 1, left)
    out_ref[...] = idx - capacity


def find_prefixsum_pallas(value, prefixsum, capacity: int, *, interpret=False):
    """Pallas counterpart of :func:`find_prefixsum_body`; returns int64
    leaf indices, bit-exact vs the XLA body (same compares, same exact
    f64 subtractions)."""
    import functools

    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    out = pl.pallas_call(
        functools.partial(
            _descent_kernel,
            levels=capacity.bit_length() - 1,
            capacity=capacity,
        ),
        out_shape=jax.ShapeDtypeStruct(prefixsum.shape, jnp.int32),
        interpret=interpret,
    )(value, prefixsum)
    return out.astype(jnp.int64)


def _descent_lowers(capacity: int, n: int) -> bool:
    """Probe: does the f64 descent lower on this backend? (It does not
    on current TPU Mosaic — f64 vectors — which is exactly what the
    auto knob needs to know.)"""
    import jax
    import jax.numpy as jnp

    from ray_tpu import sharding as sharding_lib

    key = (capacity, n)
    hit = _DESCENT_LOWERS.get(key)
    if hit is not None:
        return hit
    try:
        with sharding_lib.f64_scope():
            v = jnp.zeros(2 * capacity, jnp.float64)
            p = jnp.zeros(n, jnp.float64)
            jax.jit(
                lambda a, b: find_prefixsum_pallas(a, b, capacity)
            ).lower(v, p).compile()
        ok = True
    except Exception:  # pragma: no cover - backend-dependent
        ok = False
    _DESCENT_LOWERS[key] = ok
    return ok


_DESCENT_LOWERS: dict = {}


# ray-tpu: device-fn f64
def draw_body(
    sum_value,
    min_value,
    rand,
    size,
    beta,
    capacity: int,
    use_pallas: bool = False,
    interpret: bool = False,
):
    """The whole stratified proportional draw of
    ``_PrioritySampling._draw_prioritized`` as one in-program body:
    ``rand`` is the host generator's raw uniform stream (the ONLY
    host-fed input — the bit-exact generator invariant), ``size`` /
    ``beta`` are traced scalars so buffer growth and beta annealing
    never retrace. Returns ``(idx int64, weights f32, p_sample f64)``;
    every op except the two beta-powers is exact."""
    import jax.numpy as jnp

    num_items = rand.shape[-1]
    total = reduce_range_body(
        sum_value, size, jnp.add, 0.0, capacity
    )
    strata = jnp.arange(num_items, dtype=jnp.float64)
    mass = (rand + strata) / num_items * total
    if use_pallas:
        idx = find_prefixsum_pallas(
            sum_value, mass, capacity, interpret=interpret
        )
    else:
        idx = find_prefixsum_body(sum_value, mass, capacity)
    idx = jnp.clip(idx, 0, size - 1)

    p_min = (
        reduce_range_body(
            min_value, size, jnp.minimum, float("inf"), capacity
        )
        / total
    )
    max_weight = (p_min * size) ** (-beta)
    p_sample = sum_value[capacity + idx] / total
    weights = ((p_sample * size) ** (-beta) / max_weight).astype(
        jnp.float32
    )
    return idx, weights, p_sample


# ray-tpu: device-fn f64
def _rebuild_body(arr, op, capacity: int):
    """Recompute every internal node bottom-up. Bit-identical to the
    host's incremental ancestor updates: each node is always exactly
    ``op(child_left, child_right)`` of the FINAL children — the same
    two-operand f64 op the host applies."""
    n = capacity // 2
    while n >= 1:
        pairs = arr[2 * n : 4 * n].reshape(n, 2)
        arr = arr.at[n : 2 * n].set(op(pairs[:, 0], pairs[:, 1]))
        n //= 2
    return arr


class DeviceSumTree:
    """The sum+min segment-tree pair as device-resident f64 mesh
    arrays (replicated placement: the draw is a global tree walk over
    ``2·capacity·8`` bytes — tiny next to the replay rows — and every
    shard needs the full prefix structure).

    All programs build AND run inside ``sharding.f64_scope()`` so the
    f64 state survives jax's x64-off canonicalization; outputs that
    feed the learner world (indices, IS weights) leave as i32/f32.
    Updates take ALREADY-POWERED leaf values (the host keeps the
    alpha-power — see module comment) padded to power-of-two row
    buckets with a validity mask, so ragged insert tails never
    retrace; masked rows scatter to flat index 0, the one slot the
    host layout never reads."""

    def __init__(
        self,
        capacity: int,
        mesh=None,
        label: str = "default_policy",
        use_pallas=None,
        pallas_interpret: bool = False,
    ):
        assert capacity > 0 and capacity & (capacity - 1) == 0, (
            "capacity must be a positive power of 2"
        )
        import jax
        import jax.numpy as jnp

        from ray_tpu import sharding as sharding_lib

        self.capacity = int(capacity)
        self.mesh = mesh if mesh is not None else sharding_lib.get_mesh()
        self.label = label
        # None = auto: Pallas descent where the f64 kernel lowers
        # (probe-gated; interpreter always qualifies), XLA body
        # elsewhere — today that means XLA on TPU, see the module
        # comment above find_prefixsum_pallas
        self.use_pallas = use_pallas
        self.pallas_interpret = bool(pallas_interpret)
        self._update_fns = {}
        self._draw_fns = {}
        with sharding_lib.f64_scope():
            rep = sharding_lib.replicated(self.mesh)
            self.sum_value = jax.device_put(
                jnp.zeros(2 * self.capacity, jnp.float64), rep
            )
            self.min_value = jax.device_put(
                jnp.full(2 * self.capacity, jnp.inf, jnp.float64), rep
            )

    # -- updates --------------------------------------------------------

    def _build_update_fn(self, u: int, bp: int):
        import jax.numpy as jnp

        from ray_tpu import sharding as sharding_lib

        cap = self.capacity

        # ray-tpu: f64
        def fn(sum_t, min_t, idx, vals, mask):
            for i in range(u):
                flat = jnp.where(mask[i], cap + idx[i], 0)
                sum_t = sum_t.at[flat].set(
                    jnp.where(mask[i], vals[i], sum_t[flat])
                )
                min_t = min_t.at[flat].set(
                    jnp.where(mask[i], vals[i], min_t[flat])
                )
            sum_t = _rebuild_body(sum_t, jnp.add, cap)
            min_t = _rebuild_body(min_t, jnp.minimum, cap)
            return sum_t, min_t

        rep = sharding_lib.replicated(self.mesh)
        return sharding_lib.sharded_jit(
            fn,
            out_specs=(rep, rep),
            donate_argnums=(0, 1),
            label=f"tree_update[{self.label}:{u}x{bp}]",
        )

    def set_powered(self, idx, powered, active=None) -> None:
        """Write already-alpha-powered leaf values. ``idx``/``powered``
        are ``(n,)`` or ``(U, B)`` (the superstep's stacked refresh,
        applied in update order — cross-update overlapping draws
        resolve exactly as the host's sequential writes); either may
        live on host or device. ``active`` masks whole updates (the
        nan-guard's skipped slots refresh nothing)."""
        import jax
        import numpy as np_

        from ray_tpu import sharding as sharding_lib

        idx_arr = idx if isinstance(idx, jax.Array) else np_.asarray(idx)
        stacked = idx_arr.ndim == 2
        u = int(idx_arr.shape[0]) if stacked else 1
        n = int(idx_arr.shape[-1])
        bp = 1 << max(0, (n - 1).bit_length())  # next pow2 bucket
        mask = np_.zeros((u, bp), bool)
        mask[:, :n] = True
        if active is not None:
            mask &= np_.asarray(active, bool).reshape(u, 1)

        def pad(v, fill):
            if isinstance(v, jax.Array):
                v = v.reshape(u, n)
                if bp == n:
                    return v
                import jax.numpy as jnp

                return jnp.pad(
                    v, ((0, 0), (0, bp - n)), constant_values=fill
                )
            v = np_.asarray(v).reshape(u, n)
            if bp == n:
                return v
            out = np_.full((u, bp), fill, v.dtype)
            out[:, :n] = v
            return out

        key = (u, bp)
        fn = self._update_fns.get(key)
        if fn is None:
            fn = self._update_fns[key] = self._build_update_fn(u, bp)
        with sharding_lib.f64_scope():
            idx_p = pad(idx_arr, 0)
            if not isinstance(idx_p, jax.Array):
                idx_p = idx_p.astype(np_.int32)
            vals_p = pad(powered, 0.0)
            if not isinstance(vals_p, jax.Array):
                vals_p = vals_p.astype(np_.float64)
            self.sum_value, self.min_value = fn(
                self.sum_value, self.min_value, idx_p, vals_p, mask
            )

    # -- draws ----------------------------------------------------------

    def draw(self, rand, size: int, beta: float):
        """Standalone draw program (tests, benches; the buffers fuse
        this body with their row gather instead): host uniform stream
        in, ``(idx i32, weights f32)`` device arrays out."""
        import numpy as np_

        from ray_tpu import sharding as sharding_lib

        rand = np_.asarray(rand, np_.float64)
        key = rand.shape
        fn = self._draw_fns.get(key)
        if fn is None:
            import jax.numpy as jnp

            cap = self.capacity
            interp = self.pallas_interpret
            if self.use_pallas is None:
                pallas = interp or _descent_lowers(cap, rand.shape[-1])
            else:
                pallas = bool(self.use_pallas)

            # ray-tpu: f64
            def prog(sum_t, min_t, r, size_, beta_):
                idx, weights, _ = draw_body(
                    sum_t,
                    min_t,
                    r,
                    size_,
                    beta_,
                    cap,
                    use_pallas=pallas,
                    interpret=interp,
                )
                return idx.astype(jnp.int32), weights

            rep = sharding_lib.replicated(self.mesh)
            fn = self._draw_fns[key] = sharding_lib.sharded_jit(
                prog,
                out_specs=(rep, rep),
                label=f"tree_draw[{self.label}:{'x'.join(map(str, key))}]",
            )
        with sharding_lib.f64_scope():
            return fn(
                self.sum_value,
                self.min_value,
                rand,
                np_.int64(size),
                np_.float64(beta),
            )

    # -- state ----------------------------------------------------------

    def leaf_values(self, size: int):
        """Host f64 copy of the first ``size`` (already-powered)
        leaves — checkpoint state, spill handover, tests. The slice
        happens host-side: an eager device op on an f64 array outside
        the x64 scope would be silently re-canonicalized."""
        import jax

        leaves = np.asarray(
            jax.device_get(self.sum_value), np.float64
        )
        return leaves[self.capacity : self.capacity + int(size)].copy()

    def set_leaf_values(self, vals) -> None:
        vals = np.asarray(vals, np.float64)
        if len(vals):
            self.set_powered(np.arange(len(vals)), vals)
