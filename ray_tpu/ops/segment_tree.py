"""Vectorized segment trees for prioritized replay.

Counterpart of the reference's ``rllib/execution/segment_tree.py:172``
(SumSegmentTree/MinSegmentTree). The reference uses per-element python
recursion; here the tree is a flat numpy array with vectorized batch
operations (``set_items``, ``sample_idx`` for a whole batch at once) since
replay sampling happens on the host at batch granularity.
"""

from __future__ import annotations

import numpy as np


class SegmentTree:
    def __init__(self, capacity: int, operation, neutral_element: float):
        assert capacity > 0 and capacity & (capacity - 1) == 0, (
            "capacity must be a positive power of 2"
        )
        self.capacity = capacity
        self.operation = operation
        self.neutral_element = neutral_element
        self.value = np.full(2 * capacity, neutral_element, dtype=np.float64)

    def set_items(self, idx: np.ndarray, val: np.ndarray) -> None:
        idx = np.asarray(idx, dtype=np.int64) + self.capacity
        self.value[idx] = val
        idx //= 2
        while np.any(idx >= 1):
            live = idx[idx >= 1]
            self.value[live] = self.operation(
                self.value[2 * live], self.value[2 * live + 1]
            )
            idx //= 2
            idx = idx[idx >= 1]
            if len(idx) == 0:
                break

    def __setitem__(self, idx, val):
        self.set_items(np.atleast_1d(idx), np.atleast_1d(val))

    def __getitem__(self, idx):
        return self.value[self.capacity + idx]

    def reduce(self, start: int = 0, end: int | None = None) -> float:
        if end is None:
            end = self.capacity
        if end < 0:
            end += self.capacity
        result = self.neutral_element
        start += self.capacity
        end += self.capacity
        while start < end:
            if start & 1:
                result = self.operation(result, self.value[start])
                start += 1
            if end & 1:
                end -= 1
                result = self.operation(result, self.value[end])
            start //= 2
            end //= 2
        return result


class SumSegmentTree(SegmentTree):
    def __init__(self, capacity: int):
        super().__init__(capacity, np.add, 0.0)

    def sum(self, start: int = 0, end: int | None = None) -> float:
        return self.reduce(start, end)

    def find_prefixsum_idx(self, prefixsum: np.ndarray) -> np.ndarray:
        """Vectorized: for each p in prefixsum, find the highest leaf i such
        that sum(leaves[0..i-1]) <= p. Descends all queries in lockstep."""
        p = np.asarray(prefixsum, dtype=np.float64).copy()
        idx = np.ones(len(p), dtype=np.int64)
        while idx[0] < self.capacity:
            left = 2 * idx
            left_vals = self.value[left]
            go_right = p > left_vals
            p = np.where(go_right, p - left_vals, p)
            idx = np.where(go_right, left + 1, left)
        return idx - self.capacity


class MinSegmentTree(SegmentTree):
    def __init__(self, capacity: int):
        super().__init__(capacity, np.minimum, float("inf"))

    def min(self, start: int = 0, end: int | None = None) -> float:
        return self.reduce(start, end)
