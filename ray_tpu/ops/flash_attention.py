"""Fused attention (flash-attention style) as a Pallas TPU kernel.

The hot op of the attention model family (``models/attention.py`` GTrXL;
reference ``rllib/models/torch/attention_net.py:37`` materializes the
full (T, S) score matrix through torch softmax). This kernel computes
``softmax(q kᵀ / √d + mask) v`` with the online-softmax recurrence:
scores for one (query-block, key-block) tile at a time live in VMEM and
the running (max, sum, accumulator) statistics are carried across key
blocks — the (T, S) attention matrix never touches HBM. Accumulation is
float32 regardless of input dtype (MXU-native bf16 inputs welcome).

Masking is the banded-causal form both call sites need, parameterized by
a static ``causal_offset`` M: query i attends key j iff ``j <= i + M``
(GTrXL's [memory | fragment] window uses M = memory_len; plain causal
self-attention is M = 0; ``None`` disables masking). Shapes stay static:
the wrapper pads T/S up to block multiples and the kernel masks the
padded tail, so XLA compiles one program per shape.

Differentiation: ``jax.custom_vjp`` with the backward pass rematerialized
through the XLA reference implementation — the forward avoids the O(T·S)
HBM intermediate; the backward recomputes it inside one fused XLA
program (the standard remat trade: FLOPs for memory). The reference
path doubles as the CPU fallback, so the op is portable: Pallas on TPU,
XLA elsewhere, and ``interpret=True`` exercises the kernel in tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU memory spaces; absent on CPU-only hosts is fine (interpret)
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    _VMEM = None

_BLOCK_Q = 128
_BLOCK_K = 128
_NEG_INF = -1e30


def _reference_attention(q, k, v, causal_offset):
    """XLA reference: identical math with the (T, S) matrix materialized
    (used for the backward pass, the CPU path, and golden tests).
    q: (N, T, D), k/v: (N, S, D)."""
    scale = 1.0 / jnp.sqrt(jnp.float32(q.shape[-1]))
    scores = jnp.einsum(
        "ntd,nsd->nts", q.astype(jnp.float32), k.astype(jnp.float32)
    ) * scale
    if causal_offset is not None:
        T, S = scores.shape[-2:]
        i = jnp.arange(T)[:, None]
        j = jnp.arange(S)[None, :]
        valid = j <= i + causal_offset
        scores = jnp.where(valid, scores, _NEG_INF)
        probs = jax.nn.softmax(scores, axis=-1)
        # rows with zero valid keys are defined as zero output (matches
        # the kernel's l=0 → 0 convention), not softmax-of-all-masked
        probs = jnp.where(valid.any(-1, keepdims=True), probs, 0.0)
    else:
        probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("nts,nsd->ntd", probs, v.astype(jnp.float32)).astype(
        q.dtype
    )


def _online_softmax_stream(
    q_ref, k_ref, v_ref, row, offset, s_actual, block_k
):
    """The shared online-softmax recurrence: stream key blocks through
    VMEM carrying (m, l, acc). ``offset`` may be a static int or a
    traced scalar (key j valid iff ``j <= row + offset``); ``None``
    disables the band. Returns float32 (m (BQ,1), l (BQ,1),
    acc (BQ,D) UNNORMALIZED)."""
    q = q_ref[0].astype(jnp.float32)  # (BQ, D)
    bq, d = q.shape
    q = q * (1.0 / jnp.sqrt(jnp.float32(d)))
    num_kb = k_ref.shape[1] // block_k

    def body(kb, carry):
        m_prev, l_prev, acc = carry
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(
            jnp.float32
        )
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(
            jnp.float32
        )
        s = q @ k_blk.T  # (BQ, BK)
        col = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1
        )
        valid = col < s_actual
        if offset is not None:
            valid = valid & (col <= row + offset)
        s = jnp.where(valid, s, _NEG_INF)
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        # masked columns contribute exactly zero mass (exp(s - m) would
        # be 1 for rows whose scores are ALL masked)
        p = jnp.where(valid, jnp.exp(s - m_new), 0.0)
        corr = jnp.exp(m_prev - m_new)
        l_new = corr * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        acc = corr * acc + p @ v_blk
        return m_new, l_new, acc

    m0 = jnp.full((bq, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc0 = jnp.zeros((bq, d), jnp.float32)
    return jax.lax.fori_loop(0, num_kb, body, (m0, l0, acc0))


def _fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, *, s_actual, causal_offset, block_k
):
    """One (batch·head, query-block) program producing NORMALIZED
    attention output (static banded offset)."""
    qi = pl.program_id(1)
    bq = q_ref.shape[1]
    row = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, 1), 0)
    _, l, acc = _online_softmax_stream(
        q_ref, k_ref, v_ref, row, causal_offset, s_actual, block_k
    )
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _block_kernel(
    off_ref, q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, *,
    s_actual, block_k,
):
    """Stats-returning variant for ring attention: the same shared
    online-softmax stream, but the banded-causal offset is a RUNTIME
    scalar (SMEM) — inside a shard_map ring the offset depends on the
    traced device index — and the per-row (max, sum) statistics are
    emitted so ring hops can merge partial results exactly."""
    qi = pl.program_id(1)
    bq = q_ref.shape[1]
    row = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, 1), 0)
    m, l, acc = _online_softmax_stream(
        q_ref, k_ref, v_ref, row, off_ref[0], s_actual, block_k
    )
    o_ref[0] = acc  # UNNORMALIZED accumulator (caller merges/divides)
    m_ref[0] = m
    l_ref[0] = l


def flash_block_attention_stats(q, k, v, offset, *, interpret=False):
    """One attention block with running statistics, for ring attention.

    q: (N, T, D); k, v: (N, S, D); offset: int32 scalar array — key j
    is visible to query i iff ``j <= i + offset`` (pass S for "no
    mask"). Returns (acc (N, T, D) float32 UNNORMALIZED, m (N, T), l
    (N, T)) — exactly the quantities the flash merge combines across
    blocks. Forward-only (ring-level callers own differentiation)."""
    setup = _pallas_setup(q, k, v)
    n, t, d = q.shape
    bq, bk, qp, kp, vp, tp, grid, vmem = setup
    smem = (
        {}
        if _VMEM is None
        else {"memory_space": pltpu.SMEM}
    )
    acc, m, l = pl.pallas_call(
        functools.partial(
            _block_kernel, s_actual=k.shape[1], block_k=bk
        ),
        out_shape=[
            jax.ShapeDtypeStruct((n, tp, d), jnp.float32),
            jax.ShapeDtypeStruct((n, tp, 1), jnp.float32),
            jax.ShapeDtypeStruct((n, tp, 1), jnp.float32),
        ],
        grid=grid,
        in_specs=[
            pl.BlockSpec((1,), lambda b, i: (0,), **smem),
            *_qkv_specs(bq, kp.shape[1], d, vmem),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i: (b, i, 0), **vmem),
            pl.BlockSpec((1, bq, 1), lambda b, i: (b, i, 0), **vmem),
            pl.BlockSpec((1, bq, 1), lambda b, i: (b, i, 0), **vmem),
        ],
        interpret=interpret,
    )(jnp.asarray(offset, jnp.int32).reshape(1), qp, kp, vp)
    return acc[:, :t], m[:, :t, 0], l[:, :t, 0]


def _ceil_to(x, m):
    return ((x + m - 1) // m) * m


def _pallas_setup(q, k, v):
    """Shared block-size / padding / grid scaffolding for both
    pallas_call wrappers. Block sizes are rounded up to multiples of 8
    so the (sublane, lane) tiles Mosaic carves out of each block stay
    aligned to the TPU's native (8, 128) vreg tiling — an unaligned
    block (e.g. bq=20 from a T=20 GTrXL unroll) would force Mosaic to
    retile on every load. Padding (below) absorbs the rounding."""
    n, t, d = q.shape
    s = k.shape[1]
    bq = min(_BLOCK_Q, _ceil_to(max(8, t), 8))
    bk = min(_BLOCK_K, _ceil_to(max(8, s), 8))
    qp = _pad_to(q, 1, bq)
    kp = _pad_to(k, 1, bk)
    vp = _pad_to(v, 1, bk)
    tp = qp.shape[1]
    grid = (n, tp // bq)
    vmem = {} if _VMEM is None else {"memory_space": _VMEM}
    return bq, bk, qp, kp, vp, tp, grid, vmem


def _qkv_specs(bq, s_pad, d, vmem):
    """The q (blocked) + k/v (full) input BlockSpecs both wrappers use."""
    return [
        pl.BlockSpec((1, bq, d), lambda b, i: (b, i, 0), **vmem),
        pl.BlockSpec((1, s_pad, d), lambda b, i: (b, 0, 0), **vmem),
        pl.BlockSpec((1, s_pad, d), lambda b, i: (b, 0, 0), **vmem),
    ]


def _pad_to(x, axis, multiple):
    size = x.shape[axis]
    pad = (-size) % multiple
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _flash_fwd_pallas(q, k, v, causal_offset, interpret):
    t, d = q.shape[1:]
    bq, bk, qp, kp, vp, tp, grid, vmem = _pallas_setup(q, k, v)
    out = pl.pallas_call(
        functools.partial(
            _fwd_kernel,
            s_actual=k.shape[1],
            causal_offset=causal_offset,
            block_k=bk,
        ),
        out_shape=jax.ShapeDtypeStruct(qp.shape, q.dtype),
        grid=grid,
        in_specs=_qkv_specs(bq, kp.shape[1], d, vmem),
        out_specs=pl.BlockSpec(
            (1, bq, d), lambda b, i: (b, i, 0), **vmem
        ),
        interpret=interpret,
    )(qp, kp, vp)
    return out[:, :t]


@functools.lru_cache(maxsize=None)
def _pallas_lowers(t, s, d):
    """One-time probe (cached per shape class): does the forward kernel
    actually lower on this backend? Mosaic's supported-shape envelope
    shifts between releases; when a shape class fails to lower we fall
    back to the XLA reference path instead of crashing the hot loop.
    The probe compiles n=1 (batch·head count never affects lowering —
    it is only the leading grid dimension)."""
    try:
        zq = jnp.zeros((1, t, d), jnp.float32)
        zk = jnp.zeros((1, s, d), jnp.float32)
        jax.jit(
            lambda a, b: _flash_fwd_pallas(a, b, b, 0, False)
        ).lower(zq, zk).compile()
        return True
    except Exception:  # pragma: no cover - backend-dependent
        return False


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_attention(q, k, v, causal_offset, interpret):
    return _flash_fwd_pallas(q, k, v, causal_offset, interpret)


def _flash_fwd_rule(q, k, v, causal_offset, interpret):
    return _flash_fwd_pallas(q, k, v, causal_offset, interpret), (q, k, v)


def _flash_bwd_rule(causal_offset, interpret, residuals, g):
    q, k, v = residuals
    _, vjp = jax.vjp(
        lambda q_, k_, v_: _reference_attention(
            q_, k_, v_, causal_offset
        ),
        q, k, v,
    )
    return vjp(g)


_flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention(
    q, k, v, *, causal_offset=None, use_pallas=None, interpret=False
):
    """Fused multi-head attention.

    q: (B, H, T, D); k, v: (B, H, S, D) → (B, H, T, D).
    ``causal_offset=M`` masks key j for query i unless ``j <= i + M``
    (None = full attention). ``use_pallas=None`` auto-selects: the
    Pallas kernel on TPU backends, the XLA reference elsewhere.
    ``interpret=True`` forces the kernel through the Pallas interpreter
    (CPU testing of the real kernel)."""
    B, H, T, D = q.shape
    S = k.shape[2]
    if use_pallas is None:
        use_pallas = interpret or (
            jax.default_backend() == "tpu" and _pallas_lowers(T, S, D)
        )
    qf = q.reshape(B * H, T, D)
    kf = k.reshape(B * H, S, D)
    vf = v.reshape(B * H, S, D)
    if use_pallas:
        out = _flash_attention(qf, kf, vf, causal_offset, interpret)
    else:
        out = _reference_attention(qf, kf, vf, causal_offset)
    return out.reshape(B, H, T, D)
