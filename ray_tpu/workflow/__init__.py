from ray_tpu.workflow.workflow import run, run_async, step

__all__ = ["step", "run", "run_async"]
