from ray_tpu.workflow.workflow import (
    WorkflowCanceledError,
    cancel,
    get_output,
    get_status,
    list_all,
    resume,
    run,
    run_async,
    step,
)

__all__ = [
    "step",
    "run",
    "run_async",
    "list_all",
    "get_status",
    "get_output",
    "resume",
    "cancel",
    "WorkflowCanceledError",
]
