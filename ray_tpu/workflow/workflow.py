"""Durable workflows: DAGs of steps with persisted results.

Counterpart of the reference's ``python/ray/workflow/api.py`` + the
lazy DAG nodes of ``python/ray/dag/dag_node.py``: ``@workflow.step``
functions bind into a DAG; ``workflow.run(node, workflow_id, storage)``
executes it with every step's result checkpointed to disk, so a re-run
of the same workflow_id resumes — completed steps are skipped and their
stored results reused."""

from __future__ import annotations

import hashlib
import os
import pickle
from typing import Any, Callable, Dict, List, Optional

import ray_tpu as ray

_DEFAULT_STORAGE = os.path.expanduser("~/.ray_tpu_workflows")


class StepNode:
    """Lazy DAG node (reference dag/dag_node.py DAGNode)."""

    def __init__(self, fn: Callable, args, kwargs):
        self.fn = fn
        self.args = args
        self.kwargs = kwargs

    def _step_id(self, resolved_args, resolved_kwargs) -> str:
        """Deterministic id from the function name + argument values
        (content-addressed resume: same step, same inputs -> cached)."""
        try:
            blob = pickle.dumps(
                (self.fn.__name__, resolved_args, resolved_kwargs)
            )
        except Exception:
            blob = repr(
                (self.fn.__name__, resolved_args, resolved_kwargs)
            ).encode()
        return (
            f"{self.fn.__name__}-"
            f"{hashlib.sha256(blob).hexdigest()[:16]}"
        )

    def __repr__(self):
        return f"StepNode({self.fn.__name__})"


class _StepFunction:
    def __init__(self, fn: Callable):
        self.fn = fn

    def bind(self, *args, **kwargs) -> StepNode:
        return StepNode(self.fn, args, kwargs)

    # calling directly runs eagerly (convenience)
    def __call__(self, *args, **kwargs):
        return self.fn(*args, **kwargs)


def step(fn: Callable) -> _StepFunction:
    """reference workflow.step decorator."""
    return _StepFunction(fn)


class _Execution:
    def __init__(self, workflow_id: str, storage: str):
        self.dir = os.path.join(storage, workflow_id)
        os.makedirs(self.dir, exist_ok=True)
        self.steps_run: List[str] = []
        self.steps_cached: List[str] = []

    def _path(self, step_id: str) -> str:
        return os.path.join(self.dir, f"{step_id}.pkl")

    def resolve(self, node: Any):
        if isinstance(node, StepNode):
            args = tuple(self.resolve(a) for a in node.args)
            kwargs = {
                k: self.resolve(v) for k, v in node.kwargs.items()
            }
            step_id = node._step_id(args, kwargs)
            path = self._path(step_id)
            if os.path.exists(path):
                self.steps_cached.append(step_id)
                with open(path, "rb") as f:
                    return pickle.load(f)
            value = node.fn(*args, **kwargs)
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                pickle.dump(value, f)
            os.replace(tmp, path)  # atomic: crash-safe checkpoint
            self.steps_run.append(step_id)
            return value
        if isinstance(node, (list, tuple)):
            return type(node)(self.resolve(x) for x in node)
        if isinstance(node, dict):
            return {k: self.resolve(v) for k, v in node.items()}
        return node


def run(
    dag: StepNode,
    *,
    workflow_id: str,
    storage: Optional[str] = None,
) -> Any:
    """Execute the DAG durably; resuming a workflow_id skips completed
    steps (reference workflow.run + resume)."""
    ex = _Execution(workflow_id, storage or _DEFAULT_STORAGE)
    result = ex.resolve(dag)
    # expose execution stats for tests/observability
    run.last_execution = ex  # type: ignore[attr-defined]
    return result


@ray.remote
def _run_remote(dag, workflow_id, storage):
    return run(dag, workflow_id=workflow_id, storage=storage)


def run_async(
    dag: StepNode,
    *,
    workflow_id: str,
    storage: Optional[str] = None,
):
    """Run the workflow in a worker process; returns an ObjectRef."""
    return _run_remote.remote(
        dag, workflow_id, storage or _DEFAULT_STORAGE
    )
