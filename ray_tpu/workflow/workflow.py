"""Durable workflows: DAGs of steps with persisted results.

Counterpart of the reference's ``python/ray/workflow/api.py`` + the
lazy DAG nodes of ``python/ray/dag/dag_node.py``: ``@workflow.step``
functions bind into a DAG; ``workflow.run(node, workflow_id, storage)``
executes it with every step's result checkpointed to disk, so a re-run
of the same workflow_id resumes — completed steps are skipped and their
stored results reused.

Beyond the DAG core, this module carries the reference's step options
(``max_retries`` with backoff, ``catch_exceptions`` —
``workflow/api.py step options``), dynamic continuations (a step may
RETURN another ``StepNode``; the engine keeps resolving — the
reference's ``workflow.continuation``), and the management surface
(``list_all / get_status / get_output / resume / cancel`` —
``workflow/api.py`` management functions) backed by per-workflow
status + DAG files, so a workflow can be resumed by id alone after a
driver restart.
"""

from __future__ import annotations

import contextlib
import fcntl
import hashlib
import json
import os
import pickle
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import ray_tpu as ray
from ray_tpu.util.atomic_io import atomic_write

_DEFAULT_STORAGE = os.path.expanduser("~/.ray_tpu_workflows")

RUNNING = "RUNNING"
SUCCEEDED = "SUCCEEDED"
FAILED = "FAILED"
CANCELED = "CANCELED"


class StepNode:
    """Lazy DAG node (reference dag/dag_node.py DAGNode)."""

    def __init__(
        self,
        fn: Callable,
        args,
        kwargs,
        *,
        max_retries: int = 0,
        retry_delay_s: float = 0.1,
        catch_exceptions: bool = False,
        name: Optional[str] = None,
    ):
        self.fn = fn
        self.args = args
        self.kwargs = kwargs
        self.max_retries = max_retries
        self.retry_delay_s = retry_delay_s
        self.catch_exceptions = catch_exceptions
        self.name = name or fn.__name__

    def _step_id(self, resolved_args, resolved_kwargs) -> str:
        """Deterministic id from the step name + argument values
        (content-addressed resume: same step, same inputs -> cached)."""
        try:
            blob = pickle.dumps(
                (self.name, resolved_args, resolved_kwargs)
            )
        except Exception:
            blob = repr(
                (self.name, resolved_args, resolved_kwargs)
            ).encode()
        return (
            f"{self.name}-"
            f"{hashlib.sha256(blob).hexdigest()[:16]}"
        )

    def __repr__(self):
        return f"StepNode({self.name})"


class _StepFunction:
    def __init__(self, fn: Callable, opts: Optional[Dict] = None):
        self.fn = fn
        self._opts = dict(opts or {})

    def options(
        self,
        *,
        max_retries: Optional[int] = None,
        retry_delay_s: Optional[float] = None,
        catch_exceptions: Optional[bool] = None,
        name: Optional[str] = None,
    ) -> "_StepFunction":
        """reference ``Step.options(max_retries=…,
        catch_exceptions=…)``."""
        opts = dict(self._opts)
        for k, v in (
            ("max_retries", max_retries),
            ("retry_delay_s", retry_delay_s),
            ("catch_exceptions", catch_exceptions),
            ("name", name),
        ):
            if v is not None:
                opts[k] = v
        return _StepFunction(self.fn, opts)

    def bind(self, *args, **kwargs) -> StepNode:
        return StepNode(self.fn, args, kwargs, **self._opts)

    # calling directly runs eagerly (convenience)
    def __call__(self, *args, **kwargs):
        return self.fn(*args, **kwargs)


def step(fn: Callable) -> _StepFunction:
    """reference workflow.step decorator."""
    return _StepFunction(fn)


class _Canceled(BaseException):
    pass


class _Execution:
    def __init__(self, workflow_id: str, storage: str):
        self.workflow_id = workflow_id
        self.storage = storage
        self.dir = os.path.join(storage, workflow_id)
        os.makedirs(self.dir, exist_ok=True)
        self.steps_run: List[str] = []
        self.steps_cached: List[str] = []

    def _path(self, step_id: str) -> str:
        return os.path.join(self.dir, f"{step_id}.pkl")

    def _check_canceled(self):
        if _read_status(self.dir).get("status") == CANCELED:
            raise _Canceled(self.workflow_id)

    def _run_step(self, node: StepNode, args, kwargs):
        attempts = node.max_retries + 1
        for k in range(attempts):
            self._check_canceled()
            try:
                value = node.fn(*args, **kwargs)
            except Exception as e:
                if k + 1 >= attempts:
                    if node.catch_exceptions:
                        return (None, e)
                    raise
                time.sleep(node.retry_delay_s * (2**k))
                continue
            # dynamic continuation (reference workflow.continuation): a
            # step returning a StepNode hands control to a NEW sub-DAG.
            # Resolve it BEFORE the catch_exceptions tuple wrap — a
            # (StepNode, None) tuple would hide the continuation from
            # resolve() and checkpoint it unexecuted. catch_exceptions
            # covers the WHOLE continuation chain (reference semantics);
            # the outer step already succeeded and is not retried.
            try:
                while isinstance(value, StepNode):
                    value = self.resolve(value)
            except _Canceled:
                raise
            except Exception as e:
                if node.catch_exceptions:
                    return (None, e)
                raise
            return (value, None) if node.catch_exceptions else value

    def resolve(self, node: Any):
        if isinstance(node, StepNode):
            args = tuple(self.resolve(a) for a in node.args)
            kwargs = {
                k: self.resolve(v) for k, v in node.kwargs.items()
            }
            step_id = node._step_id(args, kwargs)
            path = self._path(step_id)
            if os.path.exists(path):
                self.steps_cached.append(step_id)
                with open(path, "rb") as f:
                    return pickle.load(f)
            # dynamic continuations are resolved inside _run_step (so
            # catch_exceptions wrapping can't hide them)
            value = self._run_step(node, args, kwargs)
            # atomic + fsync'd: crash-safe step checkpoint
            atomic_write(path, lambda f: pickle.dump(value, f))
            self.steps_run.append(step_id)
            return value
        if isinstance(node, (list, tuple)):
            return type(node)(self.resolve(x) for x in node)
        if isinstance(node, dict):
            return {k: self.resolve(v) for k, v in node.items()}
        return node


# -- per-workflow metadata (status + stored DAG) ---------------------------


def _read_status(wf_dir: str) -> Dict:
    try:
        with open(os.path.join(wf_dir, "status.json")) as f:
            return json.load(f)
    except (FileNotFoundError, json.JSONDecodeError):
        return {}


def _write_status(wf_dir: str, **fields) -> None:
    cur = _read_status(wf_dir)
    cur.update(fields)
    atomic_write(
        os.path.join(wf_dir, "status.json"),
        lambda f: f.write(json.dumps(cur).encode()),
    )


@contextlib.contextmanager
def _status_lock(wf_dir: str):
    """flock serializing status transitions, so run()'s canceled-check
    + RUNNING write is atomic against a concurrent cancel()."""
    os.makedirs(wf_dir, exist_ok=True)
    with open(os.path.join(wf_dir, ".status.lock"), "w") as f:
        fcntl.flock(f, fcntl.LOCK_EX)
        try:
            yield
        finally:
            fcntl.flock(f, fcntl.LOCK_UN)


def run(
    dag: StepNode,
    *,
    workflow_id: str,
    storage: Optional[str] = None,
    _resuming: bool = False,
) -> Any:
    """Execute the DAG durably; resuming a workflow_id skips completed
    steps (reference workflow.run + resume)."""
    storage = storage or _DEFAULT_STORAGE
    ex = _Execution(workflow_id, storage)
    # persist the DAG so resume(workflow_id) works from the id alone
    # (cloudpickle: step closures serialize too)
    dag_path = os.path.join(ex.dir, "dag.pkl")
    if not os.path.exists(dag_path):
        try:
            from ray_tpu.core import serialization as _ser

            blob = _ser.dumps(dag)
            atomic_write(dag_path, lambda f: f.write(blob))
        except Exception:
            pass  # truly unpicklable DAG: resume-by-id unavailable
    # a cancel() issued before (or racing) this startup write must not
    # be clobbered by the RUNNING transition — the flock makes
    # check+write atomic against cancel(); a CANCELED id needs an
    # explicit resume() to run again
    with _status_lock(ex.dir):
        if (
            not _resuming
            and _read_status(ex.dir).get("status") == CANCELED
        ):
            raise WorkflowCanceledError(workflow_id)
        _write_status(
            ex.dir,
            status=RUNNING,
            start_time=time.time(),
            end_time=None,
        )
    try:
        result = ex.resolve(dag)
    except _Canceled:
        _write_status(ex.dir, end_time=time.time())
        raise WorkflowCanceledError(workflow_id) from None
    except BaseException as e:
        _write_status(
            ex.dir, status=FAILED, end_time=time.time(), error=repr(e)
        )
        raise
    with open(os.path.join(ex.dir, "__result__.pkl"), "wb") as f:
        pickle.dump(result, f)
    _write_status(ex.dir, status=SUCCEEDED, end_time=time.time())
    # expose execution stats for tests/observability
    run.last_execution = ex  # type: ignore[attr-defined]
    return result


class WorkflowCanceledError(RuntimeError):
    pass


@ray.remote
def _run_remote(dag, workflow_id, storage):
    return run(dag, workflow_id=workflow_id, storage=storage)


def run_async(
    dag: StepNode,
    *,
    workflow_id: str,
    storage: Optional[str] = None,
):
    """Run the workflow in a worker process; returns an ObjectRef."""
    return _run_remote.remote(
        dag, workflow_id, storage or _DEFAULT_STORAGE
    )


# -- management API (reference workflow/api.py) ----------------------------


def list_all(
    storage: Optional[str] = None,
) -> List[Tuple[str, str]]:
    """[(workflow_id, status)] for every workflow in the storage
    (reference workflow.list_all)."""
    storage = storage or _DEFAULT_STORAGE
    out = []
    try:
        ids = sorted(os.listdir(storage))
    except FileNotFoundError:
        return []
    for wid in ids:
        wf_dir = os.path.join(storage, wid)
        if os.path.isdir(wf_dir):
            out.append((wid, _read_status(wf_dir).get("status", "")))
    return out


def get_status(
    workflow_id: str, storage: Optional[str] = None
) -> Optional[str]:
    wf_dir = os.path.join(storage or _DEFAULT_STORAGE, workflow_id)
    return _read_status(wf_dir).get("status")


def get_output(workflow_id: str, storage: Optional[str] = None) -> Any:
    """Stored final result of a SUCCEEDED workflow (reference
    workflow.get_output)."""
    path = os.path.join(
        storage or _DEFAULT_STORAGE, workflow_id, "__result__.pkl"
    )
    with open(path, "rb") as f:
        return pickle.load(f)


def resume(workflow_id: str, storage: Optional[str] = None) -> Any:
    """Re-run a workflow from its stored DAG; completed steps load
    from their checkpoints (reference workflow.resume)."""
    storage = storage or _DEFAULT_STORAGE
    dag_path = os.path.join(storage, workflow_id, "dag.pkl")
    try:
        from ray_tpu.core import serialization as _ser

        with open(dag_path, "rb") as f:
            dag = _ser.loads(f.read())
    except FileNotFoundError:
        raise ValueError(
            f"workflow {workflow_id!r} has no stored DAG to resume"
        ) from None
    return run(
        dag, workflow_id=workflow_id, storage=storage, _resuming=True
    )


def cancel(workflow_id: str, storage: Optional[str] = None) -> None:
    """Mark a workflow canceled; its execution stops before the next
    step starts (reference workflow.cancel — cooperative, like the
    reference's checkpoint-boundary cancellation). Only a KNOWN
    workflow (one that has started, i.e. has stored state) can be
    canceled — canceling an arbitrary never-run id would brick it:
    run() refuses CANCELED ids and resume() has no DAG to load."""
    wf_dir = os.path.join(storage or _DEFAULT_STORAGE, workflow_id)
    if not (
        os.path.exists(os.path.join(wf_dir, "status.json"))
        or os.path.exists(os.path.join(wf_dir, "dag.pkl"))
    ):
        raise ValueError(f"unknown workflow {workflow_id!r}")
    with _status_lock(wf_dir):
        _write_status(wf_dir, status=CANCELED, end_time=time.time())
