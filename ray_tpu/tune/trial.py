"""Trial: one configuration's lifecycle record
(reference ``ray/tune/experiment/trial.py``)."""

from __future__ import annotations

import uuid
from typing import Any, Dict, Optional

PENDING = "PENDING"
RUNNING = "RUNNING"
PAUSED = "PAUSED"
TERMINATED = "TERMINATED"
ERROR = "ERROR"


class Trial:
    def __init__(self, trainable_name: str, config: Dict,
                 stopping_criterion: Optional[Dict] = None,
                 trial_id: Optional[str] = None):
        self.trainable_name = trainable_name
        self.config = config
        self.stopping_criterion = stopping_criterion or {}
        self.trial_id = trial_id or uuid.uuid4().hex[:8]
        self.status = PENDING
        self.runner = None  # the Trainable instance
        self.last_result: Dict[str, Any] = {}
        self.results: list = []
        self.checkpoint_path: Optional[str] = None
        self.error: Optional[str] = None

    def should_stop(self, result: Dict) -> bool:
        if result.get("done"):
            # function trainables mark their natural end
            return True
        for k, v in self.stopping_criterion.items():
            if result.get(k, float("-inf")) >= v:
                return True
        return False

    def __repr__(self):
        return (
            f"Trial({self.trainable_name}_{self.trial_id}, "
            f"{self.status})"
        )
