"""Experiment-dir syncing (reference ``python/ray/tune/syncer.py``).

The reference uploads trial checkpoints + experiment state to cloud
storage (``SyncConfig(upload_dir=...)``, Syncer subclasses per
backend) so a dead head node's experiments resume elsewhere. Same
seam here: a :class:`Syncer` ABC with an mtime-delta filesystem
implementation (shared-FS / NFS posture — the idiomatic durable
storage on TPU pods; an object-store backend can subclass Syncer
without touching callers). ``tune.run(sync_config=SyncConfig(...))``
syncs after every experiment-state write, and ``resume=True`` pulls
the mirror down first when the local dir is missing."""

from __future__ import annotations

import os
import shutil
from typing import Optional


class SyncConfig:
    """reference tune/syncer.py SyncConfig."""

    def __init__(
        self,
        upload_dir: Optional[str] = None,
        syncer: Optional["Syncer"] = None,
        sync_period_s: float = 0.0,
    ):
        self.upload_dir = upload_dir
        self.syncer = syncer or (
            FileSyncer() if upload_dir else None
        )
        self.sync_period_s = float(sync_period_s)


class Syncer:
    def sync_up(self, local_dir: str, remote_dir: str) -> None:
        raise NotImplementedError

    def sync_down(self, remote_dir: str, local_dir: str) -> None:
        raise NotImplementedError

    def exists(self, remote_dir: str) -> bool:
        """Whether the remote location has anything to pull — the
        backend owns remote-path semantics (an object-store syncer
        checks its bucket; callers never os.path a remote URI)."""
        raise NotImplementedError


class FileSyncer(Syncer):
    """mtime-delta directory mirror: only new/changed files copy."""

    @staticmethod
    def _copy_delta(src: str, dst: str) -> int:
        copied = 0
        for root, _, files in os.walk(src):
            rel = os.path.relpath(root, src)
            out_root = (
                dst if rel == "." else os.path.join(dst, rel)
            )
            os.makedirs(out_root, exist_ok=True)
            for f in files:
                s = os.path.join(root, f)
                d = os.path.join(out_root, f)
                try:
                    if (
                        not os.path.exists(d)
                        or os.path.getmtime(s) > os.path.getmtime(d)
                        or os.path.getsize(s) != os.path.getsize(d)
                    ):
                        shutil.copy2(s, d)
                        copied += 1
                except OSError:
                    pass
        return copied

    def sync_up(self, local_dir: str, remote_dir: str) -> None:
        os.makedirs(remote_dir, exist_ok=True)
        self._copy_delta(local_dir, remote_dir)

    def sync_down(self, remote_dir: str, local_dir: str) -> None:
        self._copy_delta(remote_dir, local_dir)

    def exists(self, remote_dir: str) -> bool:
        return os.path.exists(remote_dir)
