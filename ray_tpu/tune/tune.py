"""tune.run: experiment runner.

Counterpart of the reference's ``ray/tune/tune.py:118`` (tune.run) +
``tune/execution/trial_runner.py:226`` (TrialRunner.step :793). Trials run
time-sliced in-process (one TPU learner per host; the reference's
placement-group-per-trial model maps to sequential mesh occupancy here),
which preserves ASHA/PBT semantics: every trial advances one
``train()`` per scheduling round.
"""

from __future__ import annotations

import os
import traceback
from typing import Any, Dict, List, Optional, Type, Union

from ray_tpu.tune.schedulers import (
    CONTINUE,
    STOP,
    FIFOScheduler,
    TrialScheduler,
)
from ray_tpu.tune.search import BasicVariantGenerator
from ray_tpu.tune.trial import (
    ERROR,
    PENDING,
    RUNNING,
    TERMINATED,
    Trial,
)


class ExperimentAnalysis:
    """reference ray/tune/analysis/experiment_analysis.py."""

    def __init__(self, trials: List[Trial],
                 metric: str = "episode_reward_mean",
                 mode: str = "max"):
        self.trials = trials
        self.default_metric = metric
        self.default_mode = mode

    def get_best_trial(
        self, metric: Optional[str] = None, mode: Optional[str] = None
    ) -> Optional[Trial]:
        metric = metric or self.default_metric
        mode = mode or self.default_mode
        best, best_v = None, None
        for t in self.trials:
            v = t.last_result.get(metric)
            if v is None:
                continue
            if (
                best_v is None
                or (mode == "max" and v > best_v)
                or (mode == "min" and v < best_v)
            ):
                best, best_v = t, v
        return best

    @property
    def best_config(self) -> Optional[Dict]:
        t = self.get_best_trial()
        return t.config if t else None

    @property
    def results(self) -> Dict[str, Dict]:
        return {t.trial_id: t.last_result for t in self.trials}

    def dataframe(self) -> List[Dict]:
        return [
            {"trial_id": t.trial_id, **t.last_result}
            for t in self.trials
        ]


class TrialRunner:
    """reference tune/execution/trial_runner.py:226."""

    def __init__(
        self,
        trainable_cls,
        trials: List[Trial],
        scheduler: Optional[TrialScheduler] = None,
        max_iterations: int = 100,
        checkpoint_freq: int = 0,
        local_dir: Optional[str] = None,
        callbacks: Optional[List] = None,
    ):
        self.trainable_cls = trainable_cls
        self.trials = trials
        self.scheduler = scheduler or FIFOScheduler()
        self.max_iterations = max_iterations
        self.checkpoint_freq = checkpoint_freq
        self.local_dir = local_dir
        self.callbacks = callbacks or []

    def is_finished(self) -> bool:
        return all(
            t.status in (TERMINATED, ERROR) for t in self.trials
        )

    def step(self) -> None:
        """Advance every live trial by one training iteration
        (reference trial_runner.py:793)."""
        for trial in self.trials:
            if trial.status in (TERMINATED, ERROR):
                continue
            if trial.runner is None:
                try:
                    trial.runner = self.trainable_cls(
                        config=trial.config
                    )
                    trial.status = RUNNING
                except Exception:
                    trial.status = ERROR
                    trial.error = traceback.format_exc()
                    continue
            try:
                result = trial.runner.train()
            except Exception:
                trial.status = ERROR
                trial.error = traceback.format_exc()
                self._cleanup_trial(trial)
                continue
            trial.last_result = result
            trial.results.append(result)
            for cb in self.callbacks:
                cb(trial, result)
            if self.checkpoint_freq and (
                result["training_iteration"] % self.checkpoint_freq
                == 0
            ):
                trial.checkpoint_path = trial.runner.save()
            decision = self.scheduler.on_trial_result(
                self, trial, result
            )
            if (
                decision == STOP
                or trial.should_stop(result)
                or result["training_iteration"] >= self.max_iterations
            ):
                trial.status = TERMINATED
                self.scheduler.on_trial_complete(self, trial, result)
                if self.checkpoint_freq:
                    trial.checkpoint_path = trial.runner.save()
                self._cleanup_trial(trial)

    def _cleanup_trial(self, trial: Trial) -> None:
        if trial.runner is not None:
            try:
                trial.runner.stop()
            except Exception:
                pass
            trial.runner = None


def run(
    run_or_experiment: Union[str, Type],
    *,
    config: Optional[Dict] = None,
    stop: Optional[Dict] = None,
    num_samples: int = 1,
    scheduler: Optional[TrialScheduler] = None,
    checkpoint_freq: int = 0,
    local_dir: Optional[str] = None,
    metric: str = "episode_reward_mean",
    mode: str = "max",
    max_iterations: int = 100,
    callbacks: Optional[List] = None,
    verbose: int = 1,
    seed: int = 0,
) -> ExperimentAnalysis:
    """reference tune/tune.py:118."""
    if isinstance(run_or_experiment, str):
        from ray_tpu.algorithms.registry import get_algorithm_class

        trainable_cls = get_algorithm_class(run_or_experiment)
        name = run_or_experiment
    else:
        trainable_cls = run_or_experiment
        name = trainable_cls.__name__

    stop = dict(stop or {})
    max_iters = int(stop.pop("training_iteration", max_iterations))
    gen = BasicVariantGenerator(config or {}, num_samples, seed)
    trials = [
        Trial(name, v, stopping_criterion=stop)
        for v in iter(gen.next_variant, None)
    ]
    runner = TrialRunner(
        trainable_cls,
        trials,
        scheduler=scheduler,
        max_iterations=max_iters,
        checkpoint_freq=checkpoint_freq,
        local_dir=local_dir,
        callbacks=callbacks,
    )
    while not runner.is_finished():
        runner.step()
        if verbose:
            live = sum(1 for t in trials if t.status == RUNNING)
            best = ExperimentAnalysis(
                trials, metric, mode
            ).get_best_trial()
            if best is not None:
                print(
                    f"[tune] live={live} "
                    f"best[{metric}]="
                    f"{best.last_result.get(metric)}"
                )
    return ExperimentAnalysis(trials, metric, mode)
