"""tune.run: experiment runner.

Counterpart of the reference's ``ray/tune/tune.py:118`` (tune.run) +
``tune/execution/trial_runner.py:226`` (TrialRunner.step :793) +
``tune/execution/ray_trial_executor.py`` (trials as concurrently
scheduled actors).

Two execution modes:
- **parallel** (default for multi-trial experiments): each trial is a
  dedicated non-daemon actor process hosting the Trainable; up to
  ``max_concurrent`` trials advance truly concurrently, results are
  processed as they complete (schedulers see them event-driven, like
  the reference's RayTrialExecutor event loop). Trial actors run on the
  CPU JAX platform (the chip belongs to the driver), so this is the
  searcher/scheduler path, not the single-big-run path.
- **sequential in-process** (``parallel=False``, or one trial): trials
  time-slice the driver — the mode that owns the real TPU mesh.
"""

from __future__ import annotations

import os
import pickle
import traceback
from typing import Any, Dict, List, Optional, Type, Union

import ray_tpu as ray
from ray_tpu.tune.schedulers import (
    CONTINUE,
    STOP,
    FIFOScheduler,
    TrialScheduler,
)
from ray_tpu.tune.search import BasicVariantGenerator
from ray_tpu.tune.trainable import Trainable
from ray_tpu.tune.search import Domain as SearchDomain
from ray_tpu.tune.trial import (
    ERROR,
    PENDING,
    RUNNING,
    TERMINATED,
    Trial,
)


@ray.remote
class _TrialActor:
    """One trial's Trainable, hosted in a dedicated process
    (reference ray_trial_executor.py wraps trainables the same way)."""

    def __init__(self, trainable_cls, config):
        self._t = trainable_cls(config=config)

    def train(self):
        return self._t.train()

    def save(self, checkpoint_dir=None):
        return self._t.save(checkpoint_dir)

    def restore(self, path):
        self._t.restore(path)

    def stop(self):
        self._t.stop()

    def get_exploit_state(self):
        return self._t.get_exploit_state()

    def apply_exploit(self, state, scalars):
        self._t.apply_exploit(state, scalars)


class _RemoteTrainableProxy:
    """Synchronous facade over a _TrialActor, so schedulers (PBT
    exploit protocol, checkpointing) treat remote and in-process
    trials identically. Consumed refs are freed immediately — store
    entries otherwise live until driver shutdown, and exploit states
    carry full model weights."""

    def __init__(self, actor):
        self.actor = actor

    def _call(self, method, *args):
        ref = method.remote(*args)
        try:
            return ray.get(ref)
        finally:
            ray.free([ref])

    def save(self, checkpoint_dir=None):
        return self._call(self.actor.save, checkpoint_dir)

    def restore(self, path):
        self._call(self.actor.restore, path)

    def stop(self):
        self._call(self.actor.stop)

    def get_exploit_state(self):
        return self._call(self.actor.get_exploit_state)

    def apply_exploit(self, state, scalars):
        self._call(self.actor.apply_exploit, state, scalars)


class ExperimentAnalysis:
    """reference ray/tune/analysis/experiment_analysis.py."""

    def __init__(self, trials: List[Trial],
                 metric: str = "episode_reward_mean",
                 mode: str = "max"):
        self.trials = trials
        self.default_metric = metric
        self.default_mode = mode

    def get_best_trial(
        self, metric: Optional[str] = None, mode: Optional[str] = None
    ) -> Optional[Trial]:
        metric = metric or self.default_metric
        mode = mode or self.default_mode
        best, best_v = None, None
        for t in self.trials:
            v = t.last_result.get(metric)
            if v is None:
                continue
            if (
                best_v is None
                or (mode == "max" and v > best_v)
                or (mode == "min" and v < best_v)
            ):
                best, best_v = t, v
        return best

    @property
    def best_config(self) -> Optional[Dict]:
        t = self.get_best_trial()
        return t.config if t else None

    @property
    def results(self) -> Dict[str, Dict]:
        return {t.trial_id: t.last_result for t in self.trials}

    def dataframe(self) -> List[Dict]:
        return [
            {"trial_id": t.trial_id, **t.last_result}
            for t in self.trials
        ]


class TrialRunner:
    """reference tune/execution/trial_runner.py:226."""

    def __init__(
        self,
        trainable_cls,
        trials: List[Trial],
        scheduler: Optional[TrialScheduler] = None,
        max_iterations: int = 100,
        checkpoint_freq: int = 0,
        local_dir: Optional[str] = None,
        callbacks: Optional[List] = None,
        parallel: bool = False,
        max_concurrent: Optional[int] = None,
        experiment_dir: Optional[str] = None,
        resume: bool = False,
        search_alg=None,
        num_samples: int = 1,
        trial_name: str = "trial",
        stopping_criterion: Optional[Dict] = None,
        base_config: Optional[Dict] = None,
        sync_config=None,
        mesh_slots: Optional[List] = None,
    ):
        self.trainable_cls = trainable_cls
        self.trials = trials
        self.scheduler = scheduler or FIFOScheduler()
        self.max_iterations = max_iterations
        self.checkpoint_freq = checkpoint_freq
        self.local_dir = local_dir
        self.callbacks = callbacks or []
        self.parallel = parallel
        self.max_concurrent = max_concurrent or (os.cpu_count() or 4)
        self._in_flight: Dict = {}  # train ref -> trial
        self._parallel_proven = False  # any actor created successfully
        self.experiment_dir = experiment_dir
        # ask/tell suggestion mode (reference SearchGenerator wrapping
        # a Searcher): trials are created lazily from search_alg up to
        # num_samples, and results are told back
        self.search_alg = search_alg
        self.search_num_samples = num_samples
        self._search_stop = dict(stopping_criterion or {})
        self._search_name = trial_name
        self._search_base = dict(base_config or {})
        self._search_exhausted = False
        self.sync_config = sync_config
        # disjoint per-trial submeshes (mesh-sharded concurrent mode)
        self.mesh_slots = mesh_slots
        self._trial_slot: Dict = {}
        if resume:
            self._maybe_sync_down()
            self._restore_experiment_state()

    def _maybe_sync_down(self) -> None:
        """Pull the mirrored experiment dir before resuming when the
        local one is missing (head died; the upload_dir survived —
        reference tune/syncer.py restore path)."""
        sc = self.sync_config
        if (
            sc is None
            or sc.syncer is None
            or not self.experiment_dir
        ):
            return
        if not os.path.exists(
            os.path.join(self.experiment_dir, "experiment_state.pkl")
        ):
            remote = self._remote_dir(sc)
            # the SYNCER owns remote-path semantics (an object-store
            # backend answers for s3:// URIs; never os.path them here)
            if sc.syncer.exists(remote):
                sc.syncer.sync_down(remote, self.experiment_dir)

    def _remote_dir(self, sc) -> str:
        return os.path.join(
            sc.upload_dir, os.path.basename(self.experiment_dir)
        )

    def _maybe_sync_up(self) -> None:
        sc = self.sync_config
        if (
            sc is None
            or sc.syncer is None
            or not self.experiment_dir
            or not os.path.exists(self.experiment_dir)
        ):
            return
        import time as _time

        # the final save (all trials terminal) always syncs, or a
        # throttled last write would leave the mirror stale
        force = all(
            t.status in (TERMINATED, ERROR) for t in self.trials
        )
        now = _time.monotonic()
        last = getattr(self, "_last_sync_up", 0.0)
        if not force and now - last < sc.sync_period_s:
            return  # throttle (SyncConfig.sync_period_s)
        self._last_sync_up = now
        sc.syncer.sync_up(self.experiment_dir, self._remote_dir(sc))

    def _maybe_ask_searcher(self) -> None:
        if self.search_alg is None:
            return
        # only ask for as many live trials as can actually run: TPE-
        # style searchers model completed results, so over-asking up
        # front would degrade them to random search
        cap = self.max_concurrent if self.parallel else 1
        while len(self.trials) < self.search_num_samples and (
            sum(
                1
                for t in self.trials
                if t.status not in (TERMINATED, ERROR)
            )
            < cap
        ):
            trial_id = (
                f"{self._search_name}_{len(self.trials):05d}"
            )
            config = self.search_alg.suggest(trial_id)
            if config is None:
                # searcher exhausted before num_samples: record it so
                # is_finished() doesn't wait for trials that will
                # never exist
                self._search_exhausted = True
                break
            # constants from tune.run(config=...) merge under the
            # suggested keys (real-Tune semantics: config is both the
            # space template and the shared base)
            merged = {**self._search_base, **config}
            self.trials.append(
                Trial(
                    self._search_name,
                    merged,
                    stopping_criterion=self._search_stop,
                    trial_id=trial_id,
                )
            )

    # -- experiment-state durability (driver-restart resume) ---------------
    #
    # The reference checkpoints TrialRunner state to
    # experiment_state-*.json in the experiment dir
    # (tune/execution/trial_runner.py checkpoint()/resume()); a killed
    # driver resumes with tune.run(..., resume=True). Same protocol
    # here: per-trial status/last_result/checkpoint_path snapshots,
    # written atomically after every processed result.

    @property
    def _state_path(self) -> Optional[str]:
        if not self.experiment_dir:
            return None
        return os.path.join(self.experiment_dir, "experiment_state.pkl")

    def _save_experiment_state(self) -> None:
        path = self._state_path
        if not path:
            return
        os.makedirs(self.experiment_dir, exist_ok=True)
        state = {
            t.trial_id: {
                "status": t.status
                if t.status in (TERMINATED, ERROR)
                else PENDING,
                "config": t.config,
                "last_result": t.last_result,
                "checkpoint_path": t.checkpoint_path,
                "error": t.error,
            }
            for t in self.trials
        }
        from ray_tpu.util.atomic_io import atomic_write

        # atomic + fsync'd: a crash never corrupts (or un-publishes)
        # the experiment state a resume depends on
        atomic_write(path, lambda f: pickle.dump(state, f))
        self._maybe_sync_up()

    def _restore_experiment_state(self) -> None:
        path = self._state_path
        if not path or not os.path.exists(path):
            return
        with open(path, "rb") as f:
            saved = pickle.load(f)
        for trial in self.trials:
            s = saved.get(trial.trial_id)
            if s is None:
                continue
            trial.last_result = s["last_result"]
            trial.checkpoint_path = s["checkpoint_path"]
            trial.error = s["error"]
            trial.status = s["status"]
            # PENDING trials with a checkpoint restart from it (the
            # restore happens when their runner starts)

    def is_finished(self) -> bool:
        if (
            self.search_alg is not None
            and not self._search_exhausted
            and len(self.trials) < self.search_num_samples
        ):
            return False
        return all(
            t.status in (TERMINATED, ERROR) for t in self.trials
        )

    # -- shared result handling -------------------------------------------

    def _trial_checkpoint_dir(self, trial: Trial) -> Optional[str]:
        """Checkpoints land under the experiment dir when one exists,
        so experiment-state persistence and the syncer cover them
        (reference: trial logdirs inside the experiment dir)."""
        if not self.experiment_dir:
            return None
        return os.path.join(
            self.experiment_dir,
            trial.trial_id,
            f"checkpoint_{trial.last_result.get('training_iteration', 0):06d}",
        )

    def _process_result(self, trial: Trial, result: Dict) -> bool:
        """Record + schedule one result. Returns True if the trial
        should continue training."""
        trial.last_result = result
        trial.results.append(result)
        if self.search_alg is not None:
            self.search_alg.on_trial_result(trial.trial_id, result)
        for cb in self.callbacks:
            cb(trial, result)
        if self.checkpoint_freq and (
            result["training_iteration"] % self.checkpoint_freq == 0
        ):
            trial.checkpoint_path = trial.runner.save(
                self._trial_checkpoint_dir(trial)
            )
        decision = self.scheduler.on_trial_result(self, trial, result)
        if (
            decision == STOP
            or trial.should_stop(result)
            or result["training_iteration"] >= self.max_iterations
        ):
            trial.status = TERMINATED
            if self.search_alg is not None:
                self.search_alg.on_trial_complete(
                    trial.trial_id, result
                )
            self.scheduler.on_trial_complete(self, trial, result)
            if self.checkpoint_freq:
                trial.checkpoint_path = trial.runner.save(
                self._trial_checkpoint_dir(trial)
            )
            self._cleanup_trial(trial)
            self._save_experiment_state()
            return False
        self._save_experiment_state()
        return True

    def _fail_trial(self, trial: Trial, err: str) -> None:
        trial.status = ERROR
        trial.error = err
        if self.search_alg is not None:
            self.search_alg.on_trial_complete(
                trial.trial_id, error=True
            )
        # schedulers must learn about errored trials too — a
        # synchronous rung (HyperBand) would otherwise wait on the
        # dead trial's report forever
        self.scheduler.on_trial_complete(
            self, trial, trial.last_result or {}
        )
        self._cleanup_trial(trial)
        self._save_experiment_state()

    def step(self) -> None:
        self._maybe_ask_searcher()
        if self.parallel:
            self._step_parallel()
        elif self.mesh_slots:
            self._step_mesh_concurrent()
        else:
            self._step_sequential()

    # -- sequential in-process mode ----------------------------------------

    def _step_sequential(self) -> None:
        """Advance every live trial by one training iteration
        (reference trial_runner.py:793)."""
        for trial in self.trials:
            if trial.status in (TERMINATED, ERROR):
                continue
            if trial.runner is None:
                try:
                    trial.runner = self.trainable_cls(
                        config=trial.config
                    )
                    if trial.checkpoint_path:  # driver-restart resume
                        trial.runner.restore(trial.checkpoint_path)
                    trial.status = RUNNING
                except Exception:
                    self._fail_trial(trial, traceback.format_exc())
                    continue
            try:
                result = trial.runner.train()
            except Exception:
                self._fail_trial(trial, traceback.format_exc())
                continue
            self._process_result(trial, result)

    # -- mesh-sharded concurrent mode ---------------------------------------

    def _step_mesh_concurrent(self) -> None:
        """Advance live trials ONE iteration each, concurrently on
        threads, every trial jitted onto its own disjoint submesh
        (``config["_mesh"]``). Device compute overlaps across slots;
        a PBT population of S slot-sized trials costs ~1x wall clock
        instead of S x (the round-2/3 time-slicing)."""
        from concurrent.futures import ThreadPoolExecutor

        n_slots = len(self.mesh_slots)
        # assign free slots to pending trials
        used = {
            s
            for t, s in self._trial_slot.items()
            if t.status == RUNNING
        }
        for trial in self.trials:
            if trial.status != PENDING:
                continue
            free = next(
                (s for s in range(n_slots) if s not in used), None
            )
            if free is None:
                break
            try:
                cfg = dict(trial.config)
                cfg["_mesh"] = self.mesh_slots[free]
                trial.runner = self.trainable_cls(config=cfg)
                if trial.checkpoint_path:
                    trial.runner.restore(trial.checkpoint_path)
                trial.status = RUNNING
                self._trial_slot[trial] = free
                used.add(free)
            except Exception:
                self._fail_trial(trial, traceback.format_exc())
        live = [t for t in self.trials if t.status == RUNNING]
        if not live:
            return
        with ThreadPoolExecutor(max_workers=len(live)) as ex:
            futures = [
                (t, ex.submit(t.runner.train)) for t in live
            ]
            # collect EVERY result before processing any: schedulers
            # (PBT exploit) read other trials' runner state, which must
            # not race a train() still executing on a pool thread
            outcomes = []
            for trial, fut in futures:
                try:
                    outcomes.append((trial, fut.result(), None))
                except Exception:
                    outcomes.append(
                        (trial, None, traceback.format_exc())
                    )
        for trial, result, err in outcomes:
            if err is not None:
                self._fail_trial(trial, err)
                self._trial_slot.pop(trial, None)
                continue
            self._process_result(trial, result)
            if trial.status in (TERMINATED, ERROR):
                self._trial_slot.pop(trial, None)

    # -- parallel actor mode -------------------------------------------------

    def _start_trial_actor(self, trial: Trial) -> None:
        try:
            actor = _TrialActor.options(daemon=False).remote(
                self.trainable_cls, trial.config
            )
        except Exception:
            # Typically an unpicklable trainable/config. Before any
            # actor has proven viable, degrade gracefully to the
            # in-process mode rather than failing the experiment.
            if not self._parallel_proven:
                import warnings

                warnings.warn(
                    "trial actor creation failed "
                    f"({traceback.format_exc(limit=1).strip()}); "
                    "falling back to in-process sequential trials — "
                    "pass parallel=False to silence this"
                )
                self.parallel = False
            else:
                self._fail_trial(trial, traceback.format_exc())
            return
        self._parallel_proven = True
        trial.runner = _RemoteTrainableProxy(actor)
        if trial.checkpoint_path:  # driver-restart resume
            try:
                trial.runner.restore(trial.checkpoint_path)
            except Exception:
                self._fail_trial(trial, traceback.format_exc())
                return
        trial.status = RUNNING
        self._in_flight[actor.train.remote()] = trial

    def _step_parallel(self) -> None:
        """Event-driven execution over trial actors (reference
        ray_trial_executor.py event loop): keep up to max_concurrent
        trials running, process results as they complete."""
        live = set(self._in_flight.values())
        for trial in self.trials:
            if len(live) >= self.max_concurrent or not self.parallel:
                break
            if trial.status == PENDING and trial not in live:
                self._start_trial_actor(trial)
                if trial.status == RUNNING:
                    live.add(trial)
        if not self._in_flight:
            return
        ready, _ = ray.wait(
            list(self._in_flight.keys()), num_returns=1, timeout=10.0
        )
        for ref in ready:
            trial = self._in_flight.pop(ref)
            try:
                result = ray.get(ref)
            except Exception:
                self._fail_trial(trial, traceback.format_exc())
                continue
            finally:
                ray.free([ref])
            if self._process_result(trial, result):
                self._in_flight[
                    trial.runner.actor.train.remote()
                ] = trial

    def cleanup(self) -> None:
        """Stop any still-live trials (crash/interrupt path)."""
        for ref, trial in list(self._in_flight.items()):
            ray.free([ref])
        self._in_flight.clear()
        for trial in self.trials:
            if trial.runner is not None:
                self._cleanup_trial(trial)

    def _cleanup_trial(self, trial: Trial) -> None:
        if trial.runner is not None:
            try:
                trial.runner.stop()
            except Exception:
                pass
            if isinstance(trial.runner, _RemoteTrainableProxy):
                try:
                    ray.kill(trial.runner.actor)
                except Exception:
                    pass
            trial.runner = None


def run(
    run_or_experiment: Union[str, Type],
    *,
    config: Optional[Dict] = None,
    stop: Optional[Dict] = None,
    num_samples: int = 1,
    scheduler: Optional[TrialScheduler] = None,
    checkpoint_freq: int = 0,
    local_dir: Optional[str] = None,
    metric: str = "episode_reward_mean",
    mode: str = "max",
    max_iterations: int = 100,
    callbacks: Optional[List] = None,
    verbose: int = 1,
    seed: int = 0,
    parallel: Optional[bool] = None,
    max_concurrent_trials: Optional[int] = None,
    name: Optional[str] = None,
    resume: bool = False,
    search_alg=None,
    resources_per_trial: Optional[Dict] = None,
    sync_config=None,
    raise_on_failed_trial: bool = True,
) -> ExperimentAnalysis:
    """reference tune/tune.py:118.

    parallel: None (default) runs multi-trial experiments as concurrent
    actors and single-trial experiments in-process (where they own the
    TPU mesh). Force with True/False.

    resources_per_trial: {"TPU": n} (n > 0) declares accelerator
    trials: they run IN-PROCESS, time-slicing the driver's mesh
    across the population — each trainable jits onto the real TPU
    devices (a single chip/tunnel cannot be claimed by concurrent
    trial processes, so time-slicing is the single-host analog of the
    reference's GPU allocation via placement groups,
    tune/execution/ray_trial_executor.py). CPU-only trials keep the
    concurrent-actor path.

    resume: reattach to a previous run of the same experiment
    (``local_dir``/``name``): trials that finished stay finished,
    interrupted trials restart from their latest checkpoint (requires
    ``checkpoint_freq``; reference trial_runner.py resume()). Trial
    identity is positional — the deterministic variant generator must
    see the same config/num_samples/seed.
    """
    if isinstance(run_or_experiment, str):
        from ray_tpu.algorithms.registry import get_algorithm_class

        trainable_cls = get_algorithm_class(run_or_experiment)
        exp_name = name or run_or_experiment
    elif isinstance(run_or_experiment, type) and issubclass(
        run_or_experiment, Trainable
    ):
        trainable_cls = run_or_experiment
        exp_name = name or trainable_cls.__name__
    elif callable(run_or_experiment):
        # plain function trainable: tune.run(train_fn) + tune.report
        # (reference function_trainable.wrap_function)
        from ray_tpu.tune.function_trainable import wrap_function

        trainable_cls = wrap_function(run_or_experiment)
        exp_name = name or trainable_cls.__name__
    else:
        trainable_cls = run_or_experiment
        exp_name = name or trainable_cls.__name__

    if resume and not local_dir:
        raise ValueError(
            "tune.run(resume=True) needs local_dir: experiment state "
            "lives in <local_dir>/<name>/experiment_state.pkl"
        )
    stop = dict(stop or {})
    max_iters = int(stop.pop("training_iteration", max_iterations))
    if search_alg is not None:
        # suggestion mode: trials are created lazily from the searcher
        # (reference SearchGenerator); config is its space template
        trials = []
        parallel = bool(parallel) if parallel is not None else (
            num_samples > 1
        )
    else:
        gen = BasicVariantGenerator(config or {}, num_samples, seed)
        trials = [
            Trial(
                exp_name,
                v,
                stopping_criterion=stop,
                # stable across driver restarts so resume can match
                # trials to their saved state
                trial_id=f"{exp_name}_{i:05d}",
            )
            for i, v in enumerate(iter(gen.next_variant, None))
        ]
        if parallel is None:
            parallel = len(trials) > 1
    mesh_slots = None
    if resources_per_trial and resources_per_trial.get("TPU", 0) > 0:
        # Accelerator trials run in-process (concurrent actor
        # PROCESSES cannot share the chip claim), but they need not
        # time-slice: with enough devices the mesh partitions into
        # disjoint per-trial submeshes and trials run CONCURRENTLY on
        # threads — each jits onto its own devices, host python
        # interleaves, device compute overlaps (the reference's
        # fractional-GPU trial packing, ray_trial_executor.py resource
        # allocation, the TPU way). One device (or one slot's worth)
        # falls back to sequential time-slicing.
        parallel = False
        import jax

        per = int(resources_per_trial["TPU"])
        devs = jax.devices()
        slots = len(devs) // per if per >= 1 else 0
        # fractional requests (TPU: 0.5) keep the time-slicing path:
        # a submesh needs at least one whole device
        if per >= 1 and slots >= 2 and len(trials or []) != 1:
            from ray_tpu.parallel.mesh import make_mesh

            mesh_slots = [
                make_mesh(devices=devs[i * per : (i + 1) * per])
                for i in range(slots)
            ]
    experiment_dir = (
        os.path.join(local_dir, exp_name) if local_dir else None
    )
    runner = TrialRunner(
        trainable_cls,
        trials,
        scheduler=scheduler,
        max_iterations=max_iters,
        checkpoint_freq=checkpoint_freq,
        local_dir=local_dir,
        callbacks=callbacks,
        parallel=parallel,
        max_concurrent=max_concurrent_trials,
        experiment_dir=experiment_dir,
        resume=resume,
        search_alg=search_alg,
        num_samples=num_samples,
        trial_name=exp_name,
        stopping_criterion=stop,
        # constants shared by every suggested trial; Domain entries are
        # excluded (in suggestion mode the searcher owns the space)
        base_config={
            k: v
            for k, v in (config or {}).items()
            if not isinstance(v, SearchDomain)
        },
        sync_config=sync_config,
        mesh_slots=mesh_slots,
    )
    try:
        while not runner.is_finished():
            runner.step()
            if verbose:
                live = sum(1 for t in trials if t.status == RUNNING)
                best = ExperimentAnalysis(
                    trials, metric, mode
                ).get_best_trial()
                if best is not None:
                    print(
                        f"[tune] live={live} "
                        f"best[{metric}]="
                        f"{best.last_result.get(metric)}"
                    )
    finally:
        # Crash/interrupt path: without this, live non-daemon trial
        # actors (whole Trainables) outlive the experiment.
        runner.cleanup()
    errored = [t for t in trials if t.status == ERROR]
    if errored and raise_on_failed_trial:
        raise RuntimeError(
            f"{len(errored)} trial(s) errored; first: "
            f"{errored[0].error}"
        )
    return ExperimentAnalysis(trials, metric, mode)
