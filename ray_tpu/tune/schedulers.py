"""Trial schedulers: FIFO, ASHA, synchronous HyperBand, median
stopping, PBT.

Counterpart of the reference's ``ray/tune/schedulers/``
(``async_hyperband.py`` AsyncHyperBandScheduler, ``hyperband.py``
HyperBandScheduler, ``median_stopping_rule.py`` MedianStoppingRule,
``pbt.py`` PopulationBasedTraining).
"""

from __future__ import annotations

import copy
import random
from typing import Dict, List, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"
PAUSE = "PAUSE"


class TrialScheduler:
    def on_trial_result(self, runner, trial, result: Dict) -> str:
        return CONTINUE

    def on_trial_complete(self, runner, trial, result: Dict) -> None:
        pass


class FIFOScheduler(TrialScheduler):
    pass


class _Bracket:
    """One ASHA bracket: rungs at min_t * reduction^k."""

    def __init__(self, min_t: int, max_t: int, reduction_factor: float):
        self.rf = reduction_factor
        self.rungs: List[Dict] = []
        t = min_t
        while t < max_t:
            self.rungs.append({"milestone": t, "recorded": {}})
            t = int(t * reduction_factor)
        self.rungs = self.rungs[::-1]  # highest milestone first

    def on_result(self, trial_id: str, cur_iter: int, metric: float) -> str:
        action = CONTINUE
        for rung in self.rungs:
            if (
                cur_iter >= rung["milestone"]
                and trial_id not in rung["recorded"]
            ):
                rung["recorded"][trial_id] = metric
                vals = list(rung["recorded"].values())
                if len(vals) >= 2:
                    import numpy as np

                    cutoff = np.percentile(
                        vals, (1 - 1 / self.rf) * 100
                    )
                    if metric < cutoff:
                        action = STOP
                break
        return action


class AsyncHyperBandScheduler(TrialScheduler):
    """ASHA (reference schedulers/async_hyperband.py)."""

    def __init__(
        self,
        metric: str = "episode_reward_mean",
        mode: str = "max",
        time_attr: str = "training_iteration",
        max_t: int = 100,
        grace_period: int = 1,
        reduction_factor: float = 4,
    ):
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.max_t = max_t
        self._bracket = _Bracket(grace_period, max_t, reduction_factor)

    def on_trial_result(self, runner, trial, result: Dict) -> str:
        cur = result.get(self.time_attr, 0)
        metric = result.get(self.metric)
        if metric is None:
            return CONTINUE
        if self.mode == "min":
            metric = -metric
        if cur >= self.max_t:
            return STOP
        return self._bracket.on_result(trial.trial_id, cur, metric)


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose best result so far is worse than the median
    of the other trials' running averages at the same point
    (reference schedulers/median_stopping_rule.py — the Vizier
    median stopping rule)."""

    def __init__(
        self,
        metric: str = "episode_reward_mean",
        mode: str = "max",
        time_attr: str = "training_iteration",
        grace_period: int = 1,
        min_samples_required: int = 3,
    ):
        # the reference also offers hard_stop=False (PAUSE instead of
        # STOP); this runner has no pause/resume, so below-median
        # trials always hard-stop — offering the flag would be a
        # silent no-op
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.grace_period = grace_period
        self.min_samples = min_samples_required
        # trial_id -> list of (t, metric) results seen
        self._history: Dict[str, List] = {}

    def _sign(self, v: float) -> float:
        return -v if self.mode == "min" else v

    def _running_avg(self, trial_id: str, t: float) -> Optional[float]:
        pts = [m for (ti, m) in self._history.get(trial_id, [])
               if ti <= t]
        return sum(pts) / len(pts) if pts else None

    def on_trial_result(self, runner, trial, result: Dict) -> str:
        metric = result.get(self.metric)
        t = result.get(self.time_attr, 0)
        if metric is None:
            return CONTINUE
        metric = self._sign(metric)
        self._history.setdefault(trial.trial_id, []).append((t, metric))
        if t < self.grace_period:
            return CONTINUE
        others = [
            self._running_avg(tid, t)
            for tid in self._history
            if tid != trial.trial_id
        ]
        others = [a for a in others if a is not None]
        if len(others) < self.min_samples:
            return CONTINUE
        others.sort()
        median = others[len(others) // 2]
        best = max(m for (_, m) in self._history[trial.trial_id])
        if best < median:
            return STOP
        return CONTINUE


class HyperBandScheduler(TrialScheduler):
    """Synchronous HyperBand (reference schedulers/hyperband.py):
    trials fill brackets of size s; at each rung every bracket member
    must report before the bottom 1-1/eta fraction is stopped
    together. Synchronous halving wastes less budget on stragglers
    than ASHA when result cadences are uniform (the reference keeps
    both for the same reason)."""

    def __init__(
        self,
        metric: str = "episode_reward_mean",
        mode: str = "max",
        time_attr: str = "training_iteration",
        max_t: int = 81,
        reduction_factor: float = 3,
    ):
        if reduction_factor <= 1:
            raise ValueError(
                f"reduction_factor must be > 1, got {reduction_factor}"
            )
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.max_t = max_t
        self.eta = reduction_factor
        # rung milestones: max_t / eta^k, ascending
        self.milestones: List[int] = []
        t = max_t
        while t >= 1:
            self.milestones.append(int(t))
            t = t / self.eta
        self.milestones = sorted(set(self.milestones))[:-1]
        # milestone -> {trial_id: metric}; a rung decides once every
        # trial that can still reach it has reported there
        self._rungs: Dict[int, Dict[str, float]] = {
            m: {} for m in self.milestones
        }
        self._decided: Dict[int, set] = {m: set() for m in self.milestones}
        self._stopped_at: Dict[str, int] = {}  # cut at which rung
        self._done: set = set()  # completed/errored on their own

    def _sign(self, v: float) -> float:
        return -v if self.mode == "min" else v

    def _eligible(self, runner, m: int) -> List[str]:
        """Trials a rung-m decision must wait for / rank: everyone
        except those cut at an earlier rung and those that finished
        without ever reaching m. Completed trials that DID report at
        m stay in the ranking — under sequential trial execution the
        bracket fills one trial at a time, and the reference's
        pause-at-rung semantics degrade to exactly this."""
        out = []
        for t in getattr(runner, "trials", []):
            tid = t.trial_id
            cut = self._stopped_at.get(tid)
            if cut is not None and cut < m:
                continue
            if tid in self._done and tid not in self._rungs[m]:
                continue
            out.append(tid)
        return out

    def on_trial_result(self, runner, trial, result: Dict) -> str:
        metric = result.get(self.metric)
        t = result.get(self.time_attr, 0)
        if metric is None:
            return CONTINUE
        if t >= self.max_t:
            return STOP
        metric = self._sign(metric)
        for m in self.milestones:
            if t >= m and trial.trial_id not in self._rungs[m]:
                self._rungs[m][trial.trial_id] = metric
        # synchronous cut: once a rung's full population reported,
        # stop the bottom 1-1/eta fraction together
        for m in self.milestones:
            rung = self._rungs[m]
            undecided = [
                tid
                for tid in self._eligible(runner, m)
                if tid not in self._decided[m]
            ]
            if undecided and all(tid in rung for tid in undecided):
                ranked = sorted(
                    undecided, key=lambda tid: rung[tid], reverse=True
                )
                keep = max(1, int(len(ranked) / self.eta))
                for tid in ranked[keep:]:
                    self._stopped_at.setdefault(tid, m)
                for tid in undecided:
                    self._decided[m].add(tid)
        return (
            STOP if trial.trial_id in self._stopped_at else CONTINUE
        )

    def on_trial_complete(self, runner, trial, result: Dict) -> None:
        self._done.add(trial.trial_id)


class PopulationBasedTraining(TrialScheduler):
    """PBT (reference schedulers/pbt.py): at each perturbation interval,
    bottom-quantile trials clone the weights + hyperparams of a
    top-quantile trial, with hyperparams resampled/perturbed."""

    def __init__(
        self,
        metric: str = "episode_reward_mean",
        mode: str = "max",
        time_attr: str = "training_iteration",
        perturbation_interval: int = 4,
        hyperparam_mutations: Optional[Dict] = None,
        quantile_fraction: float = 0.25,
        resample_probability: float = 0.25,
        seed: int = 0,
    ):
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.resample_probability = resample_probability
        self._rng = random.Random(seed)
        self._last_perturb: Dict[str, int] = {}
        self.num_perturbations = 0

    def _score(self, trial) -> float:
        v = trial.last_result.get(self.metric, float("-inf"))
        return -v if self.mode == "min" else v

    def on_trial_result(self, runner, trial, result: Dict) -> str:
        t = result.get(self.time_attr, 0)
        last = self._last_perturb.get(trial.trial_id, 0)
        if t - last < self.interval:
            return CONTINUE

        trials = [
            tr
            for tr in runner.trials
            if tr.last_result and tr.status != "ERROR"
        ]
        # Don't burn the interval while the population is still sparse
        # (actors starting asynchronously): wait until a comparison is
        # actually possible.
        if len(trials) < 2:
            return CONTINUE
        self._last_perturb[trial.trial_id] = t
        ranked = sorted(trials, key=self._score, reverse=True)
        n_q = max(1, int(len(ranked) * self.quantile))
        top, bottom = ranked[:n_q], ranked[-n_q:]
        if trial in bottom and trial not in top:
            donor = self._rng.choice(top)
            self._exploit_and_explore(trial, donor)
        return CONTINUE

    def _exploit_and_explore(self, trial, donor) -> None:
        # explore: perturb mutated hyperparams from the donor's config
        new_config = copy.deepcopy(donor.config)
        for key, spec in self.mutations.items():
            if self._rng.random() < self.resample_probability:
                if callable(spec):
                    new_config[key] = spec()
                elif isinstance(spec, list):
                    new_config[key] = self._rng.choice(spec)
            else:
                factor = self._rng.choice([0.8, 1.2])
                base = donor.config.get(key)
                if isinstance(base, (int, float)):
                    new_config[key] = type(base)(base * factor)
        trial.config = new_config
        # exploit: clone donor state + apply mutated scalars through
        # the Trainable exploit protocol (works identically for
        # in-process trainables and remote trial actors). The two steps
        # fail independently: a dead donor must not cancel the explore
        # push, or trial.config would silently diverge from the live
        # policy's actual hyperparameters.
        if trial.runner is not None:
            state = None
            if donor.runner is not None:
                try:
                    state = donor.runner.get_exploit_state()
                except Exception:
                    state = None
            scalars = {
                k: v
                for k, v in new_config.items()
                if not isinstance(v, dict)
            }
            try:
                trial.runner.apply_exploit(state, scalars)
            except Exception:
                pass
        self.num_perturbations += 1
