"""Pluggable trial searchers (ask/tell) + a pure-python TPE fallback.

Counterpart of the reference's ``tune/suggest/suggestion.py`` (Searcher
ABC: ``suggest``/``on_trial_complete``) and its external integrations
(``tune/suggest/optuna.py``, ``hyperopt.py``, ``bohb.py``). The seam is
the same ask/tell contract; external libraries plug in behind
:class:`ExternalSearcher` when importable, and :class:`TPELiteSearcher`
is the in-repo model-based fallback so suggestion-driven tuning works
with zero extra dependencies.

TPE-lite: the Tree-structured Parzen Estimator recipe (Bergstra et al.,
NeurIPS 2011 — the algorithm behind hyperopt/optuna's default sampler):
after ``n_startup`` random trials, split observations at the gamma
quantile into good/bad sets, model each set with a kernel density per
parameter (Gaussian over continuous/int domains, smoothed categorical
over choices), sample candidates from the good model, and suggest the
candidate maximizing the density ratio l(x)/g(x).
"""

from __future__ import annotations

import copy
import math
import random
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.tune.search import (
    Choice,
    Domain,
    LogUniform,
    Randint,
    Uniform,
)


class Searcher:
    """reference tune/suggest/suggestion.py Searcher."""

    def __init__(self, metric: str = "episode_reward_mean",
                 mode: str = "max"):
        self.metric = metric
        self.mode = mode

    def suggest(self, trial_id: str) -> Optional[Dict]:
        """→ a concrete config for a new trial (None = exhausted)."""
        raise NotImplementedError

    def on_trial_result(self, trial_id: str, result: Dict) -> None:
        pass

    def on_trial_complete(
        self,
        trial_id: str,
        result: Optional[Dict] = None,
        error: bool = False,
    ) -> None:
        pass


def _flatten_space(config: Dict, prefix=()) -> List[Tuple[tuple, Domain]]:
    out = []
    for k, v in config.items():
        if isinstance(v, Domain):
            out.append((prefix + (k,), v))
        elif isinstance(v, dict) and "grid_search" not in v:
            out.extend(_flatten_space(v, prefix + (k,)))
    return out


def _set_path(d: Dict, path, value):
    for k in path[:-1]:
        d = d[k]
    d[path[-1]] = value


class TPELiteSearcher(Searcher):
    def __init__(
        self,
        space: Dict,
        metric: str = "episode_reward_mean",
        mode: str = "max",
        n_startup: int = 8,
        gamma: float = 0.25,
        n_candidates: int = 24,
        explore_prob: float = 0.2,
        seed: int = 0,
    ):
        super().__init__(metric, mode)
        # ε-greedy prior draws keep exploring after the good-set KDE
        # tightens (the role hyperopt's prior-weighted mixture plays:
        # without it the searcher freezes on the best startup point)
        self.explore_prob = explore_prob
        self._template = copy.deepcopy(space)
        self._space = _flatten_space(self._template)
        self._rng = random.Random(seed)
        self.n_startup = n_startup
        self.gamma = gamma
        self.n_candidates = n_candidates
        self._suggested: Dict[str, Dict[tuple, Any]] = {}
        self._observed: List[Tuple[Dict[tuple, Any], float]] = []

    # -- domain helpers ---------------------------------------------------

    def _rand(self, dom: Domain) -> Any:
        return dom.sample(self._rng)

    def _numeric_repr(self, dom, v) -> Optional[float]:
        if isinstance(dom, LogUniform):
            return math.log(v)
        if isinstance(dom, (Uniform, Randint)):
            return float(v)
        return None  # categorical

    def _from_numeric(self, dom, x: float):
        if isinstance(dom, LogUniform):
            lo, hi = dom.log_low, dom.log_high
            return math.exp(min(max(x, lo), hi))
        if isinstance(dom, Randint):
            return int(round(min(max(x, dom.low), dom.high - 1)))
        return min(max(x, dom.low), dom.high)

    def _kde_sample(self, dom, values: List[Any]):
        """Draw from the per-parameter density of one observation set."""
        if isinstance(dom, Choice):
            # smoothed categorical (counts + 1)
            cats = dom.categories
            weights = [1.0] * len(cats)
            for v in values:
                weights[cats.index(v)] += 1.0
            return self._rng.choices(cats, weights=weights)[0]
        xs = [self._numeric_repr(dom, v) for v in values]
        mu = self._rng.choice(xs)
        spread = max(xs) - min(xs) if len(xs) > 1 else 0.0
        bw = max(spread / 2.0, self._range(dom) / 10.0)
        return self._from_numeric(dom, self._rng.gauss(mu, bw))

    def _kde_logpdf(self, dom, values: List[Any], v) -> float:
        if isinstance(dom, Choice):
            cats = dom.categories
            weights = [1.0] * len(cats)
            for obs in values:
                weights[cats.index(obs)] += 1.0
            total = sum(weights)
            return math.log(weights[cats.index(v)] / total)
        xs = [self._numeric_repr(dom, obs) for obs in values]
        x = self._numeric_repr(dom, v)
        spread = max(xs) - min(xs) if len(xs) > 1 else 0.0
        bw = max(spread / 2.0, self._range(dom) / 10.0)
        acc = 0.0
        for mu in xs:
            acc += math.exp(-0.5 * ((x - mu) / bw) ** 2)
        return math.log(max(acc / (len(xs) * bw), 1e-300))

    @staticmethod
    def _range(dom) -> float:
        if isinstance(dom, LogUniform):
            return dom.log_high - dom.log_low
        return float(dom.high - dom.low)

    # -- ask / tell -------------------------------------------------------

    def suggest(self, trial_id: str) -> Optional[Dict]:
        if (
            len(self._observed) < self.n_startup
            or len(self._space) == 0
            or self._rng.random() < self.explore_prob
        ):
            values = {p: self._rand(d) for p, d in self._space}
        else:
            ranked = sorted(
                self._observed,
                key=lambda ov: ov[1],
                reverse=(self.mode == "max"),
            )
            n_good = max(1, int(self.gamma * len(ranked)))
            good = [v for v, _ in ranked[:n_good]]
            bad = [v for v, _ in ranked[n_good:]] or good
            best_score, values = -math.inf, None
            for _ in range(self.n_candidates):
                cand = {
                    p: self._kde_sample(d, [g[p] for g in good])
                    for p, d in self._space
                }
                score = sum(
                    self._kde_logpdf(d, [g[p] for g in good], cand[p])
                    - self._kde_logpdf(d, [b[p] for b in bad], cand[p])
                    for p, d in self._space
                )
                if score > best_score:
                    best_score, values = score, cand
        self._suggested[trial_id] = values
        config = copy.deepcopy(self._template)
        for path, _ in self._space:
            _set_path(config, path, values[path])
        return config

    def on_trial_complete(self, trial_id, result=None, error=False):
        values = self._suggested.pop(trial_id, None)
        if values is None or error or not result:
            return
        metric = result.get(self.metric)
        if metric is None:
            return
        self._observed.append((values, float(metric)))


class ExternalSearcher(Searcher):
    """Adapter seam for ask/tell suggestion libraries (the
    tune/suggest/optuna.py role). Wraps any object with
    ``ask() -> (trial_key, config)`` and
    ``tell(trial_key, value)``; import failures raise here — callers
    fall back to :class:`TPELiteSearcher`."""

    def __init__(self, backend, metric="episode_reward_mean", mode="max"):
        super().__init__(metric, mode)
        self._backend = backend
        self._keys: Dict[str, Any] = {}

    def suggest(self, trial_id):
        out = self._backend.ask()
        if out is None:
            return None
        key, config = out
        self._keys[trial_id] = key
        return config

    def on_trial_complete(self, trial_id, result=None, error=False):
        key = self._keys.pop(trial_id, None)
        if key is None:
            return
        value = (result or {}).get(self.metric)
        if error or value is None:
            # the backend must learn the trial FAILED, or ask/tell
            # libraries (optuna) leave it RUNNING forever and their
            # samplers never leave the startup phase
            fail = getattr(self._backend, "tell_failure", None)
            if fail is not None:
                fail(key)
            return
        self._backend.tell(key, float(value))


def create_searcher(
    name: str,
    space: Dict,
    metric: str = "episode_reward_mean",
    mode: str = "max",
    **kwargs,
) -> Searcher:
    """reference tune/suggest/__init__.py create_searcher."""
    name = name.lower()
    if name in ("tpe", "tpe-lite", "tpelite"):
        return TPELiteSearcher(space, metric, mode, **kwargs)
    if name == "optuna":  # external integration when available
        try:
            import optuna  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "optuna is not installed; use create_searcher('tpe', "
                "...) for the in-repo TPE fallback"
            ) from e
        from ray_tpu.tune.suggest_optuna import OptunaBackend

        return ExternalSearcher(
            OptunaBackend(space, metric, mode), metric, mode
        )
    raise ValueError(f"unknown searcher {name!r}")
