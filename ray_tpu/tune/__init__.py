from ray_tpu.tune.trainable import Trainable
from ray_tpu.tune.trial import Trial
from ray_tpu.tune.tune import TrialRunner
from ray_tpu.tune.tune import run, ExperimentAnalysis
from ray_tpu.tune.schedulers import (
    FIFOScheduler,
    AsyncHyperBandScheduler,
    HyperBandScheduler,
    MedianStoppingRule,
    PopulationBasedTraining,
)
from ray_tpu.tune.function_trainable import (
    get_checkpoint,
    report,
    with_parameters,
)
from ray_tpu.tune.search import (
    grid_search,
    uniform,
    loguniform,
    choice,
    randint,
    sample_from,
)

__all__ = [
    "Trainable",
    "Trial",
    "TrialRunner",
    "run",
    "ExperimentAnalysis",
    "FIFOScheduler",
    "AsyncHyperBandScheduler",
    "HyperBandScheduler",
    "MedianStoppingRule",
    "PopulationBasedTraining",
    "grid_search",
    "uniform",
    "loguniform",
    "choice",
    "randint",
    "sample_from",
    "report",
    "get_checkpoint",
    "with_parameters",
]
