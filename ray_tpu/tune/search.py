"""Search-space DSL + variant generation.

Counterpart of the reference's ``ray/tune/sample.py`` (grid_search,
uniform/choice/... distributions) and ``ray/tune/suggest/variant_generator.py``
(resolving a config dict into concrete trial variants).
"""

from __future__ import annotations

import itertools
import random
from typing import Any, Callable, Dict, List


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Uniform(Domain):
    def __init__(self, low, high):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


class LogUniform(Domain):
    def __init__(self, low, high):
        import math

        self.log_low, self.log_high = math.log(low), math.log(high)

    def sample(self, rng):
        import math

        return math.exp(rng.uniform(self.log_low, self.log_high))


class Randint(Domain):
    def __init__(self, low, high):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


class Choice(Domain):
    def __init__(self, categories):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


class Function(Domain):
    def __init__(self, fn: Callable):
        self.fn = fn

    def sample(self, rng):
        return self.fn(None)


def uniform(low, high) -> Uniform:
    return Uniform(low, high)


def loguniform(low, high) -> LogUniform:
    return LogUniform(low, high)


def randint(low, high) -> Randint:
    return Randint(low, high)


def choice(categories) -> Choice:
    return Choice(categories)


def sample_from(fn) -> Function:
    return Function(fn)


def grid_search(values: List) -> Dict:
    """reference tune/sample.py grid_search marker."""
    return {"grid_search": list(values)}


def _find_grid_axes(config: Dict, prefix=()) -> List:
    axes = []
    for k, v in config.items():
        if isinstance(v, dict) and "grid_search" in v:
            axes.append((prefix + (k,), v["grid_search"]))
        elif isinstance(v, dict):
            axes.extend(_find_grid_axes(v, prefix + (k,)))
    return axes


def _set_path(d: Dict, path, value):
    for k in path[:-1]:
        d = d[k]
    d[path[-1]] = value


def _resolve_domains(config: Dict, rng: random.Random):
    for k, v in config.items():
        if isinstance(v, Domain):
            config[k] = v.sample(rng)
        elif isinstance(v, dict) and "grid_search" not in v:
            _resolve_domains(v, rng)


def generate_variants(
    config: Dict, num_samples: int = 1, seed: int = 0
) -> List[Dict]:
    """Expand grid_search axes × num_samples random resolutions
    (reference variant_generator.generate_variants)."""
    import copy

    rng = random.Random(seed)
    axes = _find_grid_axes(config)
    grid_values = (
        itertools.product(*[vals for _, vals in axes])
        if axes
        else [()]
    )
    variants = []
    for combo in grid_values:
        for _ in range(num_samples):
            c = copy.deepcopy(config)
            for (path, _), val in zip(axes, combo):
                _set_path(c, path, val)
            _resolve_domains(c, rng)
            variants.append(c)
    return variants


class BasicVariantGenerator:
    """reference tune/suggest/basic_variant.py."""

    def __init__(self, config: Dict, num_samples: int = 1, seed: int = 0):
        self._variants = generate_variants(config, num_samples, seed)
        self._i = 0

    def next_variant(self):
        if self._i >= len(self._variants):
            return None
        v = self._variants[self._i]
        self._i += 1
        return v

    def __len__(self):
        return len(self._variants)
