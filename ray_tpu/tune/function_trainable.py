"""Function trainables: ``tune.run(train_fn)`` with ``tune.report``.

Counterpart of the reference's ``tune/trainable/function_trainable.py``
(FunctionTrainable + the ``tune.report``/``session.report`` seam): the
user function runs on a background thread inside the Trainable; each
``tune.report(**metrics)`` hands one result to ``step()`` and BLOCKS
until the runner consumes it, so the function is paced by the trial
loop exactly like a class trainable. ``tune.with_parameters`` binds
large objects into the function ahead of time.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Dict, Optional

from ray_tpu.tune.trainable import Trainable

_session = threading.local()


def report(_metrics: Optional[Dict] = None, **kwargs) -> None:
    """Inside a function trainable: deliver one result row to the
    trial loop (reference ``tune.report`` / ``session.report``)."""
    sess = getattr(_session, "current", None)
    if sess is None:
        raise RuntimeError(
            "tune.report() called outside a tune function trainable"
        )
    metrics = dict(_metrics or {})
    metrics.update(kwargs)
    sess.deliver(metrics)


def get_checkpoint():
    """Restored checkpoint dict for this trial, if any (reference
    session.get_checkpoint for function trainables)."""
    sess = getattr(_session, "current", None)
    return sess.restored if sess is not None else None


class _FnSession:
    def __init__(self, restored=None):
        # maxsize 1: report() blocks until step() consumes — the
        # function cannot run ahead of the trial loop
        self.results: "queue.Queue" = queue.Queue(maxsize=1)
        self.restored = restored

    def deliver(self, metrics: Dict) -> None:
        self.results.put(("result", metrics))

    def finish(self, error: Optional[BaseException]) -> None:
        self.results.put(("done", error))


def wrap_function(train_fn: Callable[[Dict], Any]) -> type:
    """Build a Trainable class around ``train_fn(config)`` (reference
    ``wrap_function``)."""

    class FunctionTrainable(Trainable):
        _function = staticmethod(train_fn)

        def setup(self, config: Dict) -> None:
            self._sess = _FnSession()
            self._thread: Optional[threading.Thread] = None
            self._final: Optional[Dict] = None
            self._last: Dict = {}

        def _start(self) -> None:
            def runner():
                # access the thread-local through the module: this
                # class is pickled BY VALUE into trial actors, and a
                # direct global reference would drag the unpicklable
                # threading.local along
                from ray_tpu.tune import function_trainable as _ft

                _ft._session.current = self._sess
                err: Optional[BaseException] = None
                try:
                    out = type(self)._function(dict(self.config))
                    if isinstance(out, dict):
                        self._final = out
                except BaseException as e:  # noqa: BLE001
                    err = e
                finally:
                    self._sess.finish(err)

            self._thread = threading.Thread(
                target=runner, daemon=True, name="tune_fn"
            )
            self._thread.start()

        def step(self) -> Dict:
            if self._thread is None:
                self._start()
            kind, payload = self._sess.results.get()
            if kind == "result":
                self._last = dict(payload)
                return self._last
            # function returned (or raised): surface the error, else
            # emit a final done result (reference: RESULT_DUPLICATE)
            if payload is not None:
                raise payload
            out = dict(self._final or self._last)
            out["done"] = True
            return out

        def save_checkpoint(self, checkpoint_dir: str) -> str:
            import os
            import pickle

            path = os.path.join(checkpoint_dir, "fn_state.pkl")
            with open(path, "wb") as f:
                pickle.dump(self._last, f)
            return checkpoint_dir

        def load_checkpoint(self, checkpoint_path: str) -> None:
            import os
            import pickle

            if os.path.isdir(checkpoint_path):
                checkpoint_path = os.path.join(
                    checkpoint_path, "fn_state.pkl"
                )
            with open(checkpoint_path, "rb") as f:
                self._sess.restored = pickle.load(f)

    FunctionTrainable.__name__ = getattr(
        train_fn, "__name__", "fn"
    )
    return FunctionTrainable


def with_parameters(fn: Callable, **params) -> Callable:
    """Bind constant (possibly large) objects into a function
    trainable (reference ``tune.with_parameters``); the bound values
    ride cloudpickle with the function."""

    def bound(config: Dict):
        return fn(config, **params)

    bound.__name__ = getattr(fn, "__name__", "fn")
    return bound
