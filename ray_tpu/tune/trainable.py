"""Trainable: the training-iteration protocol.

Counterpart of the reference's ``ray/tune/trainable/trainable.py:63``
(``train :303``, ``save :418``, ``restore :514``; subclass hooks ``setup``,
``step :895``, ``save_checkpoint :912``, ``load_checkpoint :952``).
"""

from __future__ import annotations

import json
import os
import pickle
import tempfile
import time
from typing import Any, Dict, Optional


class Trainable:
    def __init__(self, config: Optional[Dict] = None,
                 logger_creator=None):
        self.config = config or {}
        self._iteration = 0
        self._timesteps_total = 0
        self._episodes_total = 0
        self._time_total = 0.0
        self._start_time = time.time()
        self._logdir = None
        self._last_result: Dict = {}
        self.setup(self.config)

    # -- subclass API ----------------------------------------------------

    def setup(self, config: Dict) -> None:
        pass

    def step(self) -> Dict[str, Any]:
        raise NotImplementedError

    def save_checkpoint(self, checkpoint_dir: str) -> str:
        raise NotImplementedError

    def load_checkpoint(self, checkpoint_path: str) -> None:
        raise NotImplementedError

    def cleanup(self) -> None:
        pass

    # -- driver API ------------------------------------------------------

    @property
    def iteration(self) -> int:
        return self._iteration

    @property
    def logdir(self) -> str:
        if self._logdir is None:
            self._logdir = tempfile.mkdtemp(prefix="ray_tpu_trainable_")
        return self._logdir

    def train(self) -> Dict[str, Any]:
        """One training iteration (reference trainable.py:303)."""
        start = time.time()
        result = self.step() or {}
        self._iteration += 1
        dur = time.time() - start
        self._time_total += dur
        result.setdefault("training_iteration", self._iteration)
        result.setdefault("time_this_iter_s", dur)
        result.setdefault("time_total_s", self._time_total)
        result.setdefault(
            "timesteps_total", result.get("timesteps_total",
                                          self._timesteps_total)
        )
        result.setdefault("date", time.strftime("%Y-%m-%d_%H-%M-%S"))
        self._last_result = result
        return result

    def save(self, checkpoint_dir: Optional[str] = None) -> str:
        """reference trainable.py:418."""
        checkpoint_dir = checkpoint_dir or os.path.join(
            self.logdir, f"checkpoint_{self._iteration:06d}"
        )
        os.makedirs(checkpoint_dir, exist_ok=True)
        path = self.save_checkpoint(checkpoint_dir)
        meta = {
            "iteration": self._iteration,
            "timesteps_total": self._timesteps_total,
            "time_total": self._time_total,
        }
        from ray_tpu.util.atomic_io import atomic_write

        atomic_write(
            os.path.join(checkpoint_dir, ".tune_metadata"),
            lambda f: pickle.dump(meta, f),
        )
        return path or checkpoint_dir

    def restore(self, checkpoint_path: str) -> None:
        """reference trainable.py:514."""
        if os.path.isfile(checkpoint_path):
            checkpoint_dir = os.path.dirname(checkpoint_path)
        else:
            checkpoint_dir = checkpoint_path
        meta_path = os.path.join(checkpoint_dir, ".tune_metadata")
        if os.path.exists(meta_path):
            with open(meta_path, "rb") as f:
                meta = pickle.load(f)
            self._iteration = meta["iteration"]
            self._timesteps_total = meta["timesteps_total"]
            self._time_total = meta["time_total"]
        self.load_checkpoint(checkpoint_path)

    def stop(self) -> None:
        self.cleanup()

    # -- PBT exploit protocol ---------------------------------------------
    # Narrow surface the schedulers use, identical for in-process
    # trainables and remote trial actors (reference PBT does this via
    # checkpoint save + restore + full trial restart).

    def get_exploit_state(self):
        """Cloneable training state for a PBT exploit donor. Only
        classes with a real __setstate__ participate: object.__getstate__
        (Python 3.11+) would otherwise ship the entire __dict__ — replay
        buffers, env handles — that the recipient cannot apply anyway."""
        if not hasattr(type(self), "__setstate__"):
            return None
        return self.__getstate__()

    def apply_exploit(self, state, scalar_overrides: Dict) -> None:
        """Adopt a donor's state + mutated scalar hyperparams."""
        import copy as _copy

        if state is not None and hasattr(type(self), "__setstate__"):
            try:
                self.__setstate__(_copy.deepcopy(state))
            except Exception:
                pass
        self.config.update(scalar_overrides)
        # Push mutated scalars into the live policy: update_config
        # rebuilds schedules and drops compiled learn programs (loss
        # constants are baked into the XLA programs, so plain config
        # writes would silently have no effect).
        if hasattr(self, "get_policy"):
            try:
                pol = self.get_policy()
                if hasattr(pol, "update_config"):
                    pol.update_config(scalar_overrides)
                else:
                    pol.config.update(scalar_overrides)
            except Exception:
                pass
