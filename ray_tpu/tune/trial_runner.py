"""Re-export (the runner lives in tune.py beside run())."""

from ray_tpu.tune.tune import TrialRunner

__all__ = ["TrialRunner"]
