"""Optuna backend for :class:`ray_tpu.tune.suggest.ExternalSearcher`
(reference ``tune/suggest/optuna.py`` OptunaSearch). Import requires
``optuna``; environments without it use the in-repo TPE fallback
(``create_searcher('tpe', ...)``)."""

from __future__ import annotations

import copy
from typing import Dict

import optuna

from ray_tpu.tune.search import Choice, LogUniform, Randint, Uniform
from ray_tpu.tune.suggest import _flatten_space, _set_path


class OptunaBackend:
    """ask/tell bridge: Domain DSL → optuna distributions."""

    def __init__(self, space: Dict, metric: str, mode: str):
        self._template = copy.deepcopy(space)
        self._space = _flatten_space(self._template)
        self._study = optuna.create_study(
            direction="maximize" if mode == "max" else "minimize"
        )
        self._trials: Dict[int, optuna.trial.Trial] = {}

    def ask(self):
        trial = self._study.ask()
        config = copy.deepcopy(self._template)
        for path, dom in self._space:
            name = ".".join(path)
            if isinstance(dom, LogUniform):
                import math

                v = trial.suggest_float(
                    name,
                    math.exp(dom.log_low),
                    math.exp(dom.log_high),
                    log=True,
                )
            elif isinstance(dom, Uniform):
                v = trial.suggest_float(name, dom.low, dom.high)
            elif isinstance(dom, Randint):
                v = trial.suggest_int(name, dom.low, dom.high - 1)
            elif isinstance(dom, Choice):
                v = trial.suggest_categorical(name, dom.categories)
            else:
                v = dom.sample(__import__("random").Random())
            _set_path(config, path, v)
        self._trials[trial.number] = trial
        return trial.number, config

    def tell(self, key: int, value: float) -> None:
        self._study.tell(key, value)
        self._trials.pop(key, None)

    def tell_failure(self, key: int) -> None:
        self._study.tell(
            key, state=optuna.trial.TrialState.FAIL
        )
        self._trials.pop(key, None)
