"""ray_tpu.fleet — the elastic multi-host learner fleet (PR 17).

The learner mesh becomes a fleet the way the reference's cluster is
one (GCS node table, heartbeats, resource-change pubsub): hosts
rendezvous through a KV control plane, membership lives with a
single-writer coordinator, every mesh (re)construction is a
generation-numbered epoch, and a preemption-driven resize is a
warm-cache restart — the PR-10 reshard contract moves the state, the
geometry-keyed PR-14 AOT cache supplies the executables, so the
survivor's first post-resize step performs zero fresh compiles.

Modules (docs/fleet.md):

- :mod:`~ray_tpu.fleet.kv`          KV/rendezvous service (promoted
  from ``parallel.distributed``; blocking gets, pubsub, heartbeats);
- :mod:`~ray_tpu.fleet.coordinator` membership, mesh epochs, drain
  protocol, epoch-scoped barriers;
- :mod:`~ray_tpu.fleet.elastic`     resize/pre-seed primitives over
  the reshard contract and the AOT cache.

Crash tolerance (PR 19): the coordinator's authority is a fenced KV
lease (``LEASE_NAME``) — standbys acquire it on expiry and rebuild
from the durable KV table, stale-term writes are rejected at the
store (:class:`StaleTermError`), the KV transport retries with
backoff, and partitioned hosts self-fence at their epoch barrier
(docs/fleet.md "Failure model & leadership").
"""

from ray_tpu.fleet.coordinator import (
    BARRIER_TIMEOUT_ENV,
    CH_JOIN,
    CH_LEAVE,
    CH_NOTICE,
    EPOCH_TIMEOUT_ENV,
    HEARTBEAT_ENV,
    HORIZON_ENV,
    LEASE_NAME,
    LEASE_TTL_ENV,
    FleetCoordinator,
    HostAgent,
    K_EPOCH_PTR,
    K_MEMBERS,
    K_READY,
    MeshEpoch,
    barrier_key,
    drain_key,
    epoch_key,
)
from ray_tpu.fleet.elastic import (
    PRESEED_ENV,
    epoch_mesh,
    preseed_enabled,
    preseed_resize,
    resize_policy,
    resize_target_meshes,
    resync_epoch,
    shadow_policy,
)
from ray_tpu.fleet.kv import (
    KV_RETRY_ENV,
    HeartbeatReporter,
    KVClient,
    KVServer,
    StaleTermError,
    Subscriber,
)

__all__ = [
    "BARRIER_TIMEOUT_ENV",
    "CH_JOIN",
    "CH_LEAVE",
    "CH_NOTICE",
    "EPOCH_TIMEOUT_ENV",
    "FleetCoordinator",
    "HEARTBEAT_ENV",
    "HORIZON_ENV",
    "HeartbeatReporter",
    "HostAgent",
    "KVClient",
    "KVServer",
    "KV_RETRY_ENV",
    "K_EPOCH_PTR",
    "K_MEMBERS",
    "K_READY",
    "LEASE_NAME",
    "LEASE_TTL_ENV",
    "MeshEpoch",
    "PRESEED_ENV",
    "StaleTermError",
    "Subscriber",
    "barrier_key",
    "drain_key",
    "epoch_key",
    "epoch_mesh",
    "preseed_enabled",
    "preseed_resize",
    "resize_policy",
    "resize_target_meshes",
    "resync_epoch",
    "shadow_policy",
]
