"""ray_tpu.fleet.coordinator — membership, mesh epochs, and the drain
protocol of the elastic learner fleet.

The learner mesh becomes a fleet the same way the reference's cluster
does (GCS node table + heartbeat manager + resource-change pubsub):
hosts register with a single coordinator, liveness rides the KV
heartbeat plane (:mod:`ray_tpu.fleet.kv`), and every coordinated mesh
(re)construction is a **generation-numbered epoch** — an immutable KV
record naming the participating hosts in rank order. Hosts never
negotiate peer-to-peer; they observe epochs and meet at epoch-scoped
barriers, so a resize is a total order everyone replays.

Threading follows the FleetController discipline (docs/resilience.md,
RTA006): the subscriber thread only OBSERVES — join/leave/notice
events are queued under one lock — and the driver's ``reconcile()``
ACTS (mutates the member table, posts drains, cuts epochs). All KV
writes happen on the driver thread of the one coordinator process, so
the member table and epoch sequence have a single writer.

Leadership (PR 19, docs/fleet.md "Failure model & leadership"): the
"one coordinator" is now enforced by a fenced KV lease, not by
deployment discipline. A coordinator holds the ``fleet/leader`` lease
and stamps every fleet write with its term; the KV store rejects
writes whose term predates the lease's, so a deposed-but-still-running
ex-coordinator cannot corrupt the member table or cut a conflicting
epoch (:class:`~ray_tpu.fleet.kv.StaleTermError` is its signal to
stand down). Any host can run a ``standby=True`` coordinator: it
polls ``acquire_leadership()`` until the incumbent's lease expires,
then rebuilds the member/epoch mirror from the persisted KV table
and takes over at a higher term — failover is a warm-cache restart of
the control plane, the same shape PR 17 gave the data plane.

Epoch/drain choreography on a preemption notice for a learner host::

    host   announce_notice() ── publish fleet/notice ──▶ coordinator
    coord  reconcile(): post drain record (epoch-scoped KV key),
           drop the victim from members, cut epoch gen+1
    hosts  await_drain(gen)  — BLOCKING get, so every host observes
           the same drain record before its next superstep (lockstep
           is preserved: the drain step is the last global step)
    hosts  one final lockstep superstep (the victim's in-flight
           contribution is not lost), then barrier("drained", gen)
    victim exits; survivors wait_for_epoch(gen+1) and rebuild the
           mesh at the surviving geometry (fleet/elastic.py)

Env knobs (documented in docs/fleet.md + docs/API.md):
``RAY_TPU_FLEET_HEARTBEAT_S`` host heartbeat interval,
``RAY_TPU_FLEET_LIVENESS_HORIZON_S`` liveness horizon for
``expire_dead``, ``RAY_TPU_FLEET_BARRIER_TIMEOUT_S`` epoch-barrier
wait, ``RAY_TPU_FLEET_EPOCH_TIMEOUT_S`` wait for an epoch record.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.fleet.kv import (
    HeartbeatReporter,
    KVClient,
    StaleTermError,
    Subscriber,
)

# -- KV schema (all under the fleet/ prefix) ---------------------------

K_MEMBERS = "fleet/members"  # {host: {"rank_hint": int, ...}}
K_EPOCH_PTR = "fleet/epoch"  # latest generation number (int)
K_READY = "fleet/ready"  # coordinator's subscriber is registered

LEASE_NAME = "fleet/leader"  # the coordinator's fenced lease
LEASE_TTL_ENV = "RAY_TPU_FLEET_LEASE_TTL_S"


def epoch_key(gen: int) -> str:
    """Immutable epoch record for one generation."""
    return f"fleet/epoch/{gen}"


def drain_key(gen: int) -> str:
    """Drain record cut against generation ``gen`` (the epoch being
    torn down, not the one being built)."""
    return f"fleet/drain/{gen}"


def barrier_key(gen: int, name: str, host: str) -> str:
    return f"fleet/barrier/{gen}/{name}/{host}"


CH_JOIN = "fleet/join"
CH_LEAVE = "fleet/leave"
CH_NOTICE = "fleet/notice"
# barrier-arrival events for the fleetview aggregator
# (telemetry/fleetview.py): each host publishes its own arrival stamp
# the moment it reaches an epoch-scoped barrier, so straggler
# attribution sees every arrival even though the KV store has no key
# listing. The record carries the epoch's host tuple — the aggregator
# knows the expected arrival count without a KV read.
CH_BARRIER = "fleet/barrier_arrival"

HEARTBEAT_ENV = "RAY_TPU_FLEET_HEARTBEAT_S"
HORIZON_ENV = "RAY_TPU_FLEET_LIVENESS_HORIZON_S"
BARRIER_TIMEOUT_ENV = "RAY_TPU_FLEET_BARRIER_TIMEOUT_S"
EPOCH_TIMEOUT_ENV = "RAY_TPU_FLEET_EPOCH_TIMEOUT_S"


def _env_s(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


@dataclasses.dataclass(frozen=True)
class MeshEpoch:
    """One generation of the learner mesh: the participating hosts in
    rank order. Immutable once written — a resize never edits an
    epoch, it cuts the next one (the reference's cluster view is the
    same append-only shape: node table revisions, not mutations)."""

    gen: int
    hosts: Tuple[str, ...]  # index == jax process rank
    reason: str = "bootstrap"
    created_at: float = 0.0

    @property
    def num_processes(self) -> int:
        return len(self.hosts)

    def rank_of(self, host: str) -> int:
        return self.hosts.index(host)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "gen": self.gen,
            "hosts": list(self.hosts),
            "reason": self.reason,
            "created_at": self.created_at,
        }

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "MeshEpoch":
        return MeshEpoch(
            gen=int(d["gen"]),
            hosts=tuple(d["hosts"]),
            reason=d.get("reason", ""),
            created_at=float(d.get("created_at", 0.0)),
        )


class FleetCoordinator:
    """Single-writer membership + epoch authority (one per fleet,
    typically the rank-0 learner process or the driver).

    The subscriber thread buffers join/leave/notice events;
    ``reconcile()`` — driver-owned, like FleetController.reconcile —
    applies them to the member table and cuts epochs. Unit-testable
    without meshes: events can also be injected directly via
    ``register_host`` / ``remove_host`` / ``handle_notice`` from the
    driver thread.

    Leadership: construction with ``standby=False`` acquires the
    ``fleet/leader`` lease immediately (blocking past an incumbent's
    TTL if one exists); ``standby=True`` builds a dormant coordinator
    that does nothing until ``acquire_leadership()`` wins the lease —
    at which point it rebuilds the member/epoch mirror from the KV
    table and becomes the single writer at a HIGHER term. Every fleet
    write carries the term (``_put``), so a deposed leader's writes
    are fenced at the store; a fenced write or failed renewal flips
    ``is_leader`` off and the ex-leader must stop acting."""

    def __init__(
        self,
        kv: KVClient,
        liveness_horizon: Optional[float] = None,
        subscribe: bool = True,
        standby: bool = False,
        lease_ttl: Optional[float] = None,
        holder: Optional[str] = None,
    ):
        import socket as _socket

        self.kv = kv
        self.horizon = (
            liveness_horizon
            if liveness_horizon is not None
            else _env_s(HORIZON_ENV, 30.0)
        )
        self.lease_ttl = (
            lease_ttl
            if lease_ttl is not None
            else _env_s(LEASE_TTL_ENV, 10.0)
        )
        # holder identity is per-PROCESS: a restarted coordinator on
        # the same host is a different holder and must re-acquire
        self._holder = holder or f"{_socket.gethostname()}:{os.getpid()}"
        self._subscribe = subscribe
        self._term = 0
        self._leader = False
        self._renew_stop = threading.Event()
        self._renew_thread: Optional[threading.Thread] = None
        # one lock guards the event queue AND the member/epoch mirror;
        # never held across KV round trips
        self._lock = threading.Lock()
        self._events: List[Tuple[str, Dict[str, Any]]] = []
        self._members: Dict[str, Dict[str, Any]] = {}
        self._gen = 0
        self._epoch: Optional[MeshEpoch] = None
        self._sub: Optional[Subscriber] = None
        from ray_tpu.resilience.faults import kv_injector

        self._chaos = kv_injector()
        if not standby:
            # blocking acquire: waits out an incumbent's TTL at most
            self.acquire_leadership(
                timeout=max(30.0, 3.0 * self.lease_ttl)
            )

    # -- leadership ----------------------------------------------------

    @property
    def is_leader(self) -> bool:
        return self._leader

    @property
    def term(self) -> int:
        return self._term

    # ray-tpu: thread=driver
    def acquire_leadership(
        self,
        timeout: Optional[float] = None,
        poll_interval: Optional[float] = None,
    ) -> int:
        """Poll the lease until granted (a standby's whole job), then
        become leader: rebuild state from KV, start renewals,
        subscribe, and write the readiness gate — all at the granted
        term. Returns the term. Idempotent while already leader."""
        if self._leader:
            return self._term
        poll = (
            poll_interval
            if poll_interval is not None
            else max(0.1, self.lease_ttl / 4.0)
        )
        deadline = (
            time.monotonic() + timeout if timeout is not None else None
        )
        while True:
            resp = self.kv.lease_acquire(
                LEASE_NAME, self._holder, ttl=self.lease_ttl
            )
            if resp.get("granted"):
                self._become_leader(int(resp["term"]))
                return self._term
            if deadline is not None and time.monotonic() >= deadline:
                raise TimeoutError(
                    f"lease {LEASE_NAME} held by "
                    f"{resp.get('holder')!r} (term {resp.get('term')}, "
                    f"expires in {resp.get('expires_in', 0):.1f}s)"
                )
            # wait for the incumbent's TTL to run out, but re-probe
            # well inside it — failover wall is what --fleet-chaos
            # measures against the TTL
            time.sleep(min(poll, max(0.05, resp.get("expires_in", poll))))

    # ray-tpu: thread=driver
    def _become_leader(self, term: int) -> None:
        """The warm-cache restart of the control plane: mirror the
        durable KV state (member table, epoch pointer + record), then
        start acting at the new term. Members re-prove liveness via
        heartbeats — the mirror is a starting guess the next
        ``expire_dead`` sweep corrects."""
        promoted = self._term != 0 and term > self._term
        self._term = term
        self._leader = True
        with self._lock:
            self._members = {}
            self._gen, self._epoch = 0, None
        try:
            members = dict(self.kv.get(K_MEMBERS, timeout=0.1))
            with self._lock:
                self._members = members
        except KeyError:
            pass
        try:
            gen = int(self.kv.get(K_EPOCH_PTR, timeout=0.1))
            epoch = MeshEpoch.from_dict(
                self.kv.get(epoch_key(gen), timeout=1.0)
            )
            with self._lock:
                self._gen, self._epoch = gen, epoch
        except KeyError:
            pass
        if self._subscribe and self._sub is None:
            self._sub = Subscriber(
                self.kv,
                ["fleet/*"],
                self._on_event,
                sub_id=f"fleet-coordinator-{self._holder}",
                poll_timeout=1.0,
            )
        self._renew_stop.clear()
        self._renew_thread = threading.Thread(
            target=self._renew_loop, daemon=True
        )
        self._renew_thread.start()
        from ray_tpu.telemetry import metrics

        host = self._holder.rsplit(":", 1)[0]
        try:
            metrics.set_coordinator_term(host, term)
            if promoted or term > 1:
                metrics.inc_fleet_failover(host)
        except Exception:
            pass
        # readiness gate, written AFTER the subscriber is registered:
        # agents block on it before announcing, so a join can never be
        # published into a void (pubsub only reaches live subscribers).
        # First fenced write — a stale takeover dies right here.
        self._put(K_READY, time.time())

    # ray-tpu: thread=lease-renew
    def _renew_loop(self) -> None:
        """Renew the lease every TTL/3. A refused renewal means the
        lease expired or a rival took over at a higher term — flip
        ``is_leader`` off and stop; the driver notices via
        ``is_leader`` (or the next ``_put`` being fenced)."""
        while not self._renew_stop.wait(self.lease_ttl / 3.0):
            try:
                ok = self.kv.lease_renew(
                    LEASE_NAME,
                    self._holder,
                    self._term,
                    ttl=self.lease_ttl,
                )
            except Exception:
                # KV unreachable past the retry schedule: keep trying
                # until the TTL verdict is knowable again; writes stay
                # term-fenced either way
                continue
            if not ok:
                self._leader = False
                return

    def _put(self, key: str, value: Any) -> None:
        """Every coordinator write goes through here: term-fenced, and
        armed for ``kill_coordinator`` chaos. A fenced rejection means
        leadership is gone — record it and re-raise so the caller's
        control flow stops acting on the fleet."""
        if self._chaos is not None:
            self._chaos.on_coordinator_write()
        try:
            self.kv.put(
                key,
                value,
                term=self._term,
                lease=LEASE_NAME,
                holder=self._holder,
            )
        except StaleTermError:
            self._leader = False
            raise

    # ray-tpu: thread=fleet-sub
    def _on_event(self, channel: str, msg: Dict[str, Any]) -> None:
        """Subscriber callback: observe and queue, never act — the
        driver's reconcile() applies events (RTA006 ownership)."""
        if channel in (CH_JOIN, CH_LEAVE, CH_NOTICE):
            with self._lock:
                self._events.append((channel, dict(msg)))

    # -- driver-side API ------------------------------------------------

    # ray-tpu: thread=driver
    def reconcile(self) -> List[Tuple[str, Dict[str, Any]]]:
        """Drain queued events and apply them: joins/leaves edit the
        member table; a notice triggers the drain + epoch cut. Returns
        the events applied (for observability/tests)."""
        with self._lock:
            events, self._events = self._events, []
        for channel, msg in events:
            host = msg.get("host", "")
            if not host:
                continue
            if channel == CH_JOIN:
                self.register_host(
                    host, rank_hint=msg.get("rank_hint")
                )
            elif channel == CH_LEAVE:
                self.remove_host(host, reason=msg.get("reason", "leave"))
            elif channel == CH_NOTICE:
                self.handle_notice(
                    host, reason=msg.get("reason", "preempted")
                )
        return events

    # ray-tpu: thread=driver
    def register_host(
        self, host: str, rank_hint: Optional[int] = None
    ) -> None:
        with self._lock:
            self._members[host] = {
                "rank_hint": rank_hint,
                "joined_at": time.time(),
            }
            snapshot = dict(self._members)
        self._put(K_MEMBERS, snapshot)

    # ray-tpu: thread=driver
    def remove_host(self, host: str, reason: str = "leave") -> None:
        with self._lock:
            self._members.pop(host, None)
            snapshot = dict(self._members)
        self._put(K_MEMBERS, snapshot)

    # ray-tpu: thread=driver
    def handle_notice(
        self, host: str, reason: str = "preempted"
    ) -> Optional[MeshEpoch]:
        """Preemption notice for a learner host: post the drain record
        against the CURRENT generation (hosts block on it, so lockstep
        is preserved — every host sees the drain before its next
        superstep), drop the victim, cut the next epoch. Idempotent
        per victim: a second notice for an already-removed host is a
        no-op."""
        with self._lock:
            if host not in self._members:
                return None
            gen = self._gen
        self._put(
            drain_key(gen),
            {"victims": [host], "reason": reason, "ts": time.time()},
        )
        self.remove_host(host, reason=reason)
        from ray_tpu.telemetry import metrics

        metrics.inc_mesh_resizes(reason)
        return self.propose_epoch(reason=reason)

    # ray-tpu: thread=driver
    def propose_epoch(self, reason: str = "resize") -> MeshEpoch:
        """Cut generation ``gen+1`` over the current members. Rank
        order is deterministic: sort by (rank_hint, host) so re-runs
        and restarts agree without negotiation."""
        with self._lock:
            members = dict(self._members)
            gen = self._gen + 1
        hosts = tuple(
            sorted(
                members,
                key=lambda h: (
                    members[h].get("rank_hint")
                    if members[h].get("rank_hint") is not None
                    else 1 << 30,
                    h,
                ),
            )
        )
        epoch = MeshEpoch(
            gen=gen,
            hosts=hosts,
            reason=reason,
            created_at=time.time(),
        )
        # record first, pointer second: a reader following the pointer
        # always finds the record
        self._put(epoch_key(gen), epoch.to_dict())
        self._put(K_EPOCH_PTR, gen)
        with self._lock:
            self._gen, self._epoch = gen, epoch
        from ray_tpu.telemetry import metrics

        metrics.set_learner_fleet(len(hosts), gen)
        return epoch

    # ray-tpu: thread=driver
    def expire_dead(
        self, horizon: Optional[float] = None
    ) -> List[str]:
        """Heartbeat sweep (the gcs_heartbeat_manager role): any member
        with no heartbeat inside the horizon is treated as a crashed
        host — same removal path as a notice, but the epoch cut reason
        records it was a kill, not a drain."""
        horizon = horizon if horizon is not None else self.horizon
        alive = self.kv.alive_nodes(horizon=horizon)
        with self._lock:
            dead = [h for h in self._members if h not in alive]
        for host in dead:
            self.handle_notice(host, reason="heartbeat-expired")
        return dead

    # ray-tpu: thread=driver
    def wait_for_members(
        self, count: int, timeout: float = 60.0
    ) -> Dict[str, Dict[str, Any]]:
        """Rendezvous: reconcile until ``count`` hosts registered."""
        deadline = time.monotonic() + timeout
        while True:
            self.reconcile()
            with self._lock:
                members = dict(self._members)
            if len(members) >= count:
                return members
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"fleet rendezvous: {len(members)}/{count} hosts "
                    f"after {timeout}s: {sorted(members)}"
                )
            time.sleep(0.05)

    # ray-tpu: thread=driver
    def members(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return dict(self._members)

    # ray-tpu: thread=driver
    def current_epoch(self) -> Optional[MeshEpoch]:
        with self._lock:
            return self._epoch

    # ray-tpu: thread=driver
    def stop(self, release_lease: bool = True) -> None:
        """Clean shutdown: stop renewing, unsubscribe, and (unless
        simulating a crash — tests pass ``release_lease=False``) hand
        the lease back so a standby takes over immediately instead of
        waiting out the TTL."""
        self._renew_stop.set()
        if self._renew_thread is not None:
            self._renew_thread.join(timeout=self.lease_ttl)
            self._renew_thread = None
        if self._sub is not None:
            self._sub.stop()
            self._sub = None
        if self._leader and release_lease:
            try:
                self.kv.lease_release(LEASE_NAME, self._holder)
            except Exception:
                pass
        self._leader = False


class HostAgent:
    """Per-host fleet participant: heartbeats, join/leave/notice
    announcements, epoch observation, and epoch-scoped barriers. Holds
    no authority — every decision is the coordinator's; the agent only
    announces and observes, so any host can crash at any point without
    corrupting the member table.

    Partition self-fencing: a host whose KV heartbeats have failed
    past the liveness horizon must assume the coordinator already
    declared it dead and cut an epoch without it. ``self_fenced()``
    detects that state; ``park_until_reconnected()`` is what the
    host's step loop calls INSTEAD of dispatching supersteps — it
    probes KV until reachable, reads the epoch pointer, and reports
    whether the host may resume in-epoch (the fleet didn't move on)
    or must rejoin at the new generation."""

    def __init__(
        self,
        kv: KVClient,
        host: str,
        rank_hint: Optional[int] = None,
        heartbeat_interval: Optional[float] = None,
    ):
        self.kv = kv
        self.host = host
        self.rank_hint = rank_hint
        interval = (
            heartbeat_interval
            if heartbeat_interval is not None
            else _env_s(HEARTBEAT_ENV, 2.0)
        )
        self._hb = HeartbeatReporter(kv, host, interval=interval)

    # ray-tpu: thread=driver
    def join(self, timeout: Optional[float] = None) -> None:
        """Announce this host to the coordinator. Blocks on the
        coordinator's readiness flag first — the flag is written after
        the coordinator's subscriber registered, so the join publish
        is guaranteed an audience."""
        timeout = (
            timeout
            if timeout is not None
            else _env_s(EPOCH_TIMEOUT_ENV, 120.0)
        )
        self.kv.get(K_READY, timeout=timeout)
        self.kv.publish(
            CH_JOIN, {"host": self.host, "rank_hint": self.rank_hint}
        )

    # ray-tpu: thread=driver
    def leave(self, reason: str = "leave") -> None:
        self.kv.publish(
            CH_LEAVE, {"host": self.host, "reason": reason}
        )

    # ray-tpu: thread=driver
    def announce_notice(self, reason: str = "preempted") -> None:
        """The learner-host half of the provider-notice pipeline
        (resilience/provider_notice.py): forward the eviction signal
        to the coordinator."""
        self.kv.publish(
            CH_NOTICE, {"host": self.host, "reason": reason}
        )

    # ray-tpu: thread=driver
    def poll_drain(self, gen: int) -> Optional[Dict[str, Any]]:
        """Non-blocking peek at the drain record for generation
        ``gen`` (None if no drain posted). For loops that must not
        stall when the fleet is healthy."""
        try:
            return self.kv.get(drain_key(gen), timeout=0.05)
        except KeyError:
            return None

    # ray-tpu: thread=driver
    def await_drain(
        self, gen: int, timeout: Optional[float] = None
    ) -> Dict[str, Any]:
        """Blocking wait for the drain record — the lockstep anchor:
        every host of generation ``gen`` observes the same record
        before its drain step, so the final global superstep is
        collective on all hosts (the pattern the 2-process worker
        proved with its notice key)."""
        timeout = (
            timeout
            if timeout is not None
            else _env_s(EPOCH_TIMEOUT_ENV, 120.0)
        )
        return self.kv.get(drain_key(gen), timeout=timeout)

    # ray-tpu: thread=driver
    def wait_for_epoch(
        self, gen: int, timeout: Optional[float] = None
    ) -> MeshEpoch:
        """Blocking wait for the epoch record of generation ``gen``
        (the coordinator writes the record before the pointer, so a
        published generation is always readable)."""
        timeout = (
            timeout
            if timeout is not None
            else _env_s(EPOCH_TIMEOUT_ENV, 120.0)
        )
        return MeshEpoch.from_dict(
            self.kv.get(epoch_key(gen), timeout=timeout)
        )

    # ray-tpu: thread=driver
    def barrier(
        self,
        name: str,
        epoch: MeshEpoch,
        timeout: Optional[float] = None,
    ) -> None:
        """Epoch-scoped barrier over the epoch's hosts: each puts its
        own key, then blocks on every peer's. Keys are scoped by
        (gen, name) so barriers of different epochs can never alias —
        a late host of a dead generation cannot satisfy a new one."""
        timeout = (
            timeout
            if timeout is not None
            else _env_s(BARRIER_TIMEOUT_ENV, 60.0)
        )
        arrived_at = time.time()
        self.kv.put(
            barrier_key(epoch.gen, name, self.host), arrived_at
        )
        # fleetview feed: the same arrival as a pubsub event, so the
        # aggregator attributes barrier wait/straggler per host
        # without polling barrier keys (best-effort — a fleet without
        # an aggregator just publishes into the void)
        try:
            self.kv.publish(
                CH_BARRIER,
                {
                    "gen": epoch.gen,
                    "name": name,
                    "host": self.host,
                    "hosts": list(epoch.hosts),
                    "ts": arrived_at,
                },
            )
        except Exception:
            pass
        deadline = time.monotonic() + timeout
        for peer in epoch.hosts:
            if peer == self.host:
                continue
            remaining = max(0.1, deadline - time.monotonic())
            try:
                self.kv.get(
                    barrier_key(epoch.gen, name, peer),
                    timeout=remaining,
                )
            except KeyError:
                raise TimeoutError(
                    f"fleet barrier '{name}' gen={epoch.gen}: host "
                    f"{peer} missing after {timeout}s"
                )

    # ray-tpu: thread=driver
    def kv_outage_s(self) -> float:
        """Monotonic seconds since KV last acknowledged a heartbeat."""
        return self._hb.seconds_since_ok()

    # ray-tpu: thread=driver
    def self_fenced(self, horizon: Optional[float] = None) -> bool:
        """True when this host has been cut off from KV longer than
        the liveness horizon — the coordinator's ``expire_dead`` sweep
        may already have removed it, so dispatching another superstep
        against a possibly-reformed mesh would be a split-brain step.
        The honest move is to park (below)."""
        horizon = (
            horizon if horizon is not None else _env_s(HORIZON_ENV, 30.0)
        )
        return self.kv_outage_s() > horizon

    # ray-tpu: thread=driver
    def park_until_reconnected(
        self,
        epoch: MeshEpoch,
        timeout: Optional[float] = None,
        probe_interval: float = 0.5,
    ) -> Tuple[MeshEpoch, bool]:
        """Sit out the partition at the epoch barrier line. Probes KV
        (cheap ``clock`` op) until it answers, then reads the epoch
        pointer: if the fleet is still on ``epoch.gen`` the host
        resumes in-epoch — returns ``(epoch, True)``; if the fleet cut
        a new generation while we were gone, returns the new epoch and
        ``False`` (the caller must rejoin/rebuild, fleet/elastic.py).
        Counted in ``ray_tpu_fleet_self_fences_total{host}``."""
        timeout = (
            timeout
            if timeout is not None
            else _env_s(EPOCH_TIMEOUT_ENV, 120.0)
        )
        from ray_tpu.telemetry import metrics

        try:
            metrics.inc_self_fence(self.host)
        except Exception:
            pass
        deadline = time.monotonic() + timeout
        while True:
            try:
                self.kv.server_clock()
                break
            except Exception:
                if time.monotonic() >= deadline:
                    raise TimeoutError(
                        f"host {self.host}: KV unreachable for "
                        f"{self.kv_outage_s():.1f}s while parked "
                        f"(waited {timeout}s)"
                    )
                time.sleep(probe_interval)
        # reconnected: immediately re-prove liveness, then find out
        # whether the fleet moved on without us
        try:
            self.kv.heartbeat(self.host)
        except Exception:
            pass
        try:
            gen = int(self.kv.get(K_EPOCH_PTR, timeout=5.0))
        except KeyError:
            return epoch, True  # no epochs cut at all: nothing moved
        if gen == epoch.gen:
            return epoch, True
        return self.wait_for_epoch(gen), False

    # ray-tpu: thread=driver
    def stop(self) -> None:
        self._hb.stop()
