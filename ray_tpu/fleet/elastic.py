"""ray_tpu.fleet.elastic — live mesh resize as a warm-cache restart.

Three pieces the rest of the repo already proved, composed into one
primitive:

- the **PR-10 reshard contract**: ``Policy.set_state`` re-places any
  host state tree per the ACTIVE sharding rules, bitwise across mesh
  geometries — so moving a learner to a new mesh is "build a twin on
  the new mesh, hand it the state";
- the **PR-14 AOT executable cache**, geometry-keyed since this PR
  (``compile._mesh_geometry_token``): entries for several mesh
  geometries coexist in one cache dir, so the fleet can hold compiled
  programs for geometries it is not currently running;
- the **PR-16 program registry** sweep: the learn-program shapes of a
  config are predictable, so the resize-target geometry's programs can
  be compiled BEFORE any preemption notice exists.

``preseed_resize`` runs at fleet bring-up (or idle time): it builds a
shadow policy on each resize-target mesh and AOT-compiles its learn
program into the shared cache. When a preemption later shrinks the
fleet, ``resize_policy`` builds the survivor's twin on the new mesh —
its warmup hits the pre-seeded entry, so the resize performs ZERO
fresh compiles (asserted via the compile ledger in the tests). That is
the tentpole contract: resize is a warm-cache restart.

Env knob: ``RAY_TPU_FLEET_PRESEED=0`` disables the bring-up pre-seed
sweep (docs/fleet.md).
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

from ray_tpu.fleet.coordinator import MeshEpoch

PRESEED_ENV = "RAY_TPU_FLEET_PRESEED"


def preseed_enabled() -> bool:
    return os.environ.get(PRESEED_ENV, "1").lower() not in (
        "0",
        "false",
        "off",
    )


def shadow_policy(policy, mesh):
    """A twin of ``policy`` on ``mesh``: same class, same config, same
    seed — only the mesh injection differs, so its learn program is
    exactly the one a post-resize survivor would build."""
    cfg = dict(policy.config)
    cfg["_mesh"] = mesh
    return type(policy)(
        policy.observation_space, policy.action_space, cfg
    )


def resize_policy(policy, new_mesh):
    """The live-resize primitive: re-home a learner onto a new mesh
    geometry under the PR-10 reshard contract. Builds the twin on
    ``new_mesh`` and hands it the full state (params, opt_state,
    coefficient schedule, step counters) — ``set_state``'s
    ``_tree_to_device`` re-places every leaf per the twin's sharding
    rules, so the transfer is bitwise and training continues exactly
    where the old geometry stopped. With an AOT cache configured and
    pre-seeded (``preseed_resize``), the twin's first learn step
    installs a cached executable: zero fresh compiles."""
    import time

    from ray_tpu.telemetry import fleetview
    from ray_tpu.util import tracing

    # collective drain point + recovery-lane span: every survivor
    # resizes in lockstep, so the fleet aggregator can name the host
    # that finished re-homing last (telemetry/fleetview.py)
    t0 = time.time()
    twin = shadow_policy(policy, new_mesh)
    twin.set_state(policy.get_state())
    fleetview.record_arrival("resize")
    tracing.record_span(
        "recovery:resize",
        t0,
        time.time(),
        devices=int(
            getattr(
                getattr(new_mesh, "devices", None), "size", 0
            )
        ),
    )
    return twin


def preseed_resize(
    policy,
    mesh,
    dev_batch: Dict[str, Any],
    batch_size: int,
) -> str:
    """AOT-compile the learn program ``policy`` would run after a
    resize to ``mesh``, into the policy's configured AOT cache.

    ``dev_batch`` is a HOST tree with the post-resize global batch
    shapes (the registry's predictive specs; in the common shrink case
    the global batch is unchanged — same shapes, different mesh, which
    is exactly why the cache keys on geometry). Returns the
    ``aot_warmup`` status: ``"hit"`` (already seeded), ``"compiled"``
    (seeded now — the one ahead-of-time compile this geometry will
    ever cost), or ``"disabled"`` (no cache configured / jax build
    without executable serialization)."""
    import jax
    import numpy as np

    shadow = shadow_policy(policy, mesh)
    cache = shadow._learn_aot_cache()
    if cache is None:
        return "disabled"
    fn = shadow.learn_fn(batch_size)
    # place exactly as the learn path would (per-column sharding
    # tree), so the lowered signature matches the real post-resize
    # program's — executable values don't matter, placement does
    sh = shadow.batch_shardings(dev_batch)
    dev = {
        k: jax.device_put(
            np.asarray(v),
            sh[k] if isinstance(sh, dict) else sh,
        )
        for k, v in dev_batch.items()
    }
    status = fn.aot_warmup(
        cache,
        shadow.params,
        shadow.opt_state,
        shadow.aux_state,
        dev,
        shadow._rng,
        shadow._coeff_array(),
    )
    # the seed must be durable before a preemption can arrive
    cache.flush()
    from ray_tpu.telemetry import metrics

    metrics.inc_fleet_preseed(status)
    return status


def resize_target_meshes(mesh) -> List:
    """The ±1-host resize geometries worth pre-seeding from ``mesh``:
    today the shrink-by-one-host survivor mesh (this process's local
    devices) — the geometry a preemption forces. Growth geometries
    join when a process can host more devices than it runs (the
    restarted-fleet case pre-seeds through the same cache dir by
    construction: the new process compiles against the same keys)."""
    import jax
    import numpy as np

    local = list(jax.local_devices())
    try:
        n_mesh = int(np.asarray(mesh.devices).size)
    except Exception:
        n_mesh = len(local)
    if len(local) >= n_mesh:
        return []  # already single-host: no shrink geometry below it
    from ray_tpu import sharding as sharding_lib

    return [sharding_lib.get_mesh(devices=local)]


def resync_epoch(kv, current_gen: int, timeout: float = 30.0) -> MeshEpoch:
    """Catch up with the fleet after an absence (a parked partition, a
    coordinator failover window): follow the epoch pointer to the
    LATEST generation ≥ ``current_gen`` and return its record. The
    pointer is written after the record (coordinator invariant), so a
    readable pointer always resolves. A host that finds the returned
    generation differs from ``current_gen`` must rebuild via
    ``resize_policy``/``epoch_mesh`` before stepping — its old epoch's
    barriers are dead keys that can never complete."""
    from ray_tpu.fleet.coordinator import K_EPOCH_PTR, epoch_key

    gen = int(kv.get(K_EPOCH_PTR, timeout=timeout))
    if gen < current_gen:
        # a fresh KV (post-crash, unpersisted) can point backwards;
        # our generation knowledge wins — wait for the fleet to catch
        # up to where we already were
        gen = current_gen
    return MeshEpoch.from_dict(kv.get(epoch_key(gen), timeout=timeout))


def epoch_mesh(epoch: MeshEpoch):
    """The mesh for one :class:`MeshEpoch`. A single-host epoch builds
    over this process's local devices (the survivor path of a shrink —
    no cross-host collectives, no jax.distributed dependency). A
    multi-host epoch builds over the global device view, which
    requires the jax.distributed runtime to already span exactly the
    epoch's hosts: growing or re-pairing live processes is a process
    restart (cheap by design — the AOT cache makes the restart
    warm), not an in-process rewire."""
    import jax

    from ray_tpu import sharding as sharding_lib

    if epoch.num_processes == 1:
        return sharding_lib.get_mesh(devices=jax.local_devices())
    if jax.process_count() != epoch.num_processes:
        raise RuntimeError(
            f"epoch gen={epoch.gen} names {epoch.num_processes} "
            f"hosts but this jax runtime spans "
            f"{jax.process_count()} processes — restart the fleet "
            "at the new geometry (the AOT cache keeps it warm)"
        )
    return sharding_lib.get_mesh(devices=jax.devices())
