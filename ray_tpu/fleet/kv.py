"""ray_tpu.fleet.kv — the KV/rendezvous control plane of the learner
fleet.

Promoted out of ``ray_tpu.parallel.distributed`` (PR 17): the fleet
coordinator, the multi-host tests, and the cluster control plane all
rendezvous through this one service, so it lives with the fleet
subsystem that owns the membership protocol. Plays the reference's L1
GCS roles — KV + rendezvous (``src/ray/gcs/gcs_server/
gcs_kv_manager.cc``), heartbeat liveness (``gcs_heartbeat_manager.h:
33``), and long-poll pubsub (``src/ray/pubsub/publisher.h:298``) —
over plain TCP. ``ray_tpu.parallel.distributed`` re-exports every
public name for back-compat.

Crash tolerance (PR 19, docs/fleet.md "Failure model & leadership"):

- **liveness is monotonic** — heartbeat stamps and expiry run on
  ``time.monotonic()`` server-side, so an NTP step cannot mass-expire
  or immortalize the fleet; wall time survives only in the ``clock``
  op (the fleetview skew handshake IS about wall clocks);
- **fenced leases** — the ``lease`` op grants named leases with a
  monotonically increasing term (terms are persisted, so fencing
  survives a KV restart); a ``put`` carrying a ``term`` older than the
  lease's current term is rejected at the store
  (:class:`StaleTermError` client-side), so a zombie ex-coordinator
  physically cannot split-brain the fleet;
- **retried transport** — every client op routes through a
  :class:`~ray_tpu.resilience.retry.RetryPolicy` (transient
  connect/timeout failures back off and retry under one bounded
  per-op deadline; all ops are idempotent, so blind retry is safe);
  disable with ``RAY_TPU_KV_RETRY=0``;
- **chaos-armable** — the transport consults the fleet fault family
  of :mod:`ray_tpu.resilience.faults` (``kv_drop``/``kv_delay``/
  ``partition_host`` via ``RAY_TPU_FAULTS``) once per attempt, so the
  retry/fencing claims are proven by deterministic injection, not
  hope.
"""

from __future__ import annotations

import json
import os
import pickle
import socket
import socketserver
import threading
import time
from typing import Any, Dict, Optional

KV_RETRY_ENV = "RAY_TPU_KV_RETRY"  # "0" = raw, unretried transport
KV_RETRY_ATTEMPTS_ENV = "RAY_TPU_KV_RETRY_ATTEMPTS"


class StaleTermError(RuntimeError):
    """A lease-fenced ``put`` carried a term older than the store's —
    the writer lost leadership and must stop acting on the fleet."""


def _default_retry_policy():
    """The transport's env-tuned retry schedule (None = disabled).
    Lazy import: ``fleet.kv`` must stay importable without dragging in
    the whole resilience/recovery stack at module load."""
    if os.environ.get(KV_RETRY_ENV, "1").strip().lower() in (
        "0",
        "false",
        "off",
    ):
        return None
    from ray_tpu.resilience.retry import RetryPolicy

    try:
        attempts = int(os.environ.get(KV_RETRY_ATTEMPTS_ENV, 4))
    except ValueError:
        attempts = 4
    return RetryPolicy(
        max_attempts=max(1, attempts),
        timeout_s=None,
        backoff_s=0.05,
        backoff_mult=2.0,
        max_backoff_s=1.0,
        jitter=0.1,
    )


def _request_hmac(token: str, req: Dict) -> str:
    """Deterministic MAC over the request header (sorted-key JSON).
    Requests with a payload carry its sha256 in the header (``body``),
    so the MAC covers the payload bytes too — a captured header cannot
    be reused with a substituted pickle blob. Replay of a complete
    captured request is NOT prevented (no nonce); the token is a
    second wall on top of network isolation, not a wire-security
    protocol."""
    import hashlib
    import hmac as _hmac

    msg = json.dumps(
        {k: v for k, v in req.items() if k != "hmac"},
        sort_keys=True,
    ).encode()
    return _hmac.new(
        token.encode(), msg, hashlib.sha256
    ).hexdigest()


def _body_digest(blob: bytes) -> str:
    import hashlib

    return hashlib.sha256(blob).hexdigest()


def _body_ok(req: Dict, blob: bytes) -> bool:
    import hmac as _hmac

    return _hmac.compare_digest(
        req.get("body", ""), _body_digest(blob)
    )


def _channel_match(channel: str, patterns) -> bool:
    """Exact names, or prefix patterns ending in ``*`` (the reference's
    per-entity key subscriptions vs whole-channel subscriptions,
    ``src/ray/pubsub/publisher.h:298``)."""
    for p in patterns:
        if p.endswith("*"):
            if channel.startswith(p[:-1]):
                return True
        elif channel == p:
            return True
    return False


def _lease_op(store, req: Dict) -> Dict:
    """The ``lease`` op: named leases with monotonically increasing
    terms (the GCS-leadership half of the reference's fault-tolerance
    story, done as fencing tokens instead of an external leader
    elector).

    - ``acquire``: granted when the lease is free, expired, or already
      held by this holder. A grant that isn't a same-holder refresh
      **bumps the term** (and persists it — fencing survives a KV
      restart); a refused acquire reports the current holder and time
      to expiry so a standby knows how long to wait.
    - ``renew``: extends the expiry ONLY for the live holder at the
      current term — an expired or superseded leader's renew comes
      back ``granted: false``, which is how it learns to stop acting.
    - ``release``: drops the holder (term stays — the next acquire
      still bumps past it).
    - ``info``: term/holder/expiry plus the store's fenced-write
      count (the split-brain writes that did NOT happen).

    Liveness/expiry runs on the server's monotonic clock, same as
    heartbeats."""
    action = req.get("action", "info")
    name = req.get("name", "fleet/leader")
    holder = req.get("holder", "")
    ttl = float(req.get("ttl", 10.0))
    now = store._mono()
    with store.lock:
        cur = store.leases.get(name)
        term = store.lease_terms.get(name, 0)
        held = cur is not None and now < cur["expires"]
        if action == "acquire":
            if held and cur["holder"] != holder:
                return {
                    "ok": True,
                    "granted": False,
                    "term": term,
                    "holder": cur["holder"],
                    "expires_in": cur["expires"] - now,
                }
            if not (held and cur["holder"] == holder):
                term += 1
                store.lease_terms[name] = term
                if store.persist is not None:
                    store.persist.put(
                        "lease", name, pickle.dumps({"term": term})
                    )
            store.leases[name] = {
                "holder": holder,
                "expires": now + ttl,
                "ttl": ttl,
            }
            return {
                "ok": True,
                "granted": True,
                "term": term,
                "holder": holder,
            }
        if action == "renew":
            if (
                held
                and cur["holder"] == holder
                and int(req.get("term", -1)) == term
            ):
                cur["expires"] = now + ttl
                return {
                    "ok": True,
                    "granted": True,
                    "term": term,
                    "holder": holder,
                }
            return {
                "ok": True,
                "granted": False,
                "term": term,
                "holder": cur["holder"] if held else None,
            }
        if action == "release":
            if cur is not None and cur["holder"] == holder:
                store.leases.pop(name, None)
            return {"ok": True, "granted": True, "term": term}
        return {
            "ok": True,
            "term": term,
            "holder": cur["holder"] if held else None,
            "expires_in": (cur["expires"] - now) if held else 0.0,
            "fenced_writes": store.fenced_writes,
        }


class _KVHandler(socketserver.StreamRequestHandler):
    def handle(self):
        store = self.server.kv_store  # type: ignore[attr-defined]
        try:
            header = self.rfile.readline()
            if not header:
                return
            req = json.loads(header)
            op = req["op"]
            if store.token is not None:
                # shared-token HMAC gate: values are pickled, so an
                # unauthenticated reachable KV is code execution — the
                # reference's GCS has the same exposure and relies on
                # network isolation; this adds a cheap second wall for
                # multi-host deployments (RAY_TPU_KV_TOKEN)
                import hmac as _hmac

                if not _hmac.compare_digest(
                    req.get("hmac", ""),
                    _request_hmac(store.token, req),
                ):
                    self.wfile.write(
                        b'{"ok": false, "error": "bad hmac"}\n'
                    )
                    return
            if op == "put":
                blob = self.rfile.read(req["len"])
                if store.token is not None and not _body_ok(req, blob):
                    self.wfile.write(
                        b'{"ok": false, "error": "bad body digest"}\n'
                    )
                    return
                term = req.get("term")
                if term is not None:
                    # lease-fenced write: reject at the store when the
                    # writer's term predates the lease's — the one
                    # mechanism that makes a zombie ex-coordinator
                    # harmless no matter what it believes
                    lease_name = req.get("lease", "fleet/leader")
                    with store.lock:
                        cur_term = store.lease_terms.get(lease_name, 0)
                        stale = int(term) < cur_term
                        if stale:
                            store.fenced_writes += 1
                    if stale:
                        self.wfile.write(
                            json.dumps(
                                {
                                    "ok": False,
                                    "error": "stale term",
                                    "fenced": True,
                                    "term": cur_term,
                                }
                            ).encode()
                            + b"\n"
                        )
                        return
                with store.lock:
                    store.data[req["key"]] = blob
                    if store.persist is not None:
                        store.persist.put("kv", req["key"], blob)
                    store.cv.notify_all()
                self.wfile.write(b'{"ok": true}\n')
            elif op == "get":
                deadline = time.monotonic() + req.get("timeout", 30.0)
                with store.lock:
                    while req["key"] not in store.data:
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            break
                        store.cv.wait(remaining)
                    blob = store.data.get(req["key"])
                if blob is None:
                    self.wfile.write(b'{"ok": false}\n')
                else:
                    self.wfile.write(
                        json.dumps({"ok": True, "len": len(blob)}).encode()
                        + b"\n"
                    )
                    self.wfile.write(blob)
            elif op == "subscribe":
                import collections

                with store.lock:
                    existing = store.subs.get(req["sub"])
                    if existing is not None:
                        # re-subscribe (reconnect/retry): update the
                        # channel list, keep the buffered queue
                        existing["channels"] = list(req["channels"])
                    else:
                        store.subs[req["sub"]] = {
                            "channels": list(req["channels"]),
                            "queue": collections.deque(),
                            "dropped": 0,
                        }
                self.wfile.write(b'{"ok": true}\n')
            elif op == "unsubscribe":
                with store.lock:
                    store.subs.pop(req["sub"], None)
                    # wake any in-flight poll for this subscriber so it
                    # returns now instead of at its deadline
                    store.pub_cv.notify_all()
                self.wfile.write(b'{"ok": true}\n')
            elif op == "publish":
                blob = self.rfile.read(req["len"])
                if store.token is not None and not _body_ok(req, blob):
                    self.wfile.write(
                        b'{"ok": false, "error": "bad body digest"}\n'
                    )
                    return
                ch = req["channel"]
                delivered = 0
                with store.lock:
                    for sub in store.subs.values():
                        if _channel_match(ch, sub["channels"]):
                            sub["queue"].append((ch, blob))
                            if len(sub["queue"]) > store.sub_maxlen:
                                sub["queue"].popleft()
                                sub["dropped"] += 1
                            delivered += 1
                    store.pub_cv.notify_all()
                self.wfile.write(
                    json.dumps({"ok": True, "delivered": delivered}).encode()
                    + b"\n"
                )
            elif op == "poll":
                deadline = time.monotonic() + req.get("timeout", 30.0)
                max_msgs = req.get("max", 100)
                with store.lock:
                    sub = store.subs.get(req["sub"])
                    if sub is None:
                        self.wfile.write(
                            b'{"ok": false, "error": "no such subscriber"}\n'
                        )
                        return
                    while (
                        not sub["queue"]
                        and store.subs.get(req["sub"]) is sub
                    ):
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            break
                        store.pub_cv.wait(remaining)
                    batch = []
                    while sub["queue"] and len(batch) < max_msgs:
                        batch.append(sub["queue"].popleft())
                    dropped, sub["dropped"] = sub["dropped"], 0
                header = {
                    "ok": True,
                    "channels": [c for c, _ in batch],
                    "lens": [len(b) for _, b in batch],
                    "dropped": dropped,
                }
                self.wfile.write(json.dumps(header).encode() + b"\n")
                for _, b in batch:
                    self.wfile.write(b)
            elif op == "heartbeat":
                # liveness runs on the MONOTONIC clock (store._mono):
                # an NTP step of the wall clock must not mass-expire
                # (step forward) or immortalize (step back) the fleet
                with store.lock:
                    store.heartbeats[req["node"]] = store._mono()
                self.wfile.write(b'{"ok": true}\n')
            elif op == "clock":
                # the fleet's reference clock: the KV server runs on
                # the coordinator host, so this one stamp is what the
                # fleetview skew handshake corrects every host toward.
                # Wall clock ON PURPOSE — skew correction is about
                # wall clocks; liveness never touches this.
                self.wfile.write(
                    json.dumps(
                        {"ok": True, "ts": store._wall()}
                    ).encode()
                    + b"\n"
                )
            elif op == "nodes":
                horizon = req.get("horizon", 30.0)
                now = store._mono()
                with store.lock:
                    alive = {
                        n: now - t
                        for n, t in store.heartbeats.items()
                        if now - t <= horizon
                    }
                self.wfile.write(
                    json.dumps({"ok": True, "alive": alive}).encode()
                    + b"\n"
                )
            elif op == "lease":
                self.wfile.write(
                    json.dumps(_lease_op(store, req)).encode() + b"\n"
                )
        except Exception:
            try:
                self.wfile.write(b'{"ok": false}\n')
            except Exception:
                pass


class KVServer:
    """Blocking-get KV + heartbeat service, one per cluster (runs on the
    coordinator host).

    Trust model: values are pickled, so the service must only be
    reachable from cluster hosts (same as the reference's GCS, which is
    also unauthenticated by default). The default bind is loopback;
    pass host="0.0.0.0" explicitly for a real multi-host cluster and
    keep the port firewalled to the cluster network.

    Durability: ``persist_path`` (or ``RAY_TPU_KV_PERSIST``) backs the
    KV table with a durable store client — a restarted coordinator
    reloads every key, so driver death no longer loses cluster KV state
    (reference: GCS fault tolerance via external Redis,
    ``gcs/store_client/redis_store_client.h:27``,
    ``test_gcs_fault_tolerance.py``). Heartbeats stay volatile by
    design — liveness must be re-proven after a restart. Lease TERMS
    are durable (fencing must survive a KV restart: a zombie's stale
    term stays stale); lease holders/expiries are volatile — after a
    restart leadership is re-acquired, never assumed."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        persist_path: Optional[str] = None,
        token: Optional[str] = None,
    ):
        from ray_tpu.core.store_client import make_store_client

        # shared-secret request authentication (off by default on
        # loopback; set for any non-loopback bind)
        self.token = token or os.environ.get("RAY_TPU_KV_TOKEN")
        persist_path = persist_path or os.environ.get(
            "RAY_TPU_KV_PERSIST"
        )
        self.persist = (
            make_store_client(persist_path) if persist_path else None
        )
        self.data: Dict[str, bytes] = (
            dict(self.persist.all("kv")) if self.persist else {}
        )
        # liveness/lease clocks, injectable so tests can STEP the wall
        # clock and prove liveness doesn't care: _mono owns heartbeat
        # stamps, expiry sweeps, and lease TTLs; _wall exists only for
        # the `clock` op (the fleetview skew handshake)
        self._mono = time.monotonic
        self._wall = time.time
        self.heartbeats: Dict[str, float] = {}  # node -> _mono() stamp
        # named leases: holder/expiry are volatile, TERMS are durable
        # (reloaded below) — a restarted KV must still fence the old
        # leader's writes even though leadership itself lapsed
        self.leases: Dict[str, Dict[str, Any]] = {}
        self.lease_terms: Dict[str, int] = {}
        self.fenced_writes = 0
        if self.persist is not None:
            for name, blob in self.persist.all("lease").items():
                try:
                    self.lease_terms[name] = int(
                        pickle.loads(blob)["term"]
                    )
                except Exception:
                    pass
        # pubsub fan-out: subscriber id -> {channels, queue, dropped}.
        # Queues are bounded (drop-oldest, counted) so one stalled
        # subscriber cannot hold the coordinator's memory hostage —
        # the reference's publisher has the same bounded-buffer policy
        # (src/ray/pubsub/publisher.h:298 max buffered bytes).
        self.subs: Dict[str, Dict] = {}
        self.sub_maxlen = int(
            os.environ.get("RAY_TPU_PUBSUB_MAXLEN", 1000)
        )
        self.lock = threading.Lock()
        self.cv = threading.Condition(self.lock)
        self.pub_cv = threading.Condition(self.lock)
        class _Server(socketserver.ThreadingTCPServer):
            # a restarted coordinator must be able to rebind its
            # well-known port while old connections sit in TIME_WAIT
            allow_reuse_address = True

        self._server = _Server(
            (host, port), _KVHandler, bind_and_activate=True
        )
        self._server.daemon_threads = True
        self._server.kv_store = self  # type: ignore[attr-defined]
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        self._thread.start()

    @property
    def address(self) -> str:
        return f"{socket.gethostname()}:{self.port}"

    def shutdown(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self.persist is not None:
            self.persist.close()


class KVClient:
    """Client for KVServer (usable from any host).

    Transport is retried by default: transient connect/timeout
    failures back off and re-attempt on the
    :class:`~ray_tpu.resilience.retry.RetryPolicy` schedule under one
    bounded per-op deadline (every KV op is idempotent — last-write-
    wins puts, keyed barrier/drain records — so blind retry is safe).
    ``node`` is this client's host identity, used for retry/reconnect
    metric labels and ``partition_host`` chaos matching."""

    def __init__(
        self,
        address: str,
        token: Optional[str] = None,
        retry: Any = None,
        node: Optional[str] = None,
    ):
        host, port = address.rsplit(":", 1)
        self.host, self.port = host, int(port)
        self.token = token or os.environ.get("RAY_TPU_KV_TOKEN")
        self.node = node or socket.gethostname()
        # retry: None = env default schedule, False = raw transport,
        # or an explicit RetryPolicy
        if retry is None:
            retry = _default_retry_policy()
        elif retry is False:
            retry = None
        self._retry = retry
        from ray_tpu.resilience.faults import kv_injector

        self._chaos = kv_injector()

    # ray-tpu: kv-retry-wrapper
    def _roundtrip(self, req: Dict, payload: bytes = b"") -> Any:
        """The retried transport (the ONE sanctioned path to the wire
        — RTA013): route each attempt through the policy with a
        deadline of the op timeout plus one connect window, so a
        control-plane thread's op costs O(op timeout) even across a KV
        restart, never O(attempts x timeout) and never forever."""
        if self._retry is None:
            return self._roundtrip_once(req, payload)
        op = req["op"]

        def _on_retry(attempt, err):
            from ray_tpu.telemetry import metrics as _tm

            try:
                _tm.inc_kv_retries(self.node, op)
            except Exception:
                pass

        deadline = float(req.get("timeout", 30.0)) + 60.0
        return self._retry.call(
            lambda: self._roundtrip_once(req, payload),
            retry_on=(ConnectionError, TimeoutError, OSError),
            on_retry=_on_retry,
            deadline_s=deadline,
        )

    # ray-tpu: kv-retry-wrapper
    def _roundtrip_once(self, req: Dict, payload: bytes = b"") -> Any:
        """One raw socket attempt. Only the retried wrapper above may
        call this (RTA013) — a bare attempt on a control-plane thread
        dies on the first KV restart window."""
        if self._chaos is not None:
            self._chaos.on_kv_op(self.node, req["op"])
        if self.token is not None:
            if payload:
                req = dict(req, body=_body_digest(payload))
            req = dict(req, hmac=_request_hmac(self.token, req))
        # socket deadline must outlast a server-side blocking get, or
        # long waits surface as TimeoutError instead of KeyError
        sock_timeout = float(req.get("timeout", 30.0)) + 30.0
        with socket.create_connection(
            (self.host, self.port), timeout=sock_timeout
        ) as s:
            f = s.makefile("rwb")
            f.write(json.dumps(req).encode() + b"\n")
            if payload:
                f.write(payload)
            f.flush()
            resp = json.loads(f.readline())
            if req["op"] == "get" and resp.get("ok"):
                resp["blob"] = f.read(resp["len"])
            elif req["op"] == "poll" and resp.get("ok"):
                resp["blobs"] = [f.read(n) for n in resp["lens"]]
            return resp

    def put(
        self,
        key: str,
        value: Any,
        term: Optional[int] = None,
        lease: Optional[str] = None,
        holder: Optional[str] = None,
    ) -> None:
        """Last-write-wins put. With ``term`` the write is LEASE-
        FENCED: the server rejects it (:class:`StaleTermError`) when
        the term predates the named lease's current term — the
        coordinator passes its term on every write so a deposed
        leader's writes die at the store."""
        blob = pickle.dumps(value)
        req: Dict[str, Any] = {
            "op": "put",
            "key": key,
            "len": len(blob),
        }
        if term is not None:
            req["term"] = int(term)
            req["holder"] = holder or self.node
            if lease is not None:
                req["lease"] = lease
        resp = self._roundtrip(req, blob)
        if resp.get("fenced"):
            from ray_tpu.telemetry import metrics as _tm

            try:
                _tm.inc_fleet_fenced_write(holder or self.node)
            except Exception:
                pass
            raise StaleTermError(
                f"fenced write to {key!r}: term {term} predates "
                f"store term {resp.get('term')} — leadership lost"
            )

    def get(self, key: str, timeout: float = 30.0) -> Any:
        resp = self._roundtrip(
            {"op": "get", "key": key, "timeout": timeout}
        )
        if not resp.get("ok"):
            raise KeyError(key)
        return pickle.loads(resp["blob"])

    def subscribe(self, sub: str, channels) -> None:
        """Register a subscriber for exact channels or ``prefix*``
        patterns; messages buffer server-side until polled."""
        self._roundtrip(
            {"op": "subscribe", "sub": sub, "channels": list(channels)}
        )

    def unsubscribe(self, sub: str) -> None:
        self._roundtrip({"op": "unsubscribe", "sub": sub})

    def publish(self, channel: str, message: Any) -> int:
        """Fan a message out to every matching subscriber's buffer;
        returns the number of subscribers it reached."""
        blob = pickle.dumps(message)
        return self._roundtrip(
            {"op": "publish", "channel": channel, "len": len(blob)},
            blob,
        ).get("delivered", 0)

    def poll(self, sub: str, timeout: float = 30.0, max_msgs: int = 100):
        """Long-poll a batch of buffered messages (the reference's
        long-poll batch pubsub, ``src/ray/pubsub/publisher.h:298``).
        Returns (messages, dropped) where messages is a list of
        (channel, value) and dropped counts overflow losses since the
        last poll."""
        resp = self._roundtrip(
            {"op": "poll", "sub": sub, "timeout": timeout, "max": max_msgs}
        )
        if not resp.get("ok"):
            raise KeyError(resp.get("error", sub))
        msgs = [
            (c, pickle.loads(b))
            for c, b in zip(resp["channels"], resp["blobs"])
        ]
        return msgs, resp.get("dropped", 0)

    def heartbeat(self, node: str) -> None:
        self._roundtrip({"op": "heartbeat", "node": node})

    def server_clock(self) -> float:
        """One ``time.time()`` stamp read off the KV server (the
        coordinator host's clock) — the reference frame of the
        fleetview skew correction."""
        resp = self._roundtrip({"op": "clock"})
        if not resp.get("ok"):
            raise RuntimeError("kv clock op rejected")
        return float(resp["ts"])

    def alive_nodes(self, horizon: float = 30.0) -> Dict[str, float]:
        return self._roundtrip({"op": "nodes", "horizon": horizon})[
            "alive"
        ]

    # -- fenced leases (see _lease_op for the state machine) -----------

    def lease_acquire(
        self, name: str, holder: str, ttl: float = 10.0
    ) -> Dict[str, Any]:
        """Try to take the named lease. Returns the op's full verdict:
        ``granted`` plus ``term`` on success; ``holder``/``expires_in``
        of the incumbent on refusal (so a standby knows how long to
        wait before re-probing)."""
        return self._roundtrip(
            {
                "op": "lease",
                "action": "acquire",
                "name": name,
                "holder": holder,
                "ttl": ttl,
            }
        )

    def lease_renew(
        self, name: str, holder: str, term: int, ttl: float = 10.0
    ) -> bool:
        """Extend the lease — granted only for the live holder at the
        current term. False means leadership is gone (expired or
        superseded): stop acting."""
        return bool(
            self._roundtrip(
                {
                    "op": "lease",
                    "action": "renew",
                    "name": name,
                    "holder": holder,
                    "term": int(term),
                    "ttl": ttl,
                }
            ).get("granted")
        )

    def lease_release(self, name: str, holder: str) -> None:
        """Voluntarily drop the lease (clean shutdown): the next
        acquire is granted immediately instead of waiting out the TTL.
        The term survives — release never un-fences."""
        self._roundtrip(
            {
                "op": "lease",
                "action": "release",
                "name": name,
                "holder": holder,
            }
        )

    def lease_info(self, name: str) -> Dict[str, Any]:
        """Current term/holder/expiry plus the store's fenced-write
        count (how many split-brain writes did NOT happen)."""
        return self._roundtrip(
            {"op": "lease", "action": "info", "name": name}
        )


class Subscriber:
    """Background long-poll loop dispatching published messages to a
    callback (the reference's subscriber-side long-poll client,
    ``src/ray/pubsub/subscriber.h``). ``callback(channel, message)``
    runs on the poll thread; exceptions are swallowed so one bad
    handler doesn't kill the stream.

    Survives a KV outage: transport failures back off exponentially
    (0.1 s → 5 s) and every recovery — a successful re-subscribe after
    a KV restart, or the first successful poll after transport
    failures — is counted in ``reconnects`` and surfaced as
    ``ray_tpu_kv_reconnects_total{host}``. The loop never goes
    permanently quiet."""

    def __init__(
        self,
        client: KVClient,
        channels,
        callback,
        sub_id: Optional[str] = None,
        poll_timeout: float = 5.0,
        host: Optional[str] = None,
    ):
        import uuid

        self.client = client
        self.sub_id = sub_id or f"sub_{uuid.uuid4().hex[:8]}"
        self.callback = callback
        self.poll_timeout = poll_timeout
        self.host = host or client.node
        self.dropped = 0
        self.reconnects = 0
        self.failures = 0
        self.last_error: Optional[str] = None
        self._channels = list(channels)
        client.subscribe(self.sub_id, self._channels)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _mark_reconnect(self):
        self.reconnects += 1
        from ray_tpu.telemetry import metrics as _tm

        try:
            _tm.inc_kv_reconnects(self.host)
        except Exception:
            pass

    # ray-tpu: thread=kv-sub
    def _run(self):
        backoff = 0.1
        degraded = False  # saw a transport failure since last success
        while not self._stop.is_set():
            try:
                msgs, dropped = self.client.poll(
                    self.sub_id, timeout=self.poll_timeout
                )
                self.dropped += dropped
                if degraded:
                    degraded = False
                    self._mark_reconnect()
                backoff = 0.1
            except KeyError as e:
                if self._stop.is_set():
                    return
                if "no such subscriber" in str(e):
                    # server lost our registration (KV restart — the
                    # KV table persists but subscriptions are
                    # volatile): re-subscribe and keep polling
                    try:
                        self.client.subscribe(self.sub_id, self._channels)
                        self._mark_reconnect()
                        degraded = False
                        backoff = 0.1
                    except Exception:
                        time.sleep(min(backoff, 5.0))
                        backoff = min(backoff * 2.0, 5.0)
                else:
                    # a different rejection (e.g. token mismatch) will
                    # not heal by retrying fast — record it so the
                    # owner can see why nothing is arriving
                    self.last_error = str(e)
                    time.sleep(1.0)
                continue
            except Exception as e:
                # transient KV outage (restart window, partition): log
                # the error, back off, and KEEP polling — a control-
                # plane subscriber that records one failure and goes
                # quiet turns a 2-second KV blip into a deaf fleet
                if self._stop.is_set():
                    return
                self.last_error = str(e)
                self.failures += 1
                degraded = True
                time.sleep(min(backoff, 5.0))
                backoff = min(backoff * 2.0, 5.0)
                continue
            for ch, msg in msgs:
                try:
                    self.callback(ch, msg)
                except Exception:
                    pass

    def stop(self):
        self._stop.set()
        try:
            self.client.unsubscribe(self.sub_id)
        except Exception:
            pass
        self._thread.join(timeout=self.poll_timeout + 1.0)


class HeartbeatReporter:
    """Background liveness pings (the gcs_heartbeat_manager role).

    Each ping doubles as a transport-health probe: the measured KV
    round trip lands in ``ray_tpu_kv_rtt_seconds{host}`` (readable via
    ``last_rtt_s`` too), which the fleetview exporter publishes with
    the rest of the host's snapshot (docs/observability.md).

    Outage accounting: ``seconds_since_ok()`` is the monotonic age of
    the last ping the KV actually acknowledged — the signal
    ``HostAgent.self_fenced`` compares against the liveness horizon to
    decide the host may already look dead to the coordinator.
    Recoveries count into ``reconnects`` /
    ``ray_tpu_kv_reconnects_total{host}``."""

    def __init__(self, client: KVClient, node: str, interval: float = 5.0):
        self.client = client
        self.node = node
        self.interval = interval
        self.last_rtt_s: Optional[float] = None
        self.failures = 0
        self.reconnects = 0
        self.last_error: Optional[str] = None
        # start "ok": the agent just talked to KV to construct itself
        self._last_ok_mono = time.monotonic()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def seconds_since_ok(self) -> float:
        """Monotonic seconds since KV last acknowledged a ping."""
        return time.monotonic() - self._last_ok_mono

    # ray-tpu: thread=kv-heartbeat
    def _run(self):
        degraded = False
        while not self._stop.wait(self.interval):
            try:
                t0 = time.monotonic()
                self.client.heartbeat(self.node)
                self.last_rtt_s = time.monotonic() - t0
                self._last_ok_mono = time.monotonic()
                if degraded:
                    degraded = False
                    self.reconnects += 1
                    from ray_tpu.telemetry import metrics as _tm

                    try:
                        _tm.inc_kv_reconnects(self.node)
                    except Exception:
                        pass
                from ray_tpu.telemetry import metrics as _tm

                _tm.set_kv_rtt(self.node, self.last_rtt_s)
            except Exception as e:
                # KV unreachable past the retry schedule: keep the
                # loop alive (the next interval re-probes) and let
                # seconds_since_ok() grow — self-fencing reads it
                self.failures += 1
                self.last_error = str(e)
                degraded = True

    def stop(self):
        self._stop.set()
