"""ray_tpu.fleet.kv — the KV/rendezvous control plane of the learner
fleet.

Promoted out of ``ray_tpu.parallel.distributed`` (PR 17): the fleet
coordinator, the multi-host tests, and the cluster control plane all
rendezvous through this one service, so it lives with the fleet
subsystem that owns the membership protocol. Plays the reference's L1
GCS roles — KV + rendezvous (``src/ray/gcs/gcs_server/
gcs_kv_manager.cc``), heartbeat liveness (``gcs_heartbeat_manager.h:
33``), and long-poll pubsub (``src/ray/pubsub/publisher.h:298``) —
over plain TCP. ``ray_tpu.parallel.distributed`` re-exports every
public name for back-compat.
"""

from __future__ import annotations

import json
import os
import pickle
import socket
import socketserver
import threading
import time
from typing import Any, Dict, Optional


def _request_hmac(token: str, req: Dict) -> str:
    """Deterministic MAC over the request header (sorted-key JSON).
    Requests with a payload carry its sha256 in the header (``body``),
    so the MAC covers the payload bytes too — a captured header cannot
    be reused with a substituted pickle blob. Replay of a complete
    captured request is NOT prevented (no nonce); the token is a
    second wall on top of network isolation, not a wire-security
    protocol."""
    import hashlib
    import hmac as _hmac

    msg = json.dumps(
        {k: v for k, v in req.items() if k != "hmac"},
        sort_keys=True,
    ).encode()
    return _hmac.new(
        token.encode(), msg, hashlib.sha256
    ).hexdigest()


def _body_digest(blob: bytes) -> str:
    import hashlib

    return hashlib.sha256(blob).hexdigest()


def _body_ok(req: Dict, blob: bytes) -> bool:
    import hmac as _hmac

    return _hmac.compare_digest(
        req.get("body", ""), _body_digest(blob)
    )


def _channel_match(channel: str, patterns) -> bool:
    """Exact names, or prefix patterns ending in ``*`` (the reference's
    per-entity key subscriptions vs whole-channel subscriptions,
    ``src/ray/pubsub/publisher.h:298``)."""
    for p in patterns:
        if p.endswith("*"):
            if channel.startswith(p[:-1]):
                return True
        elif channel == p:
            return True
    return False


class _KVHandler(socketserver.StreamRequestHandler):
    def handle(self):
        store = self.server.kv_store  # type: ignore[attr-defined]
        try:
            header = self.rfile.readline()
            if not header:
                return
            req = json.loads(header)
            op = req["op"]
            if store.token is not None:
                # shared-token HMAC gate: values are pickled, so an
                # unauthenticated reachable KV is code execution — the
                # reference's GCS has the same exposure and relies on
                # network isolation; this adds a cheap second wall for
                # multi-host deployments (RAY_TPU_KV_TOKEN)
                import hmac as _hmac

                if not _hmac.compare_digest(
                    req.get("hmac", ""),
                    _request_hmac(store.token, req),
                ):
                    self.wfile.write(
                        b'{"ok": false, "error": "bad hmac"}\n'
                    )
                    return
            if op == "put":
                blob = self.rfile.read(req["len"])
                if store.token is not None and not _body_ok(req, blob):
                    self.wfile.write(
                        b'{"ok": false, "error": "bad body digest"}\n'
                    )
                    return
                with store.lock:
                    store.data[req["key"]] = blob
                    if store.persist is not None:
                        store.persist.put("kv", req["key"], blob)
                    store.cv.notify_all()
                self.wfile.write(b'{"ok": true}\n')
            elif op == "get":
                deadline = time.monotonic() + req.get("timeout", 30.0)
                with store.lock:
                    while req["key"] not in store.data:
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            break
                        store.cv.wait(remaining)
                    blob = store.data.get(req["key"])
                if blob is None:
                    self.wfile.write(b'{"ok": false}\n')
                else:
                    self.wfile.write(
                        json.dumps({"ok": True, "len": len(blob)}).encode()
                        + b"\n"
                    )
                    self.wfile.write(blob)
            elif op == "subscribe":
                import collections

                with store.lock:
                    existing = store.subs.get(req["sub"])
                    if existing is not None:
                        # re-subscribe (reconnect/retry): update the
                        # channel list, keep the buffered queue
                        existing["channels"] = list(req["channels"])
                    else:
                        store.subs[req["sub"]] = {
                            "channels": list(req["channels"]),
                            "queue": collections.deque(),
                            "dropped": 0,
                        }
                self.wfile.write(b'{"ok": true}\n')
            elif op == "unsubscribe":
                with store.lock:
                    store.subs.pop(req["sub"], None)
                    # wake any in-flight poll for this subscriber so it
                    # returns now instead of at its deadline
                    store.pub_cv.notify_all()
                self.wfile.write(b'{"ok": true}\n')
            elif op == "publish":
                blob = self.rfile.read(req["len"])
                if store.token is not None and not _body_ok(req, blob):
                    self.wfile.write(
                        b'{"ok": false, "error": "bad body digest"}\n'
                    )
                    return
                ch = req["channel"]
                delivered = 0
                with store.lock:
                    for sub in store.subs.values():
                        if _channel_match(ch, sub["channels"]):
                            sub["queue"].append((ch, blob))
                            if len(sub["queue"]) > store.sub_maxlen:
                                sub["queue"].popleft()
                                sub["dropped"] += 1
                            delivered += 1
                    store.pub_cv.notify_all()
                self.wfile.write(
                    json.dumps({"ok": True, "delivered": delivered}).encode()
                    + b"\n"
                )
            elif op == "poll":
                deadline = time.monotonic() + req.get("timeout", 30.0)
                max_msgs = req.get("max", 100)
                with store.lock:
                    sub = store.subs.get(req["sub"])
                    if sub is None:
                        self.wfile.write(
                            b'{"ok": false, "error": "no such subscriber"}\n'
                        )
                        return
                    while (
                        not sub["queue"]
                        and store.subs.get(req["sub"]) is sub
                    ):
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            break
                        store.pub_cv.wait(remaining)
                    batch = []
                    while sub["queue"] and len(batch) < max_msgs:
                        batch.append(sub["queue"].popleft())
                    dropped, sub["dropped"] = sub["dropped"], 0
                header = {
                    "ok": True,
                    "channels": [c for c, _ in batch],
                    "lens": [len(b) for _, b in batch],
                    "dropped": dropped,
                }
                self.wfile.write(json.dumps(header).encode() + b"\n")
                for _, b in batch:
                    self.wfile.write(b)
            elif op == "heartbeat":
                with store.lock:
                    store.heartbeats[req["node"]] = time.time()
                self.wfile.write(b'{"ok": true}\n')
            elif op == "clock":
                # the fleet's reference clock: the KV server runs on
                # the coordinator host, so this one stamp is what the
                # fleetview skew handshake corrects every host toward
                self.wfile.write(
                    json.dumps(
                        {"ok": True, "ts": time.time()}
                    ).encode()
                    + b"\n"
                )
            elif op == "nodes":
                horizon = req.get("horizon", 30.0)
                now = time.time()
                with store.lock:
                    alive = {
                        n: now - t
                        for n, t in store.heartbeats.items()
                        if now - t <= horizon
                    }
                self.wfile.write(
                    json.dumps({"ok": True, "alive": alive}).encode()
                    + b"\n"
                )
        except Exception:
            try:
                self.wfile.write(b'{"ok": false}\n')
            except Exception:
                pass


class KVServer:
    """Blocking-get KV + heartbeat service, one per cluster (runs on the
    coordinator host).

    Trust model: values are pickled, so the service must only be
    reachable from cluster hosts (same as the reference's GCS, which is
    also unauthenticated by default). The default bind is loopback;
    pass host="0.0.0.0" explicitly for a real multi-host cluster and
    keep the port firewalled to the cluster network.

    Durability: ``persist_path`` (or ``RAY_TPU_KV_PERSIST``) backs the
    KV table with a durable store client — a restarted coordinator
    reloads every key, so driver death no longer loses cluster KV state
    (reference: GCS fault tolerance via external Redis,
    ``gcs/store_client/redis_store_client.h:27``,
    ``test_gcs_fault_tolerance.py``). Heartbeats stay volatile by
    design — liveness must be re-proven after a restart."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        persist_path: Optional[str] = None,
        token: Optional[str] = None,
    ):
        from ray_tpu.core.store_client import make_store_client

        # shared-secret request authentication (off by default on
        # loopback; set for any non-loopback bind)
        self.token = token or os.environ.get("RAY_TPU_KV_TOKEN")
        persist_path = persist_path or os.environ.get(
            "RAY_TPU_KV_PERSIST"
        )
        self.persist = (
            make_store_client(persist_path) if persist_path else None
        )
        self.data: Dict[str, bytes] = (
            dict(self.persist.all("kv")) if self.persist else {}
        )
        self.heartbeats: Dict[str, float] = {}
        # pubsub fan-out: subscriber id -> {channels, queue, dropped}.
        # Queues are bounded (drop-oldest, counted) so one stalled
        # subscriber cannot hold the coordinator's memory hostage —
        # the reference's publisher has the same bounded-buffer policy
        # (src/ray/pubsub/publisher.h:298 max buffered bytes).
        self.subs: Dict[str, Dict] = {}
        self.sub_maxlen = int(
            os.environ.get("RAY_TPU_PUBSUB_MAXLEN", 1000)
        )
        self.lock = threading.Lock()
        self.cv = threading.Condition(self.lock)
        self.pub_cv = threading.Condition(self.lock)
        class _Server(socketserver.ThreadingTCPServer):
            # a restarted coordinator must be able to rebind its
            # well-known port while old connections sit in TIME_WAIT
            allow_reuse_address = True

        self._server = _Server(
            (host, port), _KVHandler, bind_and_activate=True
        )
        self._server.daemon_threads = True
        self._server.kv_store = self  # type: ignore[attr-defined]
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        self._thread.start()

    @property
    def address(self) -> str:
        return f"{socket.gethostname()}:{self.port}"

    def shutdown(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self.persist is not None:
            self.persist.close()


class KVClient:
    """Client for KVServer (usable from any host)."""

    def __init__(self, address: str, token: Optional[str] = None):
        host, port = address.rsplit(":", 1)
        self.host, self.port = host, int(port)
        self.token = token or os.environ.get("RAY_TPU_KV_TOKEN")

    def _roundtrip(self, req: Dict, payload: bytes = b"") -> Any:
        if self.token is not None:
            if payload:
                req = dict(req, body=_body_digest(payload))
            req = dict(req, hmac=_request_hmac(self.token, req))
        # socket deadline must outlast a server-side blocking get, or
        # long waits surface as TimeoutError instead of KeyError
        sock_timeout = float(req.get("timeout", 30.0)) + 30.0
        with socket.create_connection(
            (self.host, self.port), timeout=sock_timeout
        ) as s:
            f = s.makefile("rwb")
            f.write(json.dumps(req).encode() + b"\n")
            if payload:
                f.write(payload)
            f.flush()
            resp = json.loads(f.readline())
            if req["op"] == "get" and resp.get("ok"):
                resp["blob"] = f.read(resp["len"])
            elif req["op"] == "poll" and resp.get("ok"):
                resp["blobs"] = [f.read(n) for n in resp["lens"]]
            return resp

    def put(self, key: str, value: Any) -> None:
        blob = pickle.dumps(value)
        self._roundtrip(
            {"op": "put", "key": key, "len": len(blob)}, blob
        )

    def get(self, key: str, timeout: float = 30.0) -> Any:
        resp = self._roundtrip(
            {"op": "get", "key": key, "timeout": timeout}
        )
        if not resp.get("ok"):
            raise KeyError(key)
        return pickle.loads(resp["blob"])

    def subscribe(self, sub: str, channels) -> None:
        """Register a subscriber for exact channels or ``prefix*``
        patterns; messages buffer server-side until polled."""
        self._roundtrip(
            {"op": "subscribe", "sub": sub, "channels": list(channels)}
        )

    def unsubscribe(self, sub: str) -> None:
        self._roundtrip({"op": "unsubscribe", "sub": sub})

    def publish(self, channel: str, message: Any) -> int:
        """Fan a message out to every matching subscriber's buffer;
        returns the number of subscribers it reached."""
        blob = pickle.dumps(message)
        return self._roundtrip(
            {"op": "publish", "channel": channel, "len": len(blob)},
            blob,
        ).get("delivered", 0)

    def poll(self, sub: str, timeout: float = 30.0, max_msgs: int = 100):
        """Long-poll a batch of buffered messages (the reference's
        long-poll batch pubsub, ``src/ray/pubsub/publisher.h:298``).
        Returns (messages, dropped) where messages is a list of
        (channel, value) and dropped counts overflow losses since the
        last poll."""
        resp = self._roundtrip(
            {"op": "poll", "sub": sub, "timeout": timeout, "max": max_msgs}
        )
        if not resp.get("ok"):
            raise KeyError(resp.get("error", sub))
        msgs = [
            (c, pickle.loads(b))
            for c, b in zip(resp["channels"], resp["blobs"])
        ]
        return msgs, resp.get("dropped", 0)

    def heartbeat(self, node: str) -> None:
        self._roundtrip({"op": "heartbeat", "node": node})

    def server_clock(self) -> float:
        """One ``time.time()`` stamp read off the KV server (the
        coordinator host's clock) — the reference frame of the
        fleetview skew correction."""
        resp = self._roundtrip({"op": "clock"})
        if not resp.get("ok"):
            raise RuntimeError("kv clock op rejected")
        return float(resp["ts"])

    def alive_nodes(self, horizon: float = 30.0) -> Dict[str, float]:
        return self._roundtrip({"op": "nodes", "horizon": horizon})[
            "alive"
        ]


class Subscriber:
    """Background long-poll loop dispatching published messages to a
    callback (the reference's subscriber-side long-poll client,
    ``src/ray/pubsub/subscriber.h``). ``callback(channel, message)``
    runs on the poll thread; exceptions are swallowed so one bad
    handler doesn't kill the stream."""

    def __init__(
        self,
        client: KVClient,
        channels,
        callback,
        sub_id: Optional[str] = None,
        poll_timeout: float = 5.0,
    ):
        import uuid

        self.client = client
        self.sub_id = sub_id or f"sub_{uuid.uuid4().hex[:8]}"
        self.callback = callback
        self.poll_timeout = poll_timeout
        self.dropped = 0
        self.last_error: Optional[str] = None
        self._channels = list(channels)
        client.subscribe(self.sub_id, self._channels)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        while not self._stop.is_set():
            try:
                msgs, dropped = self.client.poll(
                    self.sub_id, timeout=self.poll_timeout
                )
                self.dropped += dropped
            except KeyError as e:
                if self._stop.is_set():
                    return
                if "no such subscriber" in str(e):
                    # server lost our registration (KV restart — the
                    # KV table persists but subscriptions are
                    # volatile): re-subscribe and keep polling
                    try:
                        self.client.subscribe(self.sub_id, self._channels)
                    except Exception:
                        time.sleep(0.2)
                else:
                    # a different rejection (e.g. token mismatch) will
                    # not heal by retrying fast — record it so the
                    # owner can see why nothing is arriving
                    self.last_error = str(e)
                    time.sleep(1.0)
                continue
            except Exception as e:
                if self._stop.is_set():
                    return
                self.last_error = str(e)
                time.sleep(0.2)
                continue
            for ch, msg in msgs:
                try:
                    self.callback(ch, msg)
                except Exception:
                    pass

    def stop(self):
        self._stop.set()
        try:
            self.client.unsubscribe(self.sub_id)
        except Exception:
            pass
        self._thread.join(timeout=self.poll_timeout + 1.0)


class HeartbeatReporter:
    """Background liveness pings (the gcs_heartbeat_manager role).

    Each ping doubles as a transport-health probe: the measured KV
    round trip lands in ``ray_tpu_kv_rtt_seconds{host}`` (readable via
    ``last_rtt_s`` too), which the fleetview exporter publishes with
    the rest of the host's snapshot (docs/observability.md)."""

    def __init__(self, client: KVClient, node: str, interval: float = 5.0):
        self.client = client
        self.node = node
        self.interval = interval
        self.last_rtt_s: Optional[float] = None
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        while not self._stop.wait(self.interval):
            try:
                t0 = time.monotonic()
                self.client.heartbeat(self.node)
                self.last_rtt_s = time.monotonic() - t0
                from ray_tpu.telemetry import metrics as _tm

                _tm.set_kv_rtt(self.node, self.last_rtt_s)
            except Exception:
                pass

    def stop(self):
        self._stop.set()
