"""Per-task/actor runtime environments (env_vars, working_dir,
py_modules).

Counterpart of the reference's ``python/ray/_private/runtime_env/``
plugins (``working_dir.py``, ``py_modules.py``, env-var injection) with
its URI-cache behavior: directories are zipped once driver-side,
content-addressed by hash, shipped with the task/actor spec, and
extracted exactly once per worker host into a shared cache directory —
repeat uses hit the cache (the reference's
``_private/runtime_env/uri_cache.py`` role).

Scope vs the reference: conda/pip/container provisioning is out — this
image is sealed (no package installs), and the TPU-first posture is
one prebuilt environment per host. The seam is the same dict schema,
so a provisioning plugin can slot in where ``_PACKERS`` dispatches.

Supported keys::

    {"env_vars": {"K": "V"},
     "working_dir": "/path/to/dir",   # zipped, extracted, chdir'd
     "py_modules": ["/path/to/pkg"]}  # zipped, extracted, sys.path

Workers apply env_vars around each task/actor-init (actor processes
are dedicated, so their env simply persists); extracted paths persist
for the worker's lifetime.
"""

from __future__ import annotations

import hashlib
import io
import os
import sys
import tempfile
import zipfile
from typing import Any, Dict, List, Optional, Tuple

# one entry PER PATH (latest content only): iterative edits of a big
# working_dir must not accumulate stale archive copies in the driver
_ZIP_CACHE: Dict[str, Tuple[Tuple[float, int], str, bytes]] = {}

_MAX_ARCHIVE_BYTES = 256 * 1024 * 1024


def _zip_dir(path: str) -> Tuple[str, bytes]:
    """(content_hash, zip_bytes) for a directory; cached by
    (realpath, latest_mtime) so repeat submissions don't re-zip."""
    path = os.path.realpath(path)
    latest = os.path.getmtime(path)
    total = 0
    for root, _, files in os.walk(path):
        for f in files:
            try:
                st = os.stat(os.path.join(root, f))
                latest = max(latest, st.st_mtime)
                total += st.st_size
            except OSError:
                pass
    # size rides the key because filesystem mtime granularity can
    # swallow rapid successive edits
    stamp = (latest, total)
    hit = _ZIP_CACHE.get(path)
    if hit is not None and hit[0] == stamp:
        return hit[1], hit[2]
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for root, _, files in os.walk(path):
            for f in sorted(files):
                full = os.path.join(root, f)
                rel = os.path.relpath(full, path)
                zf.write(full, rel)
    data = buf.getvalue()
    if len(data) > _MAX_ARCHIVE_BYTES:
        raise ValueError(
            f"runtime_env archive for {path!r} is "
            f"{len(data) / 1e6:.0f} MB (cap "
            f"{_MAX_ARCHIVE_BYTES / 1e6:.0f} MB) — exclude data files"
        )
    digest = hashlib.sha256(data).hexdigest()[:16]
    _ZIP_CACHE[path] = (stamp, digest, data)
    return digest, data


def pack_runtime_env(spec: Optional[Dict]) -> Optional[Dict]:
    """Driver-side: resolve paths into content-addressed archives so
    the packed env is host-independent (ships over the cluster wire
    to remote node agents unchanged)."""
    if not spec:
        return None
    unknown = set(spec) - {"env_vars", "working_dir", "py_modules"}
    if unknown:
        raise ValueError(
            f"unsupported runtime_env keys {sorted(unknown)}; "
            "supported: env_vars, working_dir, py_modules "
            "(conda/pip/container are out of scope — see "
            "core/runtime_env.py)"
        )
    packed: Dict[str, Any] = {}
    env_vars = spec.get("env_vars")
    if env_vars:
        packed["env_vars"] = {
            str(k): str(v) for k, v in env_vars.items()
        }
    archives: List[Dict] = []
    wd = spec.get("working_dir")
    if wd:
        digest, data = _zip_dir(wd)
        archives.append(
            {"kind": "working_dir", "hash": digest, "data": data}
        )
    for mod in spec.get("py_modules") or []:
        digest, data = _zip_dir(mod)
        archives.append(
            {
                "kind": "py_module",
                "hash": digest,
                "name": os.path.basename(os.path.realpath(mod)),
                "data": data,
            }
        )
    if archives:
        packed["archives"] = archives
    return packed or None


def _cache_root() -> str:
    return os.path.join(
        tempfile.gettempdir(), "ray_tpu_runtime_env"
    )


def _extract(archive: Dict) -> str:
    """Idempotent per-host extraction (the URI cache): returns the
    extracted directory for this content hash."""
    dest = os.path.join(_cache_root(), archive["hash"])
    marker = os.path.join(dest, ".complete")
    if not os.path.exists(marker):
        tmp = dest + f".tmp{os.getpid()}"
        os.makedirs(tmp, exist_ok=True)
        with zipfile.ZipFile(io.BytesIO(archive["data"])) as zf:
            zf.extractall(tmp)
        open(os.path.join(tmp, ".complete"), "w").close()
        try:
            # ray-tpu: allow[RTA009] directory publish for the extraction cache — concurrent workers race on the rename; the content is a re-extractable cache with no durability contract
            os.replace(tmp, dest)  # atomic: concurrent workers race safely
        except OSError:
            import shutil

            shutil.rmtree(tmp, ignore_errors=True)
    return dest


def apply_runtime_env(packed: Optional[Dict]) -> None:
    """Worker-side: set env vars, extract + activate archives.
    working_dir chdirs and heads sys.path (reference working_dir
    semantics: relative paths and local imports resolve there);
    py_modules become importable by their top-level name."""
    if not packed:
        return
    for k, v in (packed.get("env_vars") or {}).items():
        os.environ[k] = v
    for archive in packed.get("archives") or []:
        dest = _extract(archive)
        if archive["kind"] == "working_dir":
            os.chdir(dest)
            if dest not in sys.path:
                sys.path.insert(0, dest)
        else:  # py_module: importable as its original top-level name
            parent = os.path.join(
                _cache_root(), f"mods_{archive['hash']}"
            )
            link = os.path.join(parent, archive["name"])
            os.makedirs(parent, exist_ok=True)
            if not os.path.exists(link):
                try:
                    os.symlink(dest, link)
                except OSError:
                    pass
            if parent not in sys.path:
                sys.path.insert(0, parent)
