"""Restricted deserialization for cross-host CONTROL frames.

The reference separates its control plane (typed protobuf messages —
``src/ray/protobuf/core_worker.proto``, ``rpc/grpc_server.h:64``) from
user payloads; a malformed control message fails schema validation
before any user code runs. Our control frames are pickled dicts, and a
blind ``pickle.loads`` on network bytes is arbitrary code execution —
so control frames go through a restricted unpickler instead: only
builtins containers/scalars and numpy array reconstruction resolve;
any other global (``os.system``, ``subprocess.*``, ``__reduce__``
gadgets generally) raises before anything executes.

User payloads (task args, actor state) legitimately need full pickle —
they stay on ``core.serialization`` but are only deserialized AFTER
the connection authenticated (HMAC handshake, ``core/cluster.py``) and
only in fields the control schema marks opaque (``payload``, ``cls``).

Threat model: same as the KV service (``parallel/distributed.py``) —
cluster hosts only, loopback by default; the token is a second wall,
and the restricted unpickler closes the remaining pre-auth gap where
bytes had to be parsed before the HMAC could be checked.
"""

from __future__ import annotations

import hashlib
import hmac as _hmac
import io
import json
import os
import pickle
from typing import Any, Dict, Optional

# Globals a control frame may resolve. Control frames are dicts of
# primitives plus opaque bytes fields; numpy sneaks in via scalar
# config values (num_cpus as np.int64 and the like).
_ALLOWED_GLOBALS = {
    ("builtins", "dict"),
    ("builtins", "list"),
    ("builtins", "tuple"),
    ("builtins", "set"),
    ("builtins", "frozenset"),
    ("builtins", "bytes"),
    ("builtins", "bytearray"),
    ("builtins", "str"),
    ("builtins", "int"),
    ("builtins", "float"),
    ("builtins", "bool"),
    ("builtins", "complex"),
    ("numpy", "ndarray"),
    ("numpy", "dtype"),
    ("numpy.core.multiarray", "_reconstruct"),
    ("numpy._core.multiarray", "_reconstruct"),
    ("numpy.core.multiarray", "scalar"),
    ("numpy._core.multiarray", "scalar"),
}


class ControlFrameError(pickle.UnpicklingError):
    """A control frame referenced a global outside the schema."""


class _RestrictedUnpickler(pickle.Unpickler):
    def find_class(self, module: str, name: str):
        if (module, name) in _ALLOWED_GLOBALS:
            return super().find_class(module, name)
        raise ControlFrameError(
            f"control frame references forbidden global "
            f"{module}.{name}"
        )

    # reducer_override-style extensions ride find_class, but buffers
    # and persistent ids are not part of the control schema at all
    def persistent_load(self, pid):  # pragma: no cover - defense
        raise ControlFrameError("persistent ids not allowed")


def control_loads(blob: bytes) -> Any:
    """Deserialize a network control frame; raises
    :class:`ControlFrameError` on anything outside the schema."""
    return _RestrictedUnpickler(io.BytesIO(blob)).load()


def control_dumps(obj: Any) -> bytes:
    return pickle.dumps(obj, protocol=5)


# ---------------------------------------------------------------------------
# Shared-token authentication for the cluster handshake
# ---------------------------------------------------------------------------


def cluster_token() -> Optional[str]:
    """The fleet's shared secret: ``RAY_TPU_CLUSTER_TOKEN``, falling
    back to the KV service's ``RAY_TPU_KV_TOKEN`` so one secret can
    cover both planes."""
    return os.environ.get("RAY_TPU_CLUSTER_TOKEN") or os.environ.get(
        "RAY_TPU_KV_TOKEN"
    )


def register_hmac(token: str, frame: Dict) -> str:
    """MAC over the registration frame's sorted-key JSON header
    (everything except the mac itself)."""
    msg = json.dumps(
        {k: v for k, v in frame.items() if k != "hmac"},
        sort_keys=True,
        default=str,
    ).encode()
    return _hmac.new(token.encode(), msg, hashlib.sha256).hexdigest()


def register_ok(token: Optional[str], frame: Dict) -> bool:
    """The registration frame includes the server's challenge nonce,
    so the MAC (which covers every non-mac field) is single-use — a
    captured handshake cannot be replayed to enroll a rogue node."""
    if token is None:
        return True
    mac = frame.get("hmac", "")
    if not isinstance(mac, str):
        return False
    return _hmac.compare_digest(mac, register_hmac(token, frame))
