"""Restricted deserialization for cross-host CONTROL frames.

The reference separates its control plane (typed protobuf messages —
``src/ray/protobuf/core_worker.proto``, ``rpc/grpc_server.h:64``) from
user payloads; a malformed control message fails schema validation
before any user code runs. Our control frames are pickled dicts, and a
blind ``pickle.loads`` on network bytes is arbitrary code execution —
so control frames go through a restricted unpickler instead: only
builtins containers/scalars and numpy array reconstruction resolve;
any other global (``os.system``, ``subprocess.*``, ``__reduce__``
gadgets generally) raises before anything executes.

User payloads (task args, actor state) legitimately need full pickle —
they stay on ``core.serialization`` but are only deserialized AFTER
the connection authenticated (HMAC handshake, ``core/cluster.py``) and
only in fields the control schema marks opaque (``payload``, ``cls``).

Threat model: same as the KV service (``parallel/distributed.py``) —
cluster hosts only, loopback by default; the token is a second wall,
and the restricted unpickler closes the remaining pre-auth gap where
bytes had to be parsed before the HMAC could be checked.
"""

from __future__ import annotations

import hashlib
import hmac as _hmac
import io
import json
import os
import pickle
from typing import Any, Dict, Optional

# Globals a control frame may resolve. Control frames are dicts of
# primitives plus opaque bytes fields; numpy sneaks in via scalar
# config values (num_cpus as np.int64 and the like).
_ALLOWED_GLOBALS = {
    ("builtins", "dict"),
    ("builtins", "list"),
    ("builtins", "tuple"),
    ("builtins", "set"),
    ("builtins", "frozenset"),
    ("builtins", "bytes"),
    ("builtins", "bytearray"),
    ("builtins", "str"),
    ("builtins", "int"),
    ("builtins", "float"),
    ("builtins", "bool"),
    ("builtins", "complex"),
    ("numpy", "ndarray"),
    ("numpy", "dtype"),
    ("numpy.core.multiarray", "_reconstruct"),
    ("numpy._core.multiarray", "_reconstruct"),
    ("numpy.core.multiarray", "scalar"),
    ("numpy._core.multiarray", "scalar"),
}


class ControlFrameError(pickle.UnpicklingError):
    """A control frame referenced a global outside the schema."""


class _RestrictedUnpickler(pickle.Unpickler):
    def find_class(self, module: str, name: str):
        if (module, name) in _ALLOWED_GLOBALS:
            return super().find_class(module, name)
        raise ControlFrameError(
            f"control frame references forbidden global "
            f"{module}.{name}"
        )

    # reducer_override-style extensions ride find_class, but buffers
    # and persistent ids are not part of the control schema at all
    def persistent_load(self, pid):  # pragma: no cover - defense
        raise ControlFrameError("persistent ids not allowed")


def control_loads(blob: bytes) -> Any:
    """Deserialize a network control frame; raises
    :class:`ControlFrameError` on anything outside the schema."""
    return _RestrictedUnpickler(io.BytesIO(blob)).load()


def control_dumps(obj: Any) -> bytes:
    return pickle.dumps(obj, protocol=5)


# ---------------------------------------------------------------------------
# Typed frame schemas (the reference's protobuf role)
# ---------------------------------------------------------------------------

# Control frames now carry a version ("v"); receivers tolerate its
# absence (v0 peers) and unknown EXTRA fields (forward compatibility),
# but every DECLARED field must be present with its declared type —
# the validation role of the reference's typed messages
# (``src/ray/protobuf/core_worker.proto``, ``node_manager.proto``).
FRAME_VERSION = 1

_BYTESY = (bytes, bytearray)
_NUM = (int, float)

# op -> {field: (types | object-for-opaque, required)}
_SCHEMAS: Dict[str, Dict[str, tuple]] = {
    "challenge": {"nonce": (str, True)},
    "register": {
        "node_id": (str, True),
        "num_cpus": (_NUM, True),
        "nonce": (str, False),
        "hmac": (str, False),
        "data_port": (int, False),
    },
    "registered": {"ok": (bool, True)},
    "cache_obj": {
        "obj_id": (str, True),
        "payload": (_BYTESY, True),
    },
    "free_objs": {"ids": ((list, tuple), True)},
    "task": {
        "task_id": (str, True),
        "func_id": (str, True),
        "func": (_BYTESY, True),
        "payload": (_BYTESY, True),
        "name": ((str, type(None)), False),
        "num_cpus": (_NUM, False),
        "num_returns": (int, False),
        "runtime_env": (object, False),  # opaque, post-auth
    },
    "create_actor": {
        "actor_id": (str, True),
        "cls": (_BYTESY, True),
        "payload": (_BYTESY, True),
        "options": (dict, False),
    },
    "actor_call": {
        "task_id": (str, True),
        "actor_id": (str, True),
        "method": (str, True),
        "payload": (_BYTESY, True),
    },
    "kill_actor": {"actor_id": (str, True)},
    "result": {
        "task_id": (str, True),
        "ok": (bool, True),
        "payload": (_BYTESY, False),
        "name": (str, False),
        "traceback": (str, False),
        "node_obj": (dict, False),
    },
    "pull_auth": {
        "nonce": (str, True),
        "client_nonce": (str, False),
        "hmac": (str, False),
    },
    "pull": {"obj_id": (str, True)},
}


def validate_frame(msg: Any, allowed_ops) -> Dict:
    """Schema-check one control frame against the op's declared
    fields AND the receiving context's allowed op set (an agent must
    not accept head-only ops and vice versa). Raises
    :class:`ControlFrameError`; returns the frame for chaining."""
    if not isinstance(msg, dict):
        raise ControlFrameError(
            f"control frame is {type(msg).__name__}, not dict"
        )
    op = msg.get("op")
    if op not in allowed_ops:
        raise ControlFrameError(
            f"op {op!r} not allowed in this context"
        )
    schema = _SCHEMAS.get(op)
    if schema is None:
        raise ControlFrameError(f"unknown op {op!r}")
    for field, (types, required) in schema.items():
        if field not in msg:
            if required:
                raise ControlFrameError(
                    f"{op}: missing required field {field!r}"
                )
            continue
        if types is object:
            continue
        if not isinstance(msg[field], types):
            raise ControlFrameError(
                f"{op}: field {field!r} has type "
                f"{type(msg[field]).__name__}"
            )
    v = msg.get("v", 0)
    if not isinstance(v, int):
        raise ControlFrameError(f"{op}: version field not int")
    return msg


# ---------------------------------------------------------------------------
# Shared-token authentication for the cluster handshake
# ---------------------------------------------------------------------------


def cluster_token() -> Optional[str]:
    """The fleet's shared secret: ``RAY_TPU_CLUSTER_TOKEN``, falling
    back to the KV service's ``RAY_TPU_KV_TOKEN`` so one secret can
    cover both planes."""
    return os.environ.get("RAY_TPU_CLUSTER_TOKEN") or os.environ.get(
        "RAY_TPU_KV_TOKEN"
    )


def register_hmac(token: str, frame: Dict) -> str:
    """MAC over the registration frame's sorted-key JSON header
    (everything except the mac itself)."""
    msg = json.dumps(
        {k: v for k, v in frame.items() if k != "hmac"},
        sort_keys=True,
        default=str,
    ).encode()
    return _hmac.new(token.encode(), msg, hashlib.sha256).hexdigest()


def register_ok(token: Optional[str], frame: Dict) -> bool:
    """The registration frame includes the server's challenge nonce,
    so the MAC (which covers every non-mac field) is single-use — a
    captured handshake cannot be replayed to enroll a rogue node."""
    if token is None:
        return True
    mac = frame.get("hmac", "")
    if not isinstance(mac, str):
        return False
    return _hmac.compare_digest(mac, register_hmac(token, frame))
