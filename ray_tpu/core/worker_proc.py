"""Worker process main loop.

The ray_tpu counterpart of the reference worker executable
(``python/ray/_private/workers/default_worker.py`` +
``_raylet.pyx execute_task :487``): a spawned process that executes stateless
tasks and hosts actor instances, exchanging commands/results with the driver
over a duplex pipe and large payloads through shared memory.

Workers pin JAX to the CPU platform — the single TPU chip belongs to the
driver/learner; rollout actors do inference with CPU XLA.
"""

from __future__ import annotations

import os
import sys
import traceback
from typing import Any, Dict


def _resolve_args(args, kwargs, shm_cache):
    """Replace _ObjArg markers with actual values (attaching shm)."""

    def resolve(v):
        if isinstance(v, _ObjArg):
            return v.load(shm_cache)
        return v

    return [resolve(a) for a in args], {k: resolve(v) for k, v in kwargs.items()}


class _ObjArg:
    """Marker for an object-store argument passed to a worker."""

    __slots__ = (
        "obj_id", "shm_name", "inline", "has_inline", "spill_loc",
        "remote_loc",
    )

    def __init__(
        self, obj_id, shm_name=None, inline=None, has_inline=False,
        spill_loc=None, remote_loc=None,
    ):
        self.obj_id = obj_id
        self.shm_name = shm_name
        self.inline = inline
        self.has_inline = has_inline
        # (spill_uri, path): the object lives in spill storage; the
        # worker reads it from there directly
        self.spill_loc = spill_loc
        # (host, port): the object's primary copy is NODE-RESIDENT on
        # a fleet agent; the worker pulls from its data server
        # directly — the driver never materializes the bytes
        self.remote_loc = remote_loc

    def _read_spill(self, loc):
        from ray_tpu.core import serialization as ser
        from ray_tpu.core.external_storage import storage_from_uri

        blob = storage_from_uri(loc[0]).get(loc[1])
        return ser.read_from_buffer(memoryview(blob))

    def load(self, shm_cache: Dict[str, Any]):
        from ray_tpu.core import serialization as ser

        if self.obj_id in shm_cache:
            return shm_cache[self.obj_id][1]
        if self.has_inline:
            shm_cache[self.obj_id] = (None, self.inline)
            return self.inline
        if self.spill_loc is not None:
            try:
                value = self._read_spill(self.spill_loc)
            except Exception:
                # spill file gone (freed / restored+evicted between
                # marshal and here): fall back to a driver-API get
                from ray_tpu.core.worker_api import worker_client

                client = worker_client()
                if client is None:
                    raise
                value = client.get(self.obj_id, timeout=120.0)
            shm_cache[self.obj_id] = (None, value)
            return value
        if self.remote_loc is not None:
            try:
                from ray_tpu.core.cluster import fetch_remote_object

                blob = fetch_remote_object(
                    self.remote_loc[0],
                    self.remote_loc[1],
                    self.obj_id,
                )
                value = ser.loads(blob)
            except Exception:
                # node died / object freed between marshal and here:
                # the driver get surfaces the canonical error (or the
                # value, if it was re-homed)
                from ray_tpu.core.worker_api import worker_client

                client = worker_client()
                if client is None:
                    raise
                value = client.get(self.obj_id, timeout=120.0)
            shm_cache[self.obj_id] = (None, value)
            return value
        from ray_tpu.core.object_store import Segment

        try:
            shm = Segment(name=self.shm_name)
        except FileNotFoundError:
            # the driver's LRU spilled (and unlinked) this segment
            # after the task marshalled its args — at-volume runs hit
            # this when the working set exceeds the store cap. Read
            # the spilled bytes straight from the storage backend when
            # possible (no driver round trip for the data), falling
            # back to a driver-API get (which restores transparently).
            from ray_tpu.core.worker_api import worker_client

            client = worker_client()
            if client is None:
                raise
            value = None
            try:
                loc = client.spill_location(self.obj_id)
                if loc is not None:
                    value = self._read_spill(loc)
            except Exception:
                value = None
            if value is None:
                value = client.get(self.obj_id, timeout=120.0)
            shm_cache[self.obj_id] = (None, value)
            return value
        value = ser.read_from_buffer(shm.buf)
        # Keep the segment mapped as long as the value is cached: the
        # deserialized arrays are zero-copy views into it.
        shm_cache[self.obj_id] = (shm, value)
        return value


def worker_main(conn, worker_id: str, env_overrides: Dict[str, str]):
    """Entry point for spawned worker processes."""
    os.environ.update(env_overrides or {})
    # per-worker log files (reference: per-process files in the session
    # dir, tailed by the LogMonitor)
    log_dir = os.environ.get("RAY_TPU_LOG_DIR")
    if log_dir:
        try:
            os.makedirs(log_dir, exist_ok=True)
            sys.stdout = open(
                os.path.join(log_dir, f"worker-{worker_id}.out"),
                "a",
                buffering=1,
            )
            sys.stderr = open(
                os.path.join(log_dir, f"worker-{worker_id}.err"),
                "a",
                buffering=1,
            )
        except OSError:
            pass
    # Rollout workers must never claim the accelerator — it belongs to
    # the driver/learner. The inherited env (and the image's
    # sitecustomize, which registers the TPU PJRT plugin in every
    # python process) may pin jax to the TPU, so force the platform at
    # the config level. Override via worker_env={"RAY_TPU_WORKER_PLATFORM":
    # ...} in ray.init for workers that legitimately need a device.
    platform = (env_overrides or {}).get(
        "RAY_TPU_WORKER_PLATFORM", "cpu"
    )
    os.environ["JAX_PLATFORMS"] = platform
    try:
        import jax

        jax.config.update("jax_platforms", platform)
    except Exception:
        pass

    from ray_tpu.core import serialization as ser

    func_cache: Dict[str, Any] = {}
    shm_cache: Dict[str, Any] = {}
    actors: Dict[str, Any] = {}
    result_shms = []  # keep created segments alive until driver owns them

    # Bulk-result data plane: a persistent native SPSC ring to the driver
    # (the plasma role for produced-once/consumed-once payloads, e.g.
    # rollout SampleBatches — reference src/ray/object_manager/plasma/
    # store.h:55). Size-routed like plasma vs inline objects: tiny
    # results stay on the pipe; [ring_min, ring_max] rides the ring
    # (zero syscalls/record beats per-record segment churn — measured
    # 1.3-1.7x faster at 64KB-512KB); larger records go to a dedicated
    # shm segment whose lazy zero-copy driver views win once the
    # per-record copy costs more than mmap+unlink (~1MB+). Gate via
    # worker_env RAY_TPU_DISABLE_RING=1.
    ring = None
    # 16MB default: ~21 max-band (768KB) records of headroom, and small
    # enough that the create-side MAP_POPULATE prefault stays cheap.
    ring_cap = int(
        os.environ.get("RAY_TPU_RING_CAPACITY", 16 * 1024 * 1024)
    )
    ring_min = int(os.environ.get("RAY_TPU_RING_MIN_BYTES", 32 * 1024))
    ring_max = min(
        int(os.environ.get("RAY_TPU_RING_MAX_BYTES", 768 * 1024)),
        ring_cap // 2,
    )
    if os.environ.get("RAY_TPU_DISABLE_RING") != "1":
        try:
            from ray_tpu.core.shm_ring import ShmRing

            ring = ShmRing.create(f"rtring_{worker_id}", ring_cap)
            conn.send({"status": "ring", "ring_name": ring.name})
        except Exception:
            ring = None

    import threading

    # one logical producer: concurrent actor threads serialize their
    # sends (pipe AND ring — the ring is SPSC; the lock keeps this
    # process a single producer)
    send_lock = threading.Lock()
    actor_pools: Dict[str, Any] = {}  # actor_id -> ThreadPoolExecutor

    def send_error(msg, e, tb):
        from ray_tpu.util import tracing as _tracing

        _err_spans = _tracing.drain_finished()
        with send_lock:
            conn.send(
                {
                    "task_id": msg.get("task_id"),
                    "status": "err",
                    "error": str(e),
                    "error_cls": type(e).__name__,
                    "traceback": tb,
                    **({"spans": _err_spans} if _err_spans else {}),
                }
            )

    def send_value(msg, value):
        # Serialize result; bulk payloads ride the ring, very large
        # ones a fresh shm segment, small ones the pipe.
        meta, buffers = ser.serialize(value)
        size = ser.serialized_size(meta, buffers)
        # finished spans ride the result message back to the driver's
        # tracer (the reference exports via its OTel pipeline instead)
        from ray_tpu.util import tracing

        spans = tracing.drain_finished()
        extra = {"spans": spans} if spans else {}
        with send_lock:
            if ring is not None and ring_min <= size <= ring_max:
                try:
                    # Zero-copy: the serializer writes straight into
                    # the mapped ring memory (reserve→write→commit).
                    pushed = ring.push_serialized(
                        meta, buffers, size, timeout=5.0
                    )
                except (BrokenPipeError, ValueError):
                    pushed = False
                if pushed:
                    conn.send(
                        {
                            "task_id": msg["task_id"],
                            "status": "ok_ring",
                            "nbytes": size,
                            **extra,
                        }
                    )
                    return
                # ring congested/unusable: fall through
            if size >= 256 * 1024:
                from ray_tpu.core.object_store import Segment

                shm = Segment(
                    create=True,
                    size=size,
                    name=f"rt_{msg['task_id'][:24]}",
                )
                ser.write_to_buffer(shm.buf, meta, buffers)
                conn.send(
                    {
                        "task_id": msg["task_id"],
                        "status": "ok_shm",
                        "shm_name": shm.name,
                        **extra,
                    }
                )
                shm.close()  # driver owns the segment now
            else:
                conn.send(
                    {
                        "task_id": msg["task_id"],
                        "status": "ok",
                        "value_blob": ser.dumps(value),
                        **extra,
                    }
                )

    while True:
        try:
            msg = conn.recv()
        except (EOFError, KeyboardInterrupt):
            break
        mtype = msg["type"]
        if mtype == "shutdown":
            break
        try:
            if mtype == "register_func":
                func_cache[msg["func_id"]] = ser.loads(msg["func"])
                continue
            elif mtype == "runtime_env":
                # job-level env from ray.init(runtime_env=...)
                from ray_tpu.core.runtime_env import apply_runtime_env

                apply_runtime_env(msg.get("packed"))
                continue
            elif mtype == "task":
                from ray_tpu.util import tracing

                fn = func_cache[msg["func_id"]]
                args, kwargs = _resolve_args(
                    *ser.loads(msg["payload"]), shm_cache
                )
                _span = tracing.remote_span(
                    msg.get("trace_ctx"),
                    f"task:{getattr(fn, '__name__', 'fn')}",
                )
                renv = msg.get("runtime_env")
                if renv:
                    # pooled workers: the WHOLE env (vars, cwd,
                    # sys.path) applies only around the call, so a
                    # later unrelated task on this worker doesn't
                    # inherit another task's working_dir or modules.
                    # Extracted archives persist via the cache. Actors
                    # get dedicated processes, so theirs persist
                    # wholesale.
                    from ray_tpu.core.runtime_env import (
                        apply_runtime_env,
                    )

                    saved = {
                        k: os.environ.get(k)
                        for k in (renv.get("env_vars") or {})
                    }
                    saved_cwd = os.getcwd()
                    saved_path = list(sys.path)
                    apply_runtime_env(renv)
                    try:
                        with _span:
                            value = fn(*args, **kwargs)
                    finally:
                        for k, old in saved.items():
                            if old is None:
                                os.environ.pop(k, None)
                            else:
                                os.environ[k] = old
                        try:
                            os.chdir(saved_cwd)
                        except OSError:
                            pass
                        sys.path[:] = saved_path
                else:
                    with _span:
                        value = fn(*args, **kwargs)
            elif mtype == "actor_init":
                if msg.get("runtime_env"):
                    from ray_tpu.core.runtime_env import (
                        apply_runtime_env,
                    )

                    apply_runtime_env(msg["runtime_env"])
                cls = ser.loads(msg["cls"])
                args, kwargs = _resolve_args(
                    *ser.loads(msg["payload"]), shm_cache
                )
                actors[msg["actor_id"]] = cls(*args, **kwargs)
                mc = int(msg.get("max_concurrency", 1))
                if mc > 1:
                    # threaded actor (reference max_concurrency,
                    # actor.py:options): calls dispatch to a pool and
                    # may complete out of order; the user class is
                    # responsible for its own thread safety — same
                    # contract as the reference
                    from concurrent.futures import ThreadPoolExecutor

                    actor_pools[msg["actor_id"]] = ThreadPoolExecutor(
                        max_workers=mc,
                        thread_name_prefix=f"actor_{msg['actor_id'][:8]}",
                    )
                value = None
            elif mtype == "actor_call":
                from ray_tpu.util import tracing

                actor = actors[msg["actor_id"]]
                args, kwargs = _resolve_args(
                    *ser.loads(msg["payload"]), shm_cache
                )
                pool = actor_pools.get(msg["actor_id"])
                if pool is not None:

                    def _run_concurrent(
                        msg=msg, actor=actor, args=args, kwargs=kwargs
                    ):
                        try:
                            with tracing.remote_span(
                                msg.get("trace_ctx"),
                                f"actor:{type(actor).__name__}."
                                f"{msg['method']}",
                            ):
                                out = getattr(actor, msg["method"])(
                                    *args, **kwargs
                                )
                        except BaseException as e:  # noqa: BLE001
                            send_error(
                                msg, e, traceback.format_exc()
                            )
                            return
                        send_value(msg, out)

                    pool.submit(_run_concurrent)
                    continue
                with tracing.remote_span(
                    msg.get("trace_ctx"),
                    f"actor:{type(actor).__name__}.{msg['method']}",
                ):
                    value = getattr(actor, msg["method"])(
                        *args, **kwargs
                    )
            elif mtype == "free":
                for oid in msg["obj_ids"]:
                    ent = shm_cache.pop(oid, None)
                    if ent and ent[0] is not None:
                        ent[0].close()
                continue
            else:
                raise ValueError(f"unknown message type {mtype}")
        except BaseException as e:  # noqa: BLE001 — report, don't die
            tb = traceback.format_exc()
            try:
                send_error(msg, e, tb)
            except Exception:
                break
            continue

        if msg.get("task_id") is None:
            continue
        send_value(msg, value)

    for pool in actor_pools.values():
        pool.shutdown(wait=False)
    if ring is not None:
        try:
            ring.mark_closed()
            ring.close()
        except Exception:
            pass
    for shm, _ in (v for v in shm_cache.values() if v[0] is not None):
        try:
            shm.close()
        except Exception:
            pass
    try:
        conn.close()
    except Exception:
        pass
    sys.exit(0)
