"""Host object plane: ObjectRef + shared-memory store.

Plays the roles of the reference's in-process memory store
(``src/ray/core_worker/store_provider/memory_store/memory_store.h:43``) for
small objects and the plasma store (``plasma/store.h:55``) for large ones,
scoped to one host. Objects above ``SHM_THRESHOLD`` are serialized into a
POSIX shared-memory segment so any worker process on the node can map them
zero-copy; small objects travel inline over the control pipes.

Disposition vs the reference (SURVEY §2.1): distributed refcounting and
lineage reconstruction are host-scoped here — a put object lives until
``free()``, eviction, or driver shutdown; cross-host transfer belongs to
the DCN layer (ray_tpu.parallel.distributed), not this file. Spilling
(reference ``_private/external_storage.py:71`` + plasma eviction
``plasma/eviction_policy.h``): when resident shm exceeds
``object_store_memory``, least-recently-used unspilled entries move
their serialized bytes to disk and are restored transparently on access.
"""

from __future__ import annotations

import os
import threading
import time
import uuid
from multiprocessing import shared_memory
from typing import Any, Dict, Optional

from ray_tpu.core import serialization as ser

SHM_THRESHOLD = 256 * 1024  # bytes


class Segment(shared_memory.SharedMemory):
    """SharedMemory whose finalizer tolerates still-exported views.

    Task results are deserialized as zero-copy numpy views into the
    segment; if user code still references them when the segment object
    is garbage-collected (e.g. at interpreter exit without free()),
    stock SharedMemory.__del__ sprays "BufferError: cannot close
    exported pointers exist". The OS reclaims the mapping at process
    exit regardless, so the finalizer — and only the finalizer —
    swallows that error; explicit close() still raises."""

    def __del__(self):
        try:
            super().__del__()
        except BufferError:
            pass


def _ambient_store():
    """The driver's object store, if this process is the driver.
    Worker processes have no runtime, and node-AGENT processes (which
    do run api.init) are not the owner either — refs deserialized
    there point at the HEAD's objects, so counting them against the
    agent's local store would only plant phantom entries. Both stay
    untracked (the driver owns every object's lifetime, DISPOSITIONS
    single-owner posture)."""
    from ray_tpu.core import api

    rt = api._runtime
    if rt is None or getattr(rt, "node_agent", None) is not None:
        return None
    return rt.store


class ObjectRef:
    """Future handle to a task result or put object
    (reference ``python/ray/_raylet.pyx ObjectRef``).

    Driver-side handles are REFERENCE COUNTED (the local-handle half
    of the reference's ``core_worker/reference_count.h:61``): every
    live ObjectRef instance in the driver process — including task
    records pinning their argument refs for retries, and handles
    deserialized from results — holds the object; when the last one
    is garbage collected the entry is freed (immediately if ready,
    else when the in-flight result lands). Explicit ``ray.free()``
    still force-frees."""

    __slots__ = ("id", "_store", "_owned", "_worker_tracked")

    def __init__(self, id: Optional[str] = None, store=None):
        self.id = id or uuid.uuid4().hex
        self._store = store if store is not None else _ambient_store()
        self._owned = self._store is not None
        self._worker_tracked = False
        if self._owned:
            self._store.incref(self.id)
        else:
            # worker context: the driver pins handed-out refs for us;
            # account local instances so the pin releases when the
            # last one is GC'd (worker_api release piggyback)
            try:
                from ray_tpu.core.worker_api import note_ref

                self._worker_tracked = note_ref(self.id)
            except Exception:
                pass

    def __hash__(self):
        return hash(self.id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and self.id == other.id

    def hex(self) -> str:
        return self.id

    def __repr__(self):
        return f"ObjectRef({self.id[:16]})"

    def __reduce__(self):
        # Refs pickle as bare ids; the receiving side re-binds its
        # store (and takes its own count if it is the driver).
        return (ObjectRef, (self.id,))

    def __del__(self):
        if getattr(self, "_owned", False):
            try:
                self._store.decref(self.id)
            except Exception:
                pass  # interpreter/store teardown
        elif getattr(self, "_worker_tracked", False):
            try:
                from ray_tpu.core.worker_api import note_ref_deleted

                note_ref_deleted(self.id)
            except Exception:
                pass


class _Entry:
    __slots__ = (
        "value",
        "shm",
        "event",
        "error",
        "callbacks",
        "spill_path",
        "_restore_buf",
        "remote_loc",
    )

    def __init__(self):
        self.value = None
        self.shm: Optional[shared_memory.SharedMemory] = None
        self.event = threading.Event()
        self.error: Optional[BaseException] = None
        self.callbacks = []
        self.spill_path: Optional[str] = None
        self._restore_buf = None
        # primary copy lives on a fleet node: {"node_id", "host",
        # "port", "size"} — the value is pulled from the node's data
        # server only if THIS process actually reads it
        self.remote_loc: Optional[Dict] = None

    def fire(self):
        self.event.set()
        cbs, self.callbacks = self.callbacks, []
        for cb in cbs:
            cb()


class ObjectStore:
    """Driver-side object table. Thread-safe."""

    def __init__(
        self,
        max_bytes: Optional[int] = None,
        spill_uri: Optional[str] = None,
    ):
        # RLock: ObjectRef.__del__ → decref can fire at ANY point the
        # GC drops a last handle — including inside store methods that
        # already hold the lock (freeing an entry drops its callbacks'
        # closed-over refs). A plain Lock would self-deadlock there.
        self._lock = threading.RLock()
        self._entries: Dict[str, _Entry] = {}
        self.max_bytes = max_bytes  # None → never spill
        self._resident_bytes = 0
        self._lru: Dict[str, float] = {}  # obj_id -> last access
        # pluggable spill backend (reference object_spilling_config):
        # file:// by default, s3://... via the external_storage seam
        self._spill_uri = spill_uri or os.environ.get(
            "RAY_TPU_SPILL_URI", "file://"
        )
        self._storage = None  # constructed on first spill
        # live driver-side handles per object (reference
        # reference_count.h:61 local references)
        self._refcounts: Dict[str, int] = {}

    def _spill_storage(self):
        if self._storage is None:
            from ray_tpu.core.external_storage import storage_from_uri

            self._storage = storage_from_uri(self._spill_uri)
        return self._storage

    def _track_shm(self, obj_id: str, e: _Entry) -> None:
        """Lock held: account a new shm-resident entry, spilling LRU
        entries if over budget."""
        self._resident_bytes += e.shm.size
        self._lru[obj_id] = time.monotonic()
        if self.max_bytes is None:
            return
        while self._resident_bytes > self.max_bytes:
            victim = None
            for oid in sorted(self._lru, key=self._lru.get):
                cand = self._entries.get(oid)
                if cand is not None and cand.shm is not None and (
                    oid != obj_id
                ):
                    victim = (oid, cand)
                    break
            if victim is None:
                return  # nothing else evictable
            self._spill_entry(*victim)

    def _spill_entry(self, obj_id: str, e: _Entry) -> None:
        """Lock held: move the serialized bytes to disk and release the
        shm segment. User-held zero-copy views stay valid (the mapping
        lives until they are GC'd); OUR references are dropped."""
        path = self._spill_storage().put(obj_id, bytes(e.shm.buf))
        self._resident_bytes -= e.shm.size
        self._lru.pop(obj_id, None)
        e.spill_path = path
        e.value = None
        try:
            e.shm.unlink()
        except FileNotFoundError:
            pass
        try:
            e.shm.close()
        except BufferError:
            pass  # live views; mapping reclaimed at their GC
        e.shm = None

    def _maybe_restore(self, e: _Entry) -> None:
        """Lock held: bring a spilled entry back (reference
        external_storage restore path)."""
        if e.spill_path is None or e.value is not None:
            return
        blob = self._spill_storage().get(e.spill_path)
        e.value = ser.read_from_buffer(memoryview(blob))
        e._restore_buf = blob  # keep the backing bytes alive

    def _entry(self, obj_id: str) -> _Entry:
        with self._lock:
            e = self._entries.get(obj_id)
            if e is None:
                e = _Entry()
                self._entries[obj_id] = e
            return e

    def put(self, obj_id: str, value: Any, use_shm: bool = True) -> Optional[str]:
        """Store a value; returns shm segment name if spilled to shm."""
        e = self._entry(obj_id)
        shm_name = None
        if use_shm:
            meta, buffers = ser.serialize(value)
            size = ser.serialized_size(meta, buffers)
            if size >= SHM_THRESHOLD:
                shm = Segment(
                    create=True, size=size, name=f"rt_{obj_id[:24]}"
                )
                ser.write_to_buffer(shm.buf, meta, buffers)
                e.shm = shm
                shm_name = shm.name
                with self._lock:
                    self._track_shm(obj_id, e)
        e.value = value
        e.fire()
        return shm_name

    def put_error(self, obj_id: str, err: BaseException) -> None:
        e = self._entry(obj_id)
        e.error = err
        e.fire()

    def peek_error(self, obj_id: str) -> Optional[BaseException]:
        """The stored error of a READY object, without raising (None
        for pending or successful objects). Lets completion callbacks
        classify failures — e.g. a serve handle marking a replica dead
        on an actor-death error — without consuming the ref."""
        with self._lock:
            e = self._entries.get(obj_id)
        if e is None or not e.event.is_set():
            return None
        return e.error

    def put_remote(self, obj_id: str, loc: Dict) -> None:
        """Mark the object ready with its primary copy NODE-RESIDENT
        (reference: per-node plasma + object directory,
        ``object_manager/object_manager.h:114`` — the owner records a
        location, not bytes). Waiters wake immediately; the bytes only
        cross to this process if ``get`` is actually called, via a
        direct pull from the node's data server."""
        e = self._entry(obj_id)
        e.remote_loc = dict(loc)
        e.fire()

    def remote_loc(self, obj_id: str) -> Optional[Dict]:
        """Location descriptor when the primary copy is node-resident
        (None once materialized locally or for head-resident objects).
        The cluster plane uses this to marshal args as pull-from-peer
        markers instead of routing bytes through the driver."""
        with self._lock:
            e = self._entries.get(obj_id)
            if e is None or e.value is not None or e.shm is not None:
                return None
            return e.remote_loc

    def _materialize_remote(
        self,
        obj_id: str,
        e: _Entry,
        timeout: Optional[float] = None,
    ) -> None:
        """Pull a node-resident object's bytes from its data server
        (outside the store lock — network). Concurrent callers may
        both fetch; last write wins, both see a correct value.
        ``timeout`` bounds the pull — a slow peer raises
        GetTimeoutError like any other slow get."""
        import socket as _socket

        from ray_tpu.core.cluster import fetch_remote_object

        loc = e.remote_loc
        try:
            blob = fetch_remote_object(
                loc["host"], loc["port"], obj_id, timeout=timeout
            )
        except (_socket.timeout, TimeoutError) as err:
            raise GetTimeoutError(
                f"Timed out pulling node-resident object {obj_id} "
                f"from {loc.get('host')}:{loc.get('port')}"
            ) from err
        except Exception as err:
            raise RayActorError(
                f"object {obj_id} lost: node {loc.get('node_id')} "
                f"({loc.get('host')}:{loc.get('port')}) unreachable: "
                f"{err}"
            ) from err
        value = ser.loads(blob)
        with self._lock:
            if e.value is None and e.spill_path is None:
                e.value = value
                e._restore_buf = blob

    def attach_shm(self, obj_id: str, shm_name: str) -> None:
        """Register a worker-created shm segment as this object's value."""
        e = self._entry(obj_id)
        shm = Segment(name=shm_name)
        e.shm = shm
        e.value = ser.read_from_buffer(shm.buf)
        with self._lock:
            self._track_shm(obj_id, e)
        e.fire()

    def is_ready(self, obj_id: str) -> bool:
        return self._entry(obj_id).event.is_set()

    def wait(self, obj_id: str, timeout: Optional[float] = None) -> bool:
        return self._entry(obj_id).event.wait(timeout)

    def get(self, obj_id: str, timeout: Optional[float] = None) -> Any:
        deadline = (
            None if timeout is None else time.monotonic() + timeout
        )
        e = self._entry(obj_id)
        if not e.event.wait(timeout):
            raise GetTimeoutError(f"Timed out getting object {obj_id}")
        if e.error is not None:
            raise e.error
        if (
            e.remote_loc is not None
            and e.value is None
            and e.spill_path is None
        ):
            remaining = (
                None
                if deadline is None
                else max(deadline - time.monotonic(), 0.05)
            )
            self._materialize_remote(obj_id, e, timeout=remaining)
        with self._lock:
            if e.spill_path is not None and e.value is None:
                self._maybe_restore(e)
            if obj_id in self._lru:
                self._lru[obj_id] = time.monotonic()
            return e.value

    def on_ready(self, obj_id: str, callback) -> None:
        """Run callback when the object becomes available (or immediately)."""
        e = self._entry(obj_id)
        with self._lock:
            if e.event.is_set():
                run_now = True
            else:
                e.callbacks.append(callback)
                run_now = False
        if run_now:
            callback()

    def discard_callback(self, obj_id: str, callback) -> None:
        """Deregister a pending on_ready callback (no-op if absent/fired).
        Lets wait() clean up after itself instead of accumulating dead
        callbacks on never-ready entries."""
        with self._lock:
            e = self._entries.get(obj_id)
            if e is not None:
                try:
                    e.callbacks.remove(callback)
                except ValueError:
                    pass

    def spill_location(self, obj_id: str):
        """(spill_uri, path) when the object currently lives in spill
        storage — lets a worker read the bytes straight from the
        backend (local file, s3, ...) instead of round-tripping the
        value through the driver socket."""
        e = self._entries.get(obj_id)
        if e is None:
            return None
        with self._lock:
            if e.spill_path is None:
                return None
            return (self._spill_uri, e.spill_path)

    def shm_name(self, obj_id: str) -> Optional[str]:
        e = self._entries.get(obj_id)
        if e is None or e.shm is None:
            return None
        with self._lock:
            # marshalling is about to hand this name to a worker:
            # refresh the LRU stamp so the spiller prefers colder
            # entries (the worker-side load still has a driver-API
            # fallback if a spill wins the race anyway)
            if obj_id in self._lru:
                self._lru[obj_id] = time.monotonic()
            return e.shm.name if e.shm else None

    def incref(self, obj_id: str) -> None:
        with self._lock:
            self._refcounts[obj_id] = (
                self._refcounts.get(obj_id, 0) + 1
            )

    def decref(self, obj_id: str) -> None:
        """Last driver handle gone → free the entry: now if the value
        is ready, else when the in-flight result lands (a handle
        re-acquired in between cancels the deferred free)."""
        with self._lock:
            n = self._refcounts.get(obj_id)
            if n is None:
                return
            if n > 1:
                self._refcounts[obj_id] = n - 1
                return
            self._refcounts.pop(obj_id, None)
            e = self._entries.get(obj_id)
            if e is not None and e.event.is_set():
                # free INSIDE the lock (RLock, reentrant): freeing
                # after release would race a concurrent incref from a
                # handle deserialized on another thread
                self.free([obj_id])
                return

        def _free_if_unreferenced():
            with self._lock:
                if self._refcounts.get(obj_id, 0) > 0:
                    return
                self.free([obj_id])

        self.on_ready(obj_id, _free_if_unreferenced)

    def add_free_listener(self, fn) -> None:
        """Register fn(list_of_ids) called after ids are freed — the
        cluster plane uses this to invalidate per-node object caches
        (cluster.RemoteNode.free_objs)."""
        with self._lock:
            self._free_listeners = getattr(
                self, "_free_listeners", []
            ) + [fn]

    def free(self, obj_ids) -> None:
        obj_ids = list(obj_ids)  # may be a generator; iterated twice
        listeners = getattr(self, "_free_listeners", None)
        if listeners:
            for fn in listeners:
                try:
                    fn(obj_ids)
                except Exception:
                    pass
        with self._lock:
            for oid in obj_ids:
                # drop the handle count too: a later decref on an
                # explicitly freed id must be a no-op, not a deferred
                # free that resurrects a phantom entry via on_ready
                self._refcounts.pop(oid, None)
                e = self._entries.pop(oid, None)
                if e is not None and e.spill_path is not None:
                    try:
                        self._spill_storage().delete(e.spill_path)
                    except Exception:
                        pass
                    e.spill_path = None
                if e and e.shm:
                    self._resident_bytes -= e.shm.size
                    self._lru.pop(oid, None)
                    e.value = None  # drop zero-copy views first
                    try:
                        e.shm.unlink()
                    except FileNotFoundError:
                        pass
                    try:
                        e.shm.close()
                    except BufferError:
                        # Deserialized arrays still view the buffer; the
                        # mapping is released when they are GC'd.
                        pass

    def clear(self) -> None:
        with self._lock:
            ids = list(self._entries)
        self.free(ids)


class GetTimeoutError(TimeoutError):
    """reference: ray.exceptions.GetTimeoutError"""


class RayTaskError(RuntimeError):
    """A task raised; carries the remote traceback
    (reference ray.exceptions.RayTaskError)."""

    def __init__(self, function_name: str, traceback_str: str,
                 cause: Optional[BaseException] = None):
        self.function_name = function_name
        self.traceback_str = traceback_str
        self.cause = cause
        super().__init__(
            f"Task {function_name} failed:\n{traceback_str}"
        )


class RayActorError(RuntimeError):
    """Actor died or method failed (reference ray.exceptions.RayActorError)."""


class WorkerCrashedError(RuntimeError):
    """The worker process died unexpectedly."""


class RayOutOfMemoryError(RuntimeError):
    """A worker was killed by the node memory monitor (reference
    ray.exceptions.OutOfMemoryError + ``_private/memory_monitor.py``
    RayOutOfMemoryError); the message carries the node usage and the
    top per-worker RSS breakdown at kill time."""
