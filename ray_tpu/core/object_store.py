"""Host object plane: ObjectRef + shared-memory store.

Plays the roles of the reference's in-process memory store
(``src/ray/core_worker/store_provider/memory_store/memory_store.h:43``) for
small objects and the plasma store (``plasma/store.h:55``) for large ones,
scoped to one host. Objects above ``SHM_THRESHOLD`` are serialized into a
POSIX shared-memory segment so any worker process on the node can map them
zero-copy; small objects travel inline over the control pipes.

Disposition vs the reference (SURVEY §2.1): distributed refcounting /
spilling / lineage reconstruction are host-scoped here — a put object lives
until ``free()`` or driver shutdown; cross-host transfer belongs to the
(future) DCN object transport, not this file.
"""

from __future__ import annotations

import os
import threading
import uuid
from multiprocessing import shared_memory
from typing import Any, Dict, Optional

from ray_tpu.core import serialization as ser

SHM_THRESHOLD = 256 * 1024  # bytes


class Segment(shared_memory.SharedMemory):
    """SharedMemory whose finalizer tolerates still-exported views.

    Task results are deserialized as zero-copy numpy views into the
    segment; if user code still references them when the segment object
    is garbage-collected (e.g. at interpreter exit without free()),
    stock SharedMemory.__del__ sprays "BufferError: cannot close
    exported pointers exist". The OS reclaims the mapping at process
    exit regardless, so the finalizer — and only the finalizer —
    swallows that error; explicit close() still raises."""

    def __del__(self):
        try:
            super().__del__()
        except BufferError:
            pass


class ObjectRef:
    """Future handle to a task result or put object
    (reference ``python/ray/_raylet.pyx ObjectRef``)."""

    __slots__ = ("id", "_store")

    def __init__(self, id: Optional[str] = None, store=None):
        self.id = id or uuid.uuid4().hex
        self._store = store

    def __hash__(self):
        return hash(self.id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and self.id == other.id

    def hex(self) -> str:
        return self.id

    def __repr__(self):
        return f"ObjectRef({self.id[:16]})"

    def __reduce__(self):
        # Refs pickle as bare ids; the receiving side re-binds its store.
        return (ObjectRef, (self.id,))


class _Entry:
    __slots__ = ("value", "shm", "event", "error", "callbacks")

    def __init__(self):
        self.value = None
        self.shm: Optional[shared_memory.SharedMemory] = None
        self.event = threading.Event()
        self.error: Optional[BaseException] = None
        self.callbacks = []

    def fire(self):
        self.event.set()
        cbs, self.callbacks = self.callbacks, []
        for cb in cbs:
            cb()


class ObjectStore:
    """Driver-side object table. Thread-safe."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: Dict[str, _Entry] = {}

    def _entry(self, obj_id: str) -> _Entry:
        with self._lock:
            e = self._entries.get(obj_id)
            if e is None:
                e = _Entry()
                self._entries[obj_id] = e
            return e

    def put(self, obj_id: str, value: Any, use_shm: bool = True) -> Optional[str]:
        """Store a value; returns shm segment name if spilled to shm."""
        e = self._entry(obj_id)
        shm_name = None
        if use_shm:
            meta, buffers = ser.serialize(value)
            size = ser.serialized_size(meta, buffers)
            if size >= SHM_THRESHOLD:
                shm = Segment(
                    create=True, size=size, name=f"rt_{obj_id[:24]}"
                )
                ser.write_to_buffer(shm.buf, meta, buffers)
                e.shm = shm
                shm_name = shm.name
        e.value = value
        e.fire()
        return shm_name

    def put_error(self, obj_id: str, err: BaseException) -> None:
        e = self._entry(obj_id)
        e.error = err
        e.fire()

    def attach_shm(self, obj_id: str, shm_name: str) -> None:
        """Register a worker-created shm segment as this object's value."""
        e = self._entry(obj_id)
        shm = Segment(name=shm_name)
        e.shm = shm
        e.value = ser.read_from_buffer(shm.buf)
        e.fire()

    def is_ready(self, obj_id: str) -> bool:
        return self._entry(obj_id).event.is_set()

    def wait(self, obj_id: str, timeout: Optional[float] = None) -> bool:
        return self._entry(obj_id).event.wait(timeout)

    def get(self, obj_id: str, timeout: Optional[float] = None) -> Any:
        e = self._entry(obj_id)
        if not e.event.wait(timeout):
            raise GetTimeoutError(f"Timed out getting object {obj_id}")
        if e.error is not None:
            raise e.error
        return e.value

    def on_ready(self, obj_id: str, callback) -> None:
        """Run callback when the object becomes available (or immediately)."""
        e = self._entry(obj_id)
        with self._lock:
            if e.event.is_set():
                run_now = True
            else:
                e.callbacks.append(callback)
                run_now = False
        if run_now:
            callback()

    def discard_callback(self, obj_id: str, callback) -> None:
        """Deregister a pending on_ready callback (no-op if absent/fired).
        Lets wait() clean up after itself instead of accumulating dead
        callbacks on never-ready entries."""
        with self._lock:
            e = self._entries.get(obj_id)
            if e is not None:
                try:
                    e.callbacks.remove(callback)
                except ValueError:
                    pass

    def shm_name(self, obj_id: str) -> Optional[str]:
        e = self._entries.get(obj_id)
        return e.shm.name if e and e.shm else None

    def free(self, obj_ids) -> None:
        with self._lock:
            for oid in obj_ids:
                e = self._entries.pop(oid, None)
                if e and e.shm:
                    e.value = None  # drop zero-copy views first
                    try:
                        e.shm.unlink()
                    except FileNotFoundError:
                        pass
                    try:
                        e.shm.close()
                    except BufferError:
                        # Deserialized arrays still view the buffer; the
                        # mapping is released when they are GC'd.
                        pass

    def clear(self) -> None:
        with self._lock:
            ids = list(self._entries)
        self.free(ids)


class GetTimeoutError(TimeoutError):
    """reference: ray.exceptions.GetTimeoutError"""


class RayTaskError(RuntimeError):
    """A task raised; carries the remote traceback
    (reference ray.exceptions.RayTaskError)."""

    def __init__(self, function_name: str, traceback_str: str,
                 cause: Optional[BaseException] = None):
        self.function_name = function_name
        self.traceback_str = traceback_str
        self.cause = cause
        super().__init__(
            f"Task {function_name} failed:\n{traceback_str}"
        )


class RayActorError(RuntimeError):
    """Actor died or method failed (reference ray.exceptions.RayActorError)."""


class WorkerCrashedError(RuntimeError):
    """The worker process died unexpectedly."""
