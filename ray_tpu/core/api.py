"""Ray-like task/actor API over a process-based local backend.

Counterpart of the reference's Python core API
(``python/ray/_private/worker.py:984`` init, ``:2086`` get, remote_function /
actor decorator machinery ``remote_function.py:34`` / ``actor.py:377``) and,
underneath, the roles of raylet scheduling + CoreWorker submission
(``src/ray/core_worker/core_worker.h:462``), scoped to one host.

TPU-first disposition (SURVEY §2.1 table note): the heavy C++ process fabric
(GCS, raylet, gRPC transports) is replaced by a driver-resident scheduler +
spawned CPU worker processes + a shared-memory object plane. On a TPU pod
the accelerator-side "scheduling" is static SPMD placement via jax meshes;
this API exists for the CPU rollout fleet around the learner. Multi-host
fan-out rides jax.distributed (DCN) rather than a bespoke RPC stack.
"""

from __future__ import annotations

import atexit
import functools
import os
import queue
import threading
import time
import traceback
import uuid
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import multiprocessing as mp

from ray_tpu.core import serialization as ser
from ray_tpu.core.object_store import (
    ObjectRef,
    ObjectStore,
    RayActorError,
    RayOutOfMemoryError,
    RayTaskError,
    WorkerCrashedError,
)
from ray_tpu.core.worker_proc import worker_main, _ObjArg

_INLINE_ARG_MAX = 256 * 1024


class _WorkerHandle:
    def __init__(self, proc, conn, worker_id: str, dedicated: bool):
        self.proc = proc
        self.conn = conn
        self.worker_id = worker_id
        self.dedicated = dedicated  # actor-owned process
        self.idle = True
        self.dead = False
        self.registered_funcs = set()
        self.inflight: Dict[str, "_TaskRecord"] = {}
        self.send_lock = threading.Lock()
        self.recv_thread: Optional[threading.Thread] = None
        self.ring = None  # bulk-result ShmRing (attached lazily)
        self.ring_results = 0


class _TaskRecord:
    def __init__(self, task_id, msg, retries_left, name,
                 num_cpus: float = 1.0, resources=None,
                 placement_group=None, bundle_index: int = -1):
        self.task_id = task_id
        self.msg = msg
        self.retries_left = retries_left
        self.name = name
        self.num_cpus = float(num_cpus)
        self.resources = dict(resources or {})
        self.placement_group = placement_group
        self.bundle_index = int(bundle_index)
        self.acquired_bundle = -1  # set at admission
        self.submit_time = time.time()


class _ActorRecord:
    def __init__(self, actor_id, worker, cls_blob, init_msg, max_restarts,
                 daemon: bool = True):
        self.actor_id = actor_id
        self.worker = worker
        self.cls_blob = cls_blob
        self.init_msg = init_msg
        self.max_restarts = max_restarts
        self.daemon = daemon
        self.restarts = 0
        self.name: Optional[str] = None
        self.dead = False


class _Runtime:
    """Global driver state (reference: the global ``Worker`` in
    ``_private/worker.py:397``)."""

    def __init__(self, num_cpus: int, object_store_memory=None,
                 resources=None):
        self.num_cpus = num_cpus
        # Resource-aware scheduling (reference ClusterResourceScheduler
        # cluster_resource_scheduler.h:45, fixed-point bookkeeping):
        # dispatch admits a task only when its CPU + custom-resource
        # demand fits; placement groups carve out their own pools.
        self.available_cpus = float(num_cpus)
        self.total_resources = dict(resources or {})
        self.available_resources = dict(self.total_resources)
        self.store = ObjectStore(max_bytes=object_store_memory)
        # workers currently parked in a nested blocking get — they
        # lend their CPU and pool slot to their children
        self.blocked_workers = 0
        self.ctx = mp.get_context("spawn")
        self.lock = threading.RLock()
        self.pool: List[_WorkerHandle] = []
        self.actors: Dict[str, _ActorRecord] = {}
        self.named_actors: Dict[str, str] = {}
        self.pending: "queue.deque" = None
        import collections

        self.pending = collections.deque()
        self.timeline_events: List[Dict] = []
        self.shutting_down = False
        self._worker_env = {}
        self._job_runtime_env = None
        # Cross-host fleet (core/cluster.py): the head's listener and
        # the map of actors placed on remote agents
        self.cluster = None
        self.remote_actors: Dict[str, Any] = {}
        # actor_id -> (pg, num_cpus, bundle_index) for actors charged
        # against a placement-group bundle (released at kill)
        self._actor_pg_charges: Dict[str, Any] = {}
        # Durable job/actor metadata tables (the gcs_job_manager /
        # gcs_actor_manager storage role, reference
        # gcs/gcs_table_storage.cc): enabled via ray.init(state_path=)
        # or RAY_TPU_STATE_PATH. Driver death keeps the record; a
        # restarted driver (or `list_jobs`) can inspect prior runs.
        self.state_store = None
        self.job_id = f"job_{uuid.uuid4().hex[:8]}"
        state_path = os.environ.get("RAY_TPU_STATE_PATH")
        if state_path:
            self._open_state_store(state_path)

    def _open_state_store(self, path: str) -> None:
        import json as _json
        import time as _time

        from ray_tpu.core.store_client import make_store_client

        self.state_store = make_store_client(path)
        self.state_store.put(
            "jobs",
            self.job_id,
            _json.dumps(
                {
                    "job_id": self.job_id,
                    "status": "RUNNING",
                    "start_time": _time.time(),
                    "pid": os.getpid(),
                }
            ).encode(),
        )

    def _record_named_actor(self, name: str, actor_id: str, cls_name: str):
        if self.state_store is None:
            return
        import json as _json
        import time as _time

        self.state_store.put(
            "actors",
            name,
            _json.dumps(
                {
                    "name": name,
                    "actor_id": actor_id,
                    "class": cls_name,
                    "job_id": self.job_id,
                    "time": _time.time(),
                }
            ).encode(),
        )

    # -- worker lifecycle ------------------------------------------------

    def _worker_api_server(self):
        """Lazy singleton worker-API listener (nested ray.* calls)."""
        with self.lock:
            if getattr(self, "_api_server", None) is None:
                from ray_tpu.core.worker_api import WorkerAPIServer

                self._api_server = WorkerAPIServer(self)
            return self._api_server

    def _spawn_worker(
        self, dedicated: bool = False, daemon: bool = True
    ) -> _WorkerHandle:
        worker_id = uuid.uuid4().hex[:12]
        parent_conn, child_conn = self.ctx.Pipe(duplex=True)
        env = dict(self._worker_env)
        # nested ray.* calls inside this worker route back here over
        # the worker-API channel (core/worker_api.py)
        env.setdefault(
            "RAY_TPU_DRIVER_API", self._worker_api_server().address
        )
        env["RAY_TPU_WORKER_ID"] = worker_id
        # daemon=False is for actors that must spawn children of their
        # own (e.g. tune trial actors hosting an Algorithm with rollout
        # workers) — daemonic processes cannot have children.
        proc = self.ctx.Process(
            target=worker_main,
            args=(child_conn, worker_id, env),
            daemon=daemon,
            name=f"ray_tpu_worker_{worker_id}",
        )
        proc.start()
        child_conn.close()
        w = _WorkerHandle(proc, parent_conn, worker_id, dedicated)
        if self._job_runtime_env:
            # job-level runtime_env (ray.init) reaches every worker
            # before any task does (pipe ordering)
            w.conn.send(
                {
                    "type": "runtime_env",
                    "packed": self._job_runtime_env,
                }
            )
        t = threading.Thread(
            target=self._recv_loop, args=(w,), daemon=True,
            name=f"recv_{worker_id}",
        )
        w.recv_thread = t
        t.start()
        return w

    def _recv_loop(self, w: _WorkerHandle):
        while True:
            try:
                msg = w.conn.recv()
            except (EOFError, OSError):
                self._on_worker_death(w)
                return
            self._on_result(w, msg)

    def _on_result(self, w: _WorkerHandle, msg: Dict):
        status = msg["status"]
        if status == "ring":
            # Worker announced its bulk-result ring: attach as consumer.
            try:
                from ray_tpu.core.shm_ring import ShmRing

                w.ring = ShmRing.attach(msg["ring_name"])
            except Exception:
                w.ring = None
            return
        if msg.get("spans"):
            from ray_tpu.util import tracing

            tracing.record_spans(msg["spans"])
        task_id = msg.get("task_id")
        with self.lock:
            rec = w.inflight.pop(task_id, None)
        if status == "ok":
            self.store.put(
                task_id, ser.loads(msg["value_blob"]), use_shm=False
            )
        elif status == "ok_ring":
            # The record was pushed before the control message was sent,
            # so the next ring record is this task's payload (SPSC FIFO).
            data = w.ring.pop_bytes(timeout=30.0) if w.ring else None
            if data is None:
                self.store.put_error(
                    task_id,
                    WorkerCrashedError(
                        "bulk result missing from worker ring"
                    ),
                )
            else:
                w.ring_results += 1
                self.store.put(
                    task_id,
                    ser.read_from_buffer(memoryview(data)),
                    use_shm=False,
                )
        elif status == "ok_shm":
            self.store.attach_shm(task_id, msg["shm_name"])
        else:
            name = rec.name if rec else "unknown"
            err: BaseException = RayTaskError(name, msg["traceback"])
            self.store.put_error(task_id, err)
        if rec:
            self._record_event(rec, w)
            with self.lock:
                self._release(rec)
        with self.lock:
            if not w.dedicated:
                w.idle = True
        self._dispatch_pending()

    def _on_worker_death(self, w: _WorkerHandle):
        with self.lock:
            if w.dead:
                return
            w.dead = True
            if w.ring is not None:
                try:
                    w.ring.close()
                except Exception:
                    pass
                w.ring = None
            inflight = list(w.inflight.values())
            w.inflight.clear()
            for trec in inflight:
                self._release(trec)
            if not w.dedicated:
                if w in self.pool:
                    self.pool.remove(w)
            actor_rec = None
            for rec in self.actors.values():
                if rec.worker is w:
                    actor_rec = rec
                    break
        if self.shutting_down:
            return
        oom_reason = getattr(w, "oom_reason", None)
        for trec in inflight:
            if trec.retries_left > 0 and trec.msg["type"] == "task":
                trec.retries_left -= 1
                self._enqueue(trec)
            else:
                err: BaseException
                if oom_reason is not None:
                    err = RayOutOfMemoryError(
                        f"Task {trec.name} was killed by the memory "
                        f"monitor.\n{oom_reason}"
                    )
                elif actor_rec is not None:
                    err = RayActorError(
                        f"Actor {actor_rec.actor_id} died executing "
                        f"{trec.name}"
                    )
                else:
                    err = WorkerCrashedError(
                        f"Worker died executing {trec.name}"
                    )
                self.store.put_error(trec.task_id, err)
        if actor_rec is not None:
            self._maybe_restart_actor(actor_rec)
        self._dispatch_pending()

    def _maybe_restart_actor(self, rec: _ActorRecord):
        with self.lock:
            if rec.restarts >= rec.max_restarts or self.shutting_down:
                rec.dead = True
                return
            rec.restarts += 1
            w = self._spawn_worker(dedicated=True, daemon=rec.daemon)
            rec.worker = w
        with w.send_lock:
            w.conn.send(rec.init_msg)

    # -- scheduling ------------------------------------------------------

    def _enqueue(self, trec: _TaskRecord):
        with self.lock:
            self.pending.append(trec)
        self._dispatch_pending()

    def _fits(self, trec) -> bool:
        """Lock held: does the task's resource demand fit right now?"""
        pg = trec.placement_group
        if pg is not None:
            # head dispatch only admits against HEAD-hosted bundles;
            # bundles reserved on fleet agents admit via _try_spill
            return pg._fits(
                trec.num_cpus, trec.bundle_index, node_id=None
            )
        if trec.num_cpus > self.available_cpus + 1e-9:
            return False
        for k, v in trec.resources.items():
            if v > self.available_resources.get(k, 0.0) + 1e-9:
                return False
        return True

    def _acquire(self, trec) -> bool:
        """→ False when a placement-group charge lost the race between
        _fits and here (an actor creation filled the bundle): the
        caller requeues instead of dispatching an uncharged task."""
        pg = trec.placement_group
        if pg is not None:
            trec.acquired_bundle = pg._acquire(
                trec.num_cpus, trec.bundle_index
            )
            return trec.acquired_bundle >= 0
        self.available_cpus -= trec.num_cpus
        for k, v in trec.resources.items():
            self.available_resources[k] = (
                self.available_resources.get(k, 0.0) - v
            )
        return True

    def _release(self, trec) -> None:
        pg = trec.placement_group
        if pg is not None:
            pg._release(trec.num_cpus, trec.acquired_bundle)
            return
        self.available_cpus += trec.num_cpus
        for k, v in trec.resources.items():
            self.available_resources[k] = (
                self.available_resources.get(k, 0.0) + v
            )

    def _dispatch_pending(self):
        while True:
            spill = False
            with self.lock:
                if not self.pending:
                    return
                w = None
                for cand in self.pool:
                    if cand.idle and not cand.dead:
                        w = cand
                        break
                # workers parked in a nested ray.get lend out both
                # their CPU and their pool slot (worker_api.py)
                cap = self.num_cpus + getattr(
                    self, "blocked_workers", 0
                )
                if w is None and len(self.pool) < cap:
                    w = self._spawn_worker()
                    self.pool.append(w)
                if w is None:
                    spill = True
                else:
                    # FIFO with skip: the first pending task whose
                    # resource demand fits (reference
                    # cluster_task_manager queueing)
                    trec = None
                    for i, cand_t in enumerate(self.pending):
                        if self._fits(cand_t):
                            trec = cand_t
                            del self.pending[i]
                            break
                    if trec is None:
                        spill = True
                    elif not self._acquire(trec):
                        # pg bundle filled between _fits and the
                        # charge: requeue, try the spill path
                        self.pending.appendleft(trec)
                        trec = None
                        spill = True
                    else:
                        w.idle = False
                        w.inflight[trec.task_id] = trec
            if spill:
                # local head is saturated: push queued work to fleet
                # agents (the reference's lease spillback —
                # cluster_resource_scheduler.h:45)
                self._try_spill()
                return
            self._send_task(w, trec)

    def _try_spill(self):
        """Ship queued stateless tasks to fleet agents with free CPU
        capacity. Plain CPU tasks spill to the freest node;
        placement-group tasks spill to THE node hosting a fitting
        bundle (cross-node gang scheduling,
        ``raylet/placement_group_resource_manager.h`` commit side).
        Custom-resource tasks stay head-local — agents register CPUs
        only. Args marshal through the node's once-per-node pool."""
        cluster = getattr(self, "cluster", None)
        if cluster is None:
            return
        while True:
            nodes = [
                n for n in cluster.nodes.values() if not n.dead
            ]
            if not nodes:
                return
            pick = None
            with self.lock:
                for i, t in enumerate(self.pending):
                    if (
                        t.resources
                        or t.msg.get("type") != "task"
                        or getattr(t, "orig_args", None) is None
                    ):
                        continue
                    pg = t.placement_group
                    if pg is not None:
                        # the bundle's node is fixed at reservation:
                        # admit against it, run on it (CPUs already
                        # reserved there — no node-ledger charge)
                        for node in nodes:
                            if pg._fits(
                                t.num_cpus,
                                t.bundle_index,
                                node_id=node.node_id,
                            ):
                                t.acquired_bundle = pg._acquire(
                                    t.num_cpus,
                                    t.bundle_index,
                                    node_id=node.node_id,
                                )
                                if t.acquired_bundle >= 0:
                                    t.pg_spilled = True
                                    pick = (t, node)
                                    del self.pending[i]
                                break
                        if pick is not None:
                            break
                        continue
                    node = max(nodes, key=lambda n: n.free_cpus())
                    if node.free_cpus() >= t.num_cpus:
                        pick = (t, node)
                        del self.pending[i]
                        break
                if pick is None:
                    return
            t, node = pick
            try:
                m_args, m_kwargs = node.marshal_args(
                    t.orig_args, t.orig_kwargs
                )
                payload = ser.dumps((m_args, m_kwargs))
                sent = node.submit_task(t, payload)
            except BaseException:
                sent = False
            if not sent:
                # un-charge before requeue — the retry re-acquires,
                # and a leaked charge would shrink the bundle forever
                if getattr(t, "pg_spilled", False):
                    t.placement_group._release(
                        t.num_cpus, t.acquired_bundle
                    )
                    t.pg_spilled = False
                    t.acquired_bundle = -1
                with self.lock:
                    self.pending.appendleft(t)
                return

    def _send_task(self, w: _WorkerHandle, trec: _TaskRecord):
        msg = trec.msg
        try:
            with w.send_lock:
                if (
                    msg["type"] == "task"
                    and msg["func_id"] not in w.registered_funcs
                ):
                    w.conn.send(
                        {
                            "type": "register_func",
                            "func_id": msg["func_id"],
                            "func": msg["func_blob"],
                        }
                    )
                    w.registered_funcs.add(msg["func_id"])
                wire = {k: v for k, v in msg.items() if k != "func_blob"}
                w.conn.send(wire)
        except (BrokenPipeError, OSError):
            self._on_worker_death(w)

    def _record_event(self, trec: _TaskRecord, w: _WorkerHandle):
        now = time.time()
        self.timeline_events.append(
            {
                "name": trec.name,
                "cat": "task",
                "ph": "X",
                "ts": trec.submit_time * 1e6,
                "dur": (now - trec.submit_time) * 1e6,
                "pid": 1,
                "tid": hash(w.worker_id) % 10000,
            }
        )

    # -- argument marshalling --------------------------------------------

    def _marshal_arg(self, v):
        if isinstance(v, ObjectRef):
            if not self.store.is_ready(v.id):
                raise _UnreadyDep(v.id)
            shm = self.store.shm_name(v.id)
            if shm:
                return _ObjArg(v.id, shm_name=shm)
            # already spilled: ship the storage location, not the
            # bytes — the worker reads the spill file directly instead
            # of this path restoring the value into driver memory and
            # inlining it over the pipe
            loc = self.store.spill_location(v.id)
            if loc is not None:
                return _ObjArg(v.id, spill_loc=loc)
            # node-resident (fleet data plane): ship the node's data
            # server address — a local worker pulls peer-style, and
            # the driver never materializes the bytes (pulling here
            # would defeat the per-node store for every head-executed
            # task naming a fleet-produced ref)
            rloc = self.store.remote_loc(v.id)
            if rloc is not None:
                return _ObjArg(
                    v.id,
                    remote_loc=(rloc["host"], rloc["port"]),
                )
            return _ObjArg(
                v.id, inline=self.store.get(v.id), has_inline=True
            )
        return v

    def submit_task(
        self, func, func_id, func_blob, args, kwargs, options
    ) -> List[ObjectRef]:
        num_returns = options.get("num_returns", 1)
        task_id = uuid.uuid4().hex
        name = options.get("name") or getattr(func, "__name__", "task")
        # NOTE: no base-task_id ObjectRef in the multi-return case —
        # a created-then-discarded handle would refcount the base
        # entry to zero and free the tuple out from under the split
        if num_returns > 1:
            refs = [
                ObjectRef(f"{task_id}_{i}", self.store)
                for i in range(num_returns)
            ]
            self._register_split(task_id, refs)
        else:
            refs = [ObjectRef(task_id, self.store)]

        pg = None
        bundle_index = -1
        strategy = options.get("scheduling_strategy")
        if strategy is not None and hasattr(
            strategy, "placement_group"
        ):
            pg = strategy.placement_group
            bundle_index = getattr(
                strategy, "placement_group_bundle_index", -1
            )
        from ray_tpu.core.runtime_env import pack_runtime_env
        from ray_tpu.util import tracing

        trec = _TaskRecord(
            task_id,
            {
                "type": "task",
                "task_id": task_id,
                "func_id": func_id,
                "func_blob": func_blob,
                "runtime_env": (
                    options["runtime_env_packed"]
                    if "runtime_env_packed" in options
                    else pack_runtime_env(options.get("runtime_env"))
                ),
                "trace_ctx": tracing.inject_context(),
                "args": args,
                "kwargs": kwargs,
            },
            retries_left=options.get("max_retries", 3),
            name=name,
            num_cpus=(
                1 if options.get("num_cpus") is None
                else options["num_cpus"]
            ),
            resources=options.get("resources"),
            placement_group=pg,
            bundle_index=bundle_index,
        )
        # spillover needs this: an agent executing a multi-return task
        # splits the tuple NODE-SIDE (one node-resident object per
        # return) so the parts never transit the head
        trec.num_returns = num_returns
        self._submit_when_ready(trec, args, kwargs)
        return refs

    def _register_split(self, task_id: str, refs: List[ObjectRef]):
        def split():
            try:
                values = self.store.get(task_id)
            except BaseException as e:  # propagate error to all returns
                for r in refs:
                    self.store.put_error(r.id, e)
                self.store.free([task_id])
                return
            for r, v in zip(refs, values):
                self.store.put(r.id, v, use_shm=False)
            # nothing holds a handle to the base tuple entry
            self.store.free([task_id])

        self.store.on_ready(task_id, split)

    def _submit_when_ready(self, trec: _TaskRecord, args, kwargs):
        """Marshal args; if some ObjectRef deps are unready, wait for them."""
        deps = [
            a.id
            for a in list(args) + list(kwargs.values())
            if isinstance(a, ObjectRef) and not self.store.is_ready(a.id)
        ]
        if not deps:
            # pin the argument refs on the record: marshalling strips
            # them from the msg, but the entries (shm segments) must
            # outlive dispatch AND any retries — the task record is
            # exactly that lifetime (reference_count.h's
            # task-dependency references)
            trec.arg_refs = [
                a
                for a in list(trec.msg["args"])
                + list(trec.msg["kwargs"].values())
                if isinstance(a, ObjectRef)
            ]
            # keep the unmarshalled args: spillover to a fleet agent
            # must re-marshal for the remote object plane (shm names
            # in the local payload mean nothing off-host)
            trec.orig_args = list(trec.msg["args"])
            trec.orig_kwargs = dict(trec.msg["kwargs"])
            m_args = [self._marshal_arg(a) for a in trec.msg["args"]]
            m_kwargs = {
                k: self._marshal_arg(v) for k, v in trec.msg["kwargs"].items()
            }
            trec.msg["payload"] = ser.dumps((m_args, m_kwargs))
            del trec.msg["args"], trec.msg["kwargs"]
            self._enqueue(trec)
            return
        remaining = {"n": len(deps)}
        lk = threading.Lock()

        def on_dep():
            with lk:
                remaining["n"] -= 1
                done = remaining["n"] == 0
            if done:
                self._submit_when_ready(trec, trec.msg["args"], trec.msg["kwargs"])

        for d in deps:
            self.store.on_ready(d, on_dep)

    # -- actors ----------------------------------------------------------

    def _local_actor_saturated(self, options) -> bool:
        """Would placing one more dedicated-CPU actor locally
        oversubscribe the head? (Actors run on dedicated workers
        outside the task pool's CPU ledger, so they keep their own
        count.)"""
        req = options.get("num_cpus")
        req = 1.0 if req is None else float(req)
        if req <= 0:
            return False
        with self.lock:
            used = sum(
                getattr(rec, "num_cpus", 1.0)
                for rec in self.actors.values()
                if not rec.dead
            )
        return used + req > self.num_cpus

    def create_actor(self, cls, args, kwargs, options) -> "ActorHandle":
        from ray_tpu.core.runtime_env import pack_runtime_env

        # pack path-based runtime_env pieces HERE (driver-side), so
        # the spec ships host-independently — including to remote node
        # agents (reference runtime_env URI upload at submission time)
        renv_packed = options.get("runtime_env_packed")
        if renv_packed is None:
            renv_packed = pack_runtime_env(
                options.get("runtime_env")
            )
        # placement-group actors: charge a bundle and run ON the
        # bundle's node (the reference's pg-aware actor scheduling —
        # gcs_actor_scheduler honoring the bundle's node commit)
        pg_strategy = options.get("scheduling_strategy")
        pg = getattr(pg_strategy, "placement_group", None)
        pg_charge = None
        if pg is not None:
            if not pg.ready(timeout=30.0):
                raise TimeoutError(
                    f"placement group {pg.id} not ready"
                )
            ncpus = (
                1.0
                if options.get("num_cpus") is None
                else float(options["num_cpus"])
            )
            bidx = getattr(
                pg_strategy, "placement_group_bundle_index", -1
            )
            # under the runtime lock: task dispatch does its
            # _fits/_acquire pair there, so actor charges must not
            # interleave between them
            with self.lock:
                bundle, pg_node = pg._acquire_any(ncpus, bidx)
            if bundle < 0:
                raise ValueError(
                    f"placement group {pg.id} cannot admit actor "
                    f"(num_cpus={ncpus}, bundle_index={bidx})"
                )
            pg_charge = (pg, ncpus, bundle)
            options = dict(options)
            if pg_node is not None:
                # agent bundle: pin there; CPUs are paid by the pg
                # ledger, not the node's actor ledger
                options["placement_node"] = pg_node
                options["pg_charged"] = True
        if pg_charge is not None:
            # any failure between the charge and a registered actor
            # (duplicate name, node send error, unpicklable class)
            # must give the bundle back or the group bleeds capacity
            try:
                return self._create_actor_placed(
                    cls, args, kwargs, options, renv_packed,
                    pg_charge,
                )
            except BaseException:
                pgx, ncpusx, bundlex = pg_charge
                for aid, ch in list(
                    self._actor_pg_charges.items()
                ):
                    if ch is pg_charge:
                        self._actor_pg_charges.pop(aid, None)
                pgx._release(ncpusx, bundlex)
                raise
        return self._create_actor_placed(
            cls, args, kwargs, options, renv_packed, None
        )

    def _create_actor_placed(
        self, cls, args, kwargs, options, renv_packed, pg_charge
    ) -> "ActorHandle":
        node_name = options.get("placement_node")
        pg = (
            pg_charge[0] if pg_charge is not None else None
        )
        if (
            node_name is None
            and pg is None  # pg decides placement, not saturation
            and self.cluster is not None
            and self._local_actor_saturated(options)
        ):
            # automatic spillover: unpinned actors spread to fleet
            # agents once the head's CPUs are spoken for (the hybrid
            # local-first/spillback policy of the reference's
            # cluster_resource_scheduler.h:45, scoped to actors+CPUs)
            node_name = "any"
        if node_name is not None and self.cluster is not None:
            try:
                node = self.cluster.pick_node(
                    None if node_name == "any" else node_name
                )
            except ValueError:
                # requested node is gone (e.g. recreate_failed_workers
                # after a host death): fall back to local placement so
                # the fault-tolerance path keeps the run alive rather
                # than throwing (reference: dead-node leases respawn
                # wherever the cluster scheduler finds room)
                import warnings

                warnings.warn(
                    f"cluster node {node_name!r} unavailable; placing "
                    "actor locally"
                )
                node = None
            if node is not None:
                actor_id = uuid.uuid4().hex
                name = options.get("name")
                if renv_packed is not None:
                    options = dict(
                        options, runtime_env_packed=renv_packed
                    )
                r_args, r_kwargs = node.marshal_args(args, kwargs)
                with self.lock:
                    if name:
                        if name in self.named_actors:
                            raise ValueError(
                                f"Actor name {name} already taken"
                            )
                        self.named_actors[name] = actor_id
                        self._record_named_actor(
                            name, actor_id, cls.__name__
                        )
                    self.remote_actors[actor_id] = node
                    if pg_charge is not None:
                        self._actor_pg_charges[actor_id] = pg_charge
                node.create_actor(
                    actor_id, cls, r_args, r_kwargs, options
                )
                return ActorHandle(actor_id, cls.__name__)
        actor_id = uuid.uuid4().hex
        # serialize BEFORE spawning: an unpicklable class or argument
        # must not leak a freshly spawned (possibly non-daemon) worker
        # process — an orphaned non-daemon child wedges interpreter
        # exit in multiprocessing's atexit join
        cls_blob = ser.dumps(cls)
        payload = ser.dumps(
            (
                [self._marshal_arg(a) for a in args],
                {k: self._marshal_arg(v) for k, v in kwargs.items()},
            )
        )
        w = self._spawn_worker(
            dedicated=True,
            daemon=bool(options.get("daemon", True)),
        )
        init_msg = {
            "type": "actor_init",
            "actor_id": actor_id,
            "task_id": None,
            "cls": cls_blob,
            "max_concurrency": int(
                options.get("max_concurrency", 1)
            ),
            "runtime_env": renv_packed,
            "payload": payload,
        }
        rec = _ActorRecord(
            actor_id, w, cls_blob, init_msg,
            options.get("max_restarts", 0),
            daemon=bool(options.get("daemon", True)),
        )
        if pg_charge is not None:
            self._actor_pg_charges[actor_id] = pg_charge
        rec.num_cpus = (
            1.0
            if options.get("num_cpus") is None
            else float(options["num_cpus"])
        )
        # constructor ref args stay pinned for the actor's LIFETIME:
        # a restart replays init_msg, which re-attaches their shm
        rec.arg_refs = [
            a
            for a in list(args) + list(kwargs.values())
            if isinstance(a, ObjectRef)
        ]
        name = options.get("name")
        with self.lock:
            self.actors[actor_id] = rec
            if name:
                if name in self.named_actors:
                    raise ValueError(f"Actor name {name} already taken")
                self.named_actors[name] = actor_id
                rec.name = name
                self._record_named_actor(name, actor_id, cls.__name__)
        with w.send_lock:
            w.conn.send(init_msg)
        return ActorHandle(actor_id, cls.__name__)

    def call_actor(self, actor_id, method, args, kwargs, num_returns=1):
        node = self.remote_actors.get(actor_id)
        if node is not None:
            if node.dead:
                ref = ObjectRef(uuid.uuid4().hex, self.store)
                self.store.put_error(
                    ref.id,
                    RayActorError(
                        f"Actor {actor_id}'s node {node.node_id} is dead"
                    ),
                )
                return [ref] * num_returns
            # ObjectRef args ride the once-per-node pool: the value
            # ships on first use per node, the id alone afterwards
            # (cluster._PoolObj) — weight broadcast to K actors on one
            # agent moves one copy, not K
            r_args, r_kwargs = node.marshal_args(args, kwargs)
            return node.call(
                actor_id, method, r_args, r_kwargs, num_returns
            )
        with self.lock:
            rec = self.actors.get(actor_id)
        if rec is None or rec.dead:
            ref = ObjectRef(uuid.uuid4().hex, self.store)
            self.store.put_error(
                ref.id, RayActorError(f"Actor {actor_id} is dead")
            )
            return [ref]
        from ray_tpu.util import tracing

        task_id = uuid.uuid4().hex
        trec = _TaskRecord(
            task_id,
            {
                "type": "actor_call",
                "task_id": task_id,
                "actor_id": actor_id,
                "method": method,
                "trace_ctx": tracing.inject_context(),
                "payload": ser.dumps(
                    (
                        [self._marshal_arg(a) for a in args],
                        {
                            k: self._marshal_arg(v)
                            for k, v in kwargs.items()
                        },
                    )
                ),
            },
            retries_left=0,
            name=f"{method}",
            # actor calls run on the actor's dedicated process: they
            # neither acquire nor release scheduler CPUs
            num_cpus=0,
        )
        # pin shm-backed argument refs until the call completes (see
        # _submit_when_ready)
        trec.arg_refs = [
            a
            for a in list(args) + list(kwargs.values())
            if isinstance(a, ObjectRef)
        ]
        w = rec.worker
        with self.lock:
            w.inflight[task_id] = trec
        self._send_task(w, trec)
        if num_returns > 1:
            refs = [
                ObjectRef(f"{task_id}_{i}", self.store)
                for i in range(num_returns)
            ]
            self._register_split(task_id, refs)
        else:
            refs = [ObjectRef(task_id, self.store)]
        return refs

    def kill_actor(self, actor_id: str, no_restart: bool = True):
        charge = self._actor_pg_charges.pop(actor_id, None)
        if charge is not None:
            pg, ncpus, bundle = charge
            pg._release(ncpus, bundle)
        node = self.remote_actors.pop(actor_id, None)
        if node is not None:
            node.kill(actor_id)
            return
        with self.lock:
            rec = self.actors.get(actor_id)
            if rec is None:
                return
            rec.dead = True
            if no_restart:
                rec.max_restarts = 0
            w = rec.worker
        try:
            w.proc.terminate()
        except Exception:
            pass

    # -- shutdown --------------------------------------------------------

    def shutdown(self):
        self.shutting_down = True
        with self.lock:
            workers = list(self.pool) + [
                rec.worker for rec in self.actors.values()
            ]
        for w in workers:
            try:
                with w.send_lock:
                    w.conn.send({"type": "shutdown"})
            except Exception:
                pass
        deadline = time.time() + 2.0
        for w in workers:
            w.proc.join(max(0.0, deadline - time.time()))
            if w.proc.is_alive():
                w.proc.terminate()
        self.store.clear()
        if self.state_store is not None:
            import json as _json

            try:
                rec = self.state_store.get("jobs", self.job_id)
                if rec:
                    job = _json.loads(rec.decode())
                    job["status"] = "FINISHED"
                    job["end_time"] = time.time()
                    self.state_store.put(
                        "jobs", self.job_id, _json.dumps(job).encode()
                    )
            finally:
                self.state_store.close()
                self.state_store = None
        srv = getattr(self, "_api_server", None)
        if srv is not None:
            srv.shutdown()
            self._api_server = None
        mon = getattr(self, "memory_monitor", None)
        if mon is not None:
            mon.stop()
            self.memory_monitor = None
        dash = getattr(self, "dashboard", None)
        if dash is not None:
            try:
                dash.shutdown()
            except Exception:
                pass
            self.dashboard = None


class _UnreadyDep(Exception):
    def __init__(self, obj_id):
        self.obj_id = obj_id


_runtime: Optional[_Runtime] = None


def init(
    num_cpus: Optional[int] = None,
    num_gpus: Optional[int] = None,
    object_store_memory: Optional[int] = None,
    ignore_reinit_error: bool = False,
    local_mode: bool = False,
    worker_env: Optional[Dict[str, str]] = None,
    log_dir: Optional[str] = None,
    address: Optional[str] = None,
    runtime_env: Optional[Dict] = None,
    **kwargs,
) -> Dict:
    """Start the local runtime (reference ray.init,
    ``_private/worker.py:984``).

    address="host:port" JOINS an existing head's fleet as a worker
    agent: this process's runtime hosts actors the head places here
    (reference: ray start --address joining a raylet to the GCS). The
    head enables its listener with
    ``ray_tpu.core.cluster.start_cluster_server()``."""
    global _runtime, _client_mode
    if address and address.startswith("ray://"):
        # LIVE remote-driver client (reference ray.util.client,
        # python/ray/util/client/__init__.py:214): this process keeps
        # NO runtime — every ray.* verb routes over the driver-API
        # wire to the head (the same channel nested worker calls use;
        # core/worker_api.py). The head exposes it with
        # ``start_client_server()``. Trust model: the channel carries
        # pickled payloads — loopback/SSH-tunnel or trusted-network
        # use, like the reference's client server.
        if _runtime is not None:
            raise RuntimeError(
                "ray://: this process already runs a local runtime"
            )
        from ray_tpu.core import worker_api

        os.environ[worker_api.ENV_ADDR] = address[len("ray://"):]
        _client_mode = True
        client = worker_api.worker_client()
        if client is None:  # pragma: no cover - env just set
            raise ConnectionError(f"cannot reach {address}")
        return {"address": address, "mode": "client"}
    if _runtime is not None:
        if ignore_reinit_error:
            return {"address": "local"}
        raise RuntimeError(
            "ray_tpu.init() called twice; pass ignore_reinit_error=True"
        )
    n = num_cpus if num_cpus is not None else max(4, os.cpu_count() or 1)
    resources = kwargs.get("resources")
    _runtime = _Runtime(n, object_store_memory, resources=resources)
    if worker_env:
        _runtime._worker_env.update(worker_env)
    if log_dir:
        _runtime._worker_env.setdefault("RAY_TPU_LOG_DIR", log_dir)
    if runtime_env:
        from ray_tpu.core.runtime_env import pack_runtime_env

        _runtime._job_runtime_env = pack_runtime_env(runtime_env)
    state_path = kwargs.get("state_path")
    if state_path and _runtime.state_store is None:
        _runtime._open_state_store(state_path)
    if (
        kwargs.get("enable_memory_monitor")
        or os.environ.get("RAY_TPU_MEMORY_MONITOR") == "1"
    ):
        from ray_tpu.core.memory_monitor import MemoryMonitor

        _runtime.memory_monitor = MemoryMonitor(_runtime)
    if kwargs.get("dashboard"):
        from ray_tpu.dashboard.dashboard import DashboardLite
        from ray_tpu.job.job_manager import JobManager

        _runtime.dashboard = DashboardLite(
            port=int(kwargs.get("dashboard_port") or 0),
            job_manager=JobManager(state_path=state_path),
        )
    if address and address not in ("local", "auto"):
        from ray_tpu.core.cluster import NodeAgent

        _runtime.node_agent = NodeAgent(
            address,
            node_id=kwargs.get("node_id"),
            num_cpus=num_cpus,
        )
        return {
            "address": address,
            "num_cpus": n,
            "node_id": _runtime.node_agent.node_id,
        }
    return {"address": "local", "num_cpus": n}


def start_client_server(host: str = "127.0.0.1", port: int = 0) -> str:
    """Expose this head's driver API for ``ray://`` remote drivers
    (reference ``ray.util.client.server``): returns "host:port" for
    ``ray_tpu.init(address="ray://host:port")`` in another process or
    host. Loopback by default; front with an SSH tunnel / trusted
    network for remote use (pickled payloads ride this channel)."""
    from ray_tpu.core.worker_api import WorkerAPIServer

    rt = _require_runtime()
    if getattr(rt, "client_server", None) is None:
        rt.client_server = WorkerAPIServer(rt, host=host, port=port)
    return rt.client_server.address


def list_jobs(state_path: Optional[str] = None) -> List[Dict]:
    """Jobs recorded in the durable state store — including those of
    PREVIOUS (dead) drivers, which is the point (reference
    gcs_job_manager.cc job table + `ray job list`). Reads the running
    runtime's store, or the file at ``state_path``/RAY_TPU_STATE_PATH
    without a runtime."""
    import json as _json

    if _runtime is not None and _runtime.state_store is not None:
        store = _runtime.state_store
        close = False
    else:
        path = state_path or os.environ.get("RAY_TPU_STATE_PATH")
        if not path or not os.path.exists(path):
            return []
        from ray_tpu.core.store_client import make_store_client

        store = make_store_client(path)
        close = True
    try:
        return sorted(
            (
                _json.loads(v.decode())
                for v in store.all("jobs").values()
            ),
            key=lambda j: j.get("start_time", 0),
        )
    finally:
        if close:
            store.close()


_client_mode = False


def is_initialized() -> bool:
    return _runtime is not None or _client_mode


def shutdown():
    global _runtime, _client_mode
    if _client_mode:
        from ray_tpu.core import worker_api

        os.environ.pop(worker_api.ENV_ADDR, None)
        _client_mode = False
    if _runtime is not None:
        _runtime.shutdown()
        _runtime = None


atexit.register(shutdown)


def _require_runtime() -> _Runtime:
    if _runtime is None:
        if _client_mode:
            raise RuntimeError(
                "this operation needs the head's runtime and is not "
                "proxied over the ray:// client channel"
            )
        init()
    return _runtime


def _ambient_client():
    """Worker-context driver-API client, if this process is a worker
    (nested ray.* calls route to the driver instead of booting a
    private runtime inside the worker — reference: every worker is a
    CoreWorker and submits through its own task path)."""
    if _runtime is not None:
        return None
    from ray_tpu.core.worker_api import worker_client

    return worker_client()


def put(value: Any) -> ObjectRef:
    client = _ambient_client()
    if client is not None:
        return ObjectRef(client.put(value))
    rt = _require_runtime()
    ref = ObjectRef(uuid.uuid4().hex, rt.store)
    rt.store.put(ref.id, value)
    return ref


def get(
    refs: Union[ObjectRef, Sequence[ObjectRef]],
    *,
    timeout: Optional[float] = None,
):
    client = _ambient_client()
    if client is not None:
        if isinstance(refs, ObjectRef):
            return client.get(refs.id, timeout)
        return [client.get(r.id, timeout) for r in refs]
    rt = _require_runtime()
    if isinstance(refs, ObjectRef):
        return rt.store.get(refs.id, timeout)
    return [rt.store.get(r.id, timeout) for r in refs]


def wait(
    refs: Sequence[ObjectRef],
    *,
    num_returns: int = 1,
    timeout: Optional[float] = None,
    fetch_local: bool = True,
) -> Tuple[List[ObjectRef], List[ObjectRef]]:
    """reference ray.wait (worker.py)."""
    client = _ambient_client()
    if client is not None:
        refs = list(refs)
        by_id = {r.id: r for r in refs}
        ready_ids, pending_ids = client.wait(
            [r.id for r in refs], num_returns, timeout
        )
        return (
            [by_id[i] for i in ready_ids],
            [by_id[i] for i in pending_ids],
        )
    rt = _require_runtime()
    refs = list(refs)
    deadline = None if timeout is None else time.time() + timeout
    ready: List[ObjectRef] = []
    evt = threading.Event()

    def notify():
        evt.set()

    registered: set = set()
    try:
        while True:
            # Clear BEFORE scanning: a ref completing after the scan
            # sets the event, so the wakeup cannot be lost between the
            # scan and the wait.
            evt.clear()
            ready = [r for r in refs if rt.store.is_ready(r.id)]
            if len(ready) >= num_returns:
                break
            if deadline is not None and time.time() >= deadline:
                break
            for r in refs:
                if r.id not in registered and not rt.store.is_ready(
                    r.id
                ):
                    rt.store.on_ready(r.id, notify)
                    registered.add(r.id)
            remaining_t = (
                None
                if deadline is None
                else max(0.0, deadline - time.time())
            )
            evt.wait(remaining_t)
    finally:
        # Deregister: repeated wait() polls on long-pending refs must
        # not accumulate callbacks on the store entries.
        for rid in registered:
            rt.store.discard_callback(rid, notify)
    ready, not_ready = [], []
    for r in refs:
        if rt.store.is_ready(r.id) and len(ready) < num_returns:
            ready.append(r)
        else:
            not_ready.append(r)
    return ready, not_ready


class RemoteFunction:
    """reference ``remote_function.py:34``."""

    def __init__(self, func, options: Dict):
        self._func = func
        self._options = dict(options)
        self._func_id = uuid.uuid4().hex[:16]
        self._func_blob = None
        functools.update_wrapper(self, func)

    def options(self, **kwargs) -> "RemoteFunction":
        rf = RemoteFunction(self._func, {**self._options, **kwargs})
        rf._func_id = self._func_id
        rf._func_blob = self._func_blob
        return rf

    def remote(self, *args, **kwargs) -> Union[ObjectRef, List[ObjectRef]]:
        if self._func_blob is None:
            self._func_blob = ser.dumps(self._func)
        client = _ambient_client()
        if client is not None:  # nested submission from a worker
            ids = client.submit(
                self._func,
                self._func_id,
                self._func_blob,
                list(args),
                dict(kwargs),
                self._options,
            )
            refs = [ObjectRef(i) for i in ids]
        else:
            rt = _require_runtime()
            refs = rt.submit_task(
                self._func,
                self._func_id,
                self._func_blob,
                list(args),
                dict(kwargs),
                self._options,
            )
        if self._options.get("num_returns", 1) == 1:
            return refs[0]
        return refs

    def __call__(self, *args, **kwargs):
        raise TypeError(
            "Remote functions cannot be called directly; use .remote()"
        )


class ActorMethod:
    def __init__(self, handle: "ActorHandle", name: str, num_returns: int = 1):
        self._handle = handle
        self._name = name
        self._num_returns = num_returns

    def options(self, num_returns: int = 1, **kwargs) -> "ActorMethod":
        return ActorMethod(self._handle, self._name, num_returns)

    def remote(self, *args, **kwargs):
        client = _ambient_client()
        if client is not None:  # actor call from inside a worker
            ids = client.call_actor(
                self._handle._actor_id,
                self._name,
                list(args),
                dict(kwargs),
                self._num_returns,
            )
            refs = [ObjectRef(i) for i in ids]
        else:
            rt = _require_runtime()
            refs = rt.call_actor(
                self._handle._actor_id, self._name, list(args),
                dict(kwargs), self._num_returns,
            )
        if self._num_returns == 1:
            return refs[0]
        return refs


class ActorHandle:
    """reference ``actor.py:950``."""

    def __init__(self, actor_id: str, class_name: str = "Actor"):
        self._actor_id = actor_id
        self._class_name = class_name

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return ActorMethod(self, name)

    def __repr__(self):
        return f"ActorHandle({self._class_name}, {self._actor_id[:8]})"

    def __reduce__(self):
        return (ActorHandle, (self._actor_id, self._class_name))


class ActorClass:
    """reference ``actor.py:377``."""

    def __init__(self, cls, options: Dict):
        self._cls = cls
        self._options = dict(options)

    def options(self, **kwargs) -> "ActorClass":
        return ActorClass(self._cls, {**self._options, **kwargs})

    def remote(self, *args, **kwargs) -> ActorHandle:
        client = _ambient_client()
        if client is not None:  # actor creation from inside a worker
            actor_id, class_name = client.create_actor(
                ser.dumps(self._cls), list(args), dict(kwargs),
                self._options,
            )
            return ActorHandle(actor_id, class_name)
        rt = _require_runtime()
        return rt.create_actor(self._cls, list(args), dict(kwargs),
                               self._options)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            "Actor classes cannot be instantiated directly; use .remote()"
        )


def remote(*args, **options):
    """``@ray.remote`` decorator (reference ``worker.py`` remote)."""

    def decorate(obj):
        if isinstance(obj, type):
            return ActorClass(obj, options)
        return RemoteFunction(obj, options)

    if len(args) == 1 and callable(args[0]) and not options:
        return decorate(args[0])
    return decorate


def method(num_returns: int = 1, **kwargs):
    """``@ray.method`` decorator — annotates num_returns on actor methods."""

    def decorate(m):
        m.__ray_num_returns__ = num_returns
        return m

    return decorate


def kill(actor: ActorHandle, *, no_restart: bool = True):
    client = _ambient_client()
    if client is not None:
        client.kill_actor(actor._actor_id, no_restart)
        return
    rt = _require_runtime()
    rt.kill_actor(actor._actor_id, no_restart)


def cancel(ref: ObjectRef, *, force: bool = False, recursive: bool = True):
    # Best-effort: mark as errored if not yet done.
    rt = _require_runtime()
    if not rt.store.is_ready(ref.id):
        rt.store.put_error(ref.id, TaskCancelledError("cancelled"))


class TaskCancelledError(RuntimeError):
    pass


def get_actor(name: str) -> ActorHandle:
    client = _ambient_client()
    if client is not None:  # named-actor lookup from inside a worker
        return ActorHandle(client.get_actor(name))
    rt = _require_runtime()
    with rt.lock:
        actor_id = rt.named_actors.get(name)
    if actor_id is None:
        raise ValueError(f"No actor named {name!r}")
    return ActorHandle(actor_id)


class RuntimeContext:
    def __init__(self):
        self.node_id = "local"
        self.job_id = (
            _runtime.job_id if _runtime is not None else "job_local"
        )

    def get(self):
        return {"node_id": self.node_id, "job_id": self.job_id}


def get_runtime_context() -> RuntimeContext:
    return RuntimeContext()


def available_resources() -> Dict[str, float]:
    rt = _require_runtime()
    with rt.lock:
        out = {"CPU": float(rt.available_cpus)}
        out.update(rt.available_resources)
    return out


def cluster_resources() -> Dict[str, float]:
    rt = _require_runtime()
    res = {"CPU": float(rt.num_cpus)}
    res.update(rt.total_resources)
    try:
        import jax

        tpus = len(
            [d for d in jax.devices() if d.platform not in ("cpu",)]
        )
        if tpus:
            res["TPU"] = float(tpus)
    except Exception:
        pass
    return res


def nodes() -> List[Dict]:
    return [
        {
            "NodeID": "local",
            "Alive": True,
            "Resources": cluster_resources(),
        }
    ]


def timeline() -> List[Dict]:
    """Chrome-trace events (reference ``_private/state.py:435``)."""
    rt = _require_runtime()
    return list(rt.timeline_events)


def free(refs: Sequence[ObjectRef]):
    client = _ambient_client()
    if client is not None:
        client.free([r.id for r in refs])
        return
    rt = _require_runtime()
    rt.store.free([r.id for r in refs])
