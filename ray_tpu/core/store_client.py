"""Pluggable control-plane metadata storage (the GCS storage role).

Counterpart of the reference's GCS store clients —
``src/ray/gcs/store_client/in_memory_store_client.h:31`` (default,
volatile) and ``redis_store_client.h:27`` (external store that survives
GCS restart, exercised by ``python/ray/tests/test_gcs_fault_tolerance.py``)
— behind the table interface of ``gcs/gcs_table_storage.cc``.

TPU-first disposition: the control plane here is a single coordinator
process (no quorum), so durability means "survives driver/coordinator
restart", and the idiomatic single-host durable backend is sqlite (WAL
mode, stdlib, crash-safe) rather than an external Redis. The interface
is the seam: a Redis-backed client can slot in for a real multi-host
control plane without touching callers (``parallel/distributed.KVServer``,
the job table, Tune experiment state).
"""

from __future__ import annotations

import os
import sqlite3
import threading
from typing import Dict, List, Optional


class StoreClient:
    """Key → bytes tables ('kv', 'jobs', 'actors', ...)."""

    def put(self, table: str, key: str, value: bytes) -> None:
        raise NotImplementedError

    def get(self, table: str, key: str) -> Optional[bytes]:
        raise NotImplementedError

    def delete(self, table: str, key: str) -> None:
        raise NotImplementedError

    def keys(self, table: str) -> List[str]:
        raise NotImplementedError

    def all(self, table: str) -> Dict[str, bytes]:
        return {k: self.get(table, k) for k in self.keys(table)}

    def close(self) -> None:
        pass


class InMemoryStoreClient(StoreClient):
    """reference in_memory_store_client.h:31 (volatile default)."""

    def __init__(self):
        self._tables: Dict[str, Dict[str, bytes]] = {}
        self._lock = threading.Lock()

    def put(self, table, key, value):
        with self._lock:
            self._tables.setdefault(table, {})[key] = value

    def get(self, table, key):
        with self._lock:
            return self._tables.get(table, {}).get(key)

    def delete(self, table, key):
        with self._lock:
            self._tables.get(table, {}).pop(key, None)

    def keys(self, table):
        with self._lock:
            return list(self._tables.get(table, {}))

    def all(self, table):
        with self._lock:
            return dict(self._tables.get(table, {}))


class SqliteStoreClient(StoreClient):
    """Durable single-file backend (the redis_store_client.h:27 role
    for a single-coordinator control plane): a restarted coordinator
    reloads every table from the file."""

    def __init__(self, path: str):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._path = path
        # one connection guarded by a lock: the control plane's write
        # rate is metadata-scale, not data-plane-scale
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._conn.execute("PRAGMA journal_mode=WAL")
        self._conn.execute("PRAGMA synchronous=NORMAL")
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS store ("
            " tbl TEXT NOT NULL, key TEXT NOT NULL, value BLOB,"
            " PRIMARY KEY (tbl, key))"
        )
        self._conn.commit()
        self._lock = threading.Lock()

    def put(self, table, key, value):
        with self._lock:
            self._conn.execute(
                "INSERT INTO store (tbl, key, value) VALUES (?, ?, ?) "
                "ON CONFLICT(tbl, key) DO UPDATE SET value=excluded.value",
                (table, key, value),
            )
            self._conn.commit()

    def get(self, table, key):
        with self._lock:
            row = self._conn.execute(
                "SELECT value FROM store WHERE tbl=? AND key=?",
                (table, key),
            ).fetchone()
        return None if row is None else row[0]

    def delete(self, table, key):
        with self._lock:
            self._conn.execute(
                "DELETE FROM store WHERE tbl=? AND key=?", (table, key)
            )
            self._conn.commit()

    def keys(self, table):
        with self._lock:
            rows = self._conn.execute(
                "SELECT key FROM store WHERE tbl=?", (table,)
            ).fetchall()
        return [r[0] for r in rows]

    def all(self, table):
        with self._lock:
            rows = self._conn.execute(
                "SELECT key, value FROM store WHERE tbl=?", (table,)
            ).fetchall()
        return dict(rows)

    def close(self):
        with self._lock:
            self._conn.close()


def make_store_client(persist_path: Optional[str]) -> StoreClient:
    """persist_path=None → volatile; else the durable sqlite backend
    (reference: storage type is a GCS boot option, gcs_server.h:70)."""
    if persist_path:
        return SqliteStoreClient(persist_path)
    return InMemoryStoreClient()
