"""Worker log capture + tailing.

Counterpart of the reference's per-process log files in the session dir
plus ``LogMonitor`` (``_private/log_monitor.py:86``), which tails worker
logs and pushes new lines to drivers: workers redirect stdout/stderr to
``<log_dir>/worker-<id>.{out,err}`` (set ``log_dir=...`` in ray.init);
the driver-side LogMonitor polls the files and forwards new lines to a
callback (default: print with a worker prefix, the reference's
log_to_driver behavior)."""

from __future__ import annotations

import glob
import os
import threading
import time
from typing import Callable, Dict, List, Optional


class LogMonitor:
    """reference log_monitor.py:86."""

    def __init__(
        self,
        log_dir: str,
        callback: Optional[Callable[[str, str], None]] = None,
        poll_interval_s: float = 0.25,
    ):
        self.log_dir = log_dir
        self.callback = callback or (
            lambda worker, line: print(f"({worker}) {line}")
        )
        self.poll_interval_s = poll_interval_s
        self._offsets: Dict[str, int] = {}
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="log_monitor"
        )
        self._thread.start()

    def _files(self) -> List[str]:
        return sorted(
            glob.glob(os.path.join(self.log_dir, "worker-*.out"))
            + glob.glob(os.path.join(self.log_dir, "worker-*.err"))
        )

    def poll_once(self) -> int:
        """Forward any new complete lines; returns the number
        forwarded. Reads in binary with raw byte offsets (decode-then-
        re-encode drifts on non-UTF-8 output) and buffers a trailing
        partial line until its newline arrives."""
        n = 0
        for path in self._files():
            worker = os.path.basename(path).rsplit(".", 1)[0]
            try:
                size = os.path.getsize(path)
                off = self._offsets.get(path, 0)
                if size <= off:
                    continue
                with open(path, "rb") as f:
                    f.seek(off)
                    chunk = f.read()
                last_nl = chunk.rfind(b"\n")
                if last_nl < 0:
                    continue  # no complete line yet
                complete, _rest = chunk[: last_nl + 1], chunk[last_nl + 1 :]
                self._offsets[path] = off + last_nl + 1
                for raw in complete.splitlines():
                    line = raw.decode(errors="replace")
                    if line.strip():
                        self.callback(worker, line)
                        n += 1
            except OSError:
                continue
        return n

    def _run(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            self.poll_once()

    def tail(self, n: int = 100) -> List[str]:
        """Last n lines across all worker logs (dashboard/debug API)."""
        lines: List[str] = []
        for path in self._files():
            worker = os.path.basename(path).rsplit(".", 1)[0]
            try:
                with open(path, "r", errors="replace") as f:
                    for line in f.read().splitlines()[-n:]:
                        lines.append(f"({worker}) {line}")
            except OSError:
                continue
        return lines[-n:]

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)
