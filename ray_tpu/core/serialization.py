"""Zero-copy-oriented serialization for the object plane.

Counterpart of the reference's ``python/ray/_private/serialization.py`` +
plasma protocol. Uses pickle protocol 5 with out-of-band buffers: numpy
arrays (SampleBatch columns, weight pytrees) serialize as a small metadata
pickle plus raw buffers that are written contiguously into a shared-memory
segment and reconstructed as views on attach — the shm segment plays the
plasma role (``src/ray/object_manager/plasma/store.h:55``) scoped to one
host.
"""

from __future__ import annotations

import pickle
import struct
from typing import Any, List, Tuple

import cloudpickle

# Segment layout: [u64 meta_len][meta][u64 nbuf][u64 len_i ...][buf_0 pad8]...
_HDR = struct.Struct("<Q")


def serialize(obj: Any) -> Tuple[bytes, List[pickle.PickleBuffer]]:
    """→ (meta, out-of-band buffers). Functions/classes go through
    cloudpickle (reference: ray/cloudpickle fork)."""
    buffers: List[pickle.PickleBuffer] = []
    meta = cloudpickle.dumps(obj, protocol=5, buffer_callback=buffers.append)
    return meta, buffers


def deserialize(meta: bytes, buffers: List[Any]) -> Any:
    return pickle.loads(meta, buffers=buffers)


def serialized_size(meta: bytes, buffers: List[pickle.PickleBuffer]) -> int:
    total = _HDR.size * 2 + len(meta)
    for b in buffers:
        n = b.raw().nbytes
        total += _HDR.size + ((n + 7) & ~7)
    return total


def write_to_buffer(
    view: memoryview, meta: bytes, buffers: List[pickle.PickleBuffer]
) -> int:
    """Write the segment layout into ``view``; returns bytes written."""
    off = 0
    view[off : off + _HDR.size] = _HDR.pack(len(meta))
    off += _HDR.size
    view[off : off + len(meta)] = meta
    off += len(meta)
    view[off : off + _HDR.size] = _HDR.pack(len(buffers))
    off += _HDR.size
    for b in buffers:
        raw = b.raw()
        n = raw.nbytes
        view[off : off + _HDR.size] = _HDR.pack(n)
        off += _HDR.size
        view[off : off + n] = raw.cast("B")
        off += (n + 7) & ~7
    return off


def read_from_buffer(view: memoryview) -> Any:
    """Reconstruct an object from a segment; array buffers are zero-copy
    views into ``view`` (caller keeps the segment alive)."""
    off = 0
    (meta_len,) = _HDR.unpack_from(view, off)
    off += _HDR.size
    meta = bytes(view[off : off + meta_len])
    off += meta_len
    (nbuf,) = _HDR.unpack_from(view, off)
    off += _HDR.size
    buffers = []
    for _ in range(nbuf):
        (n,) = _HDR.unpack_from(view, off)
        off += _HDR.size
        buffers.append(view[off : off + n])
        off += (n + 7) & ~7
    return deserialize(meta, buffers)


def dumps(obj: Any) -> bytes:
    """Single-buffer form (for pipe transport of small objects)."""
    return cloudpickle.dumps(obj, protocol=5)


def loads(data: bytes) -> Any:
    return pickle.loads(data)
