"""Cross-host actor fleet, first rung: a head node that places actors on
remote worker agents over TCP.

Plays the multi-host scheduling/transport roles of the reference's
raylet + object manager, scoped to what distributed rollout needs:

- per-host worker agent that spawns/hosts actors
  (``src/ray/raylet/node_manager.h:142`` NodeManager,
  ``raylet/worker_pool.h:153`` WorkerPool),
- task/actor submission and result return over a persistent TCP
  connection (the gRPC transports of ``rpc/grpc_server.h:64`` +
  ``core_worker/transport/direct_actor_task_submitter.h:67``),
- argument objects resolved head-side and shipped inline
  (``object_manager/object_manager.h:114`` chunked push, scoped to
  driver-owned pull-on-submit: batches are produced once, consumed
  once, and weight broadcasts re-ship per node the way the reference
  re-pulls per node).

TPU-first disposition: the head is the single controller (the TPU
learner lives there); agents host CPU rollout actors only, so the
protocol is deliberately head↔agent star-shaped — no agent↔agent
object transfer, no distributed scheduler consensus. An agent joins
with ``ray.init(address="head:port")`` (or
``python -m ray_tpu.core.node_agent``); the head enables the fleet
with ``start_cluster_server()``.

Framing: 4-byte big-endian length + pickled dict; binary payloads ride
inside via ``core/serialization`` (pickle-5 out-of-band numpy). Trust
model matches the KV service: cluster hosts only, bind loopback by
default (``parallel/distributed.KVServer`` docstring).
"""

from __future__ import annotations

import os
import pickle
import socket
import struct
import threading
import uuid
from typing import Any, Dict, List, Optional

from ray_tpu.core import serialization as ser


def _send_frame(sock: socket.socket, lock: threading.Lock, msg: Dict) -> None:
    blob = pickle.dumps(msg, protocol=5)
    with lock:
        sock.sendall(struct.pack(">I", len(blob)) + blob)


def _recv_frame(sock: socket.socket) -> Optional[Dict]:
    header = _recv_exact(sock, 4)
    if header is None:
        return None
    (n,) = struct.unpack(">I", header)
    blob = _recv_exact(sock, n)
    if blob is None:
        return None
    return pickle.loads(blob)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


# ---------------------------------------------------------------------------
# Head side
# ---------------------------------------------------------------------------


class RemoteNode:
    """Head-side proxy for one registered agent (the NodeManager client
    role). Owns the connection; a recv thread routes results into the
    head's object store."""

    def __init__(self, runtime, node_id: str, num_cpus: int, sock):
        self.runtime = runtime
        self.node_id = node_id
        self.num_cpus = num_cpus
        self.sock = sock
        self.send_lock = threading.Lock()
        self.actor_ids: set = set()
        # guards inflight + dead against the call()/_on_disconnect()
        # race: a call that slips past a dead check must still get its
        # refs failed, never a forever-pending ray.get
        self.state_lock = threading.Lock()
        self.inflight: Dict[str, int] = {}  # task_id -> num_returns
        self.dead = False
        self._thread = threading.Thread(
            target=self._recv_loop, daemon=True,
            name=f"cluster_recv_{node_id}",
        )
        self._thread.start()

    def _recv_loop(self):
        while True:
            try:
                msg = _recv_frame(self.sock)
            except OSError:
                msg = None
            if msg is None:
                self._on_disconnect()
                return
            op = msg.get("op")
            if op == "result":
                task_id = msg["task_id"]
                with self.state_lock:
                    self.inflight.pop(task_id, None)
                if msg.get("ok"):
                    self.runtime.store.put(
                        task_id,
                        ser.loads(msg["payload"]),
                        use_shm=False,
                    )
                else:
                    from ray_tpu.core.api import RayTaskError

                    self.runtime.store.put_error(
                        task_id,
                        RayTaskError(
                            msg.get("name", "remote"),
                            msg.get("traceback", ""),
                        ),
                    )

    def _on_disconnect(self):
        """Agent died / network split: fail everything it owed us
        (the reference marks the node dead via GCS heartbeat timeout
        and fails its leases)."""
        from ray_tpu.core.api import RayActorError

        with self.state_lock:
            if self.dead:
                return
            self.dead = True
            pending = list(self.inflight)
            self.inflight.clear()
        for task_id in pending:
            self.runtime.store.put_error(
                task_id,
                RayActorError(
                    f"node {self.node_id} disconnected mid-call"
                ),
            )
        cluster = getattr(self.runtime, "cluster", None)
        if cluster is not None:
            cluster.nodes.pop(self.node_id, None)
            cluster._publish_event(
                "cluster.node_removed", {"node_id": self.node_id}
            )

    # -- actor ops -------------------------------------------------------

    def create_actor(self, actor_id, cls, args, kwargs, options):
        _send_frame(
            self.sock,
            self.send_lock,
            {
                "op": "create_actor",
                "actor_id": actor_id,
                "cls": ser.dumps(cls),
                "payload": ser.dumps((args, kwargs)),
                "options": {
                    k: v
                    for k, v in options.items()
                    if k
                    in (
                        "max_restarts",
                        "daemon",
                        "num_cpus",
                        "runtime_env_packed",  # pre-packed, host-free
                    )
                },
            },
        )
        self.actor_ids.add(actor_id)

    def call(self, actor_id, method, args, kwargs, num_returns):
        from ray_tpu.core.api import RayActorError

        task_id = uuid.uuid4().hex
        with self.state_lock:
            alive = not self.dead
            if alive:
                self.inflight[task_id] = num_returns
        if alive:
            try:
                _send_frame(
                    self.sock,
                    self.send_lock,
                    {
                        "op": "actor_call",
                        "task_id": task_id,
                        "actor_id": actor_id,
                        "method": method,
                        "payload": ser.dumps((args, kwargs)),
                    },
                )
            except OSError:
                alive = False
        if not alive:
            # registered (or send failed) against a dead node: fail the
            # ref now — _on_disconnect may already have drained inflight
            with self.state_lock:
                still = self.inflight.pop(task_id, None)
            if still is not None or self.dead:
                self.runtime.store.put_error(
                    task_id,
                    RayActorError(
                        f"node {self.node_id} disconnected mid-call"
                    ),
                )
        from ray_tpu.core.api import ObjectRef

        if num_returns > 1:
            refs = [
                ObjectRef(f"{task_id}_{i}", self.runtime.store)
                for i in range(num_returns)
            ]
            self.runtime._register_split(task_id, refs)
        else:
            refs = [ObjectRef(task_id, self.runtime.store)]
        return refs

    def kill(self, actor_id):
        try:
            _send_frame(
                self.sock,
                self.send_lock,
                {"op": "kill_actor", "actor_id": actor_id},
            )
        except OSError:
            pass
        self.actor_ids.discard(actor_id)


class ClusterServer:
    """Head-side listener: agents connect, register, and become
    placement targets (the gcs_node_manager registration role)."""

    def __init__(
        self,
        runtime,
        host: str = "127.0.0.1",
        port: int = 0,
        kv_address: Optional[str] = None,
    ):
        self.runtime = runtime
        self.nodes: Dict[str, RemoteNode] = {}
        # optional event publication: node lifecycle fans out to KV
        # pubsub subscribers (the reference's GCS node-change channel,
        # RAY_NODE_INFO_CHANNEL in gcs_node_manager.cc)
        self._kv = None
        self._event_thread = None
        kv_address = kv_address or os.environ.get("RAY_TPU_KV_ADDRESS")
        if kv_address:
            import queue

            from ray_tpu.parallel.distributed import KVClient

            self._kv = KVClient(kv_address)
            self._event_queue = queue.SimpleQueue()
            self._event_thread = threading.Thread(
                target=self._event_loop,
                daemon=True,
                name="cluster_event_pub",
            )
            self._event_thread.start()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen()
        self.port = self._sock.getsockname()[1]
        self.host = host
        self._thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="cluster_accept"
        )
        self._thread.start()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def _accept_loop(self):
        while True:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            # bounded handshake: a connection that never sends its
            # register frame (port scanner, wedged agent) must not
            # park the accept loop forever
            conn.settimeout(10.0)
            try:
                msg = _recv_frame(conn)
            except (OSError, socket.timeout):
                msg = None
            if not msg or msg.get("op") != "register":
                conn.close()
                continue
            conn.settimeout(None)
            node = RemoteNode(
                self.runtime,
                msg["node_id"],
                int(msg.get("num_cpus", 1)),
                conn,
            )
            self.nodes[msg["node_id"]] = node
            _send_frame(
                conn, node.send_lock, {"op": "registered", "ok": True}
            )
            self._publish_event(
                "cluster.node_added",
                {
                    "node_id": msg["node_id"],
                    "num_cpus": int(msg.get("num_cpus", 1)),
                },
            )

    def _publish_event(self, channel: str, payload: Dict) -> None:
        """Enqueue onto the single publisher thread: a slow/blackholed
        KV service must not stall the accept loop (agent registration)
        or the disconnect path, and one ordered queue keeps node_added
        before node_removed for the same node. Events are advisory;
        the fleet keeps working if they are lost."""
        if self._kv is None:
            return
        self._event_queue.put((channel, payload))

    def _event_loop(self):
        while True:
            channel, payload = self._event_queue.get()
            try:
                self._kv.publish(channel, payload)
            except Exception:
                pass

    def wait_for_nodes(self, n: int, timeout: float = 60.0) -> List[str]:
        import time

        deadline = time.time() + timeout
        while time.time() < deadline:
            alive = [k for k, v in self.nodes.items() if not v.dead]
            if len(alive) >= n:
                return alive
            time.sleep(0.1)
        raise TimeoutError(
            f"only {len(self.nodes)} cluster nodes joined within "
            f"{timeout}s (wanted {n})"
        )

    def pick_node(self, name: Optional[str] = None) -> RemoteNode:
        alive = {k: v for k, v in self.nodes.items() if not v.dead}
        if name is not None:
            if name not in alive:
                raise ValueError(f"no live cluster node {name!r}")
            return alive[name]
        if not alive:
            raise ValueError("no live cluster nodes")
        # least-loaded by placed actors (the hybrid scheduling policy's
        # spread half, scheduling_policy.cc, scoped to actor counts)
        return min(alive.values(), key=lambda nd: len(nd.actor_ids))

    def shutdown(self):
        try:
            self._sock.close()
        except OSError:
            pass
        for node in self.nodes.values():
            try:
                node.sock.close()
            except OSError:
                pass


def start_cluster_server(
    host: str = "127.0.0.1", port: int = 0, kv_address: Optional[str] = None
) -> str:
    """Enable the head's fleet listener; returns 'host:port' for agents
    to join. Idempotent per runtime. ``kv_address`` (or
    ``RAY_TPU_KV_ADDRESS``) turns on node-lifecycle event publication
    to that KV service's pubsub."""
    from ray_tpu.core import api

    rt = api._require_runtime()
    if getattr(rt, "cluster", None) is None:
        rt.cluster = ClusterServer(rt, host, port, kv_address=kv_address)
    return rt.cluster.address


# ---------------------------------------------------------------------------
# Agent side
# ---------------------------------------------------------------------------


class NodeAgent:
    """Joins a head's fleet and hosts actors in the LOCAL runtime
    (worker pool, object store) of this process — the raylet role for
    one host. Created by ``ray.init(address=...)``."""

    def __init__(
        self,
        address: str,
        node_id: Optional[str] = None,
        num_cpus: Optional[int] = None,
    ):
        from ray_tpu.core import api

        host, port = address.rsplit(":", 1)
        self.node_id = node_id or f"node_{uuid.uuid4().hex[:8]}"
        self.runtime = api._require_runtime()
        self.num_cpus = num_cpus or int(self.runtime.num_cpus)
        self.sock = socket.create_connection((host, int(port)))
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.send_lock = threading.Lock()
        self.actors: Dict[str, str] = {}  # head actor_id -> local id
        _send_frame(
            self.sock,
            self.send_lock,
            {
                "op": "register",
                "node_id": self.node_id,
                "num_cpus": self.num_cpus,
            },
        )
        resp = _recv_frame(self.sock)
        if not resp or not resp.get("ok"):
            raise ConnectionError(
                f"cluster head at {address} rejected registration"
            )
        self._thread = threading.Thread(
            target=self._serve_loop, daemon=True, name="node_agent"
        )
        self._thread.start()

    def _serve_loop(self):
        while True:
            try:
                msg = _recv_frame(self.sock)
            except OSError:
                msg = None
            if msg is None:
                return
            try:
                self._handle(msg)
            except Exception:
                import traceback

                if msg.get("task_id"):
                    self._send_result(
                        msg["task_id"],
                        ok=False,
                        name=msg.get("method", "cluster"),
                        tb=traceback.format_exc(),
                    )

    def _send_result(self, task_id, *, ok, payload=b"", name="", tb=""):
        _send_frame(
            self.sock,
            self.send_lock,
            {
                "op": "result",
                "task_id": task_id,
                "ok": ok,
                "payload": payload,
                "name": name,
                "traceback": tb,
            },
        )

    def _handle(self, msg: Dict):
        op = msg["op"]
        if op == "create_actor":
            cls = ser.loads(msg["cls"])
            args, kwargs = ser.loads(msg["payload"])
            handle = self.runtime.create_actor(
                cls, args, kwargs, dict(msg.get("options") or {})
            )
            self.actors[msg["actor_id"]] = handle._actor_id
        elif op == "actor_call":
            task_id = msg["task_id"]
            local_id = self.actors.get(msg["actor_id"])
            if local_id is None:
                self._send_result(
                    task_id,
                    ok=False,
                    name=msg["method"],
                    tb=f"unknown actor {msg['actor_id']}",
                )
                return
            args, kwargs = ser.loads(msg["payload"])
            refs = self.runtime.call_actor(
                local_id, msg["method"], args, kwargs, num_returns=1
            )
            ref = refs[0]

            # result callback keeps the serve loop free for the next
            # message (actor ordering is preserved by the actor's own
            # pipe queue, not by this thread)
            def on_ready(task_id=task_id, ref=ref, name=msg["method"]):
                try:
                    value = self.runtime.store.get(ref.id, timeout=0)
                except Exception:
                    import traceback

                    self._send_result(
                        task_id,
                        ok=False,
                        name=name,
                        tb=traceback.format_exc(),
                    )
                    return
                self._send_result(
                    task_id, ok=True, payload=ser.dumps(value)
                )
                self.runtime.store.free([ref.id])

            self.runtime.store.on_ready(ref.id, on_ready)
        elif op == "kill_actor":
            local_id = self.actors.pop(msg["actor_id"], None)
            if local_id is not None:
                self.runtime.kill_actor(local_id)

    def close(self):
        try:
            # shutdown() (not just close()) so the FIN goes out even
            # while _serve_loop is parked in recv on this fd — close()
            # alone leaves the kernel fd open under the blocked read
            # and the head never learns the agent left
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass


def main():  # pragma: no cover - thin CLI
    import argparse
    import time

    parser = argparse.ArgumentParser(
        description="ray_tpu node agent: join a head's actor fleet"
    )
    parser.add_argument("--address", required=True, help="head host:port")
    parser.add_argument("--node-id", default=None)
    parser.add_argument("--num-cpus", type=int, default=None)
    args = parser.parse_args()
    import ray_tpu.core.api as api

    api.init(num_cpus=args.num_cpus)
    agent = NodeAgent(args.address, args.node_id, args.num_cpus)
    print(f"node agent {agent.node_id} joined {args.address}", flush=True)
    while True:
        time.sleep(3600)


if __name__ == "__main__":  # pragma: no cover
    main()
