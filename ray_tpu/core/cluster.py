"""Cross-host actor fleet, first rung: a head node that places actors on
remote worker agents over TCP.

Plays the multi-host scheduling/transport roles of the reference's
raylet + object manager, scoped to what distributed rollout needs:

- per-host worker agent that spawns/hosts actors
  (``src/ray/raylet/node_manager.h:142`` NodeManager,
  ``raylet/worker_pool.h:153`` WorkerPool),
- task/actor submission and result return over a persistent TCP
  connection (the gRPC transports of ``rpc/grpc_server.h:64`` +
  ``core_worker/transport/direct_actor_task_submitter.h:67``),
- argument objects resolved head-side and shipped inline
  (``object_manager/object_manager.h:114`` chunked push, scoped to
  driver-owned pull-on-submit: batches are produced once, consumed
  once, and weight broadcasts re-ship per node the way the reference
  re-pulls per node).

TPU-first disposition: the head is the single controller (the TPU
learner lives there); agents host CPU rollout actors only, so the
protocol is deliberately head↔agent star-shaped — no agent↔agent
object transfer, no distributed scheduler consensus. An agent joins
with ``ray.init(address="head:port")`` (or
``python -m ray_tpu.core.node_agent``); the head enables the fleet
with ``start_cluster_server()``.

Framing: 4-byte big-endian length + a RESTRICTED-pickle control dict
(``core/wire.py``: only builtins + numpy reconstruction resolve — a
frame referencing any other global is rejected before anything runs).
User payloads (args, classes, results) ride as opaque ``bytes`` fields
inside the frame and deserialize via ``core/serialization`` (full
pickle-5, out-of-band numpy) only after the connection authenticated.
Trust model: cluster hosts only, bind loopback by default (the KV
service's model, ``parallel/distributed.KVServer`` docstring), plus a
shared-token HMAC on the registration handshake
(``RAY_TPU_CLUSTER_TOKEN`` / ``RAY_TPU_KV_TOKEN``) as a second wall —
an unauthenticated socket can no longer deliver a gadget pickle, and
full-pickle payload fields are only read off registered connections.
"""

from __future__ import annotations

import os
import socket
import struct
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

from ray_tpu.core import serialization as ser
from ray_tpu.core import wire


def _send_frame(sock: socket.socket, lock: threading.Lock, msg: Dict) -> None:
    if "v" not in msg:
        msg = {**msg, "v": wire.FRAME_VERSION}
    blob = wire.control_dumps(msg)
    with lock:
        sock.sendall(struct.pack(">I", len(blob)) + blob)


# Post-auth frames carry batch payloads (1 GiB ceiling); the pre-auth
# handshake is <1 KB, so it gets a tight cap — an unauthenticated
# socket must not be able to force a multi-GB buffered read.
_MAX_FRAME = 1 << 30
_MAX_HANDSHAKE_FRAME = 1 << 16


def _recv_frame(
    sock: socket.socket, max_len: int = _MAX_FRAME
) -> Optional[Dict]:
    header = _recv_exact(sock, 4)
    if header is None:
        return None
    (n,) = struct.unpack(">I", header)
    if n > max_len:
        raise wire.ControlFrameError(
            f"frame length {n} exceeds cap {max_len}"
        )
    blob = _recv_exact(sock, n)
    if blob is None:
        return None
    # restricted deserialization: a malicious frame raises HERE, in the
    # caller's recv loop, without resolving any forbidden global
    return wire.control_loads(blob)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


# ---------------------------------------------------------------------------
# Peer-to-peer object pulls (per-node data plane)
# ---------------------------------------------------------------------------

# pooled authenticated connections to peer data servers:
# (host, port) -> (socket, request_lock)
_peer_conns: Dict = {}
_peer_conns_lock = threading.Lock()


def _open_peer_conn(host: str, port: int, timeout: float = 30.0):
    """Connect + authenticate against a node data server (same
    challenge/HMAC handshake as head registration — a pull response
    is full-pickle on the consumer, so only authenticated cluster
    members may serve one). ``timeout`` bounds the connect AND each
    handshake read, so a peer that accepts but never speaks cannot
    stall the caller past its deadline.

    The handshake is MUTUAL when a cluster token is set: the client
    sends its own ``client_nonce`` and the server's ok-frame must echo
    it under an HMAC keyed on the shared token — verified BEFORE any
    pull payload is unpickled, so a spoofed data server cannot feed
    this consumer attacker-controlled pickle bytes."""
    sock = socket.create_connection(
        (host, int(port)), timeout=timeout
    )
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    challenge = _recv_frame(sock, max_len=_MAX_HANDSHAKE_FRAME)
    if (
        not isinstance(challenge, dict)
        or challenge.get("op") != "challenge"
    ):
        sock.close()
        raise ConnectionError("data server sent no challenge")
    client_nonce = uuid.uuid4().hex
    auth = {
        "op": "pull_auth",
        "nonce": challenge.get("nonce", ""),
        "client_nonce": client_nonce,
        # version must be IN the frame before the MAC: _send_frame
        # stamps it on unversioned frames, and the MAC covers every
        # non-mac field
        "v": wire.FRAME_VERSION,
    }
    token = wire.cluster_token()
    if token is not None:
        auth["hmac"] = wire.register_hmac(token, auth)
    lock = threading.Lock()
    _send_frame(sock, lock, auth)
    resp = _recv_frame(sock, max_len=_MAX_HANDSHAKE_FRAME)
    if not isinstance(resp, dict) or not resp.get("ok"):
        sock.close()
        raise ConnectionError("data server rejected pull auth")
    if token is not None and (
        resp.get("nonce") != client_nonce
        or not wire.register_ok(token, resp)
    ):
        sock.close()
        raise ConnectionError(
            "data server failed mutual auth (ok-frame HMAC over "
            "the client nonce missing or wrong)"
        )
    return sock, lock


def _drop_peer_conn(key, entry) -> None:
    """Remove + close one pooled connection (leak-free on every
    failure path)."""
    with _peer_conns_lock:
        if _peer_conns.get(key) is entry:
            _peer_conns.pop(key, None)
    if entry is not None:
        try:
            entry[0].close()
        except OSError:
            pass


def fetch_remote_object(
    host: str,
    port: int,
    obj_id: str,
    timeout: Optional[float] = None,
) -> bytes:
    """Pull one object's serialized bytes from a node data server.
    Connections are pooled per (host, port); one transient failure
    gets a fresh-connection retry, then the object is reported lost
    (the caller maps that to an object-lost error).

    ``timeout`` is the CALLER's deadline: when set, it is ONE
    monotonic deadline across BOTH attempts — the retry spends only
    what the first attempt left, so a slow-then-dead peer cannot
    stretch the call to 2x the requested bound. A slow peer re-raises
    ``socket.timeout`` immediately. When None ("block until
    available"), socket ops still carry a 60 s liveness bound, but a
    trip of it counts as a transient failure (retry, then
    object-lost) — never a timeout error the caller didn't opt into."""
    key = (str(host), int(port))
    deadline = (
        time.monotonic() + timeout if timeout is not None else None
    )
    last_err: Optional[Exception] = None
    for attempt in range(2):
        if deadline is None:
            sock_timeout = 60.0
        else:
            sock_timeout = deadline - time.monotonic()
            if sock_timeout <= 0:
                raise socket.timeout(
                    f"pull of {obj_id} from {host}:{port}: "
                    "deadline exhausted before retry"
                )
        with _peer_conns_lock:
            entry = _peer_conns.get(key)
        try:
            if entry is None:
                entry = _open_peer_conn(
                    *key, timeout=sock_timeout
                )
                with _peer_conns_lock:
                    cur = _peer_conns.get(key)
                    if cur is None:
                        _peer_conns[key] = entry
                    else:
                        # lost the first-connection race: use the
                        # winner's, close ours
                        loser = entry
                        entry = cur
                        try:
                            loser[0].close()
                        except OSError:
                            pass
            sock, lock = entry
            with lock:  # request/response pairs must not interleave
                sock.settimeout(sock_timeout)
                _send_frame(
                    sock,
                    threading.Lock(),
                    {"op": "pull", "obj_id": obj_id},
                )
                resp = _recv_frame(sock)
        except socket.timeout as err:
            _drop_peer_conn(key, entry)
            if timeout is not None:
                raise  # slow/hung peer: caller's timeout semantics
            last_err = err  # liveness bound tripped: transient
            continue
        except (OSError, wire.ControlFrameError) as err:
            last_err = err
            _drop_peer_conn(key, entry)
            continue
        if resp is None:
            last_err = ConnectionError("data server closed mid-pull")
            _drop_peer_conn(key, entry)
            continue
        if not resp.get("ok"):
            raise KeyError(
                f"object {obj_id} not held by {host}:{port}: "
                f"{resp.get('error', 'unknown')}"
            )
        return resp["payload"]
    raise ConnectionError(
        f"pull of {obj_id} from {host}:{port} failed: {last_err}"
    )


def _node_obj_id(obj_id: str) -> str:
    """Key under which a node-resident object's serialized bytes live
    in the producing agent's LOCAL store (so the agent's LRU/spill
    machinery manages them like any local object). HASHED: the store
    truncates shm segment names to the key's first 24 chars, so the
    distinguishing part of the id must land early — split-return ids
    (``{task_id}_{i}``) differ only at the tail and would collide."""
    import hashlib

    h = hashlib.sha1(obj_id.encode()).hexdigest()[:20]
    return f"nodeobj_{h}"


def node_obj_min_bytes() -> int:
    """Result-size threshold (bytes) above which fleet task/actor
    results stay node-resident (metadata to the head, bytes served
    peer-to-peer). <=0 disables the node data plane."""
    try:
        return int(
            os.environ.get(
                "RAY_TPU_NODE_OBJ_MIN_BYTES", 4 * 1024 * 1024
            )
        )
    except ValueError:
        return 4 * 1024 * 1024


# ---------------------------------------------------------------------------
# Head side
# ---------------------------------------------------------------------------


class _PoolObj:
    """Wire marker for an ObjectRef argument shipped through the
    once-per-node object pool: the first call naming ``obj_id`` to a
    node carries the value; later calls carry the id alone and the
    agent resolves it from its cache (the reference's pull-once-per-
    node plasma transfer, ``object_manager/object_manager.h:114``,
    scoped to head-owned objects). Weight broadcast to K actors on one
    agent therefore moves ONE copy over TCP, not K.

    ``location=(host, port)`` marks a NODE-RESIDENT object: the value
    never passed through the head — the consuming agent pulls it
    straight from the producing node's data server (the reference's
    peer-to-peer chunked pull, ``object_manager/pull_manager.h:47``)
    and caches it like a pooled value."""

    __slots__ = ("obj_id", "value", "has_value", "location")

    def __init__(
        self,
        obj_id: str,
        value=None,
        has_value: bool = False,
        location=None,
    ):
        self.obj_id = obj_id
        self.value = value
        self.has_value = has_value
        self.location = location

    def __reduce__(self):
        return (
            _PoolObj,
            (self.obj_id, self.value, self.has_value, self.location),
        )


class RemoteNode:
    """Head-side proxy for one registered agent (the NodeManager client
    role). Owns the connection; a recv thread routes results into the
    head's object store."""

    def __init__(
        self,
        runtime,
        node_id: str,
        num_cpus: int,
        sock,
        data_host: Optional[str] = None,
        data_port: Optional[int] = None,
    ):
        self.runtime = runtime
        self.node_id = node_id
        self.num_cpus = num_cpus
        self.sock = sock
        # the agent's data-server endpoint (None = agent predates the
        # node data plane / disabled): node-resident results resolve
        # against this address
        self.data_host = data_host
        self.data_port = data_port
        self.send_lock = threading.Lock()
        self.actor_ids: set = set()
        # guards inflight + dead against the call()/_on_disconnect()
        # race: a call that slips past a dead check must still get its
        # refs failed, never a forever-pending ray.get
        self.state_lock = threading.Lock()
        self.inflight: Dict[str, int] = {}  # task_id -> num_returns
        # stateless tasks spilled here: task_id -> _TaskRecord, so a
        # node death can retry them locally instead of erroring
        self.task_recs: Dict[str, Any] = {}
        self.inflight_cpus: float = 0.0
        # CPUs of dedicated actors placed on this node (spillover
        # capacity accounting shares one ledger with spilled tasks)
        self.actor_cpus: Dict[str, float] = {}
        # placement-group bundle reservations on this node
        # (util/placement_group 2PC prepare): pg_id -> CPUs
        self.pg_cpus: Dict[str, float] = {}
        # object-pool bookkeeping: ids whose value this node already
        # holds (see _PoolObj). _ship_lock serializes the
        # check-and-send so a concurrent marshal of the same ref can
        # never emit an id-only marker ahead of the value frame.
        self.shipped_objs: set = set()
        # ids whose PRIMARY copy lives on this node (node-resident
        # results): freed ids in either set are forwarded to the agent
        self.owned_objs: set = set()
        self._ship_lock = threading.Lock()
        self.dead = False
        self._thread = threading.Thread(
            target=self._recv_loop, daemon=True,
            name=f"cluster_recv_{node_id}",
        )
        self._thread.start()

    # frames an agent may send the head on an established connection
    _AGENT_OPS = frozenset({"result"})

    def _recv_loop(self):
        while True:
            try:
                msg = _recv_frame(self.sock)
                if msg is not None:
                    # typed schema check (the protobuf role): known
                    # op for THIS direction, declared fields typed
                    wire.validate_frame(msg, self._AGENT_OPS)
            except (OSError, wire.ControlFrameError):
                # a forbidden frame on an established agent connection
                # means the peer is compromised or not ours: drop it
                msg = None
            if msg is not None:
                try:
                    self._handle_agent_frame(msg)
                    continue
                except Exception:
                    # schema-valid but semantically malformed (bad
                    # result pickle, impossible split shape): the
                    # connection's state is unknown — fall through to
                    # the disconnect path instead of letting the
                    # exception kill this thread and zombify the node
                    # with its inflight tasks never failed over
                    pass
            self._on_disconnect()
            return

    def _handle_agent_frame(self, msg) -> None:
        op = msg.get("op")
        if op == "result":
            task_id = msg["task_id"]
            with self.state_lock:
                self.inflight.pop(task_id, None)
                trec = self.task_recs.pop(task_id, None)
                if trec is not None and not getattr(
                    trec, "pg_spilled", False
                ):
                    self.inflight_cpus -= trec.num_cpus
            if trec is not None and getattr(
                trec, "pg_spilled", False
            ):
                trec.placement_group._release(
                    trec.num_cpus, trec.acquired_bundle
                )
            if trec is not None and self.runtime.pending:
                # capacity freed: queued tasks may spill now —
                # wake the cluster's single dispatcher thread (a
                # per-result thread would stampede runtime.lock at
                # high task rates, and dispatching inline here
                # would stall the recv loop on a slow marshal)
                cluster = getattr(self.runtime, "cluster", None)
                if cluster is not None:
                    cluster.kick_dispatch()
            if msg.get("ok"):
                node_obj = msg.get("node_obj")
                if node_obj is not None and self.data_port:
                    split = node_obj.get("split_sizes")
                    if split is not None:
                        # agent split the multi-return tuple
                        # node-side: register each element as its
                        # own remote object under the
                        # pre-registered split ref ids; drop the
                        # base entry (its pending split callback
                        # dies with it)
                        with self.state_lock:
                            for i in range(len(split)):
                                self.owned_objs.add(
                                    f"{task_id}_{i}"
                                )
                        for i, sz in enumerate(split):
                            self.runtime.store.put_remote(
                                f"{task_id}_{i}",
                                {
                                    "node_id": self.node_id,
                                    "host": self.data_host,
                                    "port": self.data_port,
                                    "size": int(sz),
                                },
                            )
                        self.runtime.store.free([task_id])
                        return
                    # bytes stayed on the agent: record the
                    # location only (per-node data plane) — the
                    # head pulls iff something here reads the ref
                    with self.state_lock:
                        self.owned_objs.add(task_id)
                    self.runtime.store.put_remote(
                        task_id,
                        {
                            "node_id": self.node_id,
                            "host": self.data_host,
                            "port": self.data_port,
                            "size": int(node_obj.get("size", 0)),
                        },
                    )
                else:
                    self.runtime.store.put(
                        task_id,
                        ser.loads(msg["payload"]),
                        use_shm=False,
                    )
            else:
                from ray_tpu.core.api import RayTaskError

                self.runtime.store.put_error(
                    task_id,
                    RayTaskError(
                        msg.get("name", "remote"),
                        msg.get("traceback", ""),
                    ),
                )

    def _on_disconnect(self):
        """Agent died / network split: fail everything it owed us
        (the reference marks the node dead via GCS heartbeat timeout
        and fails its leases). Spilled stateless tasks with retries
        left go back into the head's queue instead — the reference's
        lease-failure resubmission (direct_task_transport.h:57)."""
        from ray_tpu.core.api import RayActorError

        with self.state_lock:
            if self.dead:
                return
            self.dead = True
            pending = list(self.inflight)
            self.inflight.clear()
            task_recs = dict(self.task_recs)
            self.task_recs.clear()
            self.inflight_cpus = 0.0
            self.shipped_objs.clear()
            # node-resident objects die with the node: their entries
            # keep the stale location and a later read surfaces an
            # object-lost error from the failed pull
            self.owned_objs.clear()
        # mark placement-group bundles hosted here as lost BEFORE
        # re-queueing anything: a task whose bundle died must error,
        # not park in the queue forever (nothing can ever admit it)
        try:
            from ray_tpu.util.placement_group import _GROUPS

            affected = [
                pg
                for pg in list(_GROUPS.values())
                if pg.node_lost(self.node_id)
            ]
        except Exception:
            affected = []
        if affected:
            doomed = []
            with self.runtime.lock:
                for t in list(self.runtime.pending):
                    if t.placement_group in affected and (
                        not t.placement_group.has_live_bundle(
                            t.num_cpus, t.bundle_index
                        )
                    ):
                        self.runtime.pending.remove(t)
                        doomed.append(t)
            for t in doomed:
                self.runtime.store.put_error(
                    t.task_id,
                    RayActorError(
                        f"placement group {t.placement_group.id} "
                        f"bundle host {self.node_id} died"
                    ),
                )
        for task_id in pending:
            trec = task_recs.get(task_id)
            if trec is not None and getattr(
                trec, "pg_spilled", False
            ):
                # give the bundle back; if no live bundle can ever
                # re-admit this task, fail it now instead of letting
                # the retry path park it forever
                trec.placement_group._release(
                    trec.num_cpus, trec.acquired_bundle
                )
                trec.pg_spilled = False
                trec.acquired_bundle = -1
                if not trec.placement_group.has_live_bundle(
                    trec.num_cpus, trec.bundle_index
                ):
                    self.runtime.store.put_error(
                        task_id,
                        RayActorError(
                            "placement group "
                            f"{trec.placement_group.id} bundle host "
                            f"{self.node_id} died mid-task"
                        ),
                    )
                    continue
            if trec is not None and trec.retries_left > 0:
                trec.retries_left -= 1
                try:
                    self.runtime._enqueue(trec)
                    continue
                except Exception:
                    pass
            self.runtime.store.put_error(
                task_id,
                RayActorError(
                    f"node {self.node_id} disconnected mid-call"
                ),
            )
        cluster = getattr(self.runtime, "cluster", None)
        if cluster is not None:
            cluster.nodes.pop(self.node_id, None)
            cluster._publish_event(
                "cluster.node_removed", {"node_id": self.node_id}
            )

    # -- argument marshalling (once-per-node object pool) ----------------

    def marshal_args(self, args, kwargs):
        """Top-level ObjectRef args become id-only :class:`_PoolObj`
        markers; the value travels in its own ``cache_obj`` frame sent
        (once per node) BEFORE this returns, under ``_ship_lock`` —
        the connection's frame order then guarantees every call naming
        the id lands after the value. Plain values pass through
        (shipped inline per call, as before)."""
        from ray_tpu.core.api import ObjectRef

        def m(v):
            if isinstance(v, ObjectRef):
                # node-resident object: never route its bytes through
                # the head — the consuming agent reads it locally (if
                # it produced it) or pulls peer-to-peer (the
                # reference's object_manager pull, pull_manager.h:47)
                loc = self.runtime.store.remote_loc(v.id)
                if loc is not None:
                    if loc.get("node_id") == self.node_id:
                        return _PoolObj(v.id)
                    # the consumer caches the pulled value like a
                    # pooled one — track it so free_objs reaches its
                    # cache too (location rides every marker: pulls
                    # are idempotent and this dodges the cross-thread
                    # marshal/send ordering race an id-only marker
                    # would reintroduce)
                    with self._ship_lock:
                        self.shipped_objs.add(v.id)
                    return _PoolObj(
                        v.id,
                        location=(loc["host"], loc["port"]),
                    )
                with self._ship_lock:
                    if v.id not in self.shipped_objs:
                        value = self.runtime.store.get(
                            v.id, timeout=60.0
                        )
                        _send_frame(
                            self.sock,
                            self.send_lock,
                            {
                                "op": "cache_obj",
                                "obj_id": v.id,
                                "payload": ser.dumps(value),
                            },
                        )
                        self.shipped_objs.add(v.id)
                return _PoolObj(v.id)
            return v

        return [m(a) for a in args], {k: m(v) for k, v in kwargs.items()}

    def free_objs(self, ids) -> None:
        """Head freed these object ids: drop them from the agent's
        cache (and our bookkeeping) so the pool can't grow unbounded."""
        with self.state_lock:
            held = [
                i
                for i in ids
                if i in self.shipped_objs or i in self.owned_objs
            ]
            self.shipped_objs.difference_update(held)
            self.owned_objs.difference_update(held)
            if self.dead or not held:
                return
        try:
            _send_frame(
                self.sock,
                self.send_lock,
                {"op": "free_objs", "ids": held},
            )
        except OSError:
            pass

    # -- stateless tasks (spillover scheduling) --------------------------

    def submit_task(self, trec, payload: bytes) -> bool:
        """Ship a queued stateless task to this agent; False if the
        node is dead (caller keeps it queued)."""
        task_id = trec.task_id
        pg_spilled = getattr(trec, "pg_spilled", False)
        with self.state_lock:
            if self.dead:
                return False
            self.inflight[task_id] = 1
            self.task_recs[task_id] = trec
            # placement-group tasks are already paid for by the
            # bundle's pg_cpus reservation on this node
            if not pg_spilled:
                self.inflight_cpus += trec.num_cpus
        try:
            _send_frame(
                self.sock,
                self.send_lock,
                {
                    "op": "task",
                    "task_id": task_id,
                    "func_id": trec.msg["func_id"],
                    "func": trec.msg["func_blob"],
                    "payload": payload,
                    "name": trec.name,
                    "num_cpus": trec.num_cpus,
                    "num_returns": int(
                        getattr(trec, "num_returns", 1)
                    ),
                    "runtime_env": trec.msg.get("runtime_env"),
                },
            )
        except OSError:
            with self.state_lock:
                self.inflight.pop(task_id, None)
                self.task_recs.pop(task_id, None)
                if not pg_spilled:
                    self.inflight_cpus -= trec.num_cpus
            # bundle release happens in _try_spill's not-sent path
            # (single owner for the un-charge, whatever failed)
            return False
        return True

    def free_cpus(self) -> float:
        with self.state_lock:
            return (
                self.num_cpus
                - self.inflight_cpus
                - sum(self.actor_cpus.values())
                - sum(self.pg_cpus.values())
            )

    def pg_reserve(self, pg_id: str, cpus: float) -> bool:
        """Prepare phase of a placement-group bundle reservation:
        atomically claim ``cpus`` out of this node's spillover
        capacity (False = insufficient — the group rolls back)."""
        with self.state_lock:
            if self.dead:
                return False
            free = (
                self.num_cpus
                - self.inflight_cpus
                - sum(self.actor_cpus.values())
                - sum(self.pg_cpus.values())
            )
            if free + 1e-9 < cpus:
                return False
            self.pg_cpus[pg_id] = (
                self.pg_cpus.get(pg_id, 0.0) + cpus
            )
            return True

    def pg_release(self, pg_id: str) -> None:
        with self.state_lock:
            self.pg_cpus.pop(pg_id, None)

    # -- actor ops -------------------------------------------------------

    def create_actor(self, actor_id, cls, args, kwargs, options):
        _send_frame(
            self.sock,
            self.send_lock,
            {
                "op": "create_actor",
                "actor_id": actor_id,
                "cls": ser.dumps(cls),
                "payload": ser.dumps((args, kwargs)),
                "options": {
                    k: v
                    for k, v in options.items()
                    if k
                    in (
                        "max_restarts",
                        "daemon",
                        "num_cpus",
                        "runtime_env_packed",  # pre-packed, host-free
                    )
                },
            },
        )
        self.actor_ids.add(actor_id)
        req = options.get("num_cpus")
        with self.state_lock:
            # pg-charged actors are paid by the bundle's pg_cpus
            # reservation; charging the actor ledger too would count
            # the same CPUs twice
            self.actor_cpus[actor_id] = (
                0.0
                if options.get("pg_charged")
                else (1.0 if req is None else float(req))
            )

    def call(self, actor_id, method, args, kwargs, num_returns):
        from ray_tpu.core.api import RayActorError

        task_id = uuid.uuid4().hex
        with self.state_lock:
            alive = not self.dead
            if alive:
                self.inflight[task_id] = num_returns
        if alive:
            try:
                _send_frame(
                    self.sock,
                    self.send_lock,
                    {
                        "op": "actor_call",
                        "task_id": task_id,
                        "actor_id": actor_id,
                        "method": method,
                        "payload": ser.dumps((args, kwargs)),
                    },
                )
            except OSError:
                alive = False
        if not alive:
            # registered (or send failed) against a dead node: fail the
            # ref now — _on_disconnect may already have drained inflight
            with self.state_lock:
                still = self.inflight.pop(task_id, None)
            if still is not None or self.dead:
                self.runtime.store.put_error(
                    task_id,
                    RayActorError(
                        f"node {self.node_id} disconnected mid-call"
                    ),
                )
        from ray_tpu.core.api import ObjectRef

        if num_returns > 1:
            refs = [
                ObjectRef(f"{task_id}_{i}", self.runtime.store)
                for i in range(num_returns)
            ]
            self.runtime._register_split(task_id, refs)
        else:
            refs = [ObjectRef(task_id, self.runtime.store)]
        return refs

    def kill(self, actor_id):
        try:
            _send_frame(
                self.sock,
                self.send_lock,
                {"op": "kill_actor", "actor_id": actor_id},
            )
        except OSError:
            pass
        self.actor_ids.discard(actor_id)
        with self.state_lock:
            self.actor_cpus.pop(actor_id, None)


class ClusterServer:
    """Head-side listener: agents connect, register, and become
    placement targets (the gcs_node_manager registration role)."""

    def __init__(
        self,
        runtime,
        host: str = "127.0.0.1",
        port: int = 0,
        kv_address: Optional[str] = None,
    ):
        self.runtime = runtime
        self.nodes: Dict[str, RemoteNode] = {}
        # shared-token gate on agent registration (None → open, the
        # loopback-only default; set RAY_TPU_CLUSTER_TOKEN for fleets)
        self._token = wire.cluster_token()
        # freed head objects invalidate per-node pool caches
        store = getattr(runtime, "store", None)
        if store is not None and hasattr(store, "add_free_listener"):
            store.add_free_listener(self._on_objects_freed)
        # one long-lived dispatcher services capacity-freed kicks from
        # every node's recv loop (spill scans touch runtime.lock and
        # can block on a marshal — never run them on a recv thread)
        self._dispatch_event = threading.Event()
        self._dispatch_thread = threading.Thread(
            target=self._dispatch_loop,
            daemon=True,
            name="cluster_spill_dispatch",
        )
        self._dispatch_thread.start()
        # optional event publication: node lifecycle fans out to KV
        # pubsub subscribers (the reference's GCS node-change channel,
        # RAY_NODE_INFO_CHANNEL in gcs_node_manager.cc)
        self._kv = None
        self._event_thread = None
        kv_address = kv_address or os.environ.get("RAY_TPU_KV_ADDRESS")
        if kv_address:
            self.attach_kv(kv_address)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen()
        self.port = self._sock.getsockname()[1]
        self.host = host
        self._thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="cluster_accept"
        )
        self._thread.start()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def attach_kv(self, kv_address: str) -> None:
        """(Re)bind the node-lifecycle event publisher to a KV pubsub
        service. Also used when start_cluster_server is called on an
        ALREADY-running server with a kv_address — the request must
        take effect, not be silently dropped by idempotency."""
        import queue

        from ray_tpu.parallel.distributed import KVClient

        if kv_address == getattr(self, "_kv_address", None):
            return  # same service already bound — don't leak clients
        # queue + thread must exist BEFORE _kv becomes non-None: a
        # concurrent _publish_event gates on _kv and would otherwise
        # hit a missing _event_queue mid-construction
        if self._event_thread is None:
            self._event_queue = queue.SimpleQueue()
            self._event_thread = threading.Thread(
                target=self._event_loop,
                daemon=True,
                name="cluster_event_pub",
            )
            self._event_thread.start()
        old = self._kv
        self._kv = KVClient(kv_address)
        self._kv_address = kv_address
        if old is not None and hasattr(old, "close"):
            try:
                old.close()
            except Exception:
                pass

    def _accept_loop(self):
        while True:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            # per-connection handshake errors (malformed frames, bogus
            # field types) must never kill the accept thread — that
            # would be a one-packet DoS on the registration surface
            try:
                self._handshake(conn)
            except Exception:
                try:
                    conn.close()
                except OSError:
                    pass

    def _handshake(self, conn) -> None:
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        # bounded handshake: a connection that never sends its
        # register frame (port scanner, wedged agent) must not
        # park the accept loop forever
        conn.settimeout(10.0)
        # challenge-response: the MAC must cover a server nonce so a
        # captured register frame cannot be replayed to enroll a
        # rogue node (whose payload fields would then get full-pickle
        # treatment)
        nonce = uuid.uuid4().hex
        _send_frame(
            conn, threading.Lock(), {"op": "challenge", "nonce": nonce}
        )
        try:
            msg = _recv_frame(conn, max_len=_MAX_HANDSHAKE_FRAME)
            if msg is not None:
                wire.validate_frame(msg, ("register",))
        except (OSError, socket.timeout, wire.ControlFrameError):
            msg = None
        if (
            not isinstance(msg, dict)
            or (self._token is not None and msg.get("nonce") != nonce)
            or not wire.register_ok(self._token, msg)
        ):
            conn.close()
            return
        conn.settimeout(None)
        data_port = msg.get("data_port") or None
        node = RemoteNode(
            self.runtime,
            str(msg["node_id"]),
            int(msg.get("num_cpus", 1)),
            conn,
            # the agent's data server listens on the same interface it
            # reached us from
            data_host=(
                conn.getpeername()[0] if data_port else None
            ),
            data_port=int(data_port) if data_port else None,
        )
        self.nodes[str(msg["node_id"])] = node
        _send_frame(
            conn, node.send_lock, {"op": "registered", "ok": True}
        )
        self._publish_event(
            "cluster.node_added",
            {
                "node_id": str(msg["node_id"]),
                "num_cpus": int(msg.get("num_cpus", 1)),
            },
        )

    def _on_objects_freed(self, ids) -> None:
        for node in list(self.nodes.values()):
            node.free_objs(ids)

    def kick_dispatch(self) -> None:
        """Wake the dispatcher: remote capacity may have freed."""
        self._dispatch_event.set()

    def _dispatch_loop(self) -> None:
        while True:
            self._dispatch_event.wait()
            self._dispatch_event.clear()
            try:
                self.runtime._dispatch_pending()
            except Exception:
                pass

    def _publish_event(self, channel: str, payload: Dict) -> None:
        """Enqueue onto the single publisher thread: a slow/blackholed
        KV service must not stall the accept loop (agent registration)
        or the disconnect path, and one ordered queue keeps node_added
        before node_removed for the same node. Events are advisory;
        the fleet keeps working if they are lost."""
        if self._kv is None:
            return
        self._event_queue.put((channel, payload))

    def _event_loop(self):
        while True:
            channel, payload = self._event_queue.get()
            try:
                self._kv.publish(channel, payload)
            except Exception:
                pass

    def wait_for_nodes(self, n: int, timeout: float = 60.0) -> List[str]:
        import time

        deadline = time.time() + timeout
        while time.time() < deadline:
            alive = [k for k, v in self.nodes.items() if not v.dead]
            if len(alive) >= n:
                return alive
            time.sleep(0.1)
        raise TimeoutError(
            f"only {len(self.nodes)} cluster nodes joined within "
            f"{timeout}s (wanted {n})"
        )

    def pick_node(self, name: Optional[str] = None) -> RemoteNode:
        alive = {k: v for k, v in self.nodes.items() if not v.dead}
        if name is not None:
            if name not in alive:
                raise ValueError(f"no live cluster node {name!r}")
            return alive[name]
        if not alive:
            raise ValueError("no live cluster nodes")
        # least-loaded by placed actors (the hybrid scheduling policy's
        # spread half, scheduling_policy.cc, scoped to actor counts)
        return min(alive.values(), key=lambda nd: len(nd.actor_ids))

    def shutdown(self):
        try:
            self._sock.close()
        except OSError:
            pass
        for node in self.nodes.values():
            try:
                node.sock.close()
            except OSError:
                pass


def start_cluster_server(
    host: str = "127.0.0.1", port: int = 0, kv_address: Optional[str] = None
) -> str:
    """Enable the head's fleet listener; returns 'host:port' for agents
    to join. Idempotent per runtime. ``kv_address`` (or
    ``RAY_TPU_KV_ADDRESS``) turns on node-lifecycle event publication
    to that KV service's pubsub."""
    from ray_tpu.core import api

    rt = api._require_runtime()
    if getattr(rt, "cluster", None) is None:
        rt.cluster = ClusterServer(rt, host, port, kv_address=kv_address)
    elif kv_address is not None:
        # idempotent server, but a NEW kv_address must still bind:
        # callers asking for event publication on an already-running
        # head would otherwise silently get none
        rt.cluster.attach_kv(kv_address)
    return rt.cluster.address


# ---------------------------------------------------------------------------
# Agent side
# ---------------------------------------------------------------------------


class NodeAgent:
    """Joins a head's fleet and hosts actors in the LOCAL runtime
    (worker pool, object store) of this process — the raylet role for
    one host. Created by ``ray.init(address=...)``."""

    def __init__(
        self,
        address: str,
        node_id: Optional[str] = None,
        num_cpus: Optional[int] = None,
    ):
        from ray_tpu.core import api

        host, port = address.rsplit(":", 1)
        self.node_id = node_id or f"node_{uuid.uuid4().hex[:8]}"
        self.runtime = api._require_runtime()
        self.num_cpus = num_cpus or int(self.runtime.num_cpus)
        self.sock = socket.create_connection((host, int(port)))
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.send_lock = threading.Lock()
        self.actors: Dict[str, str] = {}  # head actor_id -> local id
        # once-per-node object pool: obj_id -> value (entries live
        # until the head's free_objs — mirrored plasma pinning)
        self._obj_cache: Dict[str, Any] = {}
        self._obj_cache_lock = threading.Lock()
        # per-node data plane: results >= this many serialized bytes
        # stay HERE (in this runtime's store, under its LRU/spill
        # budget) and only a location frame goes to the head;
        # consumers pull from the data server below
        self._node_obj_min = node_obj_min_bytes()
        self._data_sock: Optional[socket.socket] = None
        self._data_port: Optional[int] = None
        if self._node_obj_min > 0:
            try:
                self._start_data_server()
            except OSError:
                self._data_port = None  # plane off, results inline
        challenge = _recv_frame(self.sock)
        if not isinstance(challenge, dict) or challenge.get("op") != (
            "challenge"
        ):
            raise ConnectionError(
                f"cluster head at {address} sent no challenge"
            )
        reg = {
            "op": "register",
            "node_id": self.node_id,
            "num_cpus": self.num_cpus,
            "nonce": challenge.get("nonce", ""),
            # in the frame before the MAC (the MAC covers every
            # non-mac field; _send_frame stamps unversioned frames)
            "v": wire.FRAME_VERSION,
        }
        if self._data_port:
            reg["data_port"] = self._data_port
        token = wire.cluster_token()
        if token is not None:
            reg["hmac"] = wire.register_hmac(token, reg)
        _send_frame(self.sock, self.send_lock, reg)
        resp = _recv_frame(self.sock)
        if not resp or not resp.get("ok"):
            raise ConnectionError(
                f"cluster head at {address} rejected registration"
            )
        self._thread = threading.Thread(
            target=self._serve_loop, daemon=True, name="node_agent"
        )
        self._thread.start()

    # -- node data plane --------------------------------------------------

    def _start_data_server(self) -> None:
        """Bind the per-node object data server (the reference's
        object-manager listen endpoint, ``object_manager.h:114``):
        peers and the head pull node-resident objects here, straight
        from this runtime's store — the head never proxies the bytes."""
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("0.0.0.0", 0))
        srv.listen(16)
        self._data_sock = srv
        self._data_port = srv.getsockname()[1]
        threading.Thread(
            target=self._data_accept_loop,
            daemon=True,
            name="node_data_server",
        ).start()

    def _data_accept_loop(self) -> None:
        while True:
            try:
                conn, _ = self._data_sock.accept()
            except OSError:
                return
            threading.Thread(
                target=self._data_conn_loop,
                args=(conn,),
                daemon=True,
                name="node_data_conn",
            ).start()

    def _data_conn_loop(self, conn: socket.socket) -> None:
        """One peer connection: challenge/HMAC auth (same trust wall
        as head registration — pulls deserialize as full pickle on the
        consumer), then serve pull requests until the peer leaves."""
        lock = threading.Lock()
        try:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn.settimeout(10.0)
            nonce = uuid.uuid4().hex
            _send_frame(
                conn, lock, {"op": "challenge", "nonce": nonce}
            )
            msg = _recv_frame(conn, max_len=_MAX_HANDSHAKE_FRAME)
            try:
                if msg is not None:
                    wire.validate_frame(msg, ("pull_auth",))
            except wire.ControlFrameError:
                msg = None
            if (
                not isinstance(msg, dict)
                or msg.get("nonce") != nonce
                or not wire.register_ok(wire.cluster_token(), msg)
            ):
                conn.close()
                return
            # mutual auth: echo the client's nonce under an HMAC so
            # the consumer can verify it is talking to a real cluster
            # member BEFORE unpickling any pull payload
            ok_frame = {
                "ok": True,
                "nonce": str(msg.get("client_nonce", "")),
                "v": wire.FRAME_VERSION,
            }
            token = wire.cluster_token()
            if token is not None:
                ok_frame["hmac"] = wire.register_hmac(
                    token, ok_frame
                )
            _send_frame(conn, lock, ok_frame)
            conn.settimeout(None)
            while True:
                req = _recv_frame(conn, max_len=_MAX_HANDSHAKE_FRAME)
                if req is None:
                    return
                wire.validate_frame(req, ("pull",))
                obj_id = str(req.get("obj_id", ""))
                try:
                    payload = self.runtime.store.get(
                        _node_obj_id(obj_id), timeout=0
                    )
                    resp = {"ok": True, "payload": payload}
                except Exception as err:
                    resp = {"ok": False, "error": repr(err)}
                _send_frame(conn, lock, resp)
        except (OSError, wire.ControlFrameError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    # frames the head may send an agent on the established connection
    _HEAD_OPS = frozenset(
        {
            "cache_obj",
            "free_objs",
            "task",
            "create_actor",
            "actor_call",
            "kill_actor",
        }
    )

    def _serve_loop(self):
        while True:
            try:
                msg = _recv_frame(self.sock)
                if msg is not None:
                    wire.validate_frame(msg, self._HEAD_OPS)
            except (OSError, wire.ControlFrameError):
                msg = None
            if msg is None:
                return
            try:
                self._handle(msg)
            except Exception:
                import traceback

                if msg.get("task_id"):
                    self._send_result(
                        msg["task_id"],
                        ok=False,
                        name=msg.get("method", "cluster"),
                        tb=traceback.format_exc(),
                    )

    def _send_result(
        self, task_id, *, ok, payload=b"", name="", tb="", node_obj=None
    ):
        frame = {
            "op": "result",
            "task_id": task_id,
            "ok": ok,
            "payload": payload,
            "name": name,
            "traceback": tb,
        }
        if node_obj is not None:
            frame["node_obj"] = node_obj
        _send_frame(self.sock, self.send_lock, frame)

    def _send_value_result(
        self, task_id, value, name: str, num_returns: int = 1
    ) -> None:
        """Serialize + send a success result, downgrading failures:
        an unserializable value becomes an error result, and a broken
        head socket is swallowed — this runs inside the local object
        store's on_ready callbacks, where an escaped exception would
        kill the thread delivering every later local result.

        Multi-return tuples split NODE-SIDE when big: each element
        becomes its own node-resident object (``{task_id}_{i}`` —
        matching the head's pre-registered split ref ids), so exchange
        partitions (Data groupby/shuffle) never transit the head."""
        # multi-return on the data plane: serialize ELEMENTS once and
        # decide residency by their total — serializing the whole
        # tuple first would double the CPU and transient memory on
        # exactly the exchange hot path this exists for
        if (
            self._data_port
            and num_returns > 1
            and isinstance(value, (tuple, list))
            and len(value) == num_returns
        ):
            try:
                blobs = [ser.dumps(v) for v in value]
            except BaseException:
                import traceback

                try:
                    self._send_result(
                        task_id,
                        ok=False,
                        name=name,
                        tb=traceback.format_exc(),
                    )
                except OSError:
                    pass
                return
            if sum(len(b) for b in blobs) >= self._node_obj_min:
                try:
                    for i, blob in enumerate(blobs):
                        self.runtime.store.put(
                            _node_obj_id(f"{task_id}_{i}"), blob
                        )
                    self._send_result(
                        task_id,
                        ok=True,
                        node_obj={
                            "split_sizes": [len(b) for b in blobs]
                        },
                    )
                except OSError:
                    pass  # head gone
                except BaseException:
                    # a failed element store must become an error
                    # result, not a dead callback thread (this runs
                    # in the store's on_ready delivery)
                    import traceback

                    try:
                        self._send_result(
                            task_id,
                            ok=False,
                            name=name,
                            tb=traceback.format_exc(),
                        )
                    except OSError:
                        pass
                return
            # small tuple: fall through to the inline path below
        try:
            payload = ser.dumps(value)
        except BaseException:
            import traceback

            try:
                self._send_result(
                    task_id,
                    ok=False,
                    name=name,
                    tb=traceback.format_exc(),
                )
            except OSError:
                pass
            return
        try:
            if (
                self._data_port
                and len(payload) >= self._node_obj_min
            ):
                # big result: keep the bytes in THIS node's store
                # (LRU/spill managed) and send the head metadata only
                # — whoever reads the ref pulls from our data server
                self.runtime.store.put(
                    _node_obj_id(task_id), payload
                )
                self._send_result(
                    task_id,
                    ok=True,
                    node_obj={"size": len(payload)},
                )
            else:
                self._send_result(task_id, ok=True, payload=payload)
        except OSError:
            pass  # head gone; its recv loop handles the disconnect

    def _resolve_pool_args(self, args, kwargs):
        """Map :class:`_PoolObj` markers to values via the node cache
        (top-level args only — the same scope the head marshals).
        Resolution order: inline value > node cache > this node's own
        data plane (we produced it) > peer pull (``location``)."""

        def r(v):
            if isinstance(v, _PoolObj):
                with self._obj_cache_lock:
                    if v.has_value:
                        self._obj_cache[v.obj_id] = v.value
                        return v.value
                    if v.obj_id in self._obj_cache:
                        return self._obj_cache[v.obj_id]
                blob = None
                try:
                    blob = self.runtime.store.get(
                        _node_obj_id(v.obj_id), timeout=0
                    )
                except Exception:
                    blob = None
                if blob is None and v.location is not None:
                    blob = fetch_remote_object(
                        v.location[0], v.location[1], v.obj_id
                    )
                if blob is not None:
                    value = ser.loads(blob)
                    with self._obj_cache_lock:
                        self._obj_cache[v.obj_id] = value
                    return value
                raise KeyError(
                    f"object {v.obj_id} not in node cache (freed at "
                    "head while a call naming it was in flight?)"
                )
            return v

        return [r(a) for a in args], {
            k: r(v) for k, v in kwargs.items()
        }

    def _handle(self, msg: Dict):
        op = msg["op"]
        if op == "create_actor":
            cls = ser.loads(msg["cls"])
            args, kwargs = ser.loads(msg["payload"])
            args, kwargs = self._resolve_pool_args(args, kwargs)
            handle = self.runtime.create_actor(
                cls, args, kwargs, dict(msg.get("options") or {})
            )
            self.actors[msg["actor_id"]] = handle._actor_id
        elif op == "cache_obj":
            value = ser.loads(msg["payload"])
            with self._obj_cache_lock:
                self._obj_cache[msg["obj_id"]] = value
        elif op == "free_objs":
            ids = list(msg.get("ids", ()))
            with self._obj_cache_lock:
                for i in ids:
                    self._obj_cache.pop(i, None)
            # node-resident primaries we produced die with the ref
            self.runtime.store.free(
                [_node_obj_id(i) for i in ids]
            )
        elif op == "task":
            task_id = msg["task_id"]
            func_blob = msg["func"]
            args, kwargs = ser.loads(msg["payload"])
            args, kwargs = self._resolve_pool_args(args, kwargs)
            refs = self.runtime.submit_task(
                None,
                msg["func_id"],
                func_blob,
                args,
                kwargs,
                {
                    "name": msg.get("name") or "spilled_task",
                    "num_cpus": msg.get("num_cpus", 1),
                    "runtime_env_packed": msg.get("runtime_env"),
                    # retries are the HEAD's job (it re-spills or runs
                    # locally); a local retry here would double-run
                    "max_retries": 0,
                },
            )
            ref = refs[0]
            n_ret = int(msg.get("num_returns", 1))

            def on_ready(
                task_id=task_id,
                ref=ref,
                name=msg.get("name"),
                n_ret=n_ret,
            ):
                try:
                    value = self.runtime.store.get(ref.id, timeout=0)
                except Exception:
                    import traceback

                    try:
                        self._send_result(
                            task_id,
                            ok=False,
                            name=name or "spilled_task",
                            tb=traceback.format_exc(),
                        )
                    except OSError:
                        pass
                    return
                self._send_value_result(
                    task_id,
                    value,
                    name or "spilled_task",
                    num_returns=n_ret,
                )
                self.runtime.store.free([ref.id])

            self.runtime.store.on_ready(ref.id, on_ready)
        elif op == "actor_call":
            task_id = msg["task_id"]
            local_id = self.actors.get(msg["actor_id"])
            if local_id is None:
                self._send_result(
                    task_id,
                    ok=False,
                    name=msg["method"],
                    tb=f"unknown actor {msg['actor_id']}",
                )
                return
            args, kwargs = ser.loads(msg["payload"])
            args, kwargs = self._resolve_pool_args(args, kwargs)
            refs = self.runtime.call_actor(
                local_id, msg["method"], args, kwargs, num_returns=1
            )
            ref = refs[0]

            # result callback keeps the serve loop free for the next
            # message (actor ordering is preserved by the actor's own
            # pipe queue, not by this thread)
            def on_ready(task_id=task_id, ref=ref, name=msg["method"]):
                try:
                    value = self.runtime.store.get(ref.id, timeout=0)
                except Exception:
                    import traceback

                    try:
                        self._send_result(
                            task_id,
                            ok=False,
                            name=name,
                            tb=traceback.format_exc(),
                        )
                    except OSError:
                        pass
                    return
                self._send_value_result(task_id, value, name)
                self.runtime.store.free([ref.id])

            self.runtime.store.on_ready(ref.id, on_ready)
        elif op == "kill_actor":
            local_id = self.actors.pop(msg["actor_id"], None)
            if local_id is not None:
                self.runtime.kill_actor(local_id)

    def close(self):
        try:
            # shutdown() (not just close()) so the FIN goes out even
            # while _serve_loop is parked in recv on this fd — close()
            # alone leaves the kernel fd open under the blocked read
            # and the head never learns the agent left
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass
        if self._data_sock is not None:
            try:
                self._data_sock.close()
            except OSError:
                pass


def main():  # pragma: no cover - thin CLI
    import argparse
    import time

    parser = argparse.ArgumentParser(
        description="ray_tpu node agent: join a head's actor fleet"
    )
    parser.add_argument("--address", required=True, help="head host:port")
    parser.add_argument("--node-id", default=None)
    parser.add_argument("--num-cpus", type=int, default=None)
    args = parser.parse_args()
    import ray_tpu.core.api as api

    api.init(num_cpus=args.num_cpus)
    agent = NodeAgent(args.address, args.node_id, args.num_cpus)
    print(f"node agent {agent.node_id} joined {args.address}", flush=True)
    while True:
        time.sleep(3600)


if __name__ == "__main__":  # pragma: no cover
    main()
