"""CLI entry point: join a head's actor fleet as a worker agent.

    python -m ray_tpu.core.node_agent --address HEAD:PORT \
        [--node-id NAME] [--num-cpus N]

Thin wrapper over ``ray_tpu.core.cluster`` (NodeAgent); see that
module for the protocol. The raylet-process analog
(``src/ray/raylet/main.cc``): one per host, hosting actors the head
places here.
"""

from ray_tpu.core.cluster import main

if __name__ == "__main__":
    main()
