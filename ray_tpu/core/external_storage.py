"""Pluggable external storage for spilled objects.

Counterpart of the reference's ``_private/external_storage.py``
(FileSystemStorage + ExternalStorageSmartOpenImpl for S3-compatible
stores, selected by the ``object_spilling_config`` URI): the object
store spills through whichever backend the ``RAY_TPU_SPILL_URI``
scheme names. ``file://`` is in-repo; ``s3://`` (or any other scheme)
registers at the seam — the sealed image ships no cloud SDKs, so the
S3 backend raises a clear error unless ``smart_open``/``boto3`` are
installed, exactly like the reference degrades without smart_open.
"""

from __future__ import annotations

import os
import uuid
from typing import Callable, Dict

_REGISTRY: Dict[str, Callable[[str], "ExternalStorage"]] = {}


def register_external_storage(
    scheme: str, factory: Callable[[str], "ExternalStorage"]
) -> None:
    """Register ``factory(uri) -> ExternalStorage`` for a URI scheme
    (reference: the smart_open impl registering itself for s3/gs)."""
    _REGISTRY[scheme] = factory


def storage_from_uri(uri: str) -> "ExternalStorage":
    scheme = uri.split("://", 1)[0] if "://" in uri else "file"
    factory = _REGISTRY.get(scheme)
    if factory is None:
        raise ValueError(
            f"no external storage registered for {scheme!r} "
            f"(have: {sorted(_REGISTRY)}); use "
            "register_external_storage()"
        )
    return factory(uri)


class ExternalStorage:
    """Spill backend contract: opaque URLs in, bytes out."""

    def put(self, obj_id: str, data: bytes) -> str:
        """Store; returns the URL to restore/delete by."""
        raise NotImplementedError

    def get(self, url: str) -> bytes:
        raise NotImplementedError

    def delete(self, url: str) -> None:
        raise NotImplementedError


class FileSystemStorage(ExternalStorage):
    """``file://<base_dir>`` (empty base → a fresh temp dir)."""

    def __init__(self, uri: str = "file://"):
        base = uri.split("://", 1)[1] if "://" in uri else uri
        if not base:
            import tempfile

            base = tempfile.mkdtemp(prefix="ray_tpu_spill_")
        os.makedirs(base, exist_ok=True)
        self.base = base

    def put(self, obj_id: str, data: bytes) -> str:
        path = os.path.join(
            self.base, f"{obj_id}-{uuid.uuid4().hex[:8]}.bin"
        )
        with open(path, "wb") as f:
            f.write(data)
        return path

    def get(self, url: str) -> bytes:
        with open(url, "rb") as f:
            return f.read()

    def delete(self, url: str) -> None:
        try:
            os.remove(url)
        except FileNotFoundError:
            pass


class SmartOpenStorage(ExternalStorage):
    """S3/GCS via ``smart_open`` when available (reference
    ExternalStorageSmartOpenImpl). The base image has no cloud SDKs;
    constructing this without them raises with instructions rather
    than failing deep inside a spill."""

    def __init__(self, uri: str):
        try:
            from smart_open import open as smart_open_fn  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "spilling to cloud storage needs the `smart_open` "
                "package (pip install smart_open[s3]); the base "
                "image ships without cloud SDKs"
            ) from e
        self._open = smart_open_fn
        self.base = uri.rstrip("/")

    def put(self, obj_id: str, data: bytes) -> str:
        url = f"{self.base}/{obj_id}-{uuid.uuid4().hex[:8]}.bin"
        with self._open(url, "wb") as f:
            f.write(data)
        return url

    def get(self, url: str) -> bytes:
        with self._open(url, "rb") as f:
            return f.read()

    def delete(self, url: str) -> None:
        # smart_open has no unified delete; objects age out by bucket
        # lifecycle policy (the reference leaves s3 deletion to its
        # io workers' delete_spilled_objects when the SDK is present)
        pass


register_external_storage("file", FileSystemStorage)
register_external_storage("s3", SmartOpenStorage)
register_external_storage("gs", SmartOpenStorage)
