"""Worker-side driver API: nested remote calls from inside tasks and
actors.

Counterpart of the reference's worker→core-worker task submission
path (``core_worker/core_worker.h`` SubmitTask from any worker +
``_raylet.pyx`` — in Ray, every worker IS a CoreWorker and may
submit tasks, put objects, and call actors). Here the driver owns all
scheduling state, so workers reach it over a lightweight loopback TCP
RPC: ``ray.remote(...)``/``.remote()``/``ray.get/put/wait`` and actor
method calls made INSIDE a worker route through this channel
transparently (the api layer falls back to the ambient
:func:`worker_client` when no runtime is present).

Deadlock note: a worker blocked in a nested ``ray.get`` still holds
its task's CPU. Like the reference (which releases the CPU while
blocked and re-acquires on return, allowing transient
oversubscription), the server releases the calling task's CPU for the
duration of a blocking get and re-acquires it after — so a
1-CPU pool can run ``f.remote()`` inside ``g.remote()`` without
wedging.

Trust model: loopback bind, pickled payloads — identical to the
worker pipes it parallels.
"""

from __future__ import annotations

import os
import socket
import threading
from typing import Any, Dict, List, Optional

from ray_tpu.core import serialization as ser
from ray_tpu.core.cluster import _recv_frame, _send_frame

ENV_ADDR = "RAY_TPU_DRIVER_API"


class WorkerAPIServer:
    """Driver-side listener; one handler thread per worker
    connection."""

    def __init__(self, runtime, host: str = "127.0.0.1", port: int = 0):
        self.runtime = runtime
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen()
        self.port = self._sock.getsockname()[1]
        self.address = f"{host}:{self.port}"
        # Pin registries shared across a worker's connections (threaded
        # actors open one connection per thread; a release notice may
        # ride out on any of them). conns counts live connections so
        # pins drop only when the whole worker is gone.
        self._handed_lock = threading.Lock()
        self._handed_by_worker: Dict[str, Dict[str, Any]] = {}
        self._conns_by_worker: Dict[str, int] = {}
        # Per-worker CPU-lend depth (guarded by runtime.lock): a worker
        # holds ONE set of task CPUs no matter how many of its threads
        # are concurrently blocked in nested gets — only the first
        # release lends them, only the last reacquire takes them back.
        self._released: Dict[str, list] = {}
        threading.Thread(
            target=self._accept_loop, daemon=True, name="worker_api"
        ).start()

    def _accept_loop(self):
        while True:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            threading.Thread(
                target=self._serve_conn,
                args=(conn,),
                daemon=True,
                name="worker_api_conn",
            ).start()

    def _serve_conn(self, conn):
        lock = threading.Lock()
        # refs handed to THIS worker stay pinned here (the worker's
        # own ObjectRef instances are untracked): without the pin,
        # the driver-side refcount would free a nested result the
        # moment it lands, before the worker ever reads it. The
        # worker piggybacks release notices for GC'd handles on its
        # next request; pins drop when the worker's LAST connection
        # dies. The registry is keyed by worker_id (from the client's
        # hello frame) so all threads of one worker share it.
        worker_key = None
        handed: Dict[str, Any] = {}

        def _close(_reason=None):
            try:
                conn.close()
            except OSError:
                pass
            if worker_key is None:
                handed.clear()
                return
            with self._handed_lock:
                n = self._conns_by_worker.get(worker_key, 1) - 1
                if n <= 0:
                    self._conns_by_worker.pop(worker_key, None)
                    self._handed_by_worker.pop(worker_key, None)
                else:
                    self._conns_by_worker[worker_key] = n

        while True:
            try:
                msg = _recv_frame(conn)
            except OSError:
                msg = None
            if msg is None:
                _close()
                return
            if msg.get("op") == "hello":
                worker_key = msg.get("worker_key")
                if worker_key is not None:
                    with self._handed_lock:
                        handed = self._handed_by_worker.setdefault(
                            worker_key, {}
                        )
                        self._conns_by_worker[worker_key] = (
                            self._conns_by_worker.get(worker_key, 0) + 1
                        )
                try:
                    _send_frame(conn, lock, {"ok": True})
                except OSError:
                    _close()
                    return
                continue
            for rid in msg.get("release") or ():
                handed.pop(rid, None)
            try:
                reply = self._handle(msg, handed)
            except BaseException as e:  # noqa: BLE001 - ship to caller
                reply = {"ok": False, "error": ser.dumps(e)}
            try:
                _send_frame(conn, lock, reply)
            except OSError:
                _close()
                return

    # -- ops -------------------------------------------------------------

    def _handle(self, msg: Dict, handed: Dict) -> Dict:
        rt = self.runtime
        op = msg["op"]
        if op == "submit":
            func = ser.loads(msg["func_blob"])
            args, kwargs = ser.loads(msg["payload"])
            refs = rt.submit_task(
                func,
                msg["func_id"],
                msg["func_blob"],
                list(args),
                dict(kwargs),
                dict(msg.get("options") or {}),
            )
            for r in refs:
                handed[r.id] = r
            return {"ok": True, "ref_ids": [r.id for r in refs]}
        if op == "get":
            released = self._release_caller_cpu(msg.get("worker_id"))
            try:
                value = rt.store.get(
                    msg["obj_id"], timeout=msg.get("timeout")
                )
            finally:
                self._reacquire_cpu(released)
            return {"ok": True, "value": ser.dumps(value)}
        if op == "kill_actor":
            rt.kill_actor(
                msg["actor_id"], bool(msg.get("no_restart", True))
            )
            return {"ok": True}
        if op == "free":
            rt.store.free(list(msg.get("ids") or ()))
            return {"ok": True}
        if op == "spill_loc":
            loc = rt.store.spill_location(msg["obj_id"])
            if loc is None:
                return {"ok": True, "loc": None}
            return {"ok": True, "loc": list(loc)}
        if op == "put":
            from ray_tpu.core.object_store import ObjectRef

            ref = ObjectRef(store=rt.store)
            rt.store.put(ref.id, ser.loads(msg["value"]))
            handed[ref.id] = ref
            return {"ok": True, "ref_id": ref.id}
        if op == "wait":
            from ray_tpu.core import api as api_mod
            from ray_tpu.core.object_store import ObjectRef

            refs = [
                ObjectRef(i, rt.store) for i in msg["obj_ids"]
            ]
            released = self._release_caller_cpu(msg.get("worker_id"))
            try:
                ready, pending = api_mod.wait(
                    refs,
                    num_returns=msg.get("num_returns", 1),
                    timeout=msg.get("timeout"),
                )
            finally:
                self._reacquire_cpu(released)
            return {
                "ok": True,
                "ready": [r.id for r in ready],
                "pending": [r.id for r in pending],
            }
        if op == "create_actor":
            cls = ser.loads(msg["cls_blob"])
            args, kwargs = ser.loads(msg["payload"])
            handle = rt.create_actor(
                cls, list(args), dict(kwargs),
                dict(msg.get("options") or {}),
            )
            return {
                "ok": True,
                "actor_id": handle._actor_id,
                "class_name": handle._class_name,
            }
        if op == "get_actor":
            with rt.lock:
                actor_id = rt.named_actors.get(msg["name"])
            if actor_id is None:
                return {
                    "ok": False,
                    "error": ser.dumps(
                        ValueError(f"No actor named {msg['name']!r}")
                    ),
                }
            return {"ok": True, "actor_id": actor_id}
        if op == "call_actor":
            args, kwargs = ser.loads(msg["payload"])
            refs = rt.call_actor(
                msg["actor_id"],
                msg["method"],
                list(args),
                dict(kwargs),
                num_returns=msg.get("num_returns", 1),
            )
            for r in refs:
                handed[r.id] = r
            return {"ok": True, "ref_ids": [r.id for r in refs]}
        return {"ok": False, "error": ser.dumps(
            ValueError(f"unknown op {op!r}")
        )}

    def _release_caller_cpu(self, worker_id) -> Optional[str]:
        """Free the blocked task's CPU so nested work can schedule
        (reference CPU borrowing while blocked in ray.get). Returns a
        token for :meth:`_reacquire_cpu` (None = nothing released).
        Depth-counted per worker: concurrent nested gets from several
        threads of one worker lend its CPUs exactly once."""
        if worker_id is None:
            return None
        rt = self.runtime
        with rt.lock:
            ent = self._released.get(worker_id)
            if ent is not None:
                # another thread of this worker already lent the CPUs
                ent[0] += 1
                return worker_id
            for w in rt.pool:
                if w.worker_id == worker_id and w.inflight:
                    cpus = sum(
                        t.num_cpus for t in w.inflight.values()
                    )
                    if cpus == 0:
                        # 0-CPU tasks hold no slot: nothing to lend,
                        # and counting a blocked worker here would leak
                        # (inflating the spawn cap forever).
                        return None
                    rt.available_cpus += cpus
                    rt.blocked_workers += 1
                    self._released[worker_id] = [1, cpus]
                    break
            else:
                return None
        rt._dispatch_pending()
        return worker_id

    def _reacquire_cpu(self, worker_id: Optional[str]) -> None:
        if worker_id is None:
            return
        rt = self.runtime
        with rt.lock:
            ent = self._released.get(worker_id)
            if ent is None:
                return
            ent[0] -= 1
            if ent[0] <= 0:
                del self._released[worker_id]
                # transient oversubscription is allowed, as in the
                # reference: the task already owned this CPU
                rt.available_cpus -= ent[1]
                rt.blocked_workers -= 1

    def shutdown(self):
        try:
            self._sock.close()
        except OSError:
            pass


# -- worker-side client ------------------------------------------------------

_client_lock = threading.Lock()
_client: Optional["DriverAPIClient"] = None

# Worker-local handle accounting: the driver pins every ref it hands
# this worker; when the LAST local ObjectRef instance for an id is
# GC'd, the id queues here and rides out on the next request as a
# release notice (no extra roundtrips, and __del__ never touches the
# connection). ObjectRef.__init__/__del__ call these in worker
# processes (see object_store._ambient_store).
_ref_lock = threading.Lock()
_local_counts: Dict[str, int] = {}
_pending_release: List[str] = []


def note_ref(obj_id: str) -> bool:
    """Track one worker-local ObjectRef instance; returns False when
    not in a worker context (caller skips __del__ accounting)."""
    if _client is None and not os.environ.get(ENV_ADDR):
        return False
    with _ref_lock:
        _local_counts[obj_id] = _local_counts.get(obj_id, 0) + 1
    return True


def note_ref_deleted(obj_id: str) -> None:
    with _ref_lock:
        n = _local_counts.get(obj_id)
        if n is None:
            return
        if n > 1:
            _local_counts[obj_id] = n - 1
            return
        _local_counts.pop(obj_id, None)
        _pending_release.append(obj_id)


def _drain_releases() -> List[str]:
    with _ref_lock:
        out, _pending_release[:] = _pending_release[:], []
    return out


class DriverAPIClient:
    def __init__(self, address: str, worker_id: Optional[str] = None):
        host, port = address.rsplit(":", 1)
        self.sock = socket.create_connection((host, int(port)))
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.lock = threading.Lock()
        self.worker_id = worker_id
        # Identify this worker process so the server shares one pin
        # registry across all of its connections (one per thread).
        _send_frame(
            self.sock,
            threading.Lock(),
            {
                "op": "hello",
                "worker_key": worker_id or f"pid-{os.getpid()}",
            },
        )
        if _recv_frame(self.sock) is None:
            raise ConnectionError("driver API hello failed")

    def _roundtrip(self, msg: Dict) -> Dict:
        released = _drain_releases()
        if released:
            msg = dict(msg, release=released)
        # Calls on ONE client serialize behind this lock; worker_client()
        # hands each thread its own client, so threads of a
        # max_concurrency actor don't block behind another thread's get.
        with self.lock:
            _send_frame(self.sock, threading.Lock(), msg)
            reply = _recv_frame(self.sock)
        if reply is None:
            raise ConnectionError("driver API connection lost")
        if not reply.get("ok"):
            raise ser.loads(reply["error"])
        return reply

    def submit(self, func, func_id, func_blob, args, kwargs, options):
        reply = self._roundtrip(
            {
                "op": "submit",
                "func_id": func_id,
                "func_blob": func_blob,
                "payload": ser.dumps((args, kwargs)),
                "options": options,
            }
        )
        return reply["ref_ids"]

    def kill_actor(self, actor_id: str, no_restart: bool = True):
        self._roundtrip(
            {
                "op": "kill_actor",
                "actor_id": actor_id,
                "no_restart": no_restart,
            }
        )

    def free(self, ids) -> None:
        self._roundtrip({"op": "free", "ids": list(ids)})

    def spill_location(self, obj_id: str):
        """(spill_uri, path) if the object is currently spilled, else
        None — lets the worker read big spilled blocks straight from
        the storage backend instead of through this socket."""
        resp = self._roundtrip(
            {"op": "spill_loc", "obj_id": obj_id}
        )
        loc = resp.get("loc")
        return tuple(loc) if loc else None

    def get(self, obj_id: str, timeout: Optional[float]) -> Any:
        reply = self._roundtrip(
            {
                "op": "get",
                "obj_id": obj_id,
                "timeout": timeout,
                "worker_id": self.worker_id,
            }
        )
        return ser.loads(reply["value"])

    def put(self, value: Any) -> str:
        return self._roundtrip(
            {"op": "put", "value": ser.dumps(value)}
        )["ref_id"]

    def wait(self, obj_ids, num_returns, timeout):
        reply = self._roundtrip(
            {
                "op": "wait",
                "obj_ids": list(obj_ids),
                "num_returns": num_returns,
                "timeout": timeout,
                "worker_id": self.worker_id,
            }
        )
        return reply["ready"], reply["pending"]

    def create_actor(self, cls_blob, args, kwargs, options):
        reply = self._roundtrip(
            {
                "op": "create_actor",
                "cls_blob": cls_blob,
                "payload": ser.dumps((args, kwargs)),
                "options": options,
            }
        )
        return reply["actor_id"], reply["class_name"]

    def get_actor(self, name: str) -> str:
        return self._roundtrip({"op": "get_actor", "name": name})[
            "actor_id"
        ]

    def call_actor(self, actor_id, method, args, kwargs, num_returns=1):
        reply = self._roundtrip(
            {
                "op": "call_actor",
                "actor_id": actor_id,
                "method": method,
                "payload": ser.dumps((args, kwargs)),
                "num_returns": num_returns,
            }
        )
        return reply["ref_ids"]


_thread_clients = threading.local()


def worker_client() -> Optional[DriverAPIClient]:
    """The ambient driver-API client of a worker process (None on the
    driver or when the runtime predates the server).

    One client (connection) PER THREAD: in a ``max_concurrency > 1``
    actor, a thread blocked in a nested ``ray.get`` must not serialize
    the other threads' nested calls — notably when the blocked get
    depends on work another thread has yet to submit (deadlock
    otherwise). The server shares the pin registry across a worker's
    connections via the hello frame's worker_key.
    """
    global _client
    addr = os.environ.get(ENV_ADDR)
    if not addr:
        return None
    cl = getattr(_thread_clients, "client", None)
    if cl is None:
        cl = DriverAPIClient(addr, os.environ.get("RAY_TPU_WORKER_ID"))
        _thread_clients.client = cl
        with _client_lock:
            if _client is None:
                _client = cl  # note_ref()'s in-worker check
    return cl
