"""Node memory monitor: kill workers under memory pressure instead of
letting the OS OOM-killer take down the whole node.

Counterpart of the reference's memory monitor + worker killing policy
(``python/ray/_private/memory_monitor.py`` /proc-based usage readings,
``src/ray/raylet/worker_killing_policy_group_by_owner.cc`` — under
pressure, kill the LAST-started task first and prefer retriable work,
so long-running computation is protected and the node relieves itself
with the least lost progress).

Scoped to the single-host runtime: one polling thread on the driver
reads ``/proc/meminfo`` and per-worker RSS; when usage crosses the
threshold it terminates the chosen worker's process. The normal
worker-death path then retries the task (if retries remain) or fails
it with :class:`RayOutOfMemoryError` carrying the usage breakdown.
Enabled via ``ray.init(enable_memory_monitor=True)`` or
``RAY_TPU_MEMORY_MONITOR=1``; threshold via
``RAY_TPU_MEMORY_THRESHOLD`` (fraction of MemTotal, default 0.95).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, List, Optional, Tuple


def node_memory() -> Tuple[int, int]:
    """(used_bytes, total_bytes) from /proc/meminfo, counting
    reclaimable memory as free (MemAvailable, like the reference's
    psutil path)."""
    total = avail = 0
    with open("/proc/meminfo") as f:
        for line in f:
            if line.startswith("MemTotal:"):
                total = int(line.split()[1]) * 1024
            elif line.startswith("MemAvailable:"):
                avail = int(line.split()[1]) * 1024
            if total and avail:
                break
    return total - avail, total


def process_rss(pid: int) -> int:
    """Resident set size of one process in bytes (0 if gone)."""
    try:
        with open(f"/proc/{pid}/statm") as f:
            return int(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
    except (FileNotFoundError, ProcessLookupError, ValueError, OSError):
        return 0


class MemoryMonitor:
    """One per runtime; ``reader`` is injectable for tests."""

    def __init__(
        self,
        runtime,
        threshold: Optional[float] = None,
        interval_s: float = 1.0,
        reader: Optional[Callable[[], Tuple[int, int]]] = None,
        start: bool = True,
    ):
        self.runtime = runtime
        self.threshold = float(
            threshold
            if threshold is not None
            else os.environ.get("RAY_TPU_MEMORY_THRESHOLD", 0.95)
        )
        self.interval_s = interval_s
        self.reader = reader or node_memory
        self.kills = 0
        self._stop = threading.Event()
        self._thread = None
        if start:
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="memory_monitor"
            )
            self._thread.start()

    def _run(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.check_once()
            except Exception:
                pass  # monitoring must never take down the driver

    def stop(self):
        self._stop.set()

    # -- one sweep -------------------------------------------------------

    def check_once(self) -> Optional[str]:
        """If over threshold, kill one victim worker; returns its
        worker_id (or None if below threshold / no candidate)."""
        used, total = self.reader()
        if total <= 0 or used < self.threshold * total:
            return None
        victim, started = self._pick_victim()
        if victim is None:
            return None
        usage = self._usage_report(used, total)
        victim.oom_reason = (
            f"node memory pressure: {used / 2**30:.2f}/"
            f"{total / 2**30:.2f} GiB used "
            f"({100.0 * used / total:.1f}% >= threshold "
            f"{100.0 * self.threshold:.0f}%). Killed worker "
            f"{victim.worker_id} (newest task, started "
            f"{time.time() - started:.1f}s ago) to relieve pressure.\n"
            f"{usage}"
        )
        self.kills += 1
        try:
            victim.proc.terminate()
        except Exception:
            pass
        return victim.worker_id

    def _pick_victim(self):
        """The reference's group-by-owner policy, scoped: among busy
        POOL workers (plain tasks — retriable, cheapest to lose), the
        one whose running task started LAST; actors only if no task
        worker qualifies (restartable actors first)."""
        rt = self.runtime
        with rt.lock:
            best, best_t = None, -1.0
            for w in rt.pool:
                if w.dead or not w.inflight:
                    continue
                started = max(
                    t.submit_time for t in w.inflight.values()
                )
                if started > best_t:
                    best, best_t = w, started
            if best is not None:
                return best, best_t
            restartable = []
            for rec in rt.actors.values():
                if rec.dead or rec.worker.dead:
                    continue
                if rec.restarts < rec.max_restarts:
                    restartable.append(rec)
            if restartable:
                rec = max(restartable, key=lambda r: r.restarts == 0)
                return rec.worker, time.time()
        return None, -1.0

    def _usage_report(self, used: int, total: int, top: int = 5) -> str:
        rt = self.runtime
        rows: List[Tuple[int, str]] = []
        with rt.lock:
            procs = [
                (w.worker_id, w.proc.pid)
                for w in rt.pool
                if not w.dead and w.proc
            ] + [
                (f"actor:{rec.actor_id[:12]}", rec.worker.proc.pid)
                for rec in rt.actors.values()
                if not rec.dead and rec.worker.proc
            ]
        for wid, pid in procs:
            rss = process_rss(pid)
            if rss:
                rows.append((rss, f"  {wid} (pid {pid}): "
                                  f"{rss / 2**20:.0f} MiB"))
        rows.sort(reverse=True)
        lines = [r for _, r in rows[:top]]
        return "Top workers by RSS:\n" + "\n".join(lines) if lines else ""
