"""ctypes binding for the native shared-memory ring buffer.

The streaming data plane between rollout actors and the learner: the
python side serializes objects (pickle-5 with out-of-band numpy buffers
written contiguously) and moves bytes through the C++ SPSC ring
(``ray_tpu/native/shm_ring.cpp``), bypassing the pipe+re-pickle control
path entirely for bulk SampleBatch traffic.
"""

from __future__ import annotations

import ctypes
import pickle
from typing import Any, Optional

from ray_tpu.core import serialization as ser
from ray_tpu.native.build import ensure_built

_lib = None


def _load():
    global _lib
    if _lib is None:
        lib = ctypes.CDLL(ensure_built())
        lib.shmring_create.restype = ctypes.c_void_p
        lib.shmring_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.shmring_attach.restype = ctypes.c_void_p
        lib.shmring_attach.argtypes = [ctypes.c_char_p]
        lib.shmring_push.restype = ctypes.c_int
        lib.shmring_push.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_uint64,
        ]
        lib.shmring_push_wait.restype = ctypes.c_int
        lib.shmring_push_wait.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_uint64,
            ctypes.c_int64,
        ]
        lib.shmring_peek_len.restype = ctypes.c_int64
        lib.shmring_peek_len.argtypes = [ctypes.c_void_p]
        lib.shmring_pop.restype = ctypes.c_int64
        lib.shmring_pop.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_uint64,
        ]
        lib.shmring_pop_wait.restype = ctypes.c_int64
        lib.shmring_pop_wait.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_uint64,
            ctypes.c_int64,
        ]
        lib.shmring_size.restype = ctypes.c_uint64
        lib.shmring_size.argtypes = [ctypes.c_void_p]
        lib.shmring_num_pushed.restype = ctypes.c_uint64
        lib.shmring_num_pushed.argtypes = [ctypes.c_void_p]
        lib.shmring_num_popped.restype = ctypes.c_uint64
        lib.shmring_num_popped.argtypes = [ctypes.c_void_p]
        lib.shmring_mark_closed.argtypes = [ctypes.c_void_p]
        lib.shmring_is_closed.restype = ctypes.c_int
        lib.shmring_is_closed.argtypes = [ctypes.c_void_p]
        lib.shmring_close.argtypes = [ctypes.c_void_p]
        _lib = lib
    return _lib


class ShmRing:
    """One SPSC byte ring. Create on one side, attach on the other."""

    def __init__(self, name: str, handle, owner: bool):
        self.name = name
        self._h = handle
        self._owner = owner
        self._closed = False

    @classmethod
    def create(cls, name: str, capacity: int = 64 * 1024 * 1024) -> "ShmRing":
        lib = _load()
        h = lib.shmring_create(name.encode(), capacity)
        if not h:
            raise OSError(f"shmring_create({name}) failed")
        return cls(name, h, owner=True)

    @classmethod
    def attach(cls, name: str) -> "ShmRing":
        lib = _load()
        h = lib.shmring_attach(name.encode())
        if not h:
            raise OSError(f"shmring_attach({name}) failed")
        return cls(name, h, owner=False)

    # -- raw bytes -------------------------------------------------------

    def push_bytes(self, data: bytes, timeout: Optional[float] = 10.0) -> bool:
        lib = _load()
        t_ms = -1 if timeout is None else int(timeout * 1000)
        rc = lib.shmring_push_wait(self._h, data, len(data), t_ms)
        if rc == -2:
            raise ValueError(
                f"record of {len(data)} bytes exceeds ring capacity"
            )
        if rc == -3:
            raise BrokenPipeError("ring closed")
        return rc == 0

    def pop_bytes(self, timeout: Optional[float] = 10.0) -> Optional[bytes]:
        lib = _load()
        n = lib.shmring_peek_len(self._h)
        t_ms = -1 if timeout is None else int(timeout * 1000)
        if n < 0:
            # wait for a record
            buf = ctypes.create_string_buffer(1)
            n = lib.shmring_pop_wait(self._h, buf, 0, 0)
        # allocate exactly and pop
        while True:
            n = lib.shmring_peek_len(self._h)
            if n >= 0:
                buf = ctypes.create_string_buffer(int(n))
                got = lib.shmring_pop(self._h, buf, n)
                if got >= 0:
                    return buf.raw[:got]
            else:
                buf = ctypes.create_string_buffer(8)
                got = lib.shmring_pop_wait(self._h, buf, 8, t_ms)
                if got == -1:
                    return None  # timeout
                if got == -3:
                    raise BrokenPipeError("ring closed")
                if got == -2:
                    continue  # record bigger than probe buf; re-peek
                return buf.raw[:got]

    # -- objects ---------------------------------------------------------

    def push(self, obj: Any, timeout: Optional[float] = 10.0) -> bool:
        """Serialize (out-of-band numpy buffers inline) and push."""
        meta, buffers = ser.serialize(obj)
        size = ser.serialized_size(meta, buffers)
        payload = bytearray(size)
        ser.write_to_buffer(memoryview(payload), meta, buffers)
        return self.push_bytes(bytes(payload), timeout)

    def pop(self, timeout: Optional[float] = 10.0) -> Any:
        data = self.pop_bytes(timeout)
        if data is None:
            return None
        return ser.read_from_buffer(memoryview(data))

    # -- stats / lifecycle ----------------------------------------------

    def size_bytes(self) -> int:
        return _load().shmring_size(self._h)

    def num_pushed(self) -> int:
        return _load().shmring_num_pushed(self._h)

    def num_popped(self) -> int:
        return _load().shmring_num_popped(self._h)

    def mark_closed(self) -> None:
        _load().shmring_mark_closed(self._h)

    def is_closed(self) -> bool:
        return bool(_load().shmring_is_closed(self._h))

    def close(self) -> None:
        if not self._closed and self._h:
            _load().shmring_close(self._h)
            self._closed = True
            self._h = None

    def __reduce__(self):
        # Rings pickle as attach-by-name (for shipping to actors).
        return (ShmRing.attach, (self.name,))

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
