"""ctypes binding for the native shared-memory ring buffer.

The streaming data plane between rollout actors and the learner: the
python side serializes objects (pickle-5 with out-of-band numpy buffers
written contiguously) and moves bytes through the C++ SPSC ring
(``ray_tpu/native/shm_ring.cpp``), bypassing the pipe+re-pickle control
path entirely for bulk SampleBatch traffic.
"""

from __future__ import annotations

import ctypes
import pickle
from typing import Any, Optional

from ray_tpu.core import serialization as ser
from ray_tpu.native.build import ensure_built

_lib = None


def _poll(timeout: Optional[float]):
    """Attempt-pacing generator: yields immediately, then sleeps with
    50µs→2ms exponential backoff between attempts until the deadline.
    The shared wait scaffold for producer (ring full) and consumer
    (ring empty) sides."""
    import time as _time

    deadline = None if timeout is None else _time.monotonic() + timeout
    sleep_s = 50e-6
    while True:
        yield
        if deadline is not None and _time.monotonic() >= deadline:
            return
        _time.sleep(sleep_s)
        sleep_s = min(sleep_s * 2, 2e-3)


def _load():
    global _lib
    if _lib is None:
        lib = ctypes.CDLL(ensure_built())
        lib.shmring_create.restype = ctypes.c_void_p
        lib.shmring_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.shmring_attach.restype = ctypes.c_void_p
        lib.shmring_attach.argtypes = [ctypes.c_char_p]
        lib.shmring_peek_len.restype = ctypes.c_int64
        lib.shmring_peek_len.argtypes = [ctypes.c_void_p]
        lib.shmring_pop.restype = ctypes.c_int64
        lib.shmring_pop.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_uint64,
        ]
        lib.shmring_reserve.restype = ctypes.c_int64
        lib.shmring_reserve.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.shmring_commit.argtypes = [ctypes.c_void_p]
        lib.shmring_data.restype = ctypes.c_void_p
        lib.shmring_data.argtypes = [ctypes.c_void_p]
        lib.shmring_capacity.restype = ctypes.c_uint64
        lib.shmring_capacity.argtypes = [ctypes.c_void_p]
        lib.shmring_size.restype = ctypes.c_uint64
        lib.shmring_size.argtypes = [ctypes.c_void_p]
        lib.shmring_num_pushed.restype = ctypes.c_uint64
        lib.shmring_num_pushed.argtypes = [ctypes.c_void_p]
        lib.shmring_num_popped.restype = ctypes.c_uint64
        lib.shmring_num_popped.argtypes = [ctypes.c_void_p]
        lib.shmring_mark_closed.argtypes = [ctypes.c_void_p]
        lib.shmring_is_closed.restype = ctypes.c_int
        lib.shmring_is_closed.argtypes = [ctypes.c_void_p]
        lib.shmring_close.argtypes = [ctypes.c_void_p]
        _lib = lib
    return _lib


class ShmRing:
    """One SPSC byte ring. Create on one side, attach on the other."""

    def __init__(self, name: str, handle, owner: bool):
        self.name = name
        self._h = handle
        self._owner = owner
        self._closed = False
        # Writable view over the mapped data area for zero-copy pushes:
        # the serializer writes record payloads straight into shared
        # memory between reserve and commit.
        lib = _load()
        cap = lib.shmring_capacity(handle)
        addr = lib.shmring_data(handle)
        self._data = memoryview(
            (ctypes.c_char * cap).from_address(addr)
        ).cast("B")

    @classmethod
    def create(cls, name: str, capacity: int = 64 * 1024 * 1024) -> "ShmRing":
        lib = _load()
        h = lib.shmring_create(name.encode(), capacity)
        if not h:
            raise OSError(f"shmring_create({name}) failed")
        return cls(name, h, owner=True)

    @classmethod
    def attach(cls, name: str) -> "ShmRing":
        lib = _load()
        h = lib.shmring_attach(name.encode())
        if not h:
            raise OSError(f"shmring_attach({name}) failed")
        return cls(name, h, owner=False)

    # -- raw bytes -------------------------------------------------------

    def _reserve_wait(self, size: int, timeout: Optional[float]) -> int:
        """Reserve `size` bytes, waiting for the consumer to drain if the
        ring is full. Returns the payload offset or -1 on timeout."""
        lib = _load()
        for _ in _poll(timeout):
            off = lib.shmring_reserve(self._h, size)
            if off >= 0:
                return off
            if off == -2:
                raise ValueError(
                    f"record of {size} bytes cannot fit in the ring"
                )
            if off == -3:
                raise BrokenPipeError("ring closed")
        return -1

    def push_bytes(self, data, timeout: Optional[float] = 10.0) -> bool:
        off = self._reserve_wait(len(data), timeout)
        if off < 0:
            return False
        self._data[off : off + len(data)] = data
        _load().shmring_commit(self._h)
        return True

    def pop_bytes(self, timeout: Optional[float] = 10.0):
        """Pop one record (single memcpy out of shm). Returns a writable
        buffer (numpy uint8 array — uninitialized alloc, no memset) or
        None on timeout."""
        import numpy as _np

        lib = _load()
        for _ in _poll(timeout):
            n = lib.shmring_peek_len(self._h)
            if n >= 0:
                n = int(n)
                buf = _np.empty(n, _np.uint8)
                got = lib.shmring_pop(
                    self._h,
                    (ctypes.c_char * n).from_buffer(buf.data),
                    n,
                )
                if got >= 0:
                    return buf.data  # memoryview-compatible buffer
            elif lib.shmring_is_closed(self._h):
                raise BrokenPipeError("ring closed")
        return None

    # -- objects ---------------------------------------------------------

    def push_serialized(
        self, meta, buffers, size: int, timeout: Optional[float] = 10.0
    ) -> bool:
        """Write a pre-serialized record straight into shared memory
        (zero intermediate copies: reserve → write_to_buffer → commit)."""
        off = self._reserve_wait(size, timeout)
        if off < 0:
            return False
        ser.write_to_buffer(self._data[off : off + size], meta, buffers)
        _load().shmring_commit(self._h)
        return True

    def push(self, obj: Any, timeout: Optional[float] = 10.0) -> bool:
        """Serialize (out-of-band numpy buffers inline) and push."""
        meta, buffers = ser.serialize(obj)
        size = ser.serialized_size(meta, buffers)
        return self.push_serialized(meta, buffers, size, timeout)

    def pop(self, timeout: Optional[float] = 10.0) -> Any:
        data = self.pop_bytes(timeout)
        if data is None:
            return None
        return ser.read_from_buffer(memoryview(data))

    # -- stats / lifecycle ----------------------------------------------

    def size_bytes(self) -> int:
        return _load().shmring_size(self._h)

    def num_pushed(self) -> int:
        return _load().shmring_num_pushed(self._h)

    def num_popped(self) -> int:
        return _load().shmring_num_popped(self._h)

    def mark_closed(self) -> None:
        _load().shmring_mark_closed(self._h)

    def is_closed(self) -> bool:
        return bool(_load().shmring_is_closed(self._h))

    def close(self) -> None:
        if not self._closed and self._h:
            _load().shmring_close(self._h)
            self._closed = True
            self._h = None

    def __reduce__(self):
        # Rings pickle as attach-by-name (for shipping to actors).
        return (ShmRing.attach, (self.name,))

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
