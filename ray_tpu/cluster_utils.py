"""In-process multi-node test cluster.

Counterpart of the reference's ``ray.cluster_utils.Cluster``
(``python/ray/cluster_utils.py:52`` — the harness behind its
multi-node unit tests): a head runtime plus N worker-agent nodes, each
a REAL subprocess joining the head's fleet over TCP
(``core/cluster.py``), with add/remove/kill/wait primitives so tests
can script topologies and failures.

TPU-first disposition: the head owns the chip and the driver; nodes
host CPU actors only (the star-shaped fleet of ``core/cluster.py``),
so this harness scripts CPU-fleet topologies — the multi-host TPU
axis is ``jax.distributed`` and is tested by
``tests/test_multihost.py`` instead.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ray_tpu.autoscaler.node_provider import LocalSubprocessProvider


class Cluster:
    """reference cluster_utils.py:52 (scoped: head + CPU agents)."""

    def __init__(
        self,
        initialize_head: bool = True,
        head_node_args: Optional[Dict] = None,
    ):
        import ray_tpu as ray
        from ray_tpu.core.cluster import start_cluster_server

        self._nodes: List[str] = []
        self.address = None
        if initialize_head:
            ray.init(**(head_node_args or {"num_cpus": 2}))
            self.address = start_cluster_server()
            self._provider = LocalSubprocessProvider(self.address)

    def add_node(self, num_cpus: int = 1, **_) -> str:
        """Spawn a worker-agent node subprocess; returns its provider
        node id (NOT the fleet node_id — use ``wait_for_nodes`` to
        learn membership, as the reference does via the GCS)."""
        node_id = self._provider.create_node({"num_cpus": num_cpus})
        self._nodes.append(node_id)
        return node_id

    def remove_node(self, node_id: str, graceful: bool = True) -> None:
        """Terminate a node (SIGTERM; the head fails its in-flight
        work and drops it from membership)."""
        self._provider.terminate_node(node_id)
        if node_id in self._nodes:
            self._nodes.remove(node_id)

    def wait_for_nodes(self, n: int, timeout: float = 60.0) -> List[str]:
        """Block until ``n`` agent nodes are registered with the head;
        returns their fleet node_ids."""
        from ray_tpu.core import api

        rt = api._require_runtime()
        return rt.cluster.wait_for_nodes(n, timeout=timeout)

    @property
    def alive_nodes(self) -> List[str]:
        return self._provider.non_terminated_nodes()

    def shutdown(self) -> None:
        import ray_tpu as ray

        for nid in list(self._nodes):
            self.remove_node(nid)
        ray.shutdown()
