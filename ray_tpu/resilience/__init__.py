"""ray_tpu.resilience — fault tolerance for the training loop
(docs/resilience.md).

Three pieces, wired through the whole hot path:

- :mod:`~ray_tpu.resilience.faults` — a deterministic, config/env-driven
  :class:`FaultInjector` (kill worker N on sample call K, delay
  samples, poison a learn batch with NaN/Inf, crash the learner)
  usable from tests and ``bench.py --chaos``;
- :mod:`~ray_tpu.resilience.retry` — the single :class:`RetryPolicy`
  (per-attempt timeout + exponential backoff + jitter + max attempts)
  behind request-manager submission/harvest, WorkerSet sync /
  ``foreach_worker`` marshalling, and the bounded
  :func:`probe_actors` health sweep;
- :mod:`~ray_tpu.resilience.recovery` — the :class:`RecoveryManager`
  ``Algorithm.step`` consults on failure: recreate dead rollout
  workers and continue degraded, auto-restore from the latest periodic
  checkpoint on a restartable driver failure, and skip non-finite
  learn batches (``nan_guard``). Configure with
  ``AlgorithmConfig.fault_tolerance(...)``.
"""

from ray_tpu.resilience import faults  # noqa: F401
from ray_tpu.resilience.faults import (  # noqa: F401
    FaultInjector,
    InjectedCrash,
)
from ray_tpu.resilience.recovery import (  # noqa: F401
    ACTOR_DEAD_ERRORS,
    RecoveryManager,
    batch_is_finite,
)
from ray_tpu.resilience.retry import (  # noqa: F401
    DEFAULT_RETRYABLE,
    RetryPolicy,
    probe_actors,
    ray_get_retrying,
)
from ray_tpu.resilience.streamer import (  # noqa: F401
    CheckpointStreamer,
)

__all__ = [
    "ACTOR_DEAD_ERRORS",
    "CheckpointStreamer",
    "DEFAULT_RETRYABLE",
    "FaultInjector",
    "InjectedCrash",
    "RecoveryManager",
    "RetryPolicy",
    "batch_is_finite",
    "faults",
    "probe_actors",
    "ray_get_retrying",
]
