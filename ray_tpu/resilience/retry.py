"""RetryPolicy: the single timeout / exponential-backoff / jitter /
max-attempts schedule applied uniformly across the control plane.

Counterpart of the reference's scattered retry knobs
(``ray.remote(max_restarts=..., max_task_retries=...)``,
``RAY_gcs_rpc_server_reconnect_timeout_s``, rllib's hardcoded
``ray.get(..., timeout=...)`` calls): here every driver-side remote
interaction — request-manager submission, weight/filter sync,
``foreach_worker`` marshalling, health probes — draws its bound from
one :class:`RetryPolicy` built from the algorithm config
(``AlgorithmConfig.fault_tolerance(retry_...)``), so a wedged actor
costs a bounded, configured amount of time instead of an indefinite
hang, and transient faults are retried on the same schedule
everywhere.
"""

from __future__ import annotations

import dataclasses
import random
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ray_tpu.core.object_store import GetTimeoutError

# Errors worth retrying by default: timeouts and transient transport
# faults. Actor-death errors are NOT retryable — retrying against a
# corpse wastes the whole backoff schedule; the recovery layer replaces
# the actor instead.
DEFAULT_RETRYABLE = (GetTimeoutError, TimeoutError, ConnectionError, OSError)


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """One retry/timeout/backoff schedule.

    ``max_attempts`` counts total tries (1 = no retry). ``timeout_s``
    is the per-attempt bound handed to ``ray.get``/``ray.wait`` style
    calls (None = caller's default). Backoff between attempt *k* and
    *k+1* is ``backoff_s * backoff_mult**k`` capped at
    ``max_backoff_s``, plus up to ``jitter`` fraction of itself
    (decorrelates a fleet of retriers hammering one recovering
    endpoint). ``seed`` makes the jitter deterministic for tests."""

    max_attempts: int = 3
    timeout_s: Optional[float] = 60.0
    backoff_s: float = 0.05
    backoff_mult: float = 2.0
    max_backoff_s: float = 2.0
    jitter: float = 0.1
    seed: Optional[int] = None

    @classmethod
    def from_config(cls, config: Dict) -> "RetryPolicy":
        """Build from the flat config keys
        ``AlgorithmConfig.fault_tolerance`` writes."""
        cfg = config or {}
        return cls(
            max_attempts=int(cfg.get("retry_max_attempts", 3)),
            timeout_s=cfg.get("retry_timeout_s", 60.0),
            backoff_s=float(cfg.get("retry_backoff_s", 0.05)),
            backoff_mult=float(cfg.get("retry_backoff_mult", 2.0)),
            max_backoff_s=float(cfg.get("retry_max_backoff_s", 2.0)),
            jitter=float(cfg.get("retry_jitter", 0.1)),
            seed=cfg.get("seed"),
        )

    def delay(self, attempt: int, rng: Optional[random.Random] = None) -> float:
        """Backoff before retry number ``attempt`` (0-based)."""
        base = min(
            self.backoff_s * (self.backoff_mult ** attempt),
            self.max_backoff_s,
        )
        if self.jitter <= 0.0:
            return base
        rng = rng or (
            random.Random(self.seed + attempt)
            if self.seed is not None
            else random
        )
        return base * (1.0 + self.jitter * rng.random())

    def schedule(self) -> List[float]:
        """The full backoff schedule (len = retries = attempts - 1)."""
        return [
            self.delay(a) for a in range(max(0, self.max_attempts - 1))
        ]

    def call(
        self,
        fn: Callable[[], Any],
        *,
        retry_on: Optional[Tuple] = None,
        on_retry: Optional[Callable[[int, BaseException], None]] = None,
        deadline_s: Optional[float] = None,
    ) -> Any:
        """Run ``fn()`` under this schedule: retryable errors sleep the
        backoff and try again; the final attempt's error propagates.
        Non-retryable errors propagate immediately. ``deadline_s``
        bounds the WHOLE schedule with one wall clock: once it is
        exhausted no further attempt launches and the last error
        propagates — the shape the retried KV transport needs so a
        control-plane thread's op cost stays ``O(op timeout)``, not
        ``O(attempts × op timeout)``."""
        retry_on = retry_on or DEFAULT_RETRYABLE
        deadline = (
            time.monotonic() + deadline_s
            if deadline_s is not None
            else None
        )
        last: Optional[BaseException] = None
        for attempt in range(max(1, self.max_attempts)):
            try:
                return fn()
            except retry_on as e:  # noqa: PERF203 — retry loop
                last = e
                if attempt >= self.max_attempts - 1:
                    raise
                pause = self.delay(attempt)
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= pause:
                        raise
                if on_retry is not None:
                    on_retry(attempt, e)
                time.sleep(pause)
        raise last  # pragma: no cover — loop always returns or raises


def ray_get_retrying(
    ref,
    policy: RetryPolicy,
    *,
    timeout_s: Optional[float] = None,
):
    """``ray.get`` bounded by the policy: each attempt waits at most
    ``timeout_s`` (default ``policy.timeout_s``); timeouts retry on the
    backoff schedule, actor errors propagate immediately."""
    import ray_tpu as ray

    t = policy.timeout_s if timeout_s is None else timeout_s
    return policy.call(
        lambda: ray.get(ref, timeout=t),
        retry_on=(GetTimeoutError,),
    )


def probe_actors(
    actors: Sequence,
    *,
    timeout_s: float = 10.0,
    ping: Callable = None,
) -> List[int]:
    """Bounded parallel health sweep → 0-based indices of unhealthy
    actors. All pings launch concurrently and share ONE wall-clock
    budget (``timeout_s``), so a single wedged actor delays the sweep
    by at most the budget — never ``N × budget`` like a sequential
    per-corpse ``ray.get`` would. An actor is unhealthy when its ping
    errors (dead) or fails to answer inside the budget (wedged)."""
    import ray_tpu as ray

    if not actors:
        return []
    ping = ping or (lambda a: a.ping.remote())
    refs = []
    bad: List[int] = []
    for i, a in enumerate(actors):
        try:
            refs.append((i, ping(a)))
        except Exception:
            # submission to a known-dead actor can raise synchronously
            bad.append(i)
    pending = [r for _, r in refs]
    ray.wait(
        pending, num_returns=len(pending), timeout=max(0.0, timeout_s)
    )
    ready_now, _ = ray.wait(pending, num_returns=len(pending), timeout=0)
    ready_ids = {r.id for r in ready_now}
    for i, r in refs:
        if r.id not in ready_ids:
            bad.append(i)  # wedged: no answer inside the budget
            continue
        try:
            ray.get(r, timeout=0.1)
        except Exception:
            bad.append(i)  # dead: ping completed with an error
    return sorted(bad)
