"""Checkpoint discovery shared by recovery and serving.

One place answers "what is the newest restorable state under this
``checkpoint_root``?" for every consumer: the driver-side
:class:`~ray_tpu.resilience.recovery.RecoveryManager` (crash restore),
a restarted driver pointed at the same root, and the serve plane's
checkpoint hot-reload watcher (``serve/policy_server.py``). Before
this module the preference logic lived inside ``RecoveryManager``;
factoring it out keeps the two consumers from drifting — the serve
watcher restores from EXACTLY the snapshot a crashed trainer would.

The preference contract (docs/resilience.md):

- a **stream tail** (continuous ``CheckpointStreamer`` snapshot under
  ``<root>/stream/``) wins whenever its iteration is **at least** the
  newest periodic checkpoint's — streaming bounds work lost to ~1
  superstep, the periodic path to ``checkpoint_frequency`` iterations;
- an unreadable tail (pruned mid-read, torn by a dying writer) falls
  back to the periodic checkpoint rather than erroring — every probe
  here is prune-safe, because the trainer deletes old snapshots and
  checkpoints while watchers are looking.
"""

from __future__ import annotations

import os
from typing import Any, List, Optional, Tuple

PERIODIC_PREFIX = "checkpoint_"


class MembershipFeed:
    """Versioned replica-membership feed for routing consumers.

    The serve controller publishes a deployment's live replica set
    onto a long-poll key every time membership changes (scale-up,
    scale-down, dead-replica replacement); the ingress coalescing
    router polls this feed between batches and adopts the new set —
    the same membership discipline ``DeploymentHandle``'s listener
    thread follows, exposed as a poll surface so the router never
    needs its own listener thread. ``current()`` is cheap (one lock'd
    dict read); ``wait_changed`` long-polls for pushes."""

    def __init__(self, host, key: str):
        self._host = host
        self._key = key

    def current(self) -> Tuple[int, List[Any]]:
        version, members = self._host.current(self._key)
        return version, list(members or [])

    def wait_changed(
        self, version: int, timeout: Optional[float] = None
    ) -> Optional[Tuple[int, List[Any]]]:
        out = self._host.listen(self._key, version, timeout=timeout)
        if out is None:
            return None
        new_version, members = out
        return new_version, list(members or [])


def latest_periodic(checkpoint_root: Optional[str]) -> Optional[str]:
    """Newest ``checkpoint_*`` entry under ``checkpoint_root`` (the
    zero-padded iteration names sort chronologically), or None. Same
    scan the RecoveryManager constructor has always run."""
    if not checkpoint_root or not os.path.isdir(checkpoint_root):
        return None
    try:
        ckpts = sorted(
            d
            for d in os.listdir(checkpoint_root)
            if d.startswith(PERIODIC_PREFIX)
        )
    except OSError:
        return None
    if not ckpts:
        return None
    return os.path.join(checkpoint_root, ckpts[-1])


def latest_stream_tail(checkpoint_root: Optional[str]) -> Optional[str]:
    """Newest continuous-stream snapshot under ``<root>/stream/``."""
    if not checkpoint_root:
        return None
    from ray_tpu.resilience.streamer import CheckpointStreamer

    return CheckpointStreamer.latest(
        CheckpointStreamer.stream_root(checkpoint_root)
    )


def periodic_iteration(path: Optional[str]) -> int:
    """Iteration baked into a periodic checkpoint's directory name
    (``checkpoint_{iteration:06d}``); -1 when unparseable."""
    if not path:
        return -1
    try:
        return int(os.path.basename(path).split("_")[-1])
    except ValueError:
        return -1


def pick_restore_target(
    periodic: Optional[str], stream_tail: Optional[str]
) -> Tuple[str, Optional[str]]:
    """``(kind, path)`` — the newest of a periodic checkpoint and a
    stream tail, kinds ``"checkpoint"`` / ``"stream"``. The stream
    tail wins when its recorded iteration is at least the periodic
    checkpoint's; an unreadable tail loses (prune-safe fallback).
    Exactly the RecoveryManager preference, regression-pinned by
    tests/test_serve_policy.py."""
    if stream_tail is None:
        return ("checkpoint", periodic)
    if periodic is None:
        return ("stream", stream_tail)
    from ray_tpu.resilience.streamer import CheckpointStreamer

    try:
        tail_iter = CheckpointStreamer.peek(stream_tail)["iteration"]
    except Exception:
        return ("checkpoint", periodic)
    if tail_iter >= periodic_iteration(periodic):
        return ("stream", stream_tail)
    return ("checkpoint", periodic)


def discover(
    checkpoint_root: Optional[str],
) -> Tuple[str, Optional[str]]:
    """Scan ``checkpoint_root`` and return the preferred
    ``(kind, path)`` restore target (path None when nothing exists
    yet) — the one-call surface the serve watcher polls."""
    return pick_restore_target(
        latest_periodic(checkpoint_root),
        latest_stream_tail(checkpoint_root),
    )


def target_version(kind: str, path: str) -> Tuple[int, int]:
    """Orderable ``(iteration, superstep)`` freshness of a restore
    target, so a watcher can decide "newer than what I loaded?" across
    kinds. Periodic checkpoints carry no superstep (0); raises when
    the target vanished or is torn (callers retry the next poll)."""
    if kind == "stream":
        from ray_tpu.resilience.streamer import CheckpointStreamer

        head = CheckpointStreamer.peek(path)
        return (int(head["iteration"]), int(head["superstep"]))
    it = periodic_iteration(path)
    if it < 0:
        raise ValueError(f"unversioned periodic checkpoint {path!r}")
    return (it, 0)
