"""Continuous checkpoint streaming: bound work-lost-on-crash to ~one
superstep without ever blocking the training loop.

Periodic checkpoints (``checkpoint_frequency``) trade durability for
wall clock: a driver crash loses up to ``checkpoint_frequency``
iterations, and each save blocks the loop on a device pull + disk
write. The :class:`CheckpointStreamer` removes both costs:

- **capture is O(1) on the driver thread.** jax arrays are immutable,
  so grabbing the live ``params`` / ``opt_state`` / ``aux_state``
  pytree REFERENCES at the end of a superstep is a consistent,
  copy-free snapshot — the learner can keep updating; it only ever
  rebinds the attributes to NEW arrays. Host-side bits (coeffs,
  counters, filters) are small dict copies.
- **the D2H pull + serialization + fsync run on a background thread**,
  riding the same deferred-drain slack the stats readback uses: by
  snapshot time the producing programs have long finished, so the
  device_get is a cheap copy-out that contends with nothing on the
  dispatch queue.
- **writes reuse the PR-2 atomic discipline** (same-directory temp +
  flush + fsync + ``os.replace``, then a directory fsync), so the
  stream tail on disk is always a complete snapshot — a crash
  mid-write leaves the previous tail intact.

The stream keeps the newest ``keep`` snapshots under
``<checkpoint_root>/stream/``; :meth:`latest` finds the tail and
:meth:`restore_into` rebuilds an Algorithm from it (policy state,
counters, filters), which is how ``RecoveryManager`` recovers a
crashed driver with at most ~1 superstep of updates lost.
"""

from __future__ import annotations

import os
import pickle
import threading
from typing import Any, Dict, Optional

from ray_tpu.telemetry import metrics as telemetry_metrics
from ray_tpu.util import tracing


class CheckpointStreamer:
    def __init__(
        self,
        algorithm,
        root: str,
        *,
        every: int = 1,
        keep: int = 2,
    ):
        self.algo = algorithm
        self.root = root
        self.every = max(1, int(every))
        self.keep = max(1, int(keep))
        os.makedirs(root, exist_ok=True)
        self._superstep = 0  # supersteps offered so far
        self._last_offered = 0
        self._last_written = 0
        self.num_snapshots = 0
        self.latest_path: Optional[str] = self.latest(root)
        # depth-1 slot: a fresh capture replaces an unwritten one —
        # the stream only ever cares about the newest state
        self._slot: Optional[Dict[str, Any]] = None
        self._slot_lock = threading.Lock()
        self._wake = threading.Event()
        self._idle = threading.Event()
        self._idle.set()
        self._stop = threading.Event()
        self.error: Optional[BaseException] = None
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="ckpt_streamer"
        )
        self._thread.start()

    # -- driver-thread side ----------------------------------------------

    # capture is ref-only on the driver thread: the D2H pull lives on
    # the writer thread (RTA005 keeps it that way)
    # ray-tpu: thread=driver hot-path
    def offer(self) -> None:
        """End-of-superstep hook (driver thread, O(refs)): count the
        superstep and, every ``every`` supersteps, capture a reference
        snapshot for the writer thread."""
        self._superstep += 1
        telemetry_metrics.set_stream_lag(
            self._superstep - self._last_written
        )
        if self._superstep - self._last_offered < self.every:
            return
        self._last_offered = self._superstep
        snap = self._capture()
        with self._slot_lock:
            self._slot = snap
            self._idle.clear()
        self._wake.set()

    # ray-tpu: thread=driver hot-path
    def _capture(self) -> Dict[str, Any]:
        """Immutable-pytree snapshot: device refs for the heavy state,
        copies for the small host state. Runs on the driver thread so
        it can't race a learn step's attribute rebinds."""
        lw = self.algo.workers.local_worker()
        policies: Dict[str, Dict[str, Any]] = {}
        for pid, pol in (getattr(lw, "policy_map", None) or {}).items():
            if hasattr(pol, "params") and hasattr(pol, "opt_state"):
                policies[pid] = {
                    "params": pol.params,  # refs: immutable trees
                    "opt_state": pol.opt_state,
                    "coeff_values": dict(pol.coeff_values),
                    "global_timestep": pol.global_timestep,
                    "num_grad_updates": pol.num_grad_updates,
                    "exploration_state": pol.exploration.get_state(),
                }
            else:
                # bespoke policy without the two-phase device state:
                # fall back to its own (host-side) state dict
                policies[pid] = {"state": pol.get_state()}
        return {
            "superstep": self._superstep,
            "iteration": self.algo.iteration,
            "counters": dict(self.algo._counters),
            "episodes_total": self.algo._episodes_total,
            "policies": policies,
            "filters": lw.get_filters() if lw is not None else {},
        }

    def flush(self, timeout: float = 30.0) -> bool:
        """Block until the writer thread has drained the pending
        snapshot (tests and clean shutdown; the hot path never calls
        this)."""
        return self._idle.wait(timeout=timeout)

    def stats(self) -> Dict[str, Any]:
        return {
            "supersteps": self._superstep,
            "snapshots_written": self.num_snapshots,
            "lag_supersteps": self._superstep - self._last_written,
            "latest": self.latest_path,
        }

    def stop(self, join_timeout: float = 30.0) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread.is_alive():
            self._thread.join(timeout=join_timeout)

    # -- writer thread ----------------------------------------------------

    # ray-tpu: thread=streamer
    def _run(self) -> None:
        try:
            while True:
                self._wake.wait()
                self._wake.clear()
                if self._stop.is_set():
                    # drain the final pending snapshot, then exit
                    self._write_pending()
                    return
                self._write_pending()
        except BaseException as e:
            self.error = e
            self._idle.set()

    # ray-tpu: thread=streamer
    def _write_pending(self) -> None:
        with self._slot_lock:
            snap, self._slot = self._slot, None
        if snap is None:
            self._idle.set()
            return
        import jax

        with tracing.start_span(
            "stream:snapshot", superstep=snap["superstep"]
        ):
            policy_states = {
                pid: (
                    {
                        "weights": jax.device_get(p["params"]),
                        "opt_state": jax.device_get(p["opt_state"]),
                        "coeff_values": p["coeff_values"],
                        "global_timestep": p["global_timestep"],
                        "num_grad_updates": p["num_grad_updates"],
                        "exploration_state": p["exploration_state"],
                    }
                    if "params" in p
                    else p["state"]
                )
                for pid, p in snap["policies"].items()
            }
            payload = {
                "superstep": snap["superstep"],
                "iteration": snap["iteration"],
                "counters": snap["counters"],
                "episodes_total": snap["episodes_total"],
                "policy_states": policy_states,
                "filters": snap["filters"],
            }
            path = os.path.join(
                self.root, f"snapshot_{snap['superstep']:010d}.pkl"
            )
            from ray_tpu.util.atomic_io import atomic_write

            # atomic + file fsync + directory fsync in one helper:
            # the stream tail on disk is always a complete snapshot
            atomic_write(path, lambda f: pickle.dump(payload, f))
        self.latest_path = path
        self._last_written = snap["superstep"]
        self.num_snapshots += 1
        telemetry_metrics.inc_stream_snapshots()
        telemetry_metrics.set_stream_lag(
            self._superstep - self._last_written
        )
        self._prune()
        with self._slot_lock:
            if self._slot is None:
                self._idle.set()

    # ray-tpu: thread=streamer
    def _prune(self) -> None:
        try:
            snaps = sorted(
                f
                for f in os.listdir(self.root)
                if f.startswith("snapshot_") and f.endswith(".pkl")
            )
        except OSError:
            return
        for f in snaps[: max(0, len(snaps) - self.keep)]:
            try:
                os.unlink(os.path.join(self.root, f))
            except OSError:
                pass

    # -- restore side -----------------------------------------------------

    @staticmethod
    def stream_root(checkpoint_root: str) -> str:
        return os.path.join(checkpoint_root, "stream")

    @staticmethod
    def latest(root: str) -> Optional[str]:
        """Newest complete snapshot in ``root`` (zero-padded superstep
        names sort chronologically), or None."""
        if not root or not os.path.isdir(root):
            return None
        snaps = sorted(
            f
            for f in os.listdir(root)
            if f.startswith("snapshot_") and f.endswith(".pkl")
        )
        return os.path.join(root, snaps[-1]) if snaps else None

    @staticmethod
    def peek(path: str) -> Dict[str, Any]:
        """Header fields of a snapshot (iteration/superstep) without
        restoring it — the recovery layer compares tails this way."""
        with open(path, "rb") as f:
            payload = pickle.load(f)
        return {
            "superstep": payload.get("superstep", 0),
            "iteration": payload.get("iteration", 0),
        }

    @staticmethod
    def restore_into(algorithm, path: str) -> int:
        """Rebuild ``algorithm`` from the stream snapshot at ``path``:
        per-policy state (weights/opt-state/coeffs), driver counters,
        filters — then broadcast the restored weights to the fleet.
        Returns the restored superstep index."""
        import collections

        with open(path, "rb") as f:
            payload = pickle.load(f)
        lw = algorithm.workers.local_worker()
        for pid, state in payload.get("policy_states", {}).items():
            if pid in lw.policy_map:
                lw.policy_map[pid].set_state(state)
        lw.sync_filters(payload.get("filters", {}))
        algorithm._counters = collections.defaultdict(
            int, payload.get("counters", {})
        )
        algorithm._episodes_total = payload.get("episodes_total", 0)
        algorithm._iteration = payload.get(
            "iteration", algorithm._iteration
        )
        algorithm.workers.sync_weights()
        return int(payload.get("superstep", 0))
