"""Deterministic fault injection for chaos testing the training loop.

Counterpart of the reference's chaos utilities
(``ray._private.test_utils.kill_actor_and_wait_for_failure``, the
``testing/chaos`` NodeKiller actor): a config/env-driven injector that
the hot path consults at four choke points —

- **rollout worker sample** (``RolloutWorker.sample``): kill this
  worker process on its K-th sample call, or delay the call;
- **driver learn** (``train_ops.train_one_step`` / the PPO prefetch
  ``deliver``): inject NaN/Inf into the K-th learn batch, or raise an
  :class:`InjectedCrash` (a restartable driver-side failure);
- **learner thread** (``LearnerThread.step``): crash the thread on its
  K-th step.

Faults are specified either as a dict under
``config["fault_injection"]`` (ships to rollout actors with the rest
of the worker config) or as the ``RAY_TPU_FAULTS`` env var, e.g.::

    RAY_TPU_FAULTS="kill_worker:2@3;kill_worker:4@1;nan_batch:@2"

Spec forms (dict keys / env tokens):

- ``kill_worker``: ``[{"worker_index": W, "on_call": K}, ...]`` or
  ``"W@K,W@K"`` — worker W ``os._exit``\\ s on its K-th sample call.
- ``preempt_worker``: ``[{"worker_index": W, "on_call": K,
  "grace_s": G}]`` or ``"W@KxG"`` — a preemption WITH NOTICE: on its
  K-th sample call worker W receives an eviction notice (visible to
  the driver through :meth:`RolloutWorker.preemption_notice`) and
  dies ``os._exit``-style G seconds later. A driver that drains the
  worker inside the grace window loses nothing (docs/resilience.md
  "elastic fleets & preemption"); one that doesn't sees an ordinary
  unnoticed kill.
- ``delay_sample``: ``[{"worker_index": W, "on_call": K,
  "delay_s": S}]`` or ``"W@KxS"`` — worker W's K-th sample sleeps S
  seconds (exercises probe/harvest timeouts without killing anyone).
- ``nan_batch``: ``{"on_learn_call": K, "value": "nan"|"inf"}`` or
  ``"@K"`` — corrupt the K-th learn batch's float columns.
- ``crash_learner``: ``{"on_learn_call": K}`` or ``"@K"`` — raise
  :class:`InjectedCrash` on the K-th driver learn call.
- ``crash_learner_thread``: ``{"on_step": K}`` — raise inside
  ``LearnerThread.step`` K.

The **fleet family** (PR 19) arms the same injector inside the KV
control plane (``fleet/kv.py`` consults :func:`kv_injector` per op;
``fleet/coordinator.py`` consults it per fenced write), so control-
plane chaos is exactly as deterministic as data-plane chaos:

- ``kv_drop``: ``[{"kv_op": "put"|"", "on_call": K}]`` or
  ``"op@K"`` / ``"@K"`` — this process's K-th KV op of that kind
  (empty = any op) fails with ``ConnectionError`` ONCE; the retried
  transport must absorb it invisibly.
- ``kv_delay``: ``[{"delay_ms": MS, "on_call": K}]`` or ``"ms@K"`` —
  the K-th KV op (any kind) stalls MS milliseconds first.
- ``partition_host``: ``[{"host": H, "on_call": K, "heal_s": S}]`` or
  ``"H@K"`` / ``"H@KxS"`` — from host H's K-th KV op, EVERY op raises
  ``ConnectionError`` for S seconds (default 2.0): a network
  partition, not a blip — long enough to outrun the retry schedule,
  so the host's self-fencing path is what gets exercised.
- ``kill_coordinator``: ``{"on_write": K}`` or ``"@K"`` — the process
  hard-exits (``os._exit``) on its K-th coordinator lease-fenced KV
  write: the leader dies mid-protocol with its lease outstanding.

Every trigger fires **once** (deterministic: counts are per-process
call numbers, not timers; the partition's heal window is the one
wall-clock element, by design), and workers recreated by the recovery
layer get an empty spec so a replacement doesn't re-run its
predecessor's death sentence.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np


class InjectedCrash(RuntimeError):
    """A deliberately injected, restartable driver-side failure."""


def _arm_exit_timer(grace_s: float) -> None:
    """Arm the hard exit of an injected preemption: this PROCESS dies
    ``grace_s`` seconds from now, drained or not. Module-level so
    notice-semantics unit tests can stub it — a real timer armed in
    the test process would kill the test runner minutes later."""
    import threading

    t = threading.Timer(grace_s, os._exit, args=(1,))
    t.daemon = True
    t.start()


def _parse_env_spec(text: str) -> Dict[str, Any]:
    """``kill_worker:2@3;nan_batch:@2;delay_sample:1@2x0.5`` → dict."""
    spec: Dict[str, Any] = {}
    for token in filter(None, (t.strip() for t in text.split(";"))):
        kind, _, arg = token.partition(":")
        kind = kind.strip()
        if kind == "kill_worker":
            lst = spec.setdefault("kill_worker", [])
            for item in filter(None, arg.split(",")):
                w, _, k = item.partition("@")
                lst.append(
                    {"worker_index": int(w), "on_call": int(k or 1)}
                )
        elif kind == "preempt_worker":
            lst = spec.setdefault("preempt_worker", [])
            for item in filter(None, arg.split(",")):
                w, _, rest = item.partition("@")
                k, _, g = rest.partition("x")
                lst.append(
                    {
                        "worker_index": int(w),
                        "on_call": int(k or 1),
                        "grace_s": float(g or 10.0),
                    }
                )
        elif kind == "delay_sample":
            lst = spec.setdefault("delay_sample", [])
            for item in filter(None, arg.split(",")):
                w, _, rest = item.partition("@")
                k, _, s = rest.partition("x")
                lst.append(
                    {
                        "worker_index": int(w),
                        "on_call": int(k or 1),
                        "delay_s": float(s or 1.0),
                    }
                )
        elif kind == "nan_batch":
            _, _, k = arg.partition("@")
            spec["nan_batch"] = {"on_learn_call": int(k or 1)}
        elif kind == "crash_learner":
            _, _, k = arg.partition("@")
            spec["crash_learner"] = {"on_learn_call": int(k or 1)}
        elif kind == "crash_learner_thread":
            _, _, k = arg.partition("@")
            spec["crash_learner_thread"] = {"on_step": int(k or 1)}
        elif kind == "kv_drop":
            lst = spec.setdefault("kv_drop", [])
            for item in filter(None, arg.split(",")):
                op, _, k = item.partition("@")
                lst.append(
                    {"kv_op": op.strip(), "on_call": int(k or 1)}
                )
        elif kind == "kv_delay":
            lst = spec.setdefault("kv_delay", [])
            for item in filter(None, arg.split(",")):
                ms, _, k = item.partition("@")
                lst.append(
                    {
                        "delay_ms": float(ms or 100.0),
                        "on_call": int(k or 1),
                    }
                )
        elif kind == "partition_host":
            lst = spec.setdefault("partition_host", [])
            for item in filter(None, arg.split(",")):
                h, _, rest = item.partition("@")
                k, _, s = rest.partition("x")
                lst.append(
                    {
                        "host": h.strip(),
                        "on_call": int(k or 1),
                        "heal_s": float(s or 2.0),
                    }
                )
        elif kind == "kill_coordinator":
            _, _, k = arg.partition("@")
            spec["kill_coordinator"] = {"on_write": int(k or 1)}
    return spec


class FaultInjector:
    """Holds one parsed fault spec plus the per-process call counters
    that make every trigger deterministic."""

    def __init__(self, spec: Dict[str, Any]):
        self.spec = dict(spec or {})
        self._learn_calls = 0
        self._thread_steps = 0
        self._fired: set = set()
        # preemption-with-notice state: monotonic deadline after which
        # this process hard-exits (None = no notice outstanding)
        self._preempt_deadline: Optional[float] = None
        # fleet-family counters: this process's KV op count (total and
        # per op kind), coordinator fenced-write count, and each
        # partitioned host's heal deadline (monotonic, keyed by host —
        # only the NAMED host loses the network, even when clients
        # share this process-wide injector)
        self._kv_calls = 0
        self._kv_op_calls: Dict[str, int] = {}
        self._coord_writes = 0
        self._partition_until: Dict[str, float] = {}
        self._kv_lock = threading.Lock()

    # -- spec normalization ----------------------------------------------

    @staticmethod
    def _as_list(v) -> List[Dict]:
        if v is None:
            return []
        if isinstance(v, dict):
            return [v]
        return list(v)

    def _match_once(self, key: str, entry: Dict) -> bool:
        """True exactly once per (key, entry identity)."""
        tag = (key, tuple(sorted(entry.items())))
        if tag in self._fired:
            return False
        self._fired.add(tag)
        return True

    # -- rollout-worker side ---------------------------------------------

    def on_sample(self, worker_index: int, call_n: int) -> None:
        """Consulted by ``RolloutWorker.sample`` with the worker's own
        1-based call count. May sleep (delay fault) or never return
        (kill fault: the actor process exits hard, exactly like an OOM
        kill or preemption — no exception, no cleanup)."""
        for entry in self._as_list(self.spec.get("delay_sample")):
            if (
                int(entry.get("worker_index", -1)) == worker_index
                and int(entry.get("on_call", 1)) == call_n
                and self._match_once("delay_sample", entry)
            ):
                time.sleep(float(entry.get("delay_s", 1.0)))
        for entry in self._as_list(self.spec.get("preempt_worker")):
            if (
                int(entry.get("worker_index", -1)) == worker_index
                and int(entry.get("on_call", 1)) == call_n
                and self._match_once("preempt_worker", entry)
            ):
                # a preemption WITH NOTICE: record the eviction
                # deadline (the driver polls it) and arm the hard
                # exit. The sample in flight completes normally — the
                # notice models the cloud provider's "you have G
                # seconds" signal, not an instant death.
                grace = float(entry.get("grace_s", 10.0))
                self._preempt_deadline = time.monotonic() + grace
                _arm_exit_timer(grace)
        for entry in self._as_list(self.spec.get("kill_worker")):
            if (
                int(entry.get("worker_index", -1)) == worker_index
                and int(entry.get("on_call", 1)) == call_n
            ):
                os._exit(1)

    def preemption_notice(self) -> Optional[float]:
        """Seconds of grace remaining before this process's injected
        preemption kills it, or None when no notice is outstanding —
        the injected stand-in for a cloud provider's eviction
        endpoint."""
        if self._preempt_deadline is None:
            return None
        return max(0.0, self._preempt_deadline - time.monotonic())

    # -- driver learn side -----------------------------------------------

    def on_learn(self, batch=None) -> None:
        """Consulted once per driver-side learn call, BEFORE the
        batch reaches the policy. Counts the call, then either raises
        :class:`InjectedCrash` or corrupts the batch in place."""
        self._learn_calls += 1
        crash = self.spec.get("crash_learner")
        if crash and int(
            crash.get("on_learn_call", 1)
        ) == self._learn_calls and self._match_once(
            "crash_learner", crash
        ):
            raise InjectedCrash(
                f"injected learner crash on learn call "
                f"{self._learn_calls}"
            )
        nan = self.spec.get("nan_batch")
        if (
            nan is not None
            and batch is not None
            and int(nan.get("on_learn_call", 1)) == self._learn_calls
            and self._match_once("nan_batch", nan)
        ):
            self._corrupt(batch, nan.get("value", "nan"))

    @staticmethod
    def _corrupt(batch, value: str = "nan") -> None:
        """Poison the first writable float column of ``batch`` (a
        SampleBatch / dict of arrays / MultiAgentBatch)."""
        bad = np.inf if value == "inf" else np.nan
        policy_batches = getattr(batch, "policy_batches", None)
        targets = (
            list(policy_batches.values())
            if policy_batches is not None
            else [batch]
        )
        for b in targets:
            keys = list(b.keys()) if hasattr(b, "keys") else []
            for k in keys:
                v = b[k]
                if (
                    isinstance(v, np.ndarray)
                    and np.issubdtype(v.dtype, np.floating)
                    and v.size
                ):
                    v = v.copy()
                    v.flat[0] = bad
                    b[k] = v
                    break

    # -- learner thread side ---------------------------------------------

    def on_learner_thread_step(self) -> None:
        """Consulted by ``LearnerThread.step``; raises on the matching
        step so the thread dies the way a real learner bug would."""
        self._thread_steps += 1
        crash = self.spec.get("crash_learner_thread")
        if crash and int(
            crash.get("on_step", 1)
        ) == self._thread_steps and self._match_once(
            "crash_learner_thread", crash
        ):
            raise InjectedCrash(
                f"injected learner-thread crash on step "
                f"{self._thread_steps}"
            )


    # -- fleet control-plane side ------------------------------------------

    def on_kv_op(self, node: Optional[str], op: str) -> None:
        """Consulted by the KV transport once per op ATTEMPT (before
        the socket opens). ``node`` is the caller's host identity (for
        ``partition_host`` matching), ``op`` the wire op name. May
        sleep (``kv_delay``) or raise ``ConnectionError`` (``kv_drop``
        once; ``partition_host`` for its whole heal window) — exactly
        the failures the retried transport claims to absorb."""
        with self._kv_lock:
            self._kv_calls += 1
            total = self._kv_calls
            per_op = self._kv_op_calls.get(op, 0) + 1
            self._kv_op_calls[op] = per_op
            # an armed partition dominates every other fault: the
            # network is gone, nothing else can fire through it
            for entry in self._as_list(self.spec.get("partition_host")):
                if (
                    node is not None
                    and str(entry.get("host", "")) == node
                    and total >= int(entry.get("on_call", 1))
                    and self._match_once("partition_host", entry)
                ):
                    self._partition_until[node] = (
                        time.monotonic()
                        + float(entry.get("heal_s", 2.0))
                    )
            until = self._partition_until.get(node or "")
            partitioned = (
                until is not None and time.monotonic() < until
            )
            delay_s = 0.0
            for entry in self._as_list(self.spec.get("kv_delay")):
                if int(
                    entry.get("on_call", 1)
                ) == total and self._match_once("kv_delay", entry):
                    delay_s = float(entry.get("delay_ms", 100.0)) / 1e3
            drop = False
            for entry in self._as_list(self.spec.get("kv_drop")):
                want = str(entry.get("kv_op", "") or "")
                if (
                    (not want or want == op)
                    and int(entry.get("on_call", 1))
                    == (per_op if want else total)
                    and self._match_once("kv_drop", entry)
                ):
                    drop = True
        if delay_s > 0.0:
            time.sleep(delay_s)
        if partitioned:
            raise ConnectionError(
                f"injected partition: host {node!r} cut from KV"
            )
        if drop:
            raise ConnectionError(f"injected kv_drop on op {op!r}")

    def on_coordinator_write(self) -> None:
        """Consulted by the FleetCoordinator once per lease-fenced KV
        write. ``kill_coordinator`` hard-exits this process on the
        matching write — the leader dies mid-protocol, lease
        outstanding, exactly like a coordinator-host preemption."""
        with self._kv_lock:
            self._coord_writes += 1
            n = self._coord_writes
        kill = self.spec.get("kill_coordinator")
        if kill and int(kill.get("on_write", 1)) == n:
            os._exit(1)


# process-wide injector for the KV transport: parsed from
# RAY_TPU_FAULTS once, shared by every KVClient in the process so the
# fleet-family call counters are global (deterministic per process,
# like the reference's per-actor chaos counters). None when the env
# carries no fleet-family fault — the transport pays one cached
# None-check per op, nothing else.
_KV_INJECTOR: Optional[FaultInjector] = None
_KV_INJECTOR_ARMED: Optional[bool] = None

_FLEET_FAULT_KINDS = (
    "kv_drop",
    "kv_delay",
    "partition_host",
    "kill_coordinator",
)


def kv_injector() -> Optional[FaultInjector]:
    """The process-wide fleet-chaos injector (env-armed only —
    control-plane faults have no per-worker config channel)."""
    global _KV_INJECTOR, _KV_INJECTOR_ARMED
    if _KV_INJECTOR_ARMED is None:
        text = os.environ.get("RAY_TPU_FAULTS", "").strip()
        spec = _parse_env_spec(text) if text else {}
        fleet_spec = {
            k: v for k, v in spec.items() if k in _FLEET_FAULT_KINDS
        }
        _KV_INJECTOR = (
            FaultInjector(fleet_spec) if fleet_spec else None
        )
        _KV_INJECTOR_ARMED = _KV_INJECTOR is not None
    return _KV_INJECTOR


def from_config(config: Optional[Dict]) -> Optional[FaultInjector]:
    """Build an injector from ``config["fault_injection"]``, falling
    back to the ``RAY_TPU_FAULTS`` env var when the config carries no
    spec at all. Returns None (zero hot-path cost) when no faults are
    configured. An explicitly EMPTY config spec (``{}``) disarms the
    env fallback too — the recovery layer hands recreated workers an
    empty spec so replacements spin up clean."""
    cfg = config or {}
    spec = cfg.get("fault_injection")
    if spec is None:
        text = os.environ.get("RAY_TPU_FAULTS", "").strip()
        if text:
            spec = _parse_env_spec(text)
    if not spec:
        return None
    if isinstance(spec, str):
        spec = _parse_env_spec(spec)
    return FaultInjector(spec)
