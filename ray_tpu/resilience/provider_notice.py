"""Provider preemption-notice probe (the PR-8 leftover).

Cloud providers surface spot/preemptible eviction through a local
endpoint (GCE's ``instance/preempted`` metadata key, AWS's
``spot/instance-action``). This module is the minimal in-repo stand-in:
one non-blocking probe **rollout workers**
(:meth:`RolloutWorker.preemption_notice`), **serving replicas**
(:meth:`PolicyDeployment.preemption_notice`), and — since PR 17 —
**learner hosts** (``fleet.coordinator.HostAgent``) consult, so the
fleet controller, a serve controller, and the learner-mesh coordinator
all drain on the same signal with no per-caller plumbing. A real
deployment replaces :func:`probe` sources with the provider endpoint;
the callers don't change.

Sources, first hit wins (all are cheap enough for per-poll use):

- ``RAY_TPU_PREEMPTION_NOTICE``: grace seconds as a float (an armed
  env var preempts every process that inherits it);
- ``RAY_TPU_PREEMPTION_NOTICE_FILE``: a path; the notice is armed the
  moment the file exists, its content the grace seconds (empty or
  unparseable = 0.0, i.e. evict NOW). Touching one file preempts one
  specific worker/replica — the testing and ops surface;
- ``RAY_TPU_PREEMPTION_NOTICE_DIR``: a directory of per-host notice
  files named ``<host>`` — the learner-fleet surface: every host of a
  multi-host mesh shares ONE env value, and ``probe(host=...)``
  consults only its own file, so an orchestrator evicts one learner
  host by touching ``$DIR/host1`` without re-enving the fleet.
"""

from __future__ import annotations

import os
from typing import Optional

NOTICE_ENV = "RAY_TPU_PREEMPTION_NOTICE"
NOTICE_FILE_ENV = "RAY_TPU_PREEMPTION_NOTICE_FILE"
NOTICE_DIR_ENV = "RAY_TPU_PREEMPTION_NOTICE_DIR"


def _parse_grace(raw: str) -> float:
    try:
        return max(0.0, float(raw.strip()))
    except (TypeError, ValueError):
        return 0.0


def _probe_file(path: str) -> Optional[float]:
    try:
        with open(path) as f:
            return _parse_grace(f.read())
    except OSError:
        return None  # file absent: notice not armed (yet)


def probe(host: Optional[str] = None) -> Optional[float]:
    """Seconds of grace left before this process's provider-announced
    preemption, or None when no notice is outstanding. Non-blocking
    and exception-free — safe on every poll path. ``host`` scopes the
    directory source to one learner host's notice file; the env and
    single-file sources are host-agnostic and fire regardless."""
    raw = os.environ.get(NOTICE_ENV)
    if raw is not None and raw.strip():
        return _parse_grace(raw)
    path = os.environ.get(NOTICE_FILE_ENV)
    if path:
        got = _probe_file(path)
        if got is not None:
            return got
    root = os.environ.get(NOTICE_DIR_ENV)
    if root and host:
        return _probe_file(os.path.join(root, host))
    return None
