"""Checkpoint-based auto-recovery for the training loop.

Counterpart of the reference's failure handling in
``rllib/algorithms/algorithm.py`` (``try_recover_from_step_attempt``,
``ignore_worker_failures`` / ``recreate_failed_workers``) plus the tune
trial-level ``max_failures`` restart budget — folded into one driver-side
:class:`RecoveryManager` that ``Algorithm.step`` consults whenever a
training step raises:

- **worker death** (``RayActorError``/``WorkerCrashedError``): probe the
  fleet with a bounded timeout, drop the corpses, spawn replacements
  (weight-synced, fault-injection disarmed), and continue in degraded
  mode while they come up;
- **restartable driver-side failure** (anything else, when
  ``restore_on_failure`` is set and a checkpoint exists): restore the
  latest periodic checkpoint and continue from it;
- **non-finite learn batch** (``nan_guard``): skip the batch instead of
  corrupting params — the guard lives at the learn choke points
  (``train_ops.train_one_step``, the PPO prefetch ``deliver``) and
  reports here.

Every action burns one unit of the ``max_failures`` budget (negative =
unlimited), emits a ``recovery:*`` span and the Prometheus counters
``ray_tpu_worker_restarts_total`` / ``ray_tpu_recoveries_total{kind=}``
/ ``ray_tpu_skipped_batches_total``, and accumulates into the
per-iteration time-lost-to-recovery reported under
``info/recovery`` (and, with tracing on, the span-derived
``recovery_s`` in ``info/telemetry``).
"""

from __future__ import annotations

import collections
import os
import time
from typing import Dict, Optional

import numpy as np

import ray_tpu as ray
from ray_tpu.telemetry import metrics as telemetry_metrics
from ray_tpu.util import tracing

ACTOR_DEAD_ERRORS = (
    ray.core.object_store.RayActorError,
    ray.core.object_store.WorkerCrashedError,
)


def batch_is_finite(batch) -> bool:
    """True when every float column of a SampleBatch / MultiAgentBatch
    / plain dict-of-arrays is free of NaN/Inf. The nan-guard predicate:
    cheap relative to a learn call, and only evaluated when
    ``config["nan_guard"]`` is on."""
    policy_batches = getattr(batch, "policy_batches", None)
    targets = (
        list(policy_batches.values())
        if policy_batches is not None
        else [batch]
    )
    for b in targets:
        keys = list(b.keys()) if hasattr(b, "keys") else []
        for k in keys:
            v = b[k]
            if (
                isinstance(v, np.ndarray)
                and np.issubdtype(v.dtype, np.floating)
                and not np.isfinite(v).all()
            ):
                return False
    return True


class RecoveryManager:
    """Owns the failure budget, the periodic-checkpoint cadence, and
    the restore path for one Algorithm. Inert (but always present)
    when the config enables none of it."""

    def __init__(self, algorithm):
        self.algo = algorithm
        cfg = algorithm.config
        # < 0 = unlimited (the seed behavior of recreate/ignore flags)
        self.max_failures = int(
            cfg.get("max_failures", -1)
            if cfg.get("max_failures") is not None
            else -1
        )
        self.checkpoint_frequency = int(
            cfg.get("checkpoint_frequency") or 0
        )
        self.restore_on_failure = bool(cfg.get("restore_on_failure"))
        self.checkpoint_root = cfg.get("checkpoint_root")
        self.failures = 0
        self.num_worker_restarts = 0
        self.num_recoveries: collections.Counter = collections.Counter()
        self.num_skipped_batches = 0
        self.num_preemptions_drained = 0
        self.num_preemptions_lost = 0
        self.time_lost_s = 0.0
        self.iter_time_lost_s = 0.0
        # a restarted driver pointed at the same checkpoint_root picks
        # up where the dead one left off — from the newest periodic
        # checkpoint AND, when checkpoint streaming ran, the stream
        # tail (restore_latest prefers whichever is newer)
        from ray_tpu.resilience import discovery

        self.latest_checkpoint: Optional[str] = (
            discovery.latest_periodic(self.checkpoint_root)
        )

    # -- iteration bookkeeping -------------------------------------------

    def begin_iteration(self) -> None:
        self.iter_time_lost_s = 0.0

    def _budget_ok(self) -> bool:
        self.failures += 1
        return self.max_failures < 0 or self.failures <= self.max_failures

    def _note(self, kind: str, t0: float) -> None:
        dt = time.time() - t0
        self.time_lost_s += dt
        self.iter_time_lost_s += dt
        self.num_recoveries[kind] += 1
        telemetry_metrics.inc_recoveries(kind)

    # -- the failure protocol --------------------------------------------

    def handle_failure(self, exc: BaseException) -> bool:
        """Called by ``Algorithm.step`` when ``training_step`` raises.
        Returns True when the loop may continue (the failure was
        absorbed), False when the exception must propagate."""
        if isinstance(exc, ACTOR_DEAD_ERRORS):
            return self._recover_workers(exc)
        if (
            isinstance(exc, Exception)
            and self.restore_on_failure
            and (self.latest_checkpoint or self._stream_tail())
        ):
            return self._restore_from_checkpoint(exc)
        return False

    def _stream_tail(self) -> Optional[str]:
        """Newest continuous-stream snapshot: the live streamer's tail
        when one is attached, else whatever a previous (crashed)
        driver left under ``<checkpoint_root>/stream``."""
        streamer = getattr(self.algo, "_ckpt_streamer", None)
        if streamer is not None and streamer.latest_path:
            return streamer.latest_path
        if not self.checkpoint_root:
            return None
        from ray_tpu.resilience.streamer import CheckpointStreamer

        return CheckpointStreamer.latest(
            CheckpointStreamer.stream_root(self.checkpoint_root)
        )

    def _pick_restore_target(self):
        """(kind, path): the stream tail when it is at least as new as
        the latest periodic checkpoint (streaming bounds work lost to
        ~1 superstep; the periodic path loses up to
        ``checkpoint_frequency`` iterations), the periodic checkpoint
        otherwise. The preference itself lives in
        ``resilience.discovery`` so the serve hot-reload watcher
        restores from the same snapshot this manager would."""
        from ray_tpu.resilience import discovery

        return discovery.pick_restore_target(
            self.latest_checkpoint, self._stream_tail()
        )

    def restore_latest(self) -> Optional[str]:
        """Restore the newest recovery state (stream tail or periodic
        checkpoint) into the algorithm; returns the path restored from
        or None when nothing exists yet. Used by the failure path and
        by a restarted driver pointed at the same checkpoint_root."""
        kind, path = self._pick_restore_target()
        if path is None:
            return None
        if kind == "stream":
            from ray_tpu.resilience.streamer import CheckpointStreamer

            CheckpointStreamer.restore_into(self.algo, path)
        else:
            self.algo.restore(path)
        return path

    def _recover_workers(self, exc: BaseException) -> bool:
        cfg = self.algo.config
        recreate = bool(cfg.get("recreate_failed_workers"))
        if not recreate and not cfg.get("ignore_worker_failures"):
            return False
        if not self._budget_ok():
            return False
        t0 = time.time()
        with tracing.start_span(
            "recovery:workers", error=type(exc).__name__
        ) as span:
            restarted = 0
            if recreate:
                restarted = self.algo.workers.recreate_failed_workers()
            span.set_attribute("restarted", restarted)
        self.num_worker_restarts += restarted
        self._note("workers", t0)
        self.algo.on_recovery("workers")
        return True

    def _restore_from_checkpoint(self, exc: BaseException) -> bool:
        if not self._budget_ok():
            return False
        t0 = time.time()
        with tracing.start_span(
            "recovery:restore", error=type(exc).__name__
        ) as span:
            restored = self.restore_latest()
            span.set_attribute("restored_from", restored)
        if restored is None:
            return False
        self._note("restore", t0)
        self.algo.on_recovery("restore")
        return True

    def note_skipped_batch(self) -> None:
        """A learn choke point skipped a non-finite batch."""
        self.num_skipped_batches += 1
        telemetry_metrics.inc_skipped_batches()
        tracing.event("recovery:skip_nan_batch")

    def note_preemption(self, drained: bool) -> None:
        """A worker preemption ran its course. A DRAINED preemption is
        not a failure: the notice was honored, nothing was lost, and —
        the elastic contract — it spends ZERO recovery budget. A lost
        one is only counted here; the worker's death then flows
        through the ordinary actor-death path (which does spend
        budget)."""
        if drained:
            self.num_preemptions_drained += 1
        else:
            self.num_preemptions_lost += 1
        tracing.event("recovery:preemption", drained=drained)

    # -- periodic checkpoints --------------------------------------------

    def maybe_checkpoint(self) -> Optional[str]:
        """End-of-iteration hook: every ``checkpoint_frequency``
        iterations, save into ``checkpoint_root`` (default
        ``<logdir>/resilience``) and remember it as the restore
        target. Pruning to ``keep_checkpoints_num`` happens inside
        ``Algorithm.save_checkpoint``."""
        if self.checkpoint_frequency <= 0:
            return None
        it = self.algo.iteration + 1  # the iteration just completed
        if it % self.checkpoint_frequency:
            return None
        root = self.checkpoint_root or os.path.join(
            self.algo.logdir, "resilience"
        )
        os.makedirs(root, exist_ok=True)
        t0 = time.time()
        with tracing.start_span("recovery:checkpoint", iteration=it):
            path = self.algo.save(
                os.path.join(root, f"checkpoint_{it:06d}")
            )
        self.iter_time_lost_s += time.time() - t0
        self.latest_checkpoint = path
        return path

    # -- reporting -------------------------------------------------------

    def stats(self) -> Dict:
        return {
            "failures": self.failures,
            "worker_restarts": self.num_worker_restarts,
            "recoveries": dict(self.num_recoveries),
            "skipped_batches": self.num_skipped_batches,
            "preemptions_drained": self.num_preemptions_drained,
            "preemptions_lost": self.num_preemptions_lost,
            "time_lost_s": round(self.time_lost_s, 4),
            "time_lost_s_this_iter": round(self.iter_time_lost_s, 4),
            "latest_checkpoint": self.latest_checkpoint,
        }
