from ray_tpu.autoscaler.autoscaler import StandardAutoscaler
from ray_tpu.autoscaler.fleet import FleetController

__all__ = ["FleetController", "StandardAutoscaler"]
