from ray_tpu.autoscaler.autoscaler import StandardAutoscaler

__all__ = ["StandardAutoscaler"]
