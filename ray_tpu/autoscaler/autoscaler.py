"""Autoscaler-lite: demand-driven worker-pool scaling.

Counterpart of the reference's ``autoscaler/_private/autoscaler.py:145``
(StandardAutoscaler) + ``monitor.py:125`` + the resource-demand
scheduler (``resource_demand_scheduler.py:46``), collapsed to the
single-host runtime: the "cloud nodes" are worker processes. Upscaling
on demand already lives in the runtime's dispatch path (a pending task
with no idle worker spawns one, up to the CPU cap — the node-provider
role); this monitor owns the OTHER direction of the reference loop:
reaping workers idle longer than ``idle_timeout_s`` down to
``min_workers``, plus utilization stats.

On a real TPU cluster the accelerator fleet is statically provisioned
(pod slices); this scales the CPU rollout fleet around it."""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional


class StandardAutoscaler:
    def __init__(
        self,
        min_workers: int = 0,
        max_workers: Optional[int] = None,
        idle_timeout_s: float = 30.0,
        update_interval_s: float = 1.0,
    ):
        from ray_tpu.core.api import _require_runtime

        self.rt = _require_runtime()
        self.min_workers = int(min_workers)
        self.max_workers = int(
            max_workers
            if max_workers is not None
            else self.rt.num_cpus
        )
        self.idle_timeout_s = float(idle_timeout_s)
        self.update_interval_s = float(update_interval_s)
        self._idle_since: Dict[str, float] = {}
        self._stop = threading.Event()
        self.num_downscales = 0
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="autoscaler"
        )
        self._thread.start()

    # -- the monitor loop (reference monitor.py:125) ----------------------

    def _run(self) -> None:
        while not self._stop.wait(self.update_interval_s):
            try:
                self.update()
            except Exception:
                pass

    def update(self) -> None:
        """One reconcile pass: reap long-idle workers (upscaling is the
        dispatch path's job — one owner per direction)."""
        rt = self.rt
        now = time.monotonic()
        with rt.lock:
            backlog = len(rt.pending)
        # ---- downscale: reap long-idle workers ----
        with rt.lock:
            for w in list(rt.pool):
                if w.dead or not w.idle:
                    self._idle_since.pop(w.worker_id, None)
                    continue
                t0 = self._idle_since.setdefault(
                    w.worker_id, now
                )
                if (
                    now - t0 >= self.idle_timeout_s
                    and len(rt.pool) > self.min_workers
                    and backlog == 0
                ):
                    rt.pool.remove(w)
                    self._idle_since.pop(w.worker_id, None)
                    self.num_downscales += 1
                    try:
                        with w.send_lock:
                            w.conn.send({"type": "shutdown"})
                    except Exception:
                        pass

    def stats(self) -> Dict:
        with self.rt.lock:
            return {
                "num_workers": len(self.rt.pool),
                "pending_tasks": len(self.rt.pending),
                "num_downscales": self.num_downscales,
            }

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)
