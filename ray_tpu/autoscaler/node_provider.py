"""Node providers + node-level autoscaler.

Counterpart of the reference's node-scaling stack —
``autoscaler/_private/autoscaler.py:145`` (StandardAutoscaler),
``resource_demand_scheduler.py:46`` (demand → node count),
``node_provider.py`` (cloud provider abstraction) and
``fake_multi_node/node_provider.py:237`` (FakeMultiNodeProvider, the
test double) — sized to this framework's cluster model: a "node" is a
worker-agent process that joins the head's fleet
(``core/cluster.py``), so the LOCAL provider launches real agent
subprocesses on this machine (the fake-multi-node testing strategy,
but with genuine agents), and a cloud provider would launch VMs that
run ``python -m ray_tpu.core.node_agent --address head:port``.

Demand enters through :meth:`NodeAutoscaler.request_resources` (the
``autoscaler.sdk.request_resources`` role): the reconcile loop sizes
the fleet to ``ceil(requested_cpus / cpus_per_node)`` clamped to
[min_nodes, max_nodes], terminates nodes idle (no placed actors)
longer than ``idle_timeout_s``, and replaces nodes that died.
"""

from __future__ import annotations

import subprocess
import sys
import threading
import time
import uuid
from typing import Dict, List, Optional


class NodeProvider:
    """reference autoscaler/node_provider.py NodeProvider ABC."""

    def create_node(self, node_config: Dict) -> str:
        raise NotImplementedError

    def terminate_node(self, node_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[str]:
        raise NotImplementedError

    def is_running(self, node_id: str) -> bool:
        return node_id in self.non_terminated_nodes()


class FakeMultiNodeProvider(NodeProvider):
    """In-memory provider for autoscaler-logic tests (reference
    fake_multi_node/node_provider.py:237). Also supports killing a
    node out from under the autoscaler (chaos testing)."""

    def __init__(self):
        self.nodes: Dict[str, Dict] = {}
        self.created = 0
        self.terminated = 0

    def create_node(self, node_config: Dict) -> str:
        node_id = f"fake_{self.created}"
        self.created += 1
        self.nodes[node_id] = dict(node_config)
        return node_id

    def terminate_node(self, node_id: str) -> None:
        if self.nodes.pop(node_id, None) is not None:
            self.terminated += 1

    def non_terminated_nodes(self) -> List[str]:
        return list(self.nodes)

    def kill_node(self, node_id: str) -> None:
        """Simulate a crash (no terminate bookkeeping)."""
        self.nodes.pop(node_id, None)


class LocalSubprocessProvider(NodeProvider):
    """Real provider for one machine: each node is a worker-agent
    SUBPROCESS that joins the head's cluster server, so scaled-up
    nodes genuinely host actors (``core/cluster.py`` NodeAgent)."""

    def __init__(self, head_address: str, num_cpus: int = 2):
        self.head_address = head_address
        self.num_cpus = num_cpus
        self.procs: Dict[str, subprocess.Popen] = {}

    def create_node(self, node_config: Dict) -> str:
        import os

        node_id = f"asnode_{uuid.uuid4().hex[:6]}"
        repo = os.path.dirname(
            os.path.dirname(os.path.dirname(__file__))
        )
        env = {
            **os.environ,
            "JAX_PLATFORMS": "cpu",
            "PYTHONPATH": (
                f"{repo}:{os.environ.get('PYTHONPATH', '')}"
            ),
        }
        proc = subprocess.Popen(
            [
                sys.executable,
                "-m",
                "ray_tpu.core.node_agent",
                "--address",
                self.head_address,
                "--node-id",
                node_id,
                "--num-cpus",
                str(node_config.get("num_cpus", self.num_cpus)),
            ],
            cwd=repo,
            env=env,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        self.procs[node_id] = proc
        return node_id

    def terminate_node(self, node_id: str) -> None:
        proc = self.procs.pop(node_id, None)
        if proc is not None:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()

    def non_terminated_nodes(self) -> List[str]:
        return [
            nid
            for nid, p in self.procs.items()
            if p.poll() is None
        ]


class SSHNodeProvider(NodeProvider):
    """Remote-machine provider: starts a ``NodeAgent`` on another
    reachable host over ssh, so the autoscaler manages MACHINES, not
    just child processes (reference
    ``autoscaler/_private/aws/node_provider.py`` shape — "create a
    node" here means "start an agent on a host from the inventory",
    since the fleet's hosts pre-exist rather than being provisioned
    from a cloud API).

    ``hosts`` is the inventory to draw from, one agent per host. The
    transport is injectable (``ssh_cmd``) so tests can swap in a
    local-exec shim where no sshd runs; production uses the default
    ``["ssh", "-o", "BatchMode=yes"]``. The remote command ``exec``s
    the agent as the ssh session child, so terminating the local ssh
    client hangs up the session and takes the remote agent with it.
    """

    def __init__(
        self,
        head_address: str,
        hosts: List[str],
        *,
        ssh_cmd: Optional[List[str]] = None,
        remote_python: str = sys.executable,
        remote_repo: Optional[str] = None,
        num_cpus: int = 2,
    ):
        import os
        import shlex

        self._shlex = shlex
        self.head_address = head_address
        self.hosts = list(hosts)
        self.ssh_cmd = (
            list(ssh_cmd)
            if ssh_cmd is not None
            else ["ssh", "-o", "BatchMode=yes"]
        )
        self.remote_python = remote_python
        self.remote_repo = remote_repo or os.path.dirname(
            os.path.dirname(os.path.dirname(__file__))
        )
        self.num_cpus = num_cpus
        self.nodes: Dict[str, Dict] = {}  # node_id -> {host, proc}

    def _free_host(self) -> Optional[str]:
        used = {
            rec["host"]
            for rec in self.nodes.values()
            if rec["proc"].poll() is None
        }
        for h in self.hosts:
            if h not in used:
                return h
        return None

    def create_node(self, node_config: Dict) -> str:
        host = self._free_host()
        if host is None:
            raise RuntimeError(
                f"ssh inventory exhausted ({len(self.hosts)} hosts)"
            )
        import os

        node_id = f"sshnode_{uuid.uuid4().hex[:6]}"
        q = self._shlex.quote
        ncpus = int(node_config.get("num_cpus", self.num_cpus))
        # the fleet's shared secret must reach the remote agent or a
        # token-secured head (the normal setup for non-loopback
        # fleets — exactly this provider's use case) rejects its
        # registration and data-plane pulls. It travels over the ssh
        # session's STDIN (``VAR=value`` lines, blank line ends the
        # block), never in the argv — command lines are world-visible
        # in ``ps`` / ``/proc/*/cmdline`` on both machines.
        secret_lines = []
        for var in ("RAY_TPU_CLUSTER_TOKEN", "RAY_TPU_KV_TOKEN"):
            val = os.environ.get(var)
            if val:
                secret_lines.append(f"{var}={val}")
        remote = (
            'while IFS= read -r _kv; do [ -n "$_kv" ] || break; '
            'export "$_kv"; done; '
            f"cd {q(self.remote_repo)} && "
            f"JAX_PLATFORMS=cpu "
            f"PYTHONPATH={q(self.remote_repo)}:$PYTHONPATH "
            f"exec {q(self.remote_python)} -m ray_tpu.core.node_agent"
            f" --address {q(self.head_address)}"
            f" --node-id {q(node_id)} --num-cpus {ncpus}"
        )
        proc = subprocess.Popen(
            self.ssh_cmd + [host, remote],
            stdin=subprocess.PIPE,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        payload = "".join(f"{ln}\n" for ln in secret_lines) + "\n"
        try:
            proc.stdin.write(payload.encode())
            proc.stdin.flush()
            proc.stdin.close()
        except (BrokenPipeError, OSError):
            pass  # agent died instantly; reconcile loop replaces it
        self.nodes[node_id] = {"host": host, "proc": proc}
        return node_id

    def terminate_node(self, node_id: str) -> None:
        rec = self.nodes.pop(node_id, None)
        if rec is None:
            return
        proc = rec["proc"]
        proc.terminate()  # hangs up the ssh session -> remote agent
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()

    def non_terminated_nodes(self) -> List[str]:
        return [
            nid
            for nid, rec in self.nodes.items()
            if rec["proc"].poll() is None
        ]


class NodeAutoscaler:
    """reference StandardAutoscaler (autoscaler.py:145), node-level."""

    def __init__(
        self,
        provider: NodeProvider,
        *,
        min_nodes: int = 0,
        max_nodes: int = 4,
        cpus_per_node: int = 2,
        idle_timeout_s: float = 30.0,
        update_interval_s: float = 1.0,
        node_config: Optional[Dict] = None,
        cluster=None,
    ):
        self.provider = provider
        self.min_nodes = int(min_nodes)
        self.max_nodes = int(max_nodes)
        self.cpus_per_node = int(cpus_per_node)
        self.idle_timeout_s = float(idle_timeout_s)
        self.update_interval_s = float(update_interval_s)
        self.node_config = dict(node_config or {})
        self.cluster = cluster  # head ClusterServer (actor counts)
        self._requested_cpus = 0
        self._idle_since: Dict[str, float] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self.num_upscales = 0
        self.num_downscales = 0
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="node_autoscaler"
        )
        self._thread.start()

    def request_resources(self, num_cpus: int) -> None:
        """Declare steady-state demand (the autoscaler.sdk
        request_resources role); the loop converges the fleet to it."""
        with self._lock:
            self._requested_cpus = int(num_cpus)

    def _node_busy(self, node_id: str) -> bool:
        if self.cluster is None:
            return False
        node = self.cluster.nodes.get(node_id)
        return bool(node and node.actor_ids)

    def _run(self) -> None:
        while not self._stop.wait(self.update_interval_s):
            try:
                self.update()
            except Exception:
                pass

    def update(self) -> None:
        """One reconcile pass (reference autoscaler.py update())."""
        with self._lock:
            requested = self._requested_cpus
        demand_nodes = -(-requested // self.cpus_per_node)
        target = max(self.min_nodes, min(self.max_nodes, demand_nodes))
        nodes = self.provider.non_terminated_nodes()

        # upscale toward target
        while len(nodes) < target:
            self.provider.create_node(
                dict(self.node_config, num_cpus=self.cpus_per_node)
            )
            self.num_upscales += 1
            nodes = self.provider.non_terminated_nodes()

        # downscale: reap idle nodes above target (never busy ones)
        now = time.monotonic()
        for nid in nodes:
            if self._node_busy(nid):
                self._idle_since.pop(nid, None)
                continue
            t0 = self._idle_since.setdefault(nid, now)
            if (
                len(self.provider.non_terminated_nodes()) > target
                and now - t0 >= self.idle_timeout_s
            ):
                self.provider.terminate_node(nid)
                self._idle_since.pop(nid, None)
                self.num_downscales += 1

        # garbage-collect idle bookkeeping for dead nodes
        live = set(self.provider.non_terminated_nodes())
        for nid in list(self._idle_since):
            if nid not in live:
                self._idle_since.pop(nid, None)

    def stats(self) -> Dict:
        return {
            "num_nodes": len(self.provider.non_terminated_nodes()),
            "requested_cpus": self._requested_cpus,
            "num_upscales": self.num_upscales,
            "num_downscales": self.num_downscales,
        }

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)
