"""FleetController: elastic, preemption-native rollout fleets.

Counterpart of the reference's ``autoscaler/_private/autoscaler.py:145``
(StandardAutoscaler) + ``monitor.py:125`` applied to the ROLLOUT-WORKER
fleet instead of cloud nodes: the resource demand signal is the PR-3
telemetry layer (sampler-side queue depths starving the learner, and
per-manager in-flight counts for idleness), the "eviction notice" is
:meth:`RolloutWorker.preemption_notice` (backed by the fault injector
here, a provider endpoint in production), and scaling actions go through
:meth:`WorkerSet.scale_up` / the drain protocol.

Two halves, split by thread for safety (docs/resilience.md "elastic
fleets & preemption"):

- the **monitor thread** (daemonized; ``stop()`` joins — owned by
  ``Algorithm.setup``/``cleanup``) only OBSERVES: it polls preemption
  notices with non-blocking probe refs, watches the queue-depth gauges
  for learner starvation, and tracks per-worker idleness across the
  registered AsyncRequestsManagers. It never mutates the fleet.
- **``reconcile()``** runs on the driver thread between training-step
  rounds and APPLIES the queued decisions: drain noticed workers,
  execute scale-ups/downs, reap long-idle workers — so the WorkerSet
  never changes under a round in progress.

The fleet state machine per worker: ``joining`` (spawned,
weight+filter sync queued ahead of any sample call) → ``active`` →
``draining`` (noticed or reaped: out of every rotation, in-flight
results harvested, final filter/metric state shipped) → gone. The
idle-reaper never touches a worker with an in-flight request or a
drain in progress, and never shrinks below ``min_workers``.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List

import ray_tpu as ray
from ray_tpu.telemetry import metrics as telemetry_metrics
from ray_tpu.util import tracing

_ACTOR_DEAD_ERRORS = (
    ray.core.object_store.RayActorError,
    ray.core.object_store.WorkerCrashedError,
)

# sampler-side queues whose sustained emptiness means the learner is
# starved for samples (docs/observability.md queue catalog)
_STARVATION_QUEUES = ("learner_in", "feeder_in", "feeder_out")


class FleetController:
    def __init__(self, algorithm, worker_set, config: Dict):
        self.algo = algorithm
        self.workers = worker_set
        n0 = int(config.get("num_workers", 0))
        self.min_workers = int(config.get("min_workers") or 1)
        self.max_workers = int(
            config.get("max_workers") or max(2 * n0, n0 + 1)
        )
        self.drain_grace_s = float(config.get("drain_grace_s", 15.0))
        self.idle_timeout_s = float(
            config.get("fleet_idle_timeout_s", 30.0)
        )
        self.update_interval_s = float(
            config.get("fleet_interval_s", 1.0)
        )
        self.starvation_patience = int(
            config.get("fleet_starvation_patience", 3)
        )
        self.scale_up_step = int(config.get("scale_up_step", 1))

        self._lock = threading.Lock()
        self._managers: List = []  # registered AsyncRequestsManagers
        self._noticed: Dict[int, object] = {}  # id(w) -> worker
        self._draining: set = set()  # id(w) with drain in progress
        self._probe_refs: Dict[int, tuple] = {}  # id(w) -> (ref, w)
        self._idle_since: Dict[int, float] = {}
        self._reap_candidates: Dict[int, object] = {}
        self._pending_scale = 0
        self._starved_polls = 0
        self._drained_metrics: List = []

        self.num_scale_ups = 0
        self.num_scale_downs = 0
        self.num_drained = 0
        self.num_preempt_lost = 0
        self.num_reaped = 0

        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="fleet_controller"
        )
        self._thread.start()
        self._set_gauges()

    # -- wiring ----------------------------------------------------------

    def register_manager(self, manager) -> None:
        """Register an AsyncRequestsManager whose rotation this fleet
        feeds: drains remove workers from it, and its in-flight counts
        are the idleness signal (satellite contract: the reaper never
        reaps a worker with an in-flight request)."""
        with self._lock:
            if manager not in self._managers:
                self._managers.append(manager)

    def request_scale(self, delta: int) -> None:
        """Queue a fleet-size change, applied at the next
        ``reconcile()`` and clamped to ``[min_workers, max_workers]``
        — the API the starvation policy (and tests/bench) drive."""
        with self._lock:
            self._pending_scale += int(delta)

    def take_drained_metrics(self) -> List:
        """Episodes shipped by drained workers (fed to the Algorithm's
        metric collection so a graceful exit loses no episodes)."""
        with self._lock:
            out, self._drained_metrics = self._drained_metrics, []
        return out

    # -- monitor thread: observe only ------------------------------------

    # ray-tpu: thread=monitor
    def _run(self) -> None:
        while not self._stop.wait(self.update_interval_s):
            try:
                self.update()
            except Exception:
                pass

    # ray-tpu: thread=monitor
    def update(self) -> None:
        """One observation pass (monitor thread, or called directly by
        tests): poll preemption notices, the starvation gauges, and
        per-worker idleness. Records decisions; never acts."""
        self._poll_notices()
        self._poll_starvation()
        self._poll_idle()

    # ray-tpu: thread=monitor
    def _poll_notices(self) -> None:
        """Non-blocking notice probes: keep one outstanding
        ``preemption_notice`` call per active worker, harvest whatever
        completed. A probe queues behind the worker's in-flight sample
        calls, so notice latency is about one sample duration — well
        inside any realistic grace window."""
        with self._lock:
            skip = set(self._noticed) | self._draining
        for w in list(self.workers.remote_workers()):
            wid = id(w)
            if wid in skip or wid in self._probe_refs:
                continue
            try:
                self._probe_refs[wid] = (
                    w.preemption_notice.remote(),
                    w,
                )
            except _ACTOR_DEAD_ERRORS:
                continue
        if not self._probe_refs:
            return
        refs = [r for r, _ in self._probe_refs.values()]
        ready, _ = ray.wait(refs, num_returns=len(refs), timeout=0)
        done = {r.id for r in ready}
        for wid, (ref, w) in list(self._probe_refs.items()):
            if ref.id not in done:
                continue
            del self._probe_refs[wid]
            try:
                grace = ray.get(ref)
            except Exception:
                continue  # dead/dying worker: the failure path owns it
            finally:
                try:
                    ray.free([ref])
                except Exception:
                    pass
            if grace is not None:
                with self._lock:
                    self._noticed[wid] = w
                tracing.event(
                    "fleet:preemption_notice", grace_s=float(grace)
                )

    # ray-tpu: thread=monitor
    def _poll_starvation(self) -> None:
        """Scale-up demand off the PR-3 queue gauges: when every
        sampler-side queue the run exports sits at depth 0 for
        ``starvation_patience`` consecutive polls, the learner is
        starved — queue one scale-up step."""
        m = telemetry_metrics.get_metric(telemetry_metrics.QUEUE_DEPTH)
        if m is None:
            return
        depths = [
            v
            for tags, v in m.series()
            if dict(tags).get("queue") in _STARVATION_QUEUES
        ]
        if not depths or any(d > 0 for d in depths):
            self._starved_polls = 0
            return
        self._starved_polls += 1
        if self._starved_polls < self.starvation_patience:
            return
        self._starved_polls = 0
        with self._lock:
            if (
                self.workers.num_remote_workers() + self._pending_scale
                < self.max_workers
            ):
                self._pending_scale += self.scale_up_step

    # ray-tpu: thread=monitor
    def _poll_idle(self) -> None:
        """Idle-reap candidates: a worker with zero in-flight requests
        across every registered manager for ``idle_timeout_s``. With
        no managers registered (fully synchronous algorithms) there is
        no idleness signal and the reaper stays off. Workers that are
        draining — or have any request in flight — are never
        candidates."""
        with self._lock:
            managers = list(self._managers)
            skip = set(self._noticed) | self._draining
        if not managers:
            return
        now = time.monotonic()
        for w in list(self.workers.remote_workers()):
            wid = id(w)
            if wid in skip:
                self._idle_since.pop(wid, None)
                continue
            busy = any(m.in_flight(w) > 0 for m in managers)
            if busy:
                self._idle_since.pop(wid, None)
                continue
            t0 = self._idle_since.setdefault(wid, now)
            if now - t0 >= self.idle_timeout_s:
                with self._lock:
                    self._reap_candidates[wid] = w

    # -- driver thread: act ----------------------------------------------

    # ray-tpu: thread=driver
    def reconcile(self) -> None:
        """Apply queued decisions (driver thread, between rounds):
        drain noticed workers, reap idle ones down to ``min_workers``,
        then settle any explicit/starvation scale request within
        ``[min_workers, max_workers]``."""
        with self._lock:
            noticed = list(self._noticed.items())
            self._noticed.clear()
            for wid, _ in noticed:
                self._draining.add(wid)
        for wid, w in noticed:
            self._set_gauges()
            self._retire(w, preempted=True)
            with self._lock:
                self._draining.discard(wid)

        with self._lock:
            reap = list(self._reap_candidates.values())
            self._reap_candidates.clear()
        for w in reap:
            if self.workers.num_remote_workers() <= self.min_workers:
                break
            if w not in self.workers.remote_workers():
                continue
            with self._lock:
                # raced busy / noticed / draining since the idle
                # observation → not a reap candidate anymore (the
                # satellite contract: never reap a worker with an
                # in-flight request or a drain in progress)
                busy = (
                    id(w) in self._draining
                    or id(w) in self._noticed
                    or any(
                        m.in_flight(w) > 0 for m in self._managers
                    )
                )
            if busy:
                continue
            self._retire(w, preempted=False)

        with self._lock:
            delta, self._pending_scale = self._pending_scale, 0
        if delta:
            cur = self.workers.num_remote_workers()
            target = min(
                self.max_workers, max(self.min_workers, cur + delta)
            )
            if target > cur:
                self._scale_up(target - cur)
            elif target < cur:
                for w in list(self.workers.remote_workers())[target:]:
                    self._retire(w, preempted=False)
        self._set_gauges()

    # ray-tpu: thread=driver
    def _scale_up(self, k: int) -> None:
        with self._lock:
            draining = len(self._draining)
        telemetry_metrics.set_fleet_size(
            active=self.workers.num_remote_workers() - draining,
            draining=draining,
            joining=k,
        )
        with tracing.start_span("fleet:scale_up", workers=k):
            new = self.workers.scale_up(k)
        self.num_scale_ups += len(new)
        if new:
            tracing.event(
                "fleet:joined",
                workers=len(new),
                fleet=self.workers.num_remote_workers(),
            )
            self.algo.on_fleet_change(added=new, removed=[])

    # ray-tpu: thread=driver
    def _retire(self, w, *, preempted: bool) -> bool:
        """The drain protocol: stop submissions, collect the worker's
        final state inside the grace budget, keep its completed
        in-flight results for the normal harvest, drop the pending
        ones explicitly, and reap the process. A noticed drain is NOT
        a failure: it spends zero recovery budget. Returns True when
        the worker drained cleanly."""
        with self._lock:
            managers = list(self._managers)
        for m in managers:
            m.remove_workers([w])
        recovery = getattr(self.algo, "_recovery", None)
        t0 = time.time()
        with tracing.start_span(
            "fleet:drain", preempted=preempted
        ) as span:
            try:
                final = ray.get(
                    w.drain_for_preemption.remote(),
                    timeout=self.drain_grace_s,
                )
            except Exception:
                # died (or wedged) before the drain completed: an
                # unnoticed preemption after all — the ordinary
                # death/recovery path owns whatever is left of it
                span.set_attribute("drained", False)
                for m in managers:
                    m.retire_worker(w)
                self.workers.remove_workers([w])
                if preempted:
                    self.num_preempt_lost += 1
                    telemetry_metrics.inc_preemptions(drained=False)
                    if recovery is not None:
                        recovery.note_preemption(drained=False)
                return False
            span.set_attribute("drained", True)
            span.set_attribute("drain_s", round(time.time() - t0, 4))
        self.workers.absorb_filters(final.get("filters") or {})
        with self._lock:
            self._drained_metrics.extend(final.get("metrics") or [])
        for m in managers:
            m.retire_worker(w)
        self.workers.remove_workers([w])
        self._idle_since.pop(id(w), None)
        self._probe_refs.pop(id(w), None)
        try:
            ray.kill(w)
        except Exception:
            pass
        if preempted:
            self.num_drained += 1
            telemetry_metrics.inc_preemptions(drained=True)
            if recovery is not None:
                recovery.note_preemption(drained=True)
        else:
            self.num_reaped += 1
            self.num_scale_downs += 1
        self.algo.on_fleet_change(added=[], removed=[w])
        return True

    # -- reporting -------------------------------------------------------

    # ray-tpu: thread=driver
    def _set_gauges(self) -> None:
        with self._lock:
            draining = len(self._draining)
        active = max(
            0, self.workers.num_remote_workers() - draining
        )
        telemetry_metrics.set_fleet_size(
            active=active, draining=draining
        )

    def stats(self) -> Dict:
        with self._lock:
            draining = len(self._draining)
            pending = self._pending_scale
        return {
            "size": self.workers.num_remote_workers(),
            "min_workers": self.min_workers,
            "max_workers": self.max_workers,
            "draining": draining,
            "pending_scale": pending,
            "scale_ups": self.num_scale_ups,
            "scale_downs": self.num_scale_downs,
            "preemptions_drained": self.num_drained,
            "preemptions_lost": self.num_preempt_lost,
            "reaped_idle": self.num_reaped,
        }

    def stop(self) -> None:
        """Monitor-thread teardown (owned by ``Algorithm.cleanup``):
        signal, then JOIN — a daemonized observer must not outlive the
        WorkerSet it watches."""
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=5.0)
