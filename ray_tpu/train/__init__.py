from ray_tpu.train.trainer import DataParallelTrainer, Trainer

__all__ = ["Trainer", "DataParallelTrainer"]
