"""`python -m ray_tpu.train` — yaml/flag-driven training CLI.

Counterpart of the reference's ``rllib/train.py:160,280`` (`rllib train`):
accepts either a tuned-example style yaml experiment file or --run/--env
flags, drives tune.run, prints per-iteration progress, and writes a final
checkpoint.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict


def load_experiments(path: str) -> Dict:
    import yaml

    with open(path) as f:
        return yaml.safe_load(f)


def main(argv=None) -> int:
    from ray_tpu.utils.platform import apply_platform_override

    apply_platform_override()
    parser = argparse.ArgumentParser(description="ray_tpu train CLI")
    parser.add_argument(
        "-f", "--file", type=str, default=None,
        help="yaml experiment file (tuned_examples format)",
    )
    parser.add_argument("--run", type=str, default=None,
                        help="algorithm name, e.g. PPO")
    parser.add_argument("--env", type=str, default=None)
    parser.add_argument(
        "--stop", type=str, default="{}",
        help='json stop criteria, e.g. \'{"training_iteration": 10}\'',
    )
    parser.add_argument(
        "--config", type=str, default="{}",
        help="json config overrides",
    )
    parser.add_argument("--num-samples", type=int, default=1)
    parser.add_argument("--checkpoint-freq", type=int, default=0)
    parser.add_argument(
        "--local-dir", type=str,
        default=os.path.expanduser("~/ray_tpu_results"),
    )
    parser.add_argument("-v", "--verbose", action="store_true")
    args = parser.parse_args(argv)

    from ray_tpu.tune import run

    experiments = {}
    if args.file:
        raw = load_experiments(args.file)
        for name, spec in raw.items():
            experiments[name] = spec
    else:
        if not args.run or not args.env:
            parser.error("either --file or both --run and --env")
        experiments["default"] = {
            "run": args.run,
            "env": args.env,
            "stop": json.loads(args.stop),
            "config": json.loads(args.config),
        }

    for name, spec in experiments.items():
        config = dict(spec.get("config") or {})
        if "env" in spec:
            config["env"] = spec["env"]
        stop = dict(spec.get("stop") or {})
        # yaml reward key parity with the reference regression format
        stop.pop("time_total_s", None)
        reward_stop = stop.pop("episode_reward_mean", None)
        if reward_stop is not None:
            stop["episode_reward_mean"] = reward_stop
        timesteps = stop.pop("timesteps_total", None)
        if timesteps is not None:
            stop["timesteps_total"] = timesteps
        print(f"== running experiment {name}: {spec.get('run')} ==")
        analysis = run(
            spec["run"],
            config=config,
            stop=stop,
            num_samples=int(spec.get("num_samples", args.num_samples)),
            checkpoint_freq=args.checkpoint_freq,
            local_dir=args.local_dir,
            verbose=1 if args.verbose else 0,
        )
        best = analysis.get_best_trial()
        if best is not None:
            print(
                json.dumps(
                    {
                        "experiment": name,
                        "best_reward": best.last_result.get(
                            "episode_reward_mean"
                        ),
                        "iterations": best.last_result.get(
                            "training_iteration"
                        ),
                        "timesteps": best.last_result.get(
                            "timesteps_total"
                        ),
                    }
                )
            )
    return 0


if __name__ == "__main__":
    sys.exit(main())
