"""ray_tpu.train: function-based data-parallel training.

Counterpart of the reference's ``python/ray/train/trainer.py:99``
(Trainer) + ``train/_internal/backend_executor.py:42``
(BackendExecutor): a user train_func runs on a group of worker actors;
``session.report`` streams per-iteration metrics back; results and the
final checkpoint return to the driver.

TPU-first disposition: the reference's torch-DDP backend
(``train/torch/config.py:28``, dist.init_process_group ``:83``) maps to
TWO native mechanisms here — within a host, data parallelism is the jax
mesh inside ONE process (no worker group needed: pjit/shard_map over
local devices, see JaxPolicy); across hosts, workers join the
jax.distributed runtime (ray_tpu.parallel.distributed) and a global
mesh spans the group. This module supplies the actor-group scaffolding
+ rendezvous env plumbing around a user-supplied jax train_func."""

from __future__ import annotations

import socket
from typing import Any, Callable, Dict, List, Optional

import ray_tpu as ray
from ray_tpu.air.checkpoint import Checkpoint


@ray.remote
class _TrainWorker:
    """One member of the training group (reference backend_executor's
    worker actors)."""

    def __init__(self, rank: int, world_size: int):
        self.rank = rank
        self.world_size = world_size
        self._results: List[Dict] = []
        self._checkpoint = None

    def run(self, train_func, config, checkpoint=None, ckpt_path=None):
        from ray_tpu.air import session as air_session

        # fresh state per run: workers are reused across Trainer.run
        # calls and must not leak prior metrics/checkpoints
        self._results = []
        self._checkpoint = None

        def report_fn(metrics, ckpt):
            self._results.append(metrics)
            if ckpt is not None:
                self._checkpoint = ckpt
                if ckpt_path and self.rank == 0:
                    # durable mid-run checkpoint: the group-restart
                    # path resumes from here if a worker dies
                    # (reference train fault tolerance)
                    from ray_tpu.util.atomic_io import atomic_write

                    atomic_write(
                        ckpt_path,
                        lambda f: f.write(ckpt.to_bytes()),
                    )

        air_session._init_session(
            self.rank, self.world_size, report_fn, checkpoint
        )
        out = train_func(config or {})
        return {
            "return_value": out,
            "results": self._results,
            "checkpoint": self._checkpoint,
        }


class TrainingResult:
    def __init__(self, metrics, metrics_per_worker, checkpoint):
        self.metrics = metrics  # rank-0 last report
        self.metrics_per_worker = metrics_per_worker
        self.checkpoint = checkpoint

    def __repr__(self):
        return f"TrainingResult(metrics={self.metrics})"


class Trainer:
    """reference train/trainer.py:99 (function-trainer mode)."""

    def __init__(
        self,
        backend: str = "jax",
        num_workers: int = 1,
        use_distributed: bool = False,
        resources_per_worker: Optional[Dict] = None,
        max_failures: int = 0,
        checkpoint_dir: Optional[str] = None,
    ):
        """``max_failures`` > 0 enables worker-group fault tolerance
        (reference train fault tolerance: on a dead worker the whole
        group restarts and the train_func resumes from the latest
        reported checkpoint — which requires ``checkpoint_dir`` so
        mid-run checkpoints survive the dead actors)."""
        self.backend = backend
        self.num_workers = int(num_workers)
        self.use_distributed = use_distributed
        self.max_failures = int(max_failures)
        self.checkpoint_dir = checkpoint_dir
        self._workers: List = []

    def _free_port(self) -> int:
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    def start(self) -> None:
        ray.init(ignore_reinit_error=True)
        self._workers = [
            _TrainWorker.options(daemon=False).remote(
                i, self.num_workers
            )
            for i in range(self.num_workers)
        ]

    def run(
        self,
        train_func: Callable[[Dict], Any],
        config: Optional[Dict] = None,
        checkpoint: Optional[Checkpoint] = None,
    ) -> TrainingResult:
        """Run train_func on every worker; gather reported metrics.

        With use_distributed=True, workers receive RAY_TPU_COORDINATOR/
        NUM_PROCESSES/PROCESS_ID env config so a train_func calling
        ray_tpu.parallel.distributed.initialize() forms one jax
        multi-controller group (the torch DDP process-group analog)."""
        return self._run_group(
            train_func,
            [dict(config or {}) for _ in range(self.num_workers)],
            checkpoint,
        )

    def _run_group(
        self,
        train_func: Callable[[Dict], Any],
        per_worker_config: List[Dict],
        checkpoint: Optional[Checkpoint],
    ) -> TrainingResult:
        """Run train_func on every worker with its own config copy
        (the dataset-sharding and coordinator plumbing both ride
        this)."""
        if not self._workers:
            self.start()
        if self.use_distributed:
            coordinator = f"127.0.0.1:{self._free_port()}"
            for cfg in per_worker_config:
                cfg["_coordinator"] = coordinator
                cfg["_num_processes"] = self.num_workers

        def wrapped(cfg, _fn=train_func):
            if "_coordinator" in cfg:
                import os

                os.environ["RAY_TPU_COORDINATOR"] = cfg["_coordinator"]
                os.environ["RAY_TPU_NUM_PROCESSES"] = str(
                    cfg["_num_processes"]
                )
                from ray_tpu.air import session as air_session

                os.environ["RAY_TPU_PROCESS_ID"] = str(
                    air_session.get_world_rank()
                )
            return _fn(cfg)

        ckpt_path = None
        if self.checkpoint_dir:
            import os

            os.makedirs(self.checkpoint_dir, exist_ok=True)
            ckpt_path = os.path.join(
                self.checkpoint_dir, "latest_checkpoint.bin"
            )

        failures_left = self.max_failures
        while True:
            refs = [
                w.run.remote(wrapped, cfg, checkpoint, ckpt_path)
                for w, cfg in zip(self._workers, per_worker_config)
            ]
            try:
                outs = ray.get(refs)
                ray.free(refs)
                break
            except Exception:
                if failures_left <= 0:
                    raise
                failures_left -= 1
                # a worker died: restart the whole group (reference
                # backend_executor group restart) and resume from the
                # latest durable checkpoint, if any
                self.shutdown()
                self.start()
                if ckpt_path:
                    import os

                    if os.path.exists(ckpt_path):
                        with open(ckpt_path, "rb") as f:
                            checkpoint = Checkpoint.from_bytes(
                                f.read()
                            )
        metrics_per_worker = [o["results"] for o in outs]
        rank0 = metrics_per_worker[0]
        checkpoint_out = None
        for o in outs:
            if o["checkpoint"] is not None:
                checkpoint_out = o["checkpoint"]
                break
        return TrainingResult(
            metrics=rank0[-1] if rank0 else {},
            metrics_per_worker=metrics_per_worker,
            checkpoint=checkpoint_out,
        )

    def shutdown(self) -> None:
        for w in self._workers:
            try:
                ray.kill(w)
            except Exception:
                pass
        self._workers = []


class DataParallelTrainer(Trainer):
    """reference train/data_parallel_trainer.py: Trainer with a dataset
    sharded across workers (each worker's config carries its shard)."""

    def run(
        self,
        train_func: Callable[[Dict], Any],
        config: Optional[Dict] = None,
        dataset=None,
        checkpoint: Optional[Checkpoint] = None,
    ) -> TrainingResult:
        if dataset is None:
            return super().run(train_func, config, checkpoint)
        shards = dataset.split(self.num_workers)
        per_worker = [
            dict(config or {}, _dataset_rows=shards[i].take_all())
            for i in range(self.num_workers)
        ]
        return self._run_group(train_func, per_worker, checkpoint)
