"""Threaded actors (reference ``max_concurrency`` in actor options,
``ray/tests/test_threaded_actors.py``): calls on one actor overlap in
a thread pool instead of queueing, and may complete out of order."""

import time

import pytest

import ray_tpu as ray


@pytest.fixture(autouse=True)
def _init():
    ray.init(num_cpus=2, ignore_reinit_error=True)


def test_calls_overlap_in_time():
    @ray.remote
    class Slow:
        def work(self, delay):
            time.sleep(delay)
            return delay

    a = Slow.options(max_concurrency=4).remote()
    ray.get(a.work.remote(0.0), timeout=60)  # warm: actor spawn is slow
    t0 = time.time()
    refs = [a.work.remote(0.5) for _ in range(4)]
    assert ray.get(refs, timeout=60) == [0.5] * 4
    elapsed = time.time() - t0
    # sequential would be >= 2.0s; concurrent ~0.5s (+overhead)
    assert elapsed < 1.6, f"calls serialized: {elapsed:.2f}s"


def test_out_of_order_completion():
    @ray.remote
    class Mixed:
        def work(self, delay, tag):
            time.sleep(delay)
            return tag

    a = Mixed.options(max_concurrency=2).remote()
    slow = a.work.remote(1.0, "slow")
    fast = a.work.remote(0.0, "fast")
    ready, _ = ray.wait([slow, fast], num_returns=1, timeout=30)
    assert ray.get(ready[0], timeout=30) == "fast"
    assert ray.get(slow, timeout=30) == "slow"


def test_default_actor_stays_ordered():
    @ray.remote
    class Seq:
        def __init__(self):
            self.log = []

        def add(self, x, delay=0.0):
            time.sleep(delay)
            self.log.append(x)
            return list(self.log)

    a = Seq.remote()
    a.add.remote(1, 0.3)
    out = ray.get(a.add.remote(2), timeout=30)
    assert out == [1, 2]  # strict call order preserved
