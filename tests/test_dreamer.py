"""Dreamer world-model tests (reference rllib/algorithms/dreamer/tests)."""

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.algorithms.dreamer import Dreamer, DreamerConfig, EpisodicBuffer
from ray_tpu.env.registry import register_env


class LinearEnv(gym.Env):
    """Tiny continuous env with linear dynamics and a dense quadratic
    reward — cheap to simulate and cheap for an RSSM to model."""

    def __init__(self, config=None):
        config = config or {}
        self.horizon = int(config.get("horizon", 40))
        self.observation_space = gym.spaces.Box(
            -np.inf, np.inf, (3,), np.float32
        )
        self.action_space = gym.spaces.Box(-1.0, 1.0, (1,), np.float32)
        self._rng = np.random.default_rng(config.get("seed", 0))

    def reset(self, *, seed=None, options=None):
        self.x = self._rng.normal(0, 0.5, 3).astype(np.float32)
        self._t = 0
        return self.x.copy(), {}

    def step(self, action):
        a = float(np.clip(np.asarray(action).reshape(-1)[0], -1, 1))
        A = np.array(
            [[0.9, 0.1, 0.0], [0.0, 0.9, 0.1], [0.0, 0.0, 0.9]],
            np.float32,
        )
        self.x = A @ self.x + np.array([0.0, 0.0, 0.5], np.float32) * a
        self._t += 1
        reward = -float(np.sum(self.x**2))
        return self.x.copy(), reward, False, self._t >= self.horizon, {}


TINY_MODEL = {
    "deter_size": 16,
    "stoch_size": 8,
    "hidden_size": 32,
    "depth_size": 4,
}


def _tiny_algo(**training_overrides):
    register_env("linear_env", lambda cfg: LinearEnv(cfg))
    training = dict(
        dreamer_model=TINY_MODEL,
        batch_size=4,
        batch_length=8,
        imagine_horizon=5,
        dreamer_train_iters=2,
        prefill_timesteps=90,
        free_nats=0.0,
        action_repeat=1,
    )
    training.update(training_overrides)
    return (
        DreamerConfig()
        .environment("linear_env", env_config={"horizon": 40})
        .rollouts(num_rollout_workers=0)
        .training(**training)
        .debugging(seed=0)
        .build()
    )


def test_episodic_buffer_chunks():
    buf = EpisodicBuffer(max_length=4, length=5, seed=0)
    # a 3-row episode (< chunk length) marked with -99: must never
    # be sampled
    buf.add(
        {
            "obs": np.full((3, 1), -99.0, np.float32),
            "actions": np.zeros((3, 1), np.float32),
            "rewards": np.zeros(3, np.float32),
        }
    )
    for ep_len in (4, 10, 12):
        buf.add(
            {
                "obs": np.arange(ep_len + 1, dtype=np.float32)[:, None],
                "actions": np.zeros((ep_len + 1, 1), np.float32),
                "rewards": np.zeros(ep_len + 1, np.float32),
            }
        )
    assert buf.timesteps == 2 + 4 + 10 + 12
    batch = buf.sample(6)
    assert batch["obs"].shape == (6, 5, 1)
    # chunks are contiguous episode slices, never from the short episode
    assert batch["obs"].min() >= 0.0
    for row in batch["obs"][..., 0]:
        np.testing.assert_allclose(np.diff(row), 1.0)
    # capacity: adding a 5th episode drops the oldest
    buf.add(
        {
            "obs": np.zeros((7, 1), np.float32),
            "actions": np.zeros((7, 1), np.float32),
            "rewards": np.zeros(7, np.float32),
        }
    )
    assert len(buf.episodes) == 4


def test_rssm_observe_and_imagine_shapes():
    algo = _tiny_algo()
    B, T, H = 3, 6, 4
    rng = np.random.default_rng(0)
    obs = jnp.asarray(rng.standard_normal((B, T, 3)), jnp.float32)
    actions = jnp.asarray(rng.standard_normal((B, T, 1)), jnp.float32)
    posts, priors = algo._observe(
        algo.wm_params, obs, actions, jax.random.PRNGKey(0)
    )
    assert posts["stoch"].shape == (T, B, 8)
    assert posts["deter"].shape == (T, B, 16)
    assert np.isfinite(np.asarray(posts["mean"])).all()
    assert np.isfinite(np.asarray(priors["std"])).all()
    assert (np.asarray(priors["std"]) > 0).all()

    start = {k: v.reshape((T * B, -1)) for k, v in posts.items()}
    feats = algo._imagine(
        algo.wm_params, algo.actor_params, start, H,
        jax.random.PRNGKey(1),
    )
    assert feats.shape == (H, T * B, 8 + 16)
    assert np.isfinite(np.asarray(feats)).all()
    algo.cleanup()


def test_world_model_loss_decreases():
    algo = _tiny_algo()
    algo._train_fn = algo._build_train_fn()
    algo._prefill()
    host = algo.buffer.sample(8)
    batch = {k: jnp.asarray(v) for k, v in host.items()}

    losses = []
    for i in range(30):
        (
            algo.wm_params, algo.actor_params, algo.critic_params,
            algo.opt_model, algo.opt_actor, algo.opt_critic, stats,
        ) = algo._train_fn(
            algo.wm_params, algo.actor_params, algo.critic_params,
            algo.opt_model, algo.opt_actor, algo.opt_critic,
            batch, jax.random.PRNGKey(i),
        )
        losses.append(float(stats["model_loss"]))
        assert np.isfinite(losses[-1]), stats
    # reconstruction+reward+KL on a fixed batch must drop substantially
    assert losses[-1] < losses[0] - 1.0, losses[:3] + losses[-3:]
    algo.cleanup()


@pytest.mark.slow  # ~9 s Algorithm e2e; moved out of tier-1 by the
# PR-1 budget rule — tier-1 keeps the buffer/RSSM units and
# test_world_model_loss_decreases (the learning-signal pin)
def test_dreamer_end_to_end_and_checkpoint():
    algo = _tiny_algo(prefill_timesteps=50)
    result = algo.train()
    info = result["info"]["learner"]["default_policy"]
    for key in (
        "model_loss", "actor_loss", "critic_loss",
        "divergence", "image_loss", "reward_loss",
    ):
        assert np.isfinite(info[key]), (key, info)
    assert result["episodes_total"] >= 1
    assert result["num_env_steps_sampled"] >= 50

    state = algo.__getstate__()
    algo2 = _tiny_algo(prefill_timesteps=50)
    algo2.__setstate__(state)
    chex_leaf = jax.tree_util.tree_leaves(algo.wm_params)[0]
    chex_leaf2 = jax.tree_util.tree_leaves(algo2.wm_params)[0]
    np.testing.assert_allclose(
        np.asarray(chex_leaf), np.asarray(chex_leaf2)
    )
    algo.cleanup()
    algo2.cleanup()


class TinyImageEnv(gym.Env):
    """64x64x1 uint8 obs (a moving bright square), continuous action."""

    def __init__(self, config=None):
        config = config or {}
        self.horizon = int(config.get("horizon", 12))
        self.observation_space = gym.spaces.Box(
            0, 255, (64, 64, 1), np.uint8
        )
        self.action_space = gym.spaces.Box(-1.0, 1.0, (1,), np.float32)
        self._rng = np.random.default_rng(config.get("seed", 0))

    def _render(self):
        img = np.zeros((64, 64, 1), np.uint8)
        x = int(np.clip(self.pos, 0, 56))
        img[28:36, x : x + 8] = 255
        return img

    def reset(self, *, seed=None, options=None):
        self.pos = float(self._rng.integers(0, 56))
        self._t = 0
        return self._render(), {}

    def step(self, action):
        self.pos = float(
            np.clip(self.pos + 8.0 * float(np.asarray(action).reshape(-1)[0]), 0, 56)
        )
        self._t += 1
        reward = -abs(self.pos - 28.0) / 28.0
        return self._render(), reward, False, self._t >= self.horizon, {}


@pytest.mark.slow  # PR-1 budget rule: 11 s; the conv encoder/decoder
# path keeps tier-1 coverage via the world-model-loss and end-to-end
# dreamer tests in this file
def test_dreamer_conv_path_trains_on_images():
    """The DMC-style 64x64 conv encoder/decoder path: shapes line up,
    pixels normalize, one full training step runs with finite losses."""
    register_env("tiny_image_env", lambda cfg: TinyImageEnv(cfg))
    algo = (
        DreamerConfig()
        .environment("tiny_image_env", env_config={"horizon": 12})
        .rollouts(num_rollout_workers=0)
        .training(
            dreamer_model={
                "deter_size": 16,
                "stoch_size": 8,
                "hidden_size": 32,
                "depth_size": 4,
            },
            batch_size=2,
            batch_length=6,
            imagine_horizon=3,
            dreamer_train_iters=1,
            prefill_timesteps=24,
            free_nats=0.0,
            action_repeat=1,
        )
        .debugging(seed=0)
        .build()
    )
    # decoder must reproduce the obs shape exactly
    import jax
    import jax.numpy as jnp

    feat = jnp.zeros((3, 8 + 16), jnp.float32)
    recon = algo.wm.apply(
        algo.wm_params, feat, method=type(algo.wm).decode
    )
    assert recon.shape == (3, 64, 64, 1), recon.shape

    result = algo.train()
    info = result["info"]["learner"]["default_policy"]
    for key in ("model_loss", "image_loss", "actor_loss", "critic_loss"):
        assert np.isfinite(info[key]), (key, info)
    algo.cleanup()
