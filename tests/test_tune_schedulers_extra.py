"""HyperBand + MedianStoppingRule (reference
``tune/tests/test_trial_scheduler.py`` HyperBand / median-stopping
cases)."""

from ray_tpu.tune import (
    HyperBandScheduler,
    MedianStoppingRule,
    grid_search,
    run,
)
from ray_tpu.tune.schedulers import CONTINUE, STOP


class _Trial:
    def __init__(self, tid):
        self.trial_id = tid
        self.status = "RUNNING"


class _Runner:
    def __init__(self, trials):
        self.trials = trials


def test_median_stopping_stops_below_median():
    rule = MedianStoppingRule(
        grace_period=2, min_samples_required=2
    )
    trials = [_Trial(f"t{i}") for i in range(3)]
    runner = _Runner(trials)
    # t0/t1 report well at iters 1-2; t2 reports badly
    for it in (1, 2):
        for tr, m in zip(trials, [10.0, 9.0, 0.1]):
            decisions = rule.on_trial_result(
                runner, tr, {"training_iteration": it,
                             "episode_reward_mean": m}
            )
    assert decisions == STOP  # t2's best < median of running avgs
    # good trial continues
    assert rule.on_trial_result(
        runner, trials[0],
        {"training_iteration": 3, "episode_reward_mean": 10.0},
    ) == CONTINUE


def test_median_stopping_min_mode():
    rule = MedianStoppingRule(
        mode="min", grace_period=1, min_samples_required=2
    )
    trials = [_Trial(f"t{i}") for i in range(3)]
    runner = _Runner(trials)
    out = {}
    for tr, loss in zip(trials, [0.1, 0.2, 5.0]):
        out[tr.trial_id] = rule.on_trial_result(
            runner, tr,
            {"training_iteration": 1, "episode_reward_mean": loss},
        )
    assert out["t2"] == STOP and out["t0"] == CONTINUE


def test_hyperband_synchronous_cut():
    sched = HyperBandScheduler(max_t=9, reduction_factor=3)
    trials = [_Trial(f"t{i}") for i in range(3)]
    runner = _Runner(trials)
    # rung at t=1 and t=3; all three must report before any cut
    a = sched.on_trial_result(
        runner, trials[0],
        {"training_iteration": 1, "episode_reward_mean": 3.0},
    )
    b = sched.on_trial_result(
        runner, trials[1],
        {"training_iteration": 1, "episode_reward_mean": 2.0},
    )
    assert a == CONTINUE and b == CONTINUE  # waiting on t2
    c = sched.on_trial_result(
        runner, trials[2],
        {"training_iteration": 1, "episode_reward_mean": 1.0},
    )
    assert c == STOP  # bottom 2/3 cut once the rung is complete
    # t1 was also cut; it learns on its next report
    assert sched.on_trial_result(
        runner, trials[1],
        {"training_iteration": 2, "episode_reward_mean": 2.0},
    ) == STOP
    # the survivor keeps going to max_t, then stops
    assert sched.on_trial_result(
        runner, trials[0],
        {"training_iteration": 5, "episode_reward_mean": 3.0},
    ) == CONTINUE
    assert sched.on_trial_result(
        runner, trials[0],
        {"training_iteration": 9, "episode_reward_mean": 3.0},
    ) == STOP


def test_hyperband_end_to_end():
    from tests.test_tune import _Quadratic as Quad

    sched = HyperBandScheduler(max_t=8, reduction_factor=2)
    analysis = run(
        Quad,
        config={"x": grid_search([0.0, 1.0, 20.0, 40.0]), "lr": 0.05},
        stop={"training_iteration": 8},
        scheduler=sched,
        verbose=0,
    )
    iters = [
        t.last_result["training_iteration"] for t in analysis.trials
    ]
    assert min(iters) < 8  # someone was cut at a rung
    assert max(iters) == 8  # the best survived to the end
