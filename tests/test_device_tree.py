"""Device-resident sum tree & sharded Ape-X tests (docs/data_plane.md
"device sum tree & sharded Ape-X"): bit-exact index-draw/priority
parity between the host numpy trees and the mesh-resident f64 tree
programs, zero-recompile across buffer growth and beta annealing,
fixed-seed learn-result parity for DQN and sharded Ape-X across tree
planes, the shared initial-priority TD route, the learn-while-rollout
interleave, and the sample-path zero-copy telemetry."""

import numpy as np
import pytest

import jax

from ray_tpu.data.sample_batch import SampleBatch
from ray_tpu.execution.replay_buffer import (
    DevicePrioritizedReplayBuffer,
    PrioritizedReplayBuffer,
    powered_priorities,
)
from ray_tpu.ops.segment_tree import (
    DeviceSumTree,
    MinSegmentTree,
    SumSegmentTree,
)


def _tree(n, base):
    return {
        "obs": base + np.arange(n * 4, dtype=np.float32).reshape(n, 4),
        "rewards": np.arange(n, dtype=np.float32) + base,
    }


@pytest.mark.parametrize("alpha", [0.6, 1.0])
def test_device_tree_matches_host_stream(alpha):
    """Property test: the SAME random priority/insert/update/draw
    stream through the host SumSegmentTree/MinSegmentTree and the
    device tree — bit-exact index draws, sampled priorities (leaf
    gathers), and final leaf state, across ring wraparound and beta
    annealing."""
    cap = 64
    hs, hm = SumSegmentTree(cap), MinSegmentTree(cap)
    dt = DeviceSumTree(cap)
    rng = np.random.default_rng(0)
    size, ptr, max_pri = 0, 0, 1.0

    for step in range(120):
        # ragged insert at max priority (wraps several times)
        n = int(rng.integers(1, 9))
        pos = (ptr + np.arange(n)) % cap
        ptr = (ptr + n) % cap
        size = min(size + n, cap)
        pv, _ = powered_priorities(np.full(n, max_pri), alpha)
        hs.set_items(pos, pv)
        hm.set_items(pos, pv)
        dt.set_powered(pos, pv)
        # random priority refresh
        m = int(rng.integers(1, 7))
        uidx = rng.integers(0, size, m)
        pri = rng.random(m) * 3
        max_pri = max(max_pri, float(np.maximum(pri, 1e-6).max()))
        pv2, _ = powered_priorities(pri, alpha)
        hs.set_items(uidx, pv2)
        hm.set_items(uidx, pv2)
        dt.set_powered(uidx, pv2)
        if size >= 16 and step % 3 == 0:
            beta = 0.4 + 0.6 * step / 120  # annealing
            B = 16
            rand = rng.random(B)
            # host oracle draw (_PrioritySampling._draw_prioritized)
            total = hs.sum(0, size)
            mass = (rand + np.arange(B)) / B * total
            hidx = np.clip(hs.find_prefixsum_idx(mass), 0, size - 1)
            p_min = hm.min(0, size) / total
            max_w = (p_min * size) ** (-beta)
            p_s = hs[hidx] / total
            hw = ((p_s * size) ** (-beta) / max_w).astype(np.float32)
            didx, dw = dt.draw(rand, size, beta)
            assert np.array_equal(hidx, np.asarray(didx)), step
            assert np.array_equal(hw, np.asarray(dw)), step
            # sampled priorities: the drawn leaves match bit-for-bit
            assert np.array_equal(
                np.asarray(hs[hidx]).view(np.uint64),
                dt.leaf_values(size)[hidx].view(np.uint64),
            )
    lv = dt.leaf_values(size)
    assert np.array_equal(
        lv.view(np.uint64),
        np.asarray(hs[np.arange(size)], np.float64).view(np.uint64),
    )


def test_device_tree_stacked_update_order_and_skip():
    """The superstep's stacked (K, B) refresh: cross-update
    overlapping indices resolve in update order (last write wins,
    like the host's sequential set_items), and masked (nan-skipped)
    slots write nothing."""
    cap = 32
    hs, hm = SumSegmentTree(cap), MinSegmentTree(cap)
    dt = DeviceSumTree(cap)
    rng = np.random.default_rng(1)
    base, _ = powered_priorities(rng.random(cap) * 2, 0.6)
    hs.set_items(np.arange(cap), base)
    hm.set_items(np.arange(cap), base)
    dt.set_powered(np.arange(cap), base)

    K, B = 4, 8
    idx = rng.integers(0, cap, (K, B))
    idx[1, 0] = idx[3, 0] = idx[0, 0]  # force cross-update overlap
    powered, _ = powered_priorities(rng.random((K, B)) * 3, 0.6)
    active = np.array([True, False, True, True])
    for i in range(K):
        if active[i]:
            hs.set_items(idx[i], powered[i])
            hm.set_items(idx[i], powered[i])
    dt.set_powered(idx, powered, active=active)
    assert np.array_equal(
        dt.leaf_values(cap).view(np.uint64),
        np.asarray(hs[np.arange(cap)], np.float64).view(np.uint64),
    )
    # the min tree followed too: root min identical
    rand = np.random.default_rng(2).random(4)
    hidx = np.clip(
        hs.find_prefixsum_idx(
            (rand + np.arange(4)) / 4 * hs.sum(0, cap)
        ),
        0,
        cap - 1,
    )
    didx, _ = dt.draw(rand, cap, 0.4)
    assert np.array_equal(hidx, np.asarray(didx))


def test_device_tree_buffer_zero_recompiles_and_zero_copy():
    """One executable per program across buffer growth, wraparound,
    and beta annealing (size/beta are traced scalars), and the sample
    path ships ZERO payload bytes H2D — only the generator's raw
    uniform stream (counted apart) crosses."""
    from ray_tpu.sharding.compile import compile_stats
    from ray_tpu.telemetry import metrics as telemetry_metrics

    def path(p):
        return telemetry_metrics.h2d_bytes_by_path().get(p, 0.0)

    buf = DevicePrioritizedReplayBuffer(
        capacity=32, alpha=0.6, seed=3, device_tree=True,
        label="ztree",
    )
    rng = np.random.default_rng(4)
    buf.add_tree(_tree(8, 0.0))
    buf.sample(8, beta=0.4)  # warmup: traces draw+gather once
    buf.update_priorities(np.arange(4), rng.random(4))
    before = compile_stats()["traces"]
    sample_b, rng_b = path("replay_sample"), path("replay_rng")
    for i in range(6):
        buf.add_tree(_tree(8, float(i + 1)))  # grows, then wraps
        batch = buf.sample(8, beta=0.4 + 0.05 * i)
        buf.update_priorities(batch.indices, rng.random(8))
    assert compile_stats()["traces"] == before, "retraced"
    assert path("replay_sample") == sample_b  # zero payload bytes
    assert path("replay_rng") - rng_b == 6 * 8 * 8  # uniforms only
    # indices never existed host-side
    assert isinstance(batch.indices, jax.Array)


def test_device_tree_spill_and_cross_plane_state():
    """A memory-cap spill hands the priorities to the host ring
    without perturbing the index stream, and checkpoint state moves
    freely between tree planes."""
    ref = DevicePrioritizedReplayBuffer(
        capacity=64, alpha=0.6, seed=11, device_tree=True
    )
    sp = DevicePrioritizedReplayBuffer(
        capacity=64, alpha=0.6, seed=11, device_tree=True,
        memory_cap_bytes=500,
    )
    t = _tree(8, 0.0)
    ref.add_tree(dict(t))
    sp.add_tree(dict(t))
    assert not ref.spilled and sp.spilled
    assert sp.tree_plane == "host" and ref.tree_plane == "device"
    out = sp.sample(4, beta=0.4)
    dev_out = ref.sample(4, beta=0.4)
    assert np.array_equal(
        np.asarray(out["batch_indexes"]),
        np.asarray(dev_out.indices).astype(np.int64),
    )
    assert np.array_equal(
        out["weights"], jax.device_get(dev_out.tree["weights"])
    )
    # host-tree checkpoint restores into a device-tree buffer
    host = DevicePrioritizedReplayBuffer(
        capacity=64, alpha=0.6, seed=11, device_tree=False
    )
    host.add_tree(dict(t))
    host.update_priorities(np.arange(4), np.linspace(0.2, 2.0, 4))
    d2 = DevicePrioritizedReplayBuffer(
        capacity=64, alpha=0.6, seed=77, device_tree=True
    )
    d2.set_state(host.get_state())
    assert np.array_equal(
        d2._priority_state()["leaf_values"].view(np.uint64),
        host._priority_state()["leaf_values"].view(np.uint64),
    )
    assert d2._max_priority == host._max_priority


def _dqn_config(device_tree, **over):
    from ray_tpu.algorithms.dqn.dqn import DQNConfig

    cfg = (
        DQNConfig()
        .environment("CartPole-v1")
        .rollouts(num_rollout_workers=0, rollout_fragment_length=16)
        .training(
            train_batch_size=32,
            num_steps_sampled_before_learning_starts=48,
            replay_buffer_config={
                "prioritized_replay": True,
                "capacity": 2000,
            },
            training_intensity=8.0,
            superstep=2,
            replay_device_resident=True,
            replay_device_tree=device_tree,
            target_network_update_freq=128,
            model={"fcnet_hiddens": [16, 16]},
        )
        .debugging(seed=0)
    )
    for k, v in over.items():
        setattr(cfg, k, v)
    return cfg


@pytest.mark.slow  # ~8 s DQN e2e; moved out of tier-1 by the PR-1
# budget rule — tier-1 keeps the host/device tree parity pins above,
# test_superstep's DQN prioritized-superstep parity, and the
# prioritized device-replay DQN run in test_dispatch_diet.py
def test_dqn_per_device_tree_bitwise_parity():
    """Acceptance: fixed-seed DQN learn results are bitwise identical
    device-tree vs host-tree on the 1-shard mesh — params, sum-tree
    leaves, max-priority watermark, and generator state — through the
    fused K=2 superstep INCLUDING the stacked in-scan PER refresh."""

    def run(device_tree):
        algo = _dqn_config(device_tree).build()
        try:
            for _ in range(4):
                algo.train()
            buf = algo.local_replay_buffer.buffers["default_policy"]
            assert (buf._dtree is not None) is device_tree
            return (
                jax.device_get(algo.get_policy().params),
                algo._counters["num_env_steps_trained"],
                buf._priority_state(),
                buf._rng.bit_generator.state,
            )
        finally:
            algo.cleanup()

    ph, th, sh, gh = run(False)
    pd, td, sd, gd = run(True)
    assert th == td and th > 0
    for a, b in zip(
        jax.tree_util.tree_leaves(ph), jax.tree_util.tree_leaves(pd)
    ):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    assert np.array_equal(
        sh["leaf_values"].view(np.uint64),
        sd["leaf_values"].view(np.uint64),
    )
    assert sh["max_priority"] == sd["max_priority"]
    assert gh == gd


def _apex_config(device_tree, **over):
    from ray_tpu.algorithms.apex_dqn import ApexDQNConfig

    cfg = (
        ApexDQNConfig()
        .environment("CartPole-v1")
        .rollouts(num_rollout_workers=0, rollout_fragment_length=16)
        .training(
            train_batch_size=32,
            num_steps_sampled_before_learning_starts=64,
            num_replay_buffer_shards=2,
            superstep=2,
            replay_device_resident=True,
            replay_device_tree=device_tree,
            target_network_update_freq=256,
            model={"fcnet_hiddens": [16, 16]},
        )
        .debugging(seed=0)
    )
    for k, v in over.items():
        setattr(cfg, k, v)
    return cfg


@pytest.mark.slow  # ~17 s on this container; moved out of
# tier-1 with PR 12 (budget rule: suite at ~892 s vs the 870 s cap)
@pytest.mark.slow  # ~17 s on this container; moved out of
# tier-1 with PR 12 (budget rule: suite at ~892 s vs the 870 s cap)
def test_apex_device_shards_bitwise_parity():
    """Ape-X e2e on sharded device replay: fixed-seed param parity —
    device sum trees vs host sum trees behind the SAME mesh-placed
    shard rings (round-robin routing, per-shard seeds, superstep
    learn loop all shared) — plus shard occupancy and per-shard
    priority-state parity."""

    def run(device_tree):
        algo = _apex_config(device_tree).build()
        try:
            assert algo._apex_device and len(algo.replay_shards) == 2
            assert (
                algo.replay_shards[0]._dtree is not None
            ) is device_tree
            for _ in range(4):
                algo.train()
            return (
                jax.device_get(algo.get_policy().params),
                [len(s) for s in algo.replay_shards],
                algo._counters["num_env_steps_trained"],
                [s._priority_state() for s in algo.replay_shards],
            )
        finally:
            algo.cleanup()

    ph, szh, th, sth = run(False)
    pd, szd, td, std = run(True)
    assert szh == szd and all(s > 0 for s in szh)
    assert th == td and th > 0
    for a, b in zip(
        jax.tree_util.tree_leaves(ph), jax.tree_util.tree_leaves(pd)
    ):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(sth, std):
        assert np.array_equal(
            a["leaf_values"].view(np.uint64),
            b["leaf_values"].view(np.uint64),
        )
        assert a["max_priority"] == b["max_priority"]


def test_apex_initial_priorities_shared_td_route():
    """Regression pin: the mesh plane's initial-priority computation
    (the shared ``_td_error_device_fn`` run on the ONE uploaded
    insert tree) produces priorities bitwise identical to the legacy
    host route ``compute_td_error(batch) + 1e-6``."""
    from ray_tpu.algorithms.dqn.dqn import adjust_nstep

    # 1-shard mesh: the device route's TD forward is row-sharded, and
    # multi-shard per-shard matmul shapes round the last ulp (the
    # documented mesh property) — the bit-pin belongs on one shard
    algo = (
        _apex_config(True, worker_side_prioritization=True)
        .resources(learner_devices=1)
        .build()
    )
    try:
        policy = algo.get_policy()
        w = algo.workers.local_worker()
        batch = w.sample()
        if hasattr(batch, "policy_batches"):
            batch = batch.policy_batches["default_policy"]
        # the legacy route: n-step fold, then the host-batch TD
        # forward (fold a copy — _route_to_replay folds the original)
        ref = SampleBatch(
            {k: np.copy(np.asarray(v)) for k, v in batch.items()}
        )
        adjust_nstep(
            algo.config["n_step"], algo.config["gamma"], ref
        )
        host_prios = policy.compute_td_error(ref) + 1e-6

        captured = {}
        shard = algo.replay_shards[0]
        orig = shard.add_device_tree

        def spy(tree, priorities=None):
            captured["prios"] = priorities
            return orig(tree, priorities=priorities)

        shard.add_device_tree = spy
        algo._shard_rr = 0  # route to the spied shard
        algo._route_to_replay(batch)
        assert captured["prios"] is not None
        assert np.array_equal(
            np.asarray(host_prios), np.asarray(captured["prios"])
        )
    finally:
        algo.cleanup()


@pytest.mark.slow  # ~14 s on this container; moved out of
# tier-1 with PR 12 (budget rule: suite at ~892 s vs the 870 s cap)
@pytest.mark.slow  # ~14 s on this container; moved out of
# tier-1 with PR 12 (budget rule: suite at ~892 s vs the 870 s cap)
def test_learn_while_rollout_interleave():
    """The off-policy jax-lane interleave: deterministic fixed-seed
    results, identical sampled/trained step accounting vs the serial
    cadence, and the telemetry roll-up reports the device tree with a
    zero-payload sample path."""
    from ray_tpu.algorithms.dqn.dqn import DQNConfig
    from ray_tpu.util import tracing

    def build(interleave):
        return (
            DQNConfig()
            .environment("CartPoleJax-v0", env_backend="jax")
            .resources(learner_devices=1)
            .rollouts(
                num_rollout_workers=0,
                rollout_fragment_length=8,
                num_envs_per_worker=4,
            )
            .training(
                train_batch_size=32,
                num_steps_sampled_before_learning_starts=64,
                replay_buffer_config={
                    "prioritized_replay": True,
                    "capacity": 2000,
                },
                replay_device_resident=True,
                replay_device_tree=True,
                learn_while_rollout=interleave,
                training_intensity=4.0,
                superstep=2,
                target_network_update_freq=256,
                model={"fcnet_hiddens": [16, 16]},
            )
            .debugging(seed=0)
            .build()
        )

    def run(interleave, trace=False):
        algo = build(interleave)
        if trace:
            algo.config["telemetry_config"] = {"trace": True}
            tracing.enable()
        try:
            r = {}
            for _ in range(4):
                r = algo.train()
            return (
                jax.device_get(algo.get_policy().params),
                algo._counters["num_env_steps_sampled"],
                algo._counters["num_env_steps_trained"],
                r,
            )
        finally:
            algo.cleanup()
            if trace:
                tracing.disable()

    p0, s0, t0, _ = run(False)
    p1, s1, t1, r1 = run(True, trace=True)
    assert s0 == s1 and t0 == t1 and t1 > 0
    replay = r1["info"]["telemetry"]["replay"]
    assert replay["tree"] == "device"
    assert replay["sample_h2d_bytes"] == 0.0
    assert replay["rng_h2d_bytes"] > 0
    assert replay["d2h_bytes"] > 0  # the PER refresh |td| pull
    # the interleaved cadence is itself deterministic
    p2, s2, t2, _ = run(True)
    assert (s1, t1) == (s2, t2)
    for a, b in zip(
        jax.tree_util.tree_leaves(p1), jax.tree_util.tree_leaves(p2)
    ):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_replay_tree_ops_counters():
    """ray_tpu_replay_tree_ops_total{op=insert|update|sample,
    tree=host|device} counts each plane's tree walks."""
    from ray_tpu.telemetry import metrics as telemetry_metrics

    def series():
        m = telemetry_metrics.get_metric(
            telemetry_metrics.REPLAY_TREE_OPS_TOTAL
        )
        out = {}
        for tags, v in (m.series() if m else ()):
            d = dict(tags)
            out[(d["op"], d["tree"])] = v
        return out

    before = series()

    def delta(op, tree):
        return series().get((op, tree), 0.0) - before.get(
            (op, tree), 0.0
        )

    rng = np.random.default_rng(0)
    host = PrioritizedReplayBuffer(capacity=32, alpha=0.6, seed=1)
    host.add(SampleBatch(_tree(8, 0.0)))
    host.sample(4, beta=0.4)
    host.update_priorities(np.arange(4), rng.random(4))
    assert delta("insert", "host") == 1
    assert delta("sample", "host") == 1
    assert delta("update", "host") == 1

    dev = DevicePrioritizedReplayBuffer(
        capacity=32, alpha=0.6, seed=1, device_tree=True
    )
    dev.add_tree(_tree(8, 0.0))
    b = dev.sample(4, beta=0.4)
    dev.update_priorities(b.indices, rng.random(4))
    assert delta("insert", "device") == 1
    assert delta("sample", "device") == 1
    assert delta("update", "device") == 1
