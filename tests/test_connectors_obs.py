"""Connector pipelines, view-requirement columns, metrics export,
dashboard-lite, IMPALA tree aggregation (reference
rllib/connectors/tests, rllib/policy/tests/test_view_requirement*,
python/ray/tests/test_metrics_agent.py, dashboard tests)."""

import json
import time
import urllib.request

import gymnasium as gym
import pytest
import numpy as np

import ray_tpu as ray
from ray_tpu.connectors import (
    ClipActionsConnector,
    ClipRewardConnector,
    ConnectorContext,
    ConnectorPipeline,
    FlattenObsConnector,
    MeanStdFilterConnector,
)
from ray_tpu.connectors.connector import restore_connector


def test_connector_pipeline_and_serialization():
    ctx = ConnectorContext(
        observation_space=gym.spaces.Box(-1, 1, (4,), np.float32),
        action_space=gym.spaces.Box(-2, 2, (2,), np.float32),
    )
    pipe = ConnectorPipeline(
        ctx,
        [
            FlattenObsConnector(ctx),
            MeanStdFilterConnector(ctx, shape=(4,)),
        ],
    )
    obs = np.random.default_rng(0).standard_normal((8, 2, 2)).astype(
        np.float32
    )
    out = pipe(obs)
    assert out.shape == (8, 4)
    # serialization round trip preserves structure
    cfg = pipe.to_config()
    rebuilt = restore_connector(ctx, cfg)
    assert type(rebuilt).__name__ == "ConnectorPipeline"
    assert [type(c).__name__ for c in rebuilt.connectors] == [
        "FlattenObsConnector",
        "MeanStdFilterConnector",
    ]
    # eval mode freezes filter stats
    pipe.in_training(False)
    n_before = pipe.connectors[1].filter.rs.n
    pipe(obs)
    assert pipe.connectors[1].filter.rs.n == n_before


def test_clip_connectors():
    ctx = ConnectorContext(
        action_space=gym.spaces.Box(-1.0, 1.0, (2,), np.float32)
    )
    clip = ClipActionsConnector(ctx)
    out = clip(np.array([[5.0, -3.0], [0.5, 0.2]], np.float32))
    assert out.max() <= 1.0 and out.min() >= -1.0
    cr = ClipRewardConnector(ctx, sign=True)
    np.testing.assert_array_equal(
        cr(np.array([3.0, -0.2, 0.0])), [1.0, -1.0, 0.0]
    )


def test_view_requirements_prev_columns():
    from ray_tpu.algorithms.ppo import PPOConfig

    algo = (
        PPOConfig()
        .environment("CartPole-v1")
        .rollouts(num_rollout_workers=0, rollout_fragment_length=16)
        .training(
            train_batch_size=32,
            sgd_minibatch_size=16,
            model={"use_prev_action": True, "use_prev_reward": True},
        )
        .debugging(seed=0)
        .build()
    )
    lw = algo.workers.local_worker()
    batch = lw.sample()
    from ray_tpu.data.sample_batch import SampleBatch

    assert SampleBatch.PREV_ACTIONS in batch
    assert SampleBatch.PREV_REWARDS in batch
    # shifted by one: prev_action[t] == action[t-1] within an episode
    eps = np.asarray(batch[SampleBatch.EPS_ID])
    acts = np.asarray(batch[SampleBatch.ACTIONS])
    prev = np.asarray(batch[SampleBatch.PREV_ACTIONS])
    same_ep = eps[1:] == eps[:-1]
    np.testing.assert_array_equal(
        prev[1:][same_ep], acts[:-1][same_ep]
    )
    algo.cleanup()


def test_prev_action_reaches_recurrent_model():
    """lstm_use_prev_action must actually change the forward pass, not
    just populate a batch column."""
    from ray_tpu.algorithms.ppo.ppo import PPOJaxPolicy

    pol = PPOJaxPolicy(
        gym.spaces.Box(-1, 1, (4,), np.float32),
        gym.spaces.Discrete(2),
        {
            "model": {
                "use_lstm": True,
                "lstm_cell_size": 16,
                "lstm_use_prev_action": True,
                "lstm_use_prev_reward": True,
            },
            "train_batch_size": 8,
            "seed": 0,
        },
    )
    obs = np.zeros((4, 4), np.float32)
    state = [np.zeros((4, 16), np.float32) for _ in range(2)]
    _, _, extra0 = pol.compute_actions(
        obs, state, explore=False,
        prev_action_batch=np.zeros(4, np.int64),
        prev_reward_batch=np.zeros(4, np.float32),
    )
    _, _, extra1 = pol.compute_actions(
        obs, state, explore=False,
        prev_action_batch=np.ones(4, np.int64),
        prev_reward_batch=np.full(4, 5.0, np.float32),
    )
    from ray_tpu.data.sample_batch import SampleBatch

    assert not np.allclose(
        extra0[SampleBatch.ACTION_DIST_INPUTS],
        extra1[SampleBatch.ACTION_DIST_INPUTS],
    ), "prev action/reward inputs did not reach the model"


def test_metrics_prometheus_export():
    from ray_tpu.utils import metrics as m
    from ray_tpu.utils.metrics_exporter import (
        MetricsServer,
        format_prometheus,
    )

    m.clear_registry()
    c = m.Counter("test_requests", "reqs", ("path",))
    c.inc(2, {"path": "/a"})
    c.inc(1, {"path": "/b"})
    g = m.Gauge("test_queue_len", "queue")
    g.set(7)
    h = m.Histogram("test_latency", "lat", boundaries=[0.1, 1.0])
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    text = format_prometheus()
    assert 'test_requests{path="/a"} 2.0' in text
    assert "test_queue_len 7.0" in text
    assert "test_latency_count 3" in text
    assert "test_latency_sum" in text

    server = MetricsServer()
    blob = urllib.request.urlopen(
        f"http://127.0.0.1:{server.port}/metrics", timeout=10
    ).read()
    assert b"test_queue_len" in blob
    server.shutdown()
    m.clear_registry()


def test_dashboard_lite_endpoints():
    from ray_tpu.dashboard import DashboardLite, publish_result

    ray.init(num_cpus=1, ignore_reinit_error=True)

    @ray.remote
    def f():
        return 1

    ray.get(f.remote())
    publish_result(
        {"training_iteration": 3, "episode_reward_mean": 42.0}
    )
    dash = DashboardLite()
    cluster = json.loads(
        urllib.request.urlopen(
            f"{dash.url}/api/cluster", timeout=10
        ).read()
    )
    assert cluster["initialized"]
    assert len(cluster["workers"]) >= 1
    results = json.loads(
        urllib.request.urlopen(
            f"{dash.url}/api/results", timeout=10
        ).read()
    )
    assert any(r.get("training_iteration") == 3 for r in results)
    index = urllib.request.urlopen(dash.url, timeout=10).read()
    assert b"dashboard-lite" in index
    dash.shutdown()


@pytest.mark.slow  # >30 s on the tier-1 host: trains through aggregation actors
def test_impala_tree_aggregation():
    from ray_tpu.algorithms.impala import IMPALAConfig

    algo = (
        IMPALAConfig()
        .environment("CartPole-v1")
        .rollouts(num_rollout_workers=2, rollout_fragment_length=16)
        .training(train_batch_size=64, lr=5e-4)
        .aggregation(num_aggregation_workers=2)
        .debugging(seed=0)
        .build()
    )
    assert len(algo._aggregators) == 2
    trained = 0
    deadline = time.time() + 180
    while time.time() < deadline:
        result = algo.train()
        trained = algo._counters.get("num_env_steps_trained", 0)
        if trained >= 128:
            break
    assert trained >= 128, "learner consumed no aggregated batches"
    info = result["info"]["learner"].get("default_policy", {})
    assert np.isfinite(info.get("total_loss", np.nan))
    algo.cleanup()
