"""Ape-X distributed prioritized replay tests (reference
rllib/algorithms/apex_dqn/tests)."""

import time

import numpy as np
import pytest

import ray_tpu as ray
from ray_tpu.algorithms.apex_dqn import ApexDQNConfig, ReplayActor
from ray_tpu.data.sample_batch import SampleBatch


def _batch(n=8, seed=0):
    rng = np.random.default_rng(seed)
    return SampleBatch(
        {
            SampleBatch.OBS: rng.standard_normal((n, 4)).astype(
                np.float32
            ),
            SampleBatch.NEXT_OBS: rng.standard_normal((n, 4)).astype(
                np.float32
            ),
            SampleBatch.ACTIONS: rng.integers(0, 2, n),
            SampleBatch.REWARDS: rng.random(n).astype(np.float32),
            SampleBatch.TERMINATEDS: np.zeros(n, bool),
        }
    )


def test_replay_actor_roundtrip():
    ray.init(ignore_reinit_error=True)
    actor = ReplayActor.remote(256, 0.6, 0.4, 0)
    n = ray.get(actor.add.remote(_batch(16), np.full(16, 2.0)))
    assert n == 16
    assert ray.get(actor.sample.remote(64)) is None  # not enough yet
    for i in range(5):
        ray.get(actor.add.remote(_batch(16, i + 1), None))
    sample = ray.get(actor.sample.remote(64))
    assert sample.count == 64
    assert "weights" in sample and "batch_indexes" in sample
    ray.get(
        actor.update_priorities.remote(
            sample["batch_indexes"], np.full(64, 0.5)
        )
    )
    ray.kill(actor)


def test_per_worker_epsilon_ladder():
    from ray_tpu.algorithms.dqn.dqn import _epsilon_exploration_config

    n = 8
    eps = []
    for i in range(1, n + 1):
        ec = _epsilon_exploration_config(
            {
                "per_worker_exploration": True,
                "worker_index": i,
                "num_workers": n,
                "initial_epsilon": 1.0,
                "final_epsilon": 0.02,
                "epsilon_timesteps": 10000,
            }
        )
        assert ec["initial_epsilon"] == ec["final_epsilon"]
        eps.append(ec["initial_epsilon"])
    # ladder: eps_1 = 0.4, eps_n = 0.4^8, strictly decreasing
    assert eps[0] == pytest.approx(0.4)
    assert eps[-1] == pytest.approx(0.4**8)
    assert all(a > b for a, b in zip(eps, eps[1:]))
    # driver/local worker (index 0) keeps the annealed schedule
    ec0 = _epsilon_exploration_config(
        {
            "per_worker_exploration": True,
            "worker_index": 0,
            "num_workers": n,
            "initial_epsilon": 1.0,
            "final_epsilon": 0.02,
            "epsilon_timesteps": 10000,
        }
    )
    assert ec0["initial_epsilon"] == 1.0


@pytest.mark.slow  # ~10 s on this container; moved out of
# tier-1 with PR 12 (budget rule: suite at ~892 s vs the 870 s cap)
def test_apex_trains_and_updates_priorities():
    algo = (
        ApexDQNConfig()
        .environment("CartPole-v1")
        .rollouts(num_rollout_workers=2, rollout_fragment_length=16)
        .training(
            train_batch_size=32,
            num_steps_sampled_before_learning_starts=64,
            num_replay_buffer_shards=2,
            target_network_update_freq=64,
            lr=1e-3,
        )
        .debugging(seed=0)
        .build()
    )
    trained = 0
    deadline = time.time() + 180
    result = {}
    while time.time() < deadline:
        result = algo.train()
        trained = algo._counters.get("num_env_steps_trained", 0)
        if trained >= 256 and algo._counters["num_target_updates"] >= 1:
            break
    assert trained >= 256, "learner never consumed replay samples"
    assert algo._counters["num_target_updates"] >= 1
    info = result["info"]["learner"].get("default_policy", {})
    assert np.isfinite(info.get("mean_td_error", np.nan))
    # both shards received data
    sizes = ray.get([a.size.remote() for a in algo.replay_actors])
    assert all(s > 0 for s in sizes), sizes
    algo.cleanup()


def test_h_function_inverse_roundtrip():
    import jax.numpy as jnp

    from ray_tpu.algorithms.r2d2.r2d2 import h_function, h_inverse

    x = jnp.linspace(-50.0, 50.0, 101)
    back = h_inverse(h_function(x, 1e-3), 1e-3)
    # fp32 + the (2eps+1)^2 ~ 1.004 term limit roundtrip precision to
    # ~1e-3 relative (catastrophic cancellation near sqrt(1+tiny))
    np.testing.assert_allclose(
        np.asarray(back), np.asarray(x), atol=0.1, rtol=2e-3
    )


def test_r2d2_sequence_replay_and_training():
    from ray_tpu.algorithms.r2d2 import R2D2Config

    algo = (
        R2D2Config()
        .environment("CartPole-v1")
        .rollouts(num_rollout_workers=0, rollout_fragment_length=20)
        .training(
            train_batch_size=8,
            replay_sequence_length=10,
            replay_burn_in=2,
            num_steps_sampled_before_learning_starts=100,
            target_network_update_freq=200,
            model={"use_lstm": True, "lstm_cell_size": 32},
        )
        .debugging(seed=0)
        .build()
    )
    pol = algo.get_policy()
    assert pol.model.is_recurrent
    result = {}
    deadline = time.time() + 180
    while time.time() < deadline:
        result = algo.train()
        if algo._counters.get("num_env_steps_trained", 0) >= 160:
            break
    assert algo._counters["num_env_steps_trained"] >= 160
    info = result["info"]["learner"]["default_policy"]
    assert np.isfinite(info["mean_td_error"])
    assert len(algo.seq_buffer) > 0
    algo.cleanup()


def test_apex_ddpg_trains_on_pendulum():
    from ray_tpu.algorithms.apex_dqn import ApexDDPGConfig

    algo = (
        ApexDDPGConfig()
        .environment("Pendulum-v1")
        .rollouts(num_rollout_workers=1, rollout_fragment_length=16)
        .training(
            train_batch_size=32,
            num_steps_sampled_before_learning_starts=64,
            num_replay_buffer_shards=1,
            target_network_update_freq=10**9,  # polyak inside learn
        )
        .debugging(seed=0)
        .build()
    )
    from ray_tpu.algorithms.ddpg.ddpg import DDPGJaxPolicy

    assert isinstance(algo.get_policy(), DDPGJaxPolicy)
    deadline = time.time() + 180
    result = {}
    while time.time() < deadline:
        result = algo.train()
        if algo._counters.get("num_env_steps_trained", 0) >= 64:
            break
    assert algo._counters["num_env_steps_trained"] >= 64
    info = result["info"]["learner"].get("default_policy", {})
    assert np.isfinite(info.get("critic_loss", np.nan))
    algo.cleanup()
