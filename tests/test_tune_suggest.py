"""Searcher plug-ins: TPE-lite fallback + ask/tell adapter seam
(reference ``tune/suggest/suggestion.py`` Searcher,
``tune/suggest/optuna.py`` integration)."""

import numpy as np
import pytest

import ray_tpu.tune.tune as tune
from ray_tpu.tune.search import choice, loguniform, uniform
from ray_tpu.tune.suggest import (
    ExternalSearcher,
    TPELiteSearcher,
    create_searcher,
)
from ray_tpu.tune.trainable import Trainable


def test_tpe_concentrates_on_optimum():
    """Pure ask/tell loop on -(x-3)^2: after the random startup phase,
    TPE suggestions must concentrate near the optimum."""
    searcher = TPELiteSearcher(
        {"x": uniform(-10.0, 10.0)},
        metric="score",
        mode="max",
        n_startup=8,
        seed=0,
    )
    xs = []
    for i in range(40):
        cfg = searcher.suggest(f"t{i}")
        x = cfg["x"]
        xs.append(x)
        searcher.on_trial_complete(
            f"t{i}", {"score": -((x - 3.0) ** 2)}
        )
    startup = np.abs(np.array(xs[:8]) - 3.0)
    tail = np.abs(np.array(xs[-10:]) - 3.0)
    assert tail.mean() < startup.mean(), (
        f"TPE no better than random: tail {tail.mean():.2f} vs "
        f"startup {startup.mean():.2f}"
    )
    assert tail.min() < 1.0


def test_tpe_handles_mixed_space():
    searcher = TPELiteSearcher(
        {
            "lr": loguniform(1e-5, 1e-1),
            "layers": choice([1, 2, 3]),
            "nested": {"width": uniform(8, 64)},
        },
        metric="score",
        mode="min",
        n_startup=4,
        seed=1,
    )
    # optimum: lr near 1e-3, layers == 2, width near 32
    for i in range(30):
        cfg = searcher.suggest(f"t{i}")
        loss = (
            (np.log10(cfg["lr"]) + 3) ** 2
            + (cfg["layers"] - 2) ** 2
            + ((cfg["nested"]["width"] - 32) / 16) ** 2
        )
        searcher.on_trial_complete(f"t{i}", {"score": loss})
    best = min(searcher._observed, key=lambda ov: ov[1])
    assert best[1] < 2.0


class _Quadratic(Trainable):
    def setup(self, config):
        self.x = config["x"]

    def step(self):
        return {"episode_reward_mean": -((self.x - 3.0) ** 2)}


def test_tune_run_with_search_alg():
    searcher = create_searcher(
        "tpe", {"x": uniform(-10.0, 10.0)}, n_startup=6, seed=0
    )
    ana = tune.run(
        _Quadratic,
        config={},
        num_samples=24,
        search_alg=searcher,
        max_iterations=1,
        parallel=False,
        verbose=0,
    )
    assert len(ana.trials) == 24
    best = ana.get_best_trial()
    assert abs(best.config["x"] - 3.0) < 1.5, best.config


def test_external_searcher_adapter():
    """The ask/tell adapter drives trials from any backend object."""

    class FakeBackend:
        def __init__(self):
            self.told = []
            self.n = 0

        def ask(self):
            self.n += 1
            if self.n > 3:
                return None
            return self.n, {"x": float(self.n)}

        def tell(self, key, value):
            self.told.append((key, value))

    backend = FakeBackend()
    s = ExternalSearcher(backend, metric="m")
    cfgs = [s.suggest(f"t{i}") for i in range(4)]
    assert cfgs[-1] is None and cfgs[0] == {"x": 1.0}
    s.on_trial_complete("t0", {"m": 7.0})
    assert backend.told == [(1, 7.0)]


class _NeedsBase(Trainable):
    def setup(self, config):
        self.x = config["x"]
        self.offset = config["offset"]  # from the base config

    def step(self):
        return {"episode_reward_mean": self.x + self.offset}


def test_search_alg_merges_base_config_and_handles_exhaustion():
    """Constants in tune.run(config=...) reach every suggested trial,
    and a searcher that exhausts early terminates the run instead of
    spinning forever."""

    class TwoShot:
        def __init__(self):
            self.n = 0

        def ask(self):
            self.n += 1
            return (
                None if self.n > 2 else (self.n, {"x": float(self.n)})
            )

        def tell(self, key, value):
            pass

    ana = tune.run(
        _NeedsBase,
        config={"offset": 100.0},
        num_samples=5,  # searcher only yields 2
        search_alg=ExternalSearcher(TwoShot()),
        max_iterations=1,
        parallel=False,
        verbose=0,
    )
    assert len(ana.trials) == 2
    rewards = sorted(
        t.last_result["episode_reward_mean"] for t in ana.trials
    )
    assert rewards == [101.0, 102.0]


def test_create_searcher_optuna_absent():
    with pytest.raises(ImportError, match="tpe"):
        create_searcher("optuna", {"x": uniform(0, 1)})
    with pytest.raises(ValueError):
        create_searcher("nope", {})
