"""IMPALA tests (reference rllib/algorithms/impala/tests/test_impala.py)."""

import time

import numpy as np
import pytest

from ray_tpu.algorithms.impala import IMPALA, IMPALAConfig


def test_impala_sync_mode_trains():
    algo = (
        IMPALAConfig()
        .environment("CartPole-v1")
        .rollouts(num_rollout_workers=0, rollout_fragment_length=32)
        .training(train_batch_size=128, lr=5e-4)
        .reporting(min_time_s_per_iteration=0)
        .debugging(seed=0)
        .build()
    )
    result = algo.train()
    # learner thread is async AND its stats drain lags STATS_LAG
    # dispatches (deferred readback, docs/data_plane.md): wait until a
    # drained info lands, not merely until the first dispatch
    deadline = time.time() + 30
    while (
        "total_loss" not in algo._learner_thread.learner_info
        and time.time() < deadline
    ):
        algo.train()
    assert algo._learner_thread.num_steps > 0
    info = algo._learner_thread.learner_info
    assert np.isfinite(info["total_loss"])
    algo.cleanup()


@pytest.mark.slow  # >30 s wall on this container (PR-1 budget rule);
# tier-1 keeps IMPALA coverage via test_impala_sync_mode_trains +
# the learner-thread/superstep/elastic suites
def test_impala_async_with_workers():
    algo = (
        IMPALAConfig()
        .environment("CartPole-v1")
        .rollouts(num_rollout_workers=2, rollout_fragment_length=32)
        .training(train_batch_size=128)
        .reporting(min_time_s_per_iteration=0)
        .debugging(seed=0)
        .build()
    )
    deadline = time.time() + 300
    steps_trained = 0
    while time.time() < deadline:
        result = algo.train()
        steps_trained = algo._counters.get("num_env_steps_trained", 0)
        if steps_trained > 0:
            break
    assert steps_trained > 0, "async learner never trained a batch"
    assert algo._counters["num_env_steps_sampled"] > 0
    algo.cleanup()


@pytest.mark.slow
def test_impala_cartpole_learns():
    """Learning regression in sync mode (the async path is identical
    learner-side; multi-process rollout is too contended on 1-CPU CI)."""
    algo = (
        IMPALAConfig()
        .environment("CartPole-v1")
        .rollouts(
            num_rollout_workers=0,
            rollout_fragment_length=64,
            num_envs_per_worker=4,
        )
        .training(
            train_batch_size=512,
            lr=5e-4,
            entropy_coeff=0.01,
            vf_loss_coeff=0.5,
            grad_clip=40.0,
        )
        .reporting(min_time_s_per_iteration=1)
        .debugging(seed=11)
        .build()
    )
    best = -np.inf
    deadline = time.time() + 240
    while time.time() < deadline:
        result = algo.train()
        r = result.get("episode_reward_mean", np.nan)
        if np.isfinite(r):
            best = max(best, r)
        if best >= 100.0:
            break
    algo.cleanup()
    assert best >= 100.0, f"IMPALA failed to learn: best={best}"
