"""Tier-1 gate for the device-contract static analyzer.

- the whole-``ray_tpu/`` scan must come back with ZERO unbaselined
  findings (the CI gate: a contract violation fails the suite);
- every rule has fixture-proven true-positive AND true-negative
  coverage (``tests/analysis_fixtures/``), including the
  reconstructed PR-11 ``|td|+1e-6`` f64-promotion bug;
- suppression (``allow[rule]`` line/def scoping) and baseline
  mechanics (``(rule, path, symbol)`` keys surviving line drift,
  stale entries reported) are exercised end to end;
- the pure-AST pass runs without importing jax.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from ray_tpu.analysis import (
    default_baseline_path,
    load_baseline,
    save_baseline,
    scan_paths,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "analysis_fixtures")


def scan_fixture(name):
    return scan_paths([os.path.join(FIXTURES, name)], root=REPO)


def scan_source(tmp_path, source, baseline=None, name="mod.py"):
    p = tmp_path / name
    p.write_text(textwrap.dedent(source))
    return scan_paths([str(p)], root=str(tmp_path), baseline=baseline)


# ---------------------------------------------------------------------------
# the repo gate


class TestRepoGate:
    def test_whole_repo_scan_is_clean(self):
        baseline = load_baseline(default_baseline_path())
        res = scan_paths(
            [os.path.join(REPO, "ray_tpu")],
            root=REPO,
            baseline=baseline,
        )
        assert res.parse_errors == []
        assert res.files > 180, "scan missed most of the tree"
        assert res.findings == [], "unbaselined findings:\n" + "\n".join(
            f.render() for f in res.findings
        )
        assert res.stale_baseline == [], (
            "baseline entries whose finding is gone — remove them: "
            f"{res.stale_baseline}"
        )
        # the gate must stay a trivial fraction of the tier-1 budget:
        # budgeted against the recorded bench (`bench.py --lint`),
        # with generous headroom for a loaded single-core container
        bench_path = os.path.join(
            REPO, "benchmarks", "e2e", "static_analysis.json"
        )
        with open(bench_path) as f:
            bench = json.load(f)
        assert bench["scan_wall_s"] <= 10.0, (
            "recorded full-scan wall blew the 10 s acceptance "
            "budget — re-run `python bench.py --lint` on an idle "
            "container and investigate the regression"
        )
        assert bench["since_wall_s"] < bench["scan_wall_s"]
        assert res.duration_s < max(45.0, 5 * bench["scan_wall_s"])

    def test_cli_runs_without_jax(self):
        """`python -m ray_tpu.analysis --json` is a pure-AST pass: it
        must succeed (exit 0, ok=true) in a process where importing
        jax raises. A subtree scan keeps the subprocess cheap — the
        whole-repo gate above covers coverage; this covers the
        no-jax property."""
        code = textwrap.dedent(
            """
            import sys

            class _BlockJax:
                def find_spec(self, name, path=None, target=None):
                    if name == "jax" or name.startswith("jax."):
                        raise ImportError("jax blocked by test")
                    return None

            sys.meta_path.insert(0, _BlockJax())
            from ray_tpu.analysis.__main__ import main

            rc = main(["--json", "ray_tpu/sharding", "ray_tpu/ops"])
            assert "jax" not in sys.modules, "scan imported jax"
            sys.exit(rc)
            """
        )
        proc = subprocess.run(
            [sys.executable, "-c", code],
            cwd=REPO,
            capture_output=True,
            text=True,
            timeout=300,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        report = json.loads(proc.stdout)
        assert report["ok"] is True
        assert report["files"] >= 8


# ---------------------------------------------------------------------------
# fixture corpus: >= 1 true positive and >= 1 true negative per rule


FIXTURE_CASES = [
    ("rta001_donation.py", "RTA001", 2),
    ("rta002_trace.py", "RTA002", 4),
    ("rta003_dtype.py", "RTA003", 3),
    ("rta004_rng.py", "RTA004", 3),
    ("rta005_hostsync.py", "RTA005", 2),
    ("rta006_threads.py", "RTA006", 2),
    # the v2 rule pack (whole-program call graph + taint)
    ("rta007_eventloop.py", "RTA007", 3),
    ("rta008_lockorder.py", "RTA008", 1),
    ("rta009_durability.py", "RTA009", 4),
    ("rta010_catalog.py", "RTA010", 3),
    ("rta011_rng_order.py", "RTA011", 1),
    ("rta013_kvretry.py", "RTA013", 3),
]


class TestFixtureCorpus:
    @pytest.mark.parametrize(
        "fixture,rule,expected", FIXTURE_CASES
    )
    def test_true_positives_and_negatives(
        self, fixture, rule, expected
    ):
        res = scan_fixture(fixture)
        assert res.parse_errors == []
        hits = [f for f in res.findings if f.rule == rule]
        assert len(hits) == expected, [
            f.render() for f in res.findings
        ]
        # TRUE NEGATIVES: every finding in the file lands on a tp_*
        # symbol; the tn_* functions stay silent
        for f in res.findings:
            leaf = f.symbol.split(".")[-1]
            assert leaf.startswith("tp_") or any(
                part.startswith(("tp_", "make_tp_"))
                for part in f.symbol.split(".")
            ), f"false positive on {f.render()}"

    def test_pr11_epsilon_bug_is_flagged(self):
        """The reconstructed PR-11 `|td|+1e-6` f64-promotion bug must
        trip RTA003 at the literal-arithmetic line."""
        res = scan_fixture("rta003_dtype.py")
        hits = [
            f
            for f in res.findings
            if f.rule == "RTA003"
            and f.symbol == "tp_pr11_priority_body"
        ]
        assert hits, [f.render() for f in res.findings]
        src = open(
            os.path.join(FIXTURES, "rta003_dtype.py")
        ).read().splitlines()
        assert any("1e-6" in src[f.line - 1] for f in hits)

    def test_fixed_version_passes(self):
        """The explicit-dtype rewrite of the same body (the PR-11
        fix shape) is clean."""
        res = scan_fixture("rta003_dtype.py")
        assert not any(
            "tn_explicit_dtype_body" in f.symbol
            for f in res.findings
        )

    def test_rta012_knob_reachability(self):
        """Knob fixtures span two files (reads must be off-module):
        the unread knob and the read-but-undocumented knob are
        flagged; the documented `train_batch_size` read is clean."""
        res = scan_paths(
            [
                os.path.join(FIXTURES, "rta012_knobs.py"),
                os.path.join(FIXTURES, "rta012_knobs_reader.py"),
            ],
            root=REPO,
        )
        assert res.parse_errors == []
        got = sorted(
            (f.rule, f.message.split("`")[1]) for f in res.findings
        )
        assert got == [
            ("RTA012", "tp_undocumented_knob"),
            ("RTA012", "tp_unused_knob"),
        ], [f.render() for f in res.findings]


# ---------------------------------------------------------------------------
# suppression mechanics


VIOLATION = """
    import numpy as np

    def draw(n):
        return np.random.randint(0, n)
"""


class TestSuppression:
    def test_unsuppressed_fires(self, tmp_path):
        res = scan_source(tmp_path, VIOLATION)
        assert [f.rule for f in res.findings] == ["RTA004"]

    def test_allow_on_line(self, tmp_path):
        res = scan_source(
            tmp_path,
            """
            import numpy as np

            def draw(n):
                return np.random.randint(0, n)  # ray-tpu: allow[RTA004] legacy shim
            """,
        )
        assert res.findings == []

    def test_allow_comment_above(self, tmp_path):
        res = scan_source(
            tmp_path,
            """
            import numpy as np

            def draw(n):
                # ray-tpu: allow[RTA004] legacy shim
                return np.random.randint(0, n)
            """,
        )
        assert res.findings == []

    def test_allow_def_scope(self, tmp_path):
        res = scan_source(
            tmp_path,
            """
            import numpy as np

            # ray-tpu: allow[RTA004] fixture generator, not library code
            def draw(n):
                np.random.seed(0)
                return np.random.randint(0, n)
            """,
        )
        assert res.findings == []

    def test_allow_wrong_rule_does_not_suppress(self, tmp_path):
        res = scan_source(
            tmp_path,
            """
            import numpy as np

            def draw(n):
                # ray-tpu: allow[RTA001] wrong rule
                return np.random.randint(0, n)
            """,
        )
        assert [f.rule for f in res.findings] == ["RTA004"]

    def test_allow_scope_ends_with_function(self, tmp_path):
        """A def-scoped allow must not leak to sibling functions."""
        res = scan_source(
            tmp_path,
            """
            import numpy as np

            # ray-tpu: allow[RTA004] sanctioned here
            def draw_ok(n):
                return np.random.randint(0, n)

            def draw_bad(n):
                return np.random.randint(0, n)
            """,
        )
        assert [
            (f.rule, f.symbol) for f in res.findings
        ] == [("RTA004", "draw_bad")]

    def test_host_fn_overrides_device_marking(self, tmp_path):
        res = scan_source(
            tmp_path,
            """
            import numpy as np
            from ray_tpu.sharding.compile import sharded_jit

            def build():
                # ray-tpu: host-fn
                def helper(rows):
                    return float(np.mean(np.stack(rows)))

                # ray-tpu: device-fn
                def body(x):
                    return np.mean(x)

                return sharded_jit(body, label="fx"), helper
            """,
        )
        assert [f.symbol for f in res.findings] == ["build.body"]


# ---------------------------------------------------------------------------
# baseline mechanics


class TestBaseline:
    def test_baseline_key_survives_line_drift(self, tmp_path):
        res = scan_source(tmp_path, VIOLATION)
        assert len(res.findings) == 1
        bpath = tmp_path / "baseline.json"
        save_baseline(str(bpath), res.findings)
        entries = load_baseline(str(bpath))
        assert entries == [
            {"rule": "RTA004", "path": "mod.py", "symbol": "draw"}
        ]
        # drift the line numbers without touching the symbol
        drifted = "\n\n\n# a comment\n\n" + textwrap.dedent(VIOLATION)
        res2 = scan_source(tmp_path, drifted, baseline=entries)
        assert res2.findings == []
        assert len(res2.baselined) == 1
        assert res2.stale_baseline == []

    def test_stale_baseline_entries_reported(self, tmp_path):
        entries = [
            {"rule": "RTA004", "path": "mod.py", "symbol": "draw"},
            {
                "rule": "RTA001",
                "path": "gone.py",
                "symbol": "never_existed",
            },
        ]
        res = scan_source(tmp_path, VIOLATION, baseline=entries)
        assert res.findings == []
        assert len(res.baselined) == 1
        assert res.stale_baseline == [entries[1]]

    def test_fixed_finding_goes_stale(self, tmp_path):
        entries = [
            {"rule": "RTA004", "path": "mod.py", "symbol": "draw"}
        ]
        fixed = """
            import numpy as np

            def draw(n, seed):
                return np.random.default_rng(seed).integers(0, n)
        """
        res = scan_source(tmp_path, fixed, baseline=entries)
        assert res.findings == []
        assert res.stale_baseline == entries


# ---------------------------------------------------------------------------
# v2: whole-program machinery


class TestWholeProgram:
    def test_cross_module_device_propagation(self, tmp_path):
        """A helper in ANOTHER module called from a traced body is a
        device context: the global fixed point carries the fact
        across the import, and RTA002 fires where the v1 engine was
        blind."""
        (tmp_path / "helper.py").write_text(
            textwrap.dedent(
                """
                import numpy as np


                def mean_of(x):
                    return np.mean(x)
                """
            )
        )
        (tmp_path / "prog.py").write_text(
            textwrap.dedent(
                """
                from ray_tpu.sharding.compile import sharded_jit

                from helper import mean_of


                def build():
                    def body(x):
                        return mean_of(x)

                    return sharded_jit(body, label="m")
                """
            )
        )
        # helper alone: clean (nothing marks it device)
        solo = scan_paths([str(tmp_path / "helper.py")], root=str(tmp_path))
        assert solo.findings == []
        both = scan_paths(
            [str(tmp_path / "helper.py"), str(tmp_path / "prog.py")],
            root=str(tmp_path),
        )
        hits = [
            f
            for f in both.findings
            if f.rule == "RTA002" and f.path == "helper.py"
        ]
        assert hits, [f.render() for f in both.findings]
        assert hits[0].symbol == "mean_of"

    def test_since_scope_is_changed_plus_reverse_dependents(
        self, tmp_path
    ):
        (tmp_path / "a.py").write_text(
            "import numpy as np\n\n\n"
            "def helper(n):\n"
            "    return np.random.randint(0, n)\n"
        )
        (tmp_path / "b.py").write_text(
            "from a import helper\n\n\n"
            "def caller(n):\n"
            "    return helper(n)\n"
        )
        (tmp_path / "c.py").write_text(
            "def unrelated():\n    return 1\n"
        )
        res = scan_paths(
            [str(tmp_path)], root=str(tmp_path), changed=["a.py"]
        )
        assert res.mode == "since"
        assert res.affected_paths == {"a.py", "b.py"}
        assert [f.rule for f in res.findings] == ["RTA004"]
        # an out-of-scope change set skips a.py's finding entirely
        res2 = scan_paths(
            [str(tmp_path)], root=str(tmp_path), changed=["c.py"]
        )
        assert res2.findings == []
        assert res2.affected_paths == {"c.py"}

    def test_json_schema_is_versioned(self, tmp_path):
        from ray_tpu.analysis.engine import SCHEMA_VERSION

        res = scan_source(tmp_path, VIOLATION)
        d = res.to_dict()
        assert d["schema_version"] == SCHEMA_VERSION == 2
        assert d["mode"] == "full"
        assert set(d) >= {
            "ok", "files", "findings", "counts", "duration_s",
            "affected_files", "rules_run",
        }


class TestCLISince:
    def _git(self, cwd, *args):
        return subprocess.run(
            ["git", "-c", "user.email=t@t", "-c", "user.name=t"]
            + list(args),
            cwd=cwd,
            capture_output=True,
            text=True,
            timeout=30,
        )

    def test_since_rev_scans_only_the_diff(self, tmp_path, capsys):
        from ray_tpu.analysis.__main__ import main

        (tmp_path / "a.py").write_text("def ok():\n    return 1\n")
        (tmp_path / "b.py").write_text(
            "from a import ok\n\n\ndef caller():\n    return ok()\n"
        )
        assert self._git(tmp_path, "init", "-q").returncode == 0
        self._git(tmp_path, "add", "-A")
        assert self._git(
            tmp_path, "commit", "-qm", "seed"
        ).returncode == 0
        # clean tree: --since HEAD runs rules on nothing
        rc = main(
            [
                "--since", "HEAD", "--json", "--root", str(tmp_path),
                "--no-baseline", str(tmp_path),
            ]
        )
        report = json.loads(capsys.readouterr().out)
        assert rc == 0 and report["mode"] == "since"
        assert report["affected_files"] == 0
        # introduce a violation in a.py: scope = a.py + dependent b.py
        (tmp_path / "a.py").write_text(
            "import numpy as np\n\n\n"
            "def ok():\n    return np.random.randint(0, 3)\n"
        )
        rc = main(
            [
                "--since", "HEAD", "--json", "--root", str(tmp_path),
                "--no-baseline", str(tmp_path),
            ]
        )
        report = json.loads(capsys.readouterr().out)
        assert rc == 1
        assert report["mode"] == "since"
        assert report["affected_files"] == 2
        assert [f["rule"] for f in report["findings"]] == ["RTA004"]

    def test_write_baseline_prunes_stale_entries(
        self, tmp_path, capsys
    ):
        from ray_tpu.analysis.__main__ import main

        (tmp_path / "mod.py").write_text(textwrap.dedent(VIOLATION))
        bpath = tmp_path / "baseline.json"
        bpath.write_text(
            json.dumps(
                {
                    "version": 1,
                    "entries": [
                        {
                            "rule": "RTA004",
                            "path": "mod.py",
                            "symbol": "draw",
                        },
                        {
                            "rule": "RTA001",
                            "path": "gone.py",
                            "symbol": "long_fixed",
                        },
                    ],
                }
            )
        )
        rc = main(
            [
                "--write-baseline", "--root", str(tmp_path),
                "--baseline", str(bpath), str(tmp_path / "mod.py"),
            ]
        )
        out = capsys.readouterr().out
        assert rc == 0 and "1 stale pruned" in out
        entries = load_baseline(str(bpath))
        assert entries == [
            {"rule": "RTA004", "path": "mod.py", "symbol": "draw"}
        ]


# ---------------------------------------------------------------------------
# mutation validation: one representative violation per new rule,
# injected into a REAL module — each trips its rule and only its rule


MUTATIONS = [
    pytest.param(
        "ray_tpu/ingress/http.py",
        [
            (
                "router, admission = entry\n",
                "router, admission = entry\n"
                "        time.sleep(0.01)\n",
            )
        ],
        "RTA007",
        id="rta007-bare-sleep-in-ingress-handler",
    ),
    pytest.param(
        "ray_tpu/autoscaler/fleet.py",
        [
            (
                "self._lock = threading.Lock()\n",
                "self._lock = threading.Lock()\n"
                "        self._mut_lock = threading.Lock()\n",
            ),
            (
                "    def stats(self) -> Dict:\n",
                "    def _mut_a(self):\n"
                "        with self._lock:\n"
                "            with self._mut_lock:\n"
                "                pass\n"
                "\n"
                "    def _mut_b(self):\n"
                "        with self._mut_lock:\n"
                "            with self._lock:\n"
                "                pass\n"
                "\n"
                "    def stats(self) -> Dict:\n",
            ),
        ],
        "RTA008",
        id="rta008-swapped-lock-pair-in-fleet",
    ),
    pytest.param(
        "ray_tpu/resilience/streamer.py",
        [
            (
                "atomic_write(path, lambda f: pickle.dump(payload, f))",
                'tmp = path + ".mut"\n'
                '            with open(tmp, "wb") as _f:\n'
                "                pickle.dump(payload, _f)\n"
                "            os.replace(tmp, path)",
            )
        ],
        "RTA009",
        id="rta009-unfsynced-replace-in-streamer",
    ),
    pytest.param(
        "ray_tpu/autoscaler/fleet.py",
        [('"fleet:drain"', '"fleet:mutated_drain"')],
        "RTA010",
        id="rta010-renamed-span-in-fleet",
    ),
    pytest.param(
        "ray_tpu/algorithms/dreamer/dreamer.py",
        [("# ray-tpu: allow[RTA011]", "# (allow dropped)")],
        "RTA011",
        id="rta011-dropped-allow-in-dreamer",
    ),
    pytest.param(
        "ray_tpu/algorithms/algorithm_config.py",
        [
            (
                "self.gamma = 0.99\n",
                "self.gamma = 0.99\n"
                "        self.mut_unused_knob = 7\n",
            )
        ],
        "RTA012",
        id="rta012-dead-knob-in-config",
    ),
]


class TestMutationValidation:
    @pytest.mark.parametrize("rel,edits,rule", MUTATIONS)
    def test_injected_violation_trips_exactly_its_rule(
        self, tmp_path, rel, edits, rule
    ):
        src = open(os.path.join(REPO, rel)).read()
        target = tmp_path / os.path.basename(rel)
        target.write_text(src)
        before = scan_paths([str(target)], root=REPO)
        key = lambda f: (f.rule, f.symbol, f.message)
        baseline_keys = {key(f) for f in before.findings}

        mutated = src
        for old, new in edits:
            assert old in mutated, f"anchor drifted in {rel}: {old!r}"
            mutated = mutated.replace(old, new, 1)
        target.write_text(mutated)
        after = scan_paths([str(target)], root=REPO)
        fresh = [
            f for f in after.findings if key(f) not in baseline_keys
        ]
        assert fresh, f"mutation of {rel} tripped nothing"
        assert all(f.rule == rule for f in fresh), [
            f.render() for f in fresh
        ]
