"""Tier-1 gate for the device-contract static analyzer.

- the whole-``ray_tpu/`` scan must come back with ZERO unbaselined
  findings (the CI gate: a contract violation fails the suite);
- every rule has fixture-proven true-positive AND true-negative
  coverage (``tests/analysis_fixtures/``), including the
  reconstructed PR-11 ``|td|+1e-6`` f64-promotion bug;
- suppression (``allow[rule]`` line/def scoping) and baseline
  mechanics (``(rule, path, symbol)`` keys surviving line drift,
  stale entries reported) are exercised end to end;
- the pure-AST pass runs without importing jax.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from ray_tpu.analysis import (
    default_baseline_path,
    load_baseline,
    save_baseline,
    scan_paths,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "analysis_fixtures")


def scan_fixture(name):
    return scan_paths([os.path.join(FIXTURES, name)], root=REPO)


def scan_source(tmp_path, source, baseline=None, name="mod.py"):
    p = tmp_path / name
    p.write_text(textwrap.dedent(source))
    return scan_paths([str(p)], root=str(tmp_path), baseline=baseline)


# ---------------------------------------------------------------------------
# the repo gate


class TestRepoGate:
    def test_whole_repo_scan_is_clean(self):
        baseline = load_baseline(default_baseline_path())
        res = scan_paths(
            [os.path.join(REPO, "ray_tpu")],
            root=REPO,
            baseline=baseline,
        )
        assert res.parse_errors == []
        assert res.files > 180, "scan missed most of the tree"
        assert res.findings == [], "unbaselined findings:\n" + "\n".join(
            f.render() for f in res.findings
        )
        assert res.stale_baseline == [], (
            "baseline entries whose finding is gone — remove them: "
            f"{res.stale_baseline}"
        )
        # the gate must stay a trivial fraction of the tier-1 budget
        assert res.duration_s < 120

    def test_cli_runs_without_jax(self):
        """`python -m ray_tpu.analysis --json` is a pure-AST pass: it
        must succeed (exit 0, ok=true) in a process where importing
        jax raises. A subtree scan keeps the subprocess cheap — the
        whole-repo gate above covers coverage; this covers the
        no-jax property."""
        code = textwrap.dedent(
            """
            import sys

            class _BlockJax:
                def find_spec(self, name, path=None, target=None):
                    if name == "jax" or name.startswith("jax."):
                        raise ImportError("jax blocked by test")
                    return None

            sys.meta_path.insert(0, _BlockJax())
            from ray_tpu.analysis.__main__ import main

            rc = main(["--json", "ray_tpu/sharding", "ray_tpu/ops"])
            assert "jax" not in sys.modules, "scan imported jax"
            sys.exit(rc)
            """
        )
        proc = subprocess.run(
            [sys.executable, "-c", code],
            cwd=REPO,
            capture_output=True,
            text=True,
            timeout=300,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        report = json.loads(proc.stdout)
        assert report["ok"] is True
        assert report["files"] >= 8


# ---------------------------------------------------------------------------
# fixture corpus: >= 1 true positive and >= 1 true negative per rule


FIXTURE_CASES = [
    ("rta001_donation.py", "RTA001", 2),
    ("rta002_trace.py", "RTA002", 4),
    ("rta003_dtype.py", "RTA003", 3),
    ("rta004_rng.py", "RTA004", 3),
    ("rta005_hostsync.py", "RTA005", 2),
    ("rta006_threads.py", "RTA006", 2),
]


class TestFixtureCorpus:
    @pytest.mark.parametrize(
        "fixture,rule,expected", FIXTURE_CASES
    )
    def test_true_positives_and_negatives(
        self, fixture, rule, expected
    ):
        res = scan_fixture(fixture)
        assert res.parse_errors == []
        hits = [f for f in res.findings if f.rule == rule]
        assert len(hits) == expected, [
            f.render() for f in res.findings
        ]
        # TRUE NEGATIVES: every finding in the file lands on a tp_*
        # symbol; the tn_* functions stay silent
        for f in res.findings:
            leaf = f.symbol.split(".")[-1]
            assert leaf.startswith("tp_") or any(
                part.startswith(("tp_", "make_tp_"))
                for part in f.symbol.split(".")
            ), f"false positive on {f.render()}"

    def test_pr11_epsilon_bug_is_flagged(self):
        """The reconstructed PR-11 `|td|+1e-6` f64-promotion bug must
        trip RTA003 at the literal-arithmetic line."""
        res = scan_fixture("rta003_dtype.py")
        hits = [
            f
            for f in res.findings
            if f.rule == "RTA003"
            and f.symbol == "tp_pr11_priority_body"
        ]
        assert hits, [f.render() for f in res.findings]
        src = open(
            os.path.join(FIXTURES, "rta003_dtype.py")
        ).read().splitlines()
        assert any("1e-6" in src[f.line - 1] for f in hits)

    def test_fixed_version_passes(self):
        """The explicit-dtype rewrite of the same body (the PR-11
        fix shape) is clean."""
        res = scan_fixture("rta003_dtype.py")
        assert not any(
            "tn_explicit_dtype_body" in f.symbol
            for f in res.findings
        )


# ---------------------------------------------------------------------------
# suppression mechanics


VIOLATION = """
    import numpy as np

    def draw(n):
        return np.random.randint(0, n)
"""


class TestSuppression:
    def test_unsuppressed_fires(self, tmp_path):
        res = scan_source(tmp_path, VIOLATION)
        assert [f.rule for f in res.findings] == ["RTA004"]

    def test_allow_on_line(self, tmp_path):
        res = scan_source(
            tmp_path,
            """
            import numpy as np

            def draw(n):
                return np.random.randint(0, n)  # ray-tpu: allow[RTA004] legacy shim
            """,
        )
        assert res.findings == []

    def test_allow_comment_above(self, tmp_path):
        res = scan_source(
            tmp_path,
            """
            import numpy as np

            def draw(n):
                # ray-tpu: allow[RTA004] legacy shim
                return np.random.randint(0, n)
            """,
        )
        assert res.findings == []

    def test_allow_def_scope(self, tmp_path):
        res = scan_source(
            tmp_path,
            """
            import numpy as np

            # ray-tpu: allow[RTA004] fixture generator, not library code
            def draw(n):
                np.random.seed(0)
                return np.random.randint(0, n)
            """,
        )
        assert res.findings == []

    def test_allow_wrong_rule_does_not_suppress(self, tmp_path):
        res = scan_source(
            tmp_path,
            """
            import numpy as np

            def draw(n):
                # ray-tpu: allow[RTA001] wrong rule
                return np.random.randint(0, n)
            """,
        )
        assert [f.rule for f in res.findings] == ["RTA004"]

    def test_allow_scope_ends_with_function(self, tmp_path):
        """A def-scoped allow must not leak to sibling functions."""
        res = scan_source(
            tmp_path,
            """
            import numpy as np

            # ray-tpu: allow[RTA004] sanctioned here
            def draw_ok(n):
                return np.random.randint(0, n)

            def draw_bad(n):
                return np.random.randint(0, n)
            """,
        )
        assert [
            (f.rule, f.symbol) for f in res.findings
        ] == [("RTA004", "draw_bad")]

    def test_host_fn_overrides_device_marking(self, tmp_path):
        res = scan_source(
            tmp_path,
            """
            import numpy as np
            from ray_tpu.sharding.compile import sharded_jit

            def build():
                # ray-tpu: host-fn
                def helper(rows):
                    return float(np.mean(np.stack(rows)))

                # ray-tpu: device-fn
                def body(x):
                    return np.mean(x)

                return sharded_jit(body, label="fx"), helper
            """,
        )
        assert [f.symbol for f in res.findings] == ["build.body"]


# ---------------------------------------------------------------------------
# baseline mechanics


class TestBaseline:
    def test_baseline_key_survives_line_drift(self, tmp_path):
        res = scan_source(tmp_path, VIOLATION)
        assert len(res.findings) == 1
        bpath = tmp_path / "baseline.json"
        save_baseline(str(bpath), res.findings)
        entries = load_baseline(str(bpath))
        assert entries == [
            {"rule": "RTA004", "path": "mod.py", "symbol": "draw"}
        ]
        # drift the line numbers without touching the symbol
        drifted = "\n\n\n# a comment\n\n" + textwrap.dedent(VIOLATION)
        res2 = scan_source(tmp_path, drifted, baseline=entries)
        assert res2.findings == []
        assert len(res2.baselined) == 1
        assert res2.stale_baseline == []

    def test_stale_baseline_entries_reported(self, tmp_path):
        entries = [
            {"rule": "RTA004", "path": "mod.py", "symbol": "draw"},
            {
                "rule": "RTA001",
                "path": "gone.py",
                "symbol": "never_existed",
            },
        ]
        res = scan_source(tmp_path, VIOLATION, baseline=entries)
        assert res.findings == []
        assert len(res.baselined) == 1
        assert res.stale_baseline == [entries[1]]

    def test_fixed_finding_goes_stale(self, tmp_path):
        entries = [
            {"rule": "RTA004", "path": "mod.py", "symbol": "draw"}
        ]
        fixed = """
            import numpy as np

            def draw(n, seed):
                return np.random.default_rng(seed).integers(0, n)
        """
        res = scan_source(tmp_path, fixed, baseline=entries)
        assert res.findings == []
        assert res.stale_baseline == entries
