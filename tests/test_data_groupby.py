"""Dataset relational ops: groupby/aggregate exchange, union, zip,
unique (reference ``data/grouped_data.py`` + ``tests/test_dataset.py``
groupby cases). The groupby is a distributed hash exchange — keys are
partitioned with a process-stable hash so the same key never lands in
two aggregation tasks."""

import pytest

import ray_tpu as ray
from ray_tpu.data.dataset import Dataset


@pytest.fixture(autouse=True)
def _init():
    ray.init(num_cpus=2, ignore_reinit_error=True)


def _rows():
    return [
        {"k": i % 3, "v": float(i)} for i in range(30)
    ]


def test_groupby_count_sum_mean():
    ds = Dataset.from_items(_rows(), parallelism=4)
    counts = {
        r["k"]: r["count()"]
        for r in ds.groupby("k").count().take_all()
    }
    assert counts == {0: 10, 1: 10, 2: 10}
    sums = {
        r["k"]: r["sum(v)"]
        for r in ds.groupby("k").sum("v").take_all()
    }
    assert sums[0] == sum(float(i) for i in range(0, 30, 3))
    means = {
        r["k"]: r["mean(v)"]
        for r in ds.groupby("k").mean("v").take_all()
    }
    assert means[1] == pytest.approx(sums[1] / 10 if False else
                                     sum(float(i) for i in
                                         range(1, 30, 3)) / 10)


def test_groupby_min_max_and_callable_key():
    ds = Dataset.range(20, parallelism=3)
    lo = {
        r["key"]: r["min(None)"]
        for r in ds.groupby(lambda x: x % 2).min().take_all()
    }
    assert lo == {0: 0, 1: 1}
    hi = {
        r["key"]: r["max(None)"]
        for r in ds.groupby(lambda x: x % 2).max().take_all()
    }
    assert hi == {0: 18, 1: 19}


def test_groupby_custom_aggregate_and_map_groups():
    ds = Dataset.from_items(_rows(), parallelism=4)
    # custom fold: concatenate values as a sorted tuple
    agg = ds.groupby("k").aggregate(
        init=lambda k: [],
        accumulate=lambda a, r: a + [r["v"]],
        finalize=lambda a: tuple(sorted(a)),
        name="vals",
    )
    vals = {r["k"]: r["vals"] for r in agg.take_all()}
    assert vals[2] == tuple(float(i) for i in range(2, 30, 3))
    # map_groups: emit one normalized row per group
    out = ds.groupby("k").map_groups(
        lambda rows: [
            {
                "k": rows[0]["k"],
                "n": len(rows),
                "span": max(r["v"] for r in rows)
                - min(r["v"] for r in rows),
            }
        ]
    )
    spans = {r["k"]: (r["n"], r["span"]) for r in out.take_all()}
    assert spans == {0: (10, 27.0), 1: (10, 27.0), 2: (10, 27.0)}


def test_unique_and_union_and_zip():
    ds = Dataset.from_items(_rows(), parallelism=3)
    assert sorted(ds.unique("k")) == [0, 1, 2]
    a = Dataset.range(5, parallelism=2)
    b = Dataset.range(5, parallelism=2).map(lambda x: x + 100)
    u = a.union(b)
    assert u.count() == 10
    assert sorted(u.take_all())[-1] == 104
    z = a.zip(b)
    assert z.take_all() == [(i, i + 100) for i in range(5)]
    with pytest.raises(ValueError):
        a.zip(Dataset.range(3))


def test_reshapes_never_materialize_on_driver(monkeypatch):
    """zip/repartition/split are block-wise exchanges: rows move
    worker-to-worker; the driver routes refs and counts only (VERDICT
    r3 #8). Pin it by making driver materialization raise."""
    a = Dataset.range(40, parallelism=4)
    b = Dataset.range(40, parallelism=3).map(lambda x: x * 2)

    def boom(self):
        raise AssertionError("driver materialized rows")

    monkeypatch.setattr(Dataset, "take_all", boom)
    monkeypatch.setattr(Dataset, "_materialize", boom)
    z = a.zip(b)
    rp = a.repartition(5)
    shards = a.split(4)
    monkeypatch.undo()
    assert z.take_all() == [(i, 2 * i) for i in range(40)]
    assert rp.count() == 40
    assert rp.num_blocks() == 5
    assert sorted(rp.take_all()) == list(range(40))
    got = []
    for s in shards:
        got.extend(s.take_all())
    assert sorted(got) == list(range(40))
    # misaligned block boundaries still pair positionally
    c = Dataset.from_items(list(range(7)), parallelism=2)
    d = Dataset.from_items(list(range(7)), parallelism=5)
    assert c.zip(d).take_all() == [(i, i) for i in range(7)]


def test_groupby_single_block_local_path():
    ds = Dataset.from_items([{"k": 0, "v": 1.0}], parallelism=1)
    out = ds.groupby("k").sum("v").take_all()
    assert out == [{"k": 0, "sum(v)": 1.0}]


def test_iter_torch_batches():
    ds = Dataset.from_items(
        [{"x": float(i), "y": i} for i in range(10)], parallelism=2
    )
    batches = list(ds.iter_torch_batches(batch_size=4))
    import torch

    assert len(batches) == 3
    assert isinstance(batches[0]["x"], torch.Tensor)
    assert batches[0]["x"].tolist() == [0.0, 1.0, 2.0, 3.0]
    assert batches[-1]["y"].tolist() == [8, 9]
