"""On-hardware smoke tests (real TPU only).

The default test run forces the virtual 8-device CPU platform
(``conftest.py``); these tests only run under ``RAY_TPU_HW_TEST=1
pytest tests/test_tpu_hardware.py``, where the conftest leaves the real
backend in place. They validate that the Pallas kernels lower and match
the XLA reference for exactly the shapes the hot paths use — the
concern raised for Mosaic tile alignment on small GTrXL head dims
(reference precedent: ``rllib/models/torch/attention_net.py:37`` shapes).
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

pytestmark = pytest.mark.skipif(
    os.environ.get("RAY_TPU_HW_TEST") != "1"
    or jax.default_backend() != "tpu",
    reason="requires RAY_TPU_HW_TEST=1 and a real TPU backend",
)


# (B, H, T, S, D): GTrXL unrolls (small T, head_dim 16-32) and a
# square block like ring attention's per-hop tile.
FLASH_SHAPES = [(32, 1, 20, 70, 32), (8, 2, 10, 60, 16), (4, 4, 100, 100, 64)]
STATS_SHAPES = [(8, 128, 64), (4, 256, 128)]


@pytest.mark.parametrize("shape", FLASH_SHAPES)
def test_flash_attention_on_tpu(shape):
    from ray_tpu.ops.flash_attention import flash_attention

    B, H, T, S, D = shape
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(B, H, T, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, H, S, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, H, S, D)), jnp.float32)
    M = S - T
    out = flash_attention(q, k, v, causal_offset=M, use_pallas=True)
    ref = flash_attention(q, k, v, causal_offset=M, use_pallas=False)
    # MXU matmuls accumulate through bf16 passes on TPU; tolerance is
    # set for that, not for fp32 HBM math.
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-2)


@pytest.mark.parametrize("shape", STATS_SHAPES)
def test_flash_block_stats_on_tpu(shape):
    from ray_tpu.ops.flash_attention import (
        _reference_attention,
        flash_block_attention_stats,
    )

    N, T, D = shape
    rng = np.random.default_rng(1)
    q = jnp.asarray(rng.normal(size=(N, T, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(N, T, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(N, T, D)), jnp.float32)
    acc, m, l = flash_block_attention_stats(q, k, v, jnp.int32(T))
    out = np.asarray(acc) / np.maximum(np.asarray(l)[..., None], 1e-30)
    ref = np.asarray(_reference_attention(q, k, v, None))
    np.testing.assert_allclose(out, ref, atol=2e-2)


def test_pallas_probe_caches():
    from ray_tpu.ops.flash_attention import _pallas_lowers

    assert _pallas_lowers(20, 70, 32) is True
    # cached second call is instant
    assert _pallas_lowers(20, 70, 32) is True
