"""On-hardware smoke tests (real TPU only).

The default test run forces the virtual 8-device CPU platform
(``conftest.py``); these tests only run under ``RAY_TPU_HW_TEST=1
pytest tests/test_tpu_hardware.py``, where the conftest leaves the real
backend in place. They validate that the Pallas kernels lower and match
the XLA reference for exactly the shapes the hot paths use — the
concern raised for Mosaic tile alignment on small GTrXL head dims
(reference precedent: ``rllib/models/torch/attention_net.py:37`` shapes).
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

pytestmark = pytest.mark.skipif(
    os.environ.get("RAY_TPU_HW_TEST") != "1"
    or jax.default_backend() != "tpu",
    reason="requires RAY_TPU_HW_TEST=1 and a real TPU backend",
)


# (B, H, T, S, D): GTrXL unrolls (small T, head_dim 16-32) and a
# square block like ring attention's per-hop tile.
FLASH_SHAPES = [(32, 1, 20, 70, 32), (8, 2, 10, 60, 16), (4, 4, 100, 100, 64)]
STATS_SHAPES = [(8, 128, 64), (4, 256, 128)]


@pytest.mark.parametrize("shape", FLASH_SHAPES)
def test_flash_attention_on_tpu(shape):
    from ray_tpu.ops.flash_attention import flash_attention

    B, H, T, S, D = shape
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(B, H, T, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, H, S, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, H, S, D)), jnp.float32)
    M = S - T
    out = flash_attention(q, k, v, causal_offset=M, use_pallas=True)
    ref = flash_attention(q, k, v, causal_offset=M, use_pallas=False)
    # MXU matmuls accumulate through bf16 passes on TPU; tolerance is
    # set for that, not for fp32 HBM math.
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-2)


@pytest.mark.parametrize("shape", STATS_SHAPES)
def test_flash_block_stats_on_tpu(shape):
    from ray_tpu.ops.flash_attention import (
        _reference_attention,
        flash_block_attention_stats,
    )

    N, T, D = shape
    rng = np.random.default_rng(1)
    q = jnp.asarray(rng.normal(size=(N, T, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(N, T, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(N, T, D)), jnp.float32)
    acc, m, l = flash_block_attention_stats(q, k, v, jnp.int32(T))
    out = np.asarray(acc) / np.maximum(np.asarray(l)[..., None], 1e-30)
    ref = np.asarray(_reference_attention(q, k, v, None))
    np.testing.assert_allclose(out, ref, atol=2e-2)


def test_pallas_probe_caches():
    from ray_tpu.ops.flash_attention import _pallas_lowers

    assert _pallas_lowers(20, 70, 32) is True
    # cached second call is instant
    assert _pallas_lowers(20, 70, 32) is True


def test_pbt_trials_jit_on_tpu(tmp_path):
    """Tune trials with resources_per_trial={'TPU': 1} time-slice the
    driver's mesh: every trainable's jitted step runs on the REAL TPU
    backend, and PBT exploit still works across the population
    (reference: GPU trial resources via placement groups,
    tune/execution/ray_trial_executor.py)."""
    import ray_tpu.tune.tune as tune
    from ray_tpu.tune.schedulers import PopulationBasedTraining
    from ray_tpu.tune.search import uniform
    from ray_tpu.tune.trainable import Trainable

    platforms = []

    class JitTrainable(Trainable):
        def setup(self, config):
            self.lr = config["lr"]
            self.w = jnp.zeros(())
            self._step_fn = jax.jit(lambda w, lr: w + lr)

        def step(self):
            self.w = self._step_fn(self.w, self.lr)
            platforms.append(
                next(iter(self.w.devices())).platform
            )
            return {"episode_reward_mean": float(self.w)}

        def get_exploit_state(self):
            return {"w": jax.device_get(self.w)}

        def apply_exploit(self, state, scalars):
            self.w = jnp.asarray(state["w"])
            self.lr = scalars.get("lr", self.lr)

        def get_exploit_scalars(self):
            return {"lr": self.lr}

    ana = tune.run(
        JitTrainable,
        config={"lr": uniform(0.01, 0.1)},
        num_samples=3,
        scheduler=PopulationBasedTraining(
            time_attr="training_iteration",
            perturbation_interval=2,
            hyperparam_mutations={"lr": uniform(0.01, 0.1)},
        ),
        resources_per_trial={"TPU": 1},
        max_iterations=6,
        local_dir=str(tmp_path),
        verbose=0,
    )
    assert len(ana.trials) == 3
    assert platforms and all(p == "tpu" for p in platforms), set(
        platforms
    )
    assert all(
        t.last_result.get("training_iteration") == 6
        for t in ana.trials
    )
