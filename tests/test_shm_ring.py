"""Native shm ring buffer tests (the C++ data plane)."""

import numpy as np
import pytest

import ray_tpu as ray
from ray_tpu.core.shm_ring import ShmRing
from ray_tpu.native.build import available

pytestmark = pytest.mark.skipif(
    not available(), reason="native toolchain unavailable"
)


def test_push_pop_bytes():
    ring = ShmRing.create("test_ring_a", 1 << 20)
    assert ring.push_bytes(b"hello")
    assert ring.push_bytes(b"world" * 100)
    assert ring.pop_bytes() == b"hello"
    assert ring.pop_bytes() == b"world" * 100
    ring.close()


def test_pop_empty_times_out():
    ring = ShmRing.create("test_ring_b", 1 << 16)
    assert ring.pop_bytes(timeout=0.1) is None
    ring.close()


def test_wraparound():
    ring = ShmRing.create("test_ring_c", 4096)
    payload = bytes(1000)
    for round_ in range(20):  # forces many wraps
        assert ring.push_bytes(payload)
        assert ring.push_bytes(b"x" * (round_ + 1))
        assert ring.pop_bytes() == payload
        assert ring.pop_bytes() == b"x" * (round_ + 1)
    assert ring.num_pushed() == 40
    ring.close()


def test_backpressure_full_then_drain():
    ring = ShmRing.create("test_ring_d", 4096)
    big = bytes(1500)
    assert ring.push_bytes(big, timeout=0.2)
    assert ring.push_bytes(big, timeout=0.2)
    # third won't fit until we drain
    assert not ring.push_bytes(big, timeout=0.2)
    assert ring.pop_bytes() == big
    assert ring.push_bytes(big, timeout=0.2)
    ring.close()


def test_oversized_record_raises():
    ring = ShmRing.create("test_ring_e", 4096)
    with pytest.raises(ValueError):
        ring.push_bytes(bytes(8192))
    ring.close()


def test_object_roundtrip_numpy():
    from ray_tpu.data.sample_batch import SampleBatch

    ring = ShmRing.create("test_ring_f", 8 << 20)
    batch = SampleBatch(
        {
            "obs": np.random.default_rng(0)
            .standard_normal((64, 17))
            .astype(np.float32),
            "rewards": np.ones(64, np.float32),
        }
    )
    ring.push(batch)
    out = ring.pop()
    np.testing.assert_array_equal(out["obs"], batch["obs"])
    assert out.count == 64
    ring.close()


def test_cross_process_stream():
    """Producer actor pushes batches through the ring; driver pops."""
    ray.init(ignore_reinit_error=True)
    ring = ShmRing.create("test_ring_g", 16 << 20)

    @ray.remote
    class Producer:
        def produce(self, ring, n):
            import numpy as np

            for i in range(n):
                ring.push(
                    {"i": i, "data": np.full(10000, i, np.float32)}
                )
            return "done"

    p = Producer.remote()
    done_ref = p.produce.remote(ring, 20)
    seen = []
    for _ in range(20):
        item = ring.pop(timeout=60.0)
        assert item is not None
        assert item["data"][0] == item["i"]
        seen.append(item["i"])
    assert seen == list(range(20))
    assert ray.get(done_ref) == "done"
    ring.close()


def test_closed_ring_raises():
    ring = ShmRing.create("test_ring_h", 1 << 16)
    ring.mark_closed()
    with pytest.raises(BrokenPipeError):
        ring.push_bytes(b"x")
    ring.close()
