"""Native shm ring buffer tests (the C++ data plane)."""

import numpy as np
import pytest

import ray_tpu as ray
from ray_tpu.core.shm_ring import ShmRing
from ray_tpu.native.build import available

pytestmark = pytest.mark.skipif(
    not available(), reason="native toolchain unavailable"
)


def test_push_pop_bytes():
    ring = ShmRing.create("test_ring_a", 1 << 20)
    assert ring.push_bytes(b"hello")
    assert ring.push_bytes(b"world" * 100)
    assert ring.pop_bytes() == b"hello"
    assert ring.pop_bytes() == b"world" * 100
    ring.close()


def test_pop_empty_times_out():
    ring = ShmRing.create("test_ring_b", 1 << 16)
    assert ring.pop_bytes(timeout=0.1) is None
    ring.close()


def test_wraparound():
    ring = ShmRing.create("test_ring_c", 4096)
    payload = bytes(1000)
    for round_ in range(20):  # forces many wraps
        assert ring.push_bytes(payload)
        assert ring.push_bytes(b"x" * (round_ + 1))
        assert ring.pop_bytes() == payload
        assert ring.pop_bytes() == b"x" * (round_ + 1)
    assert ring.num_pushed() == 40
    ring.close()


def test_backpressure_full_then_drain():
    ring = ShmRing.create("test_ring_d", 4096)
    big = bytes(1500)
    assert ring.push_bytes(big, timeout=0.2)
    assert ring.push_bytes(big, timeout=0.2)
    # third won't fit until we drain
    assert not ring.push_bytes(big, timeout=0.2)
    assert ring.pop_bytes() == big
    assert ring.push_bytes(big, timeout=0.2)
    ring.close()


def test_oversized_record_raises():
    ring = ShmRing.create("test_ring_e", 4096)
    with pytest.raises(ValueError):
        ring.push_bytes(bytes(8192))
    ring.close()


def test_never_fits_at_cursor_raises_not_spins():
    """A record that cannot fit at the current cursor position (wrap
    marker + record > capacity) must fail fast with ValueError so the
    producer falls back to the segment path — not retry until timeout."""
    ring = ShmRing.create("test_ring_e2", 4096)
    assert ring.push_bytes(bytes(2040))
    assert bytes(ring.pop_bytes()) == bytes(2040)
    # Ring is empty but the cursor sits mid-buffer: 2048-byte record
    # needs wrap-skip (2048) + record (2056) > capacity (4096).
    with pytest.raises(ValueError):
        ring.push_bytes(bytes(2048), timeout=0.5)
    ring.close()


def test_object_roundtrip_numpy():
    from ray_tpu.data.sample_batch import SampleBatch

    ring = ShmRing.create("test_ring_f", 8 << 20)
    batch = SampleBatch(
        {
            "obs": np.random.default_rng(0)
            .standard_normal((64, 17))
            .astype(np.float32),
            "rewards": np.ones(64, np.float32),
        }
    )
    ring.push(batch)
    out = ring.pop()
    np.testing.assert_array_equal(out["obs"], batch["obs"])
    assert out.count == 64
    ring.close()


def test_cross_process_stream():
    """Producer actor pushes batches through the ring; driver pops."""
    ray.init(ignore_reinit_error=True)
    ring = ShmRing.create("test_ring_g", 16 << 20)

    @ray.remote
    class Producer:
        def produce(self, ring, n):
            import numpy as np

            for i in range(n):
                ring.push(
                    {"i": i, "data": np.full(10000, i, np.float32)}
                )
            return "done"

    p = Producer.remote()
    done_ref = p.produce.remote(ring, 20)
    seen = []
    for _ in range(20):
        item = ring.pop(timeout=60.0)
        assert item is not None
        assert item["data"][0] == item["i"]
        seen.append(item["i"])
    assert seen == list(range(20))
    assert ray.get(done_ref) == "done"
    ring.close()


def test_closed_ring_raises():
    ring = ShmRing.create("test_ring_h", 1 << 16)
    ring.mark_closed()
    with pytest.raises(BrokenPipeError):
        ring.push_bytes(b"x")
    ring.close()


def test_bulk_task_results_traverse_ring():
    """VERDICT r1: the native ring must be ON the data path — bulk task
    results (e.g. rollout SampleBatches) ride it, not the pipe."""
    ray.init(num_cpus=1, ignore_reinit_error=True)
    try:
        @ray.remote
        def big():
            # ~600 KB: inside the ring's routing band [32KB, 768KB]
            return np.ones((150, 1024), np.float32)

        @ray.remote
        def small():
            return 1

        out = ray.get(big.remote())
        assert out.shape == (150, 1024)
        assert ray.get(small.remote()) == 1
        rt = ray.core.api._require_runtime()
        ring_counts = [w.ring_results for w in rt.pool]
        assert sum(ring_counts) >= 1, (
            "bulk result did not traverse the shm ring"
        )
    finally:
        ray.shutdown()


def test_actor_bulk_results_traverse_ring():
    ray.init(num_cpus=1, ignore_reinit_error=True)
    try:
        @ray.remote
        class Sampler:
            def sample(self):
                return {"obs": np.zeros((256, 84), np.float32)}

        s = Sampler.remote()
        for _ in range(3):
            out = ray.get(s.sample.remote())
            assert out["obs"].shape == (256, 84)
        rt = ray.core.api._require_runtime()
        total = sum(
            w.ring_results for w in rt.pool
        ) + sum(
            rec.worker.ring_results for rec in rt.actors.values()
        )
        assert total >= 3
    finally:
        ray.shutdown()


def test_ring_throughput_beats_pipe():
    """The ring must earn its keep in its routing band.

    Results are size-routed (worker_proc.py): pipe < 32KB <= ring <=
    768KB < dedicated shm segment. In the ring band the per-record
    segment/pipe overhead (shm_open/ftruncate/mmap/unlink + resource
    tracker, or pipe chunking) dominates a single extra memcpy —
    measured 1.3-1.7x in the ring's favor at 64KB-512KB. Above ~1MB the
    segment path's lazy zero-copy views win, which is exactly why bulk
    records are routed there instead.
    """
    import time as _t

    payload = np.random.default_rng(0).standard_normal(
        (128, 1024)
    ).astype(np.float32)  # 512 KB — inside the ring band

    def run_round_trips(env):
        ray.init(num_cpus=1, ignore_reinit_error=True, worker_env=env)
        try:
            @ray.remote
            def produce():
                return payload

            ray.get(produce.remote())  # warm the worker
            t0 = _t.perf_counter()
            for _ in range(16):
                # Consume the payload: the segment fallback hands back
                # lazy zero-copy views, so without a real read it would
                # never touch the data at all and the comparison would
                # measure deferral, not transfer.
                float(ray.get(produce.remote()).sum())
            return _t.perf_counter() - t0
        finally:
            ray.shutdown()

    t_ring = run_round_trips({})
    t_fallback = run_round_trips({"RAY_TPU_DISABLE_RING": "1"})
    print(
        f"ring={t_ring:.3f}s fallback={t_fallback:.3f}s "
        f"ratio={t_fallback/t_ring:.2f}x"
    )
    # Slack for CI noise (scheduler jitter on loaded machines); the
    # measured steady-state advantage in this band is ~1.6x.
    assert t_ring < t_fallback * 1.35
