"""Fault-tolerant training loop (docs/resilience.md): retry/backoff
schedule units, fault-injection determinism, AsyncRequestsManager
re-add semantics, bounded health probes, NaN-batch skip bit-exactness,
checkpoint auto-restore, and the chaos e2e (kill 2 of 4 rollout
workers + poison one learn batch mid-PPO ``train()``; the run must
complete with the fleet restored and the recovery telemetry correct).

Reference precedent: ``ray/python/ray/tests/test_chaos.py`` (NodeKiller
chaos), rllib's ``ignore_worker_failures`` fault-tolerance tests."""

import time
import urllib.request

import numpy as np
import pytest

import ray_tpu as ray
from ray_tpu.resilience import (
    FaultInjector,
    InjectedCrash,
    RetryPolicy,
    batch_is_finite,
    probe_actors,
)
from ray_tpu.resilience.faults import _parse_env_spec


# ---------------------------------------------------------------------------
# RetryPolicy units
# ---------------------------------------------------------------------------


def test_retry_backoff_schedule():
    p = RetryPolicy(
        max_attempts=5,
        backoff_s=0.1,
        backoff_mult=2.0,
        max_backoff_s=0.5,
        jitter=0.0,
    )
    # exponential, capped, one delay per retry (attempts - 1)
    assert p.schedule() == pytest.approx([0.1, 0.2, 0.4, 0.5])
    # jitter adds AT MOST the configured fraction, deterministically
    # under a seed
    pj = RetryPolicy(
        max_attempts=3, backoff_s=0.1, jitter=0.5, seed=7
    )
    d0, d1 = pj.schedule(), pj.schedule()
    assert d0 == d1  # seeded → reproducible
    for base, d in zip([0.1, 0.2], d0):
        assert base <= d <= base * 1.5


def test_retry_call_retries_then_succeeds_then_raises():
    p = RetryPolicy(
        max_attempts=3, backoff_s=0.001, jitter=0.0
    )
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise TimeoutError("transient")
        return "ok"

    assert p.call(flaky) == "ok"
    assert calls["n"] == 3

    # budget exhausted → the last error propagates
    with pytest.raises(TimeoutError):
        p.call(lambda: (_ for _ in ()).throw(TimeoutError("always")))

    # non-retryable errors propagate immediately (no backoff burn)
    calls["n"] = 0

    def fatal():
        calls["n"] += 1
        raise ValueError("bug")

    with pytest.raises(ValueError):
        p.call(fatal)
    assert calls["n"] == 1


def test_fault_spec_env_parsing():
    spec = _parse_env_spec(
        "kill_worker:2@3,4@1;nan_batch:@2;delay_sample:1@2x0.5;"
        "crash_learner:@7"
    )
    assert spec["kill_worker"] == [
        {"worker_index": 2, "on_call": 3},
        {"worker_index": 4, "on_call": 1},
    ]
    assert spec["nan_batch"] == {"on_learn_call": 2}
    assert spec["delay_sample"] == [
        {"worker_index": 1, "on_call": 2, "delay_s": 0.5}
    ]
    assert spec["crash_learner"] == {"on_learn_call": 7}


def test_fault_injector_nan_and_crash_fire_once():
    inj = FaultInjector(
        {
            "nan_batch": {"on_learn_call": 2},
            "crash_learner": {"on_learn_call": 4},
        }
    )
    b = {"adv": np.ones(4, np.float32)}
    inj.on_learn(b)
    assert batch_is_finite(b)  # call 1: untouched
    inj.on_learn(b)
    assert not batch_is_finite(b)  # call 2: poisoned
    b2 = {"adv": np.ones(4, np.float32)}
    inj.on_learn(b2)
    assert batch_is_finite(b2)  # call 3: nan fired once only
    with pytest.raises(InjectedCrash):
        inj.on_learn(b2)  # call 4
    inj.on_learn(b2)  # call 5: crash fired once only


# ---------------------------------------------------------------------------
# AsyncRequestsManager re-add + bounded probes
# ---------------------------------------------------------------------------


@ray.remote
class _Pingable:
    def __init__(self, ping_delay=0.0):
        self.delay = float(ping_delay)

    def ping(self):
        if self.delay:
            time.sleep(self.delay)
        return "pong"

    def sample(self):
        return 1


def test_manager_readd_clears_dead_mark_and_counts():
    """Satellite: a recreated worker re-added to the manager must get
    fresh in-flight slots and a cleared dead-mark (stale state from a
    freed id() would cap it at zero slots and eat its next death
    report)."""
    from ray_tpu.execution.parallel_requests import (
        AsyncRequestsManager,
    )

    if not ray.is_initialized():
        ray.init()
    w = _Pingable.remote()
    mgr = AsyncRequestsManager(
        [w], max_remote_requests_in_flight_per_worker=2
    )
    assert mgr.submit(worker=w) and mgr.submit(worker=w)
    mgr.report_dead(w)  # caller-observed death
    assert mgr.take_dead_workers() == [w]
    assert not mgr.submit(worker=w)  # out of rotation

    # the "replacement" is the same handle here — the point is the
    # bookkeeping reset, which id()-reuse makes indistinguishable
    mgr.add_workers([w])
    assert mgr.in_flight(w) == 0  # counters reset, not inherited
    assert mgr.submit(worker=w)  # full slot budget again
    mgr.report_dead(w)
    # dead-mark was cleared on re-add: the second death reports again
    assert mgr.take_dead_workers() == [w]


def test_probe_actors_bounded_by_single_budget():
    """Satellite: one wedged actor must cost the sweep at most the
    probe budget — not a per-worker timeout each."""
    if not ray.is_initialized():
        ray.init()
    ok = _Pingable.remote()
    wedged = _Pingable.remote(ping_delay=60.0)
    t0 = time.monotonic()
    bad = probe_actors([ok, wedged, ok], timeout_s=2.0)
    elapsed = time.monotonic() - t0
    assert bad == [1]
    assert elapsed < 10.0, f"sweep took {elapsed:.1f}s for a 2s budget"


# ---------------------------------------------------------------------------
# NaN guard: skip leaves params bit-identical
# ---------------------------------------------------------------------------


def _local_ppo(**ft):
    from ray_tpu.algorithms.ppo import PPOConfig

    return (
        PPOConfig()
        .environment("CartPole-v1")
        .rollouts(num_rollout_workers=0, rollout_fragment_length=64)
        .training(
            train_batch_size=128,
            sgd_minibatch_size=64,
            num_sgd_iter=2,
            lr=3e-4,
        )
        .fault_tolerance(**ft)
        .debugging(seed=1)
        .build()
    )


def _leaves(algo):
    import jax

    return [
        np.asarray(x).copy()
        for x in jax.tree_util.tree_leaves(
            algo.get_policy().get_weights()
        )
    ]


def test_nan_guard_skips_batch_params_bit_identical():
    """A poisoned learn batch is skipped: params after the skipped
    iteration are bit-identical to params before it (the clean run
    minus the skipped batch), and the skip is counted."""
    algo = _local_ppo(
        nan_guard=True,
        fault_injection={"nan_batch": {"on_learn_call": 2}},
    )
    try:
        algo.train()  # learn call 1: clean
        before = _leaves(algo)
        r2 = algo.train()  # learn call 2: poisoned → skipped
        after = _leaves(algo)
        assert r2["info"]["recovery"]["skipped_batches"] == 1
        assert r2["info"]["num_nan_batches_skipped"] == 1
        for a, b in zip(before, after):
            np.testing.assert_array_equal(a, b)
        r3 = algo.train()  # learn call 3: clean again, learning resumes
        assert r3["info"]["recovery"]["skipped_batches"] == 1
        assert any(
            not np.array_equal(a, b)
            for a, b in zip(after, _leaves(algo))
        )
    finally:
        algo.cleanup()


def test_without_nan_guard_poison_propagates():
    """Counter-proof that the guard is load-bearing: the same poisoned
    batch with nan_guard off drives the loss non-finite."""
    algo = _local_ppo(
        nan_guard=False,
        fault_injection={"nan_batch": {"on_learn_call": 1}},
    )
    try:
        r = algo.train()
        loss = r["info"]["learner"]["default_policy"]["total_loss"]
        assert not np.isfinite(loss)
    finally:
        algo.cleanup()


# ---------------------------------------------------------------------------
# checkpoint auto-restore + pruning
# ---------------------------------------------------------------------------


def test_auto_restore_from_checkpoint_and_prune(tmp_path):
    """An injected driver-side crash mid-train() restores the latest
    periodic checkpoint and continues; periodic checkpoints prune to
    keep_checkpoints_num."""
    import os

    root = str(tmp_path / "ckpts")
    algo = _local_ppo(
        checkpoint_frequency=1,
        checkpoint_root=root,
        keep_checkpoints_num=2,
        restore_on_failure=True,
        max_failures=3,
        fault_injection={"crash_learner": {"on_learn_call": 3}},
    )
    try:
        algo.train()  # learn 1, ckpt 1
        algo.train()  # learn 2, ckpt 2
        r3 = algo.train()  # learn 3 crashes → restore ckpt 2 → retry
        rec = r3["info"]["recovery"]
        assert rec["recoveries"].get("restore") == 1
        assert rec["failures"] == 1
        assert rec["time_lost_s_this_iter"] > 0.0
        assert np.isfinite(
            r3["info"]["learner"]["default_policy"]["total_loss"]
        )
        # pruned to the newest 2 periodic checkpoints
        ckpts = sorted(
            d
            for d in os.listdir(root)
            if d.startswith("checkpoint_")
        )
        assert len(ckpts) == 2
        # the restore target still exists on disk
        assert os.path.isdir(rec["latest_checkpoint"])
    finally:
        algo.cleanup()


def test_restore_without_checkpoint_propagates():
    """restore_on_failure without a checkpoint yet → the crash must
    surface, not be silently absorbed."""
    algo = _local_ppo(
        restore_on_failure=True,
        checkpoint_frequency=5,  # no checkpoint before the crash
        fault_injection={"crash_learner": {"on_learn_call": 1}},
    )
    try:
        with pytest.raises(InjectedCrash):
            algo.train()
    finally:
        algo.cleanup()


# ---------------------------------------------------------------------------
# chaos e2e
# ---------------------------------------------------------------------------


@pytest.mark.slow  # PR-1 budget rule: 23 s; every failure mode it
# composes (worker kill + probe/recreate, nan-batch skip, recovery
# counters) keeps tier-1 coverage via the individual tests above
def test_chaos_e2e_kill_two_of_four_workers_and_nan_batch():
    """The acceptance scenario: FaultInjector kills 2 of 4 rollout
    workers and poisons one learn batch mid-PPO-run; ``train()`` must
    complete without a driver crash, the fleet must be restored to
    full size (replacements disarmed — they don't re-die), and the
    recovery counts must land in ``info/recovery`` AND the Prometheus
    scrape."""
    from ray_tpu.algorithms.ppo import PPOConfig
    from ray_tpu.telemetry import metrics as tm

    restarts0 = tm.counter_total(tm.WORKER_RESTARTS_TOTAL)
    skipped0 = tm.counter_total(tm.SKIPPED_BATCHES_TOTAL)
    algo = (
        PPOConfig()
        .environment("CartPole-v1")
        .rollouts(num_rollout_workers=4, rollout_fragment_length=32)
        .training(
            train_batch_size=128,
            sgd_minibatch_size=64,
            num_sgd_iter=2,
            lr=3e-4,
        )
        .fault_tolerance(
            recreate_failed_workers=True,
            nan_guard=True,
            max_failures=10,
            worker_health_probe_timeout_s=10.0,
            fault_injection={
                "kill_worker": [
                    {"worker_index": 2, "on_call": 2},
                    {"worker_index": 3, "on_call": 3},
                ],
                "nan_batch": {"on_learn_call": 2},
            },
        )
        .telemetry(metrics_port=0)
        .debugging(seed=1)
        .build()
    )
    try:
        last = {}
        for _ in range(4):
            last = algo.train()  # must never raise
        rec = last["info"]["recovery"]
        assert algo.workers.num_remote_workers() == 4, (
            "fleet not restored"
        )
        assert rec["worker_restarts"] >= 2
        assert rec["skipped_batches"] == 1
        assert rec["time_lost_s"] > 0.0
        assert np.isfinite(
            last["info"]["learner"]["default_policy"]["total_loss"]
        )
        assert (
            tm.counter_total(tm.WORKER_RESTARTS_TOTAL) - restarts0
            >= 2
        )
        assert (
            tm.counter_total(tm.SKIPPED_BATCHES_TOTAL) - skipped0
            == 1
        )
        # the same counts must be scrapeable (acceptance: Prometheus
        # reports the restarts/recoveries/skipped-batch counts)
        port = algo._telemetry.metrics_port
        scrape = (
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10
            )
            .read()
            .decode()
        )
        assert "ray_tpu_worker_restarts_total" in scrape
        assert "ray_tpu_skipped_batches_total" in scrape
        assert 'ray_tpu_recoveries_total{kind="workers"}' in scrape
    finally:
        algo.cleanup()
