"""PPO end-to-end tests (reference rllib/algorithms/ppo/tests/test_ppo.py
and the CartPole learning regression
``tuned_examples/ppo/cartpole-ppo.yaml``)."""

import jax
import numpy as np
import pytest

from ray_tpu.algorithms.ppo import PPO, PPOConfig
from ray_tpu.data.sample_batch import SampleBatch


def small_config(**training):
    cfg = (
        PPOConfig()
        .environment("CartPole-v1")
        .rollouts(num_rollout_workers=0, rollout_fragment_length=128)
        .training(
            train_batch_size=512,
            sgd_minibatch_size=128,
            num_sgd_iter=4,
            lr=3e-4,
            **training,
        )
        .debugging(seed=1)
    )
    return cfg


def test_ppo_compilation_and_step():
    algo = small_config().build()
    result = algo.train()
    assert result["training_iteration"] == 1
    assert result["num_env_steps_sampled"] >= 512
    learner = result["info"]["learner"]["default_policy"]
    assert "total_loss" in learner
    assert np.isfinite(learner["total_loss"])
    assert "kl" in learner and "cur_kl_coeff" in learner
    algo.cleanup()


def test_ppo_compute_single_action():
    algo = small_config().build()
    env_creator = None
    import gymnasium as gym

    env = gym.make("CartPole-v1")
    obs, _ = env.reset(seed=0)
    a = algo.compute_single_action(obs)
    assert env.action_space.contains(int(a))
    algo.cleanup()


def test_ppo_checkpoint_restore(tmp_path):
    """reference rllib/tests/test_checkpoint_restore.py."""
    algo = small_config().build()
    algo.train()
    ckpt = algo.save(str(tmp_path / "ckpt"))
    w_before = algo.get_policy().get_weights()

    algo2 = small_config().build()
    algo2.restore(ckpt)
    w_after = algo2.get_policy().get_weights()

    import jax

    flat1 = jax.tree_util.tree_leaves(w_before)
    flat2 = jax.tree_util.tree_leaves(w_after)
    for a, b in zip(flat1, flat2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))
    algo.cleanup()
    algo2.cleanup()


@pytest.mark.slow
def test_ppo_cartpole_learns():
    """Learning regression: reward must improve substantially within a
    small number of iterations (scaled-down version of
    tuned_examples/ppo/cartpole-ppo.yaml: reward 150 within 100k steps)."""
    algo = (
        PPOConfig()
        .environment("CartPole-v1")
        .rollouts(
            num_rollout_workers=0,
            rollout_fragment_length=256,
            num_envs_per_worker=4,
        )
        .training(
            train_batch_size=2048,
            sgd_minibatch_size=256,
            num_sgd_iter=8,
            lr=3e-4,
            entropy_coeff=0.01,
            gamma=0.99,
            lambda_=0.95,
            clip_param=0.2,
            kl_coeff=0.0,
        )
        .debugging(seed=7)
        .build()
    )
    best = -np.inf
    for i in range(25):
        result = algo.train()
        mean_r = result.get("episode_reward_mean", np.nan)
        if np.isfinite(mean_r):
            best = max(best, mean_r)
        if best >= 150.0:
            break
    algo.cleanup()
    assert best >= 150.0, f"PPO failed to learn CartPole: best={best}"


@pytest.mark.slow  # ~10 s on this container; moved out of
# tier-1 with PR 12 (budget rule: suite at ~892 s vs the 870 s cap)
def test_ppo_with_remote_workers():
    cfg = (
        PPOConfig()
        .environment("CartPole-v1")
        .rollouts(num_rollout_workers=2, rollout_fragment_length=64)
        .training(
            train_batch_size=256, sgd_minibatch_size=64, num_sgd_iter=2
        )
        .debugging(seed=3)
    )
    algo = cfg.build()
    result = algo.train()
    assert result["num_env_steps_sampled"] >= 256
    algo.cleanup()


def test_evaluate_syncs_filters_and_uses_remote_eval_workers():
    """ADVICE r1: evaluation must sync MeanStd filter stats (not just
    weights) and actually use the remote eval workers it creates."""
    from ray_tpu.algorithms.ppo import PPO, PPOConfig

    algo = (
        PPOConfig()
        .environment("CartPole-v1")
        .rollouts(
            num_rollout_workers=0,
            rollout_fragment_length=64,
            observation_filter="MeanStdFilter",
        )
        .training(
            train_batch_size=128, sgd_minibatch_size=64, num_sgd_iter=2
        )
        .evaluation(
            evaluation_interval=1,
            evaluation_duration=2,
            evaluation_num_workers=1,
        )
        .debugging(seed=0)
        .build()
    )
    algo.train()
    ev = algo.evaluate()
    assert "episode_reward_mean" in ev
    assert np.isfinite(ev["episode_reward_mean"])
    # local eval worker's filter received the training statistics
    train_filt = algo.workers.local_worker().get_filters()
    eval_filt = algo.evaluation_workers.local_worker().get_filters()
    assert train_filt, "MeanStdFilter expected on the training worker"
    for pid, f in train_filt.items():
        # eval filter received the training statistics (>= because eval
        # sampling may have pushed more into its own copy since)
        assert eval_filt[pid].rs.num >= f.rs.num > 0
    algo.cleanup()


@pytest.mark.slow  # ~9 s full save/rebuild cycle; moved out of tier-1
# by the PR-1 budget rule — tier-1 keeps test_ppo_checkpoint_restore
# (same save/restore machinery, explicit class)
def test_from_checkpoint_rebuilds_without_class(tmp_path):
    """Algorithm.from_checkpoint resolves the concrete class and
    config from checkpoint metadata alone (reference
    Algorithm.from_checkpoint, algorithm.py:315)."""
    from ray_tpu.algorithms.algorithm import Algorithm
    from ray_tpu.algorithms.registry import get_algorithm_class

    PPO = get_algorithm_class("PPO")
    algo = PPO(config={
        "env": "CartPole-v1",
        "train_batch_size": 256,
        "sgd_minibatch_size": 128,
        "num_workers": 0,
    })
    algo.train()
    w0 = algo.get_policy().get_weights()
    algo.save_checkpoint(str(tmp_path))
    algo.cleanup()

    algo2 = Algorithm.from_checkpoint(str(tmp_path))
    try:
        assert type(algo2).__name__ == "PPO"
        assert algo2.config["train_batch_size"] == 256
        import numpy as np

        w1 = algo2.get_policy().get_weights()
        trees_equal = all(
            np.allclose(a, b)
            for a, b in zip(
                jax.tree_util.tree_leaves(w0),
                jax.tree_util.tree_leaves(w1),
            )
        )
        assert trees_equal
        algo2.train()  # restored instance keeps training
    finally:
        algo2.cleanup()
