"""Durable control plane: KV/job-table persistence and driver-restart
resume (reference ``python/ray/tests/test_gcs_fault_tolerance.py``; the
storage seam mirrors ``gcs/store_client/redis_store_client.h:27`` with
sqlite as the single-coordinator durable backend)."""

import json
import os
import pathlib
import signal
import subprocess
import sys
import time

import pytest

REPO = pathlib.Path(__file__).resolve().parents[1]


def test_sqlite_store_roundtrip(tmp_path):
    from ray_tpu.core.store_client import SqliteStoreClient

    path = str(tmp_path / "gcs.db")
    s = SqliteStoreClient(path)
    s.put("kv", "a", b"1")
    s.put("kv", "a", b"2")  # upsert
    s.put("jobs", "a", b"job-a")  # same key, different table
    assert s.get("kv", "a") == b"2"
    assert s.get("jobs", "a") == b"job-a"
    assert s.get("kv", "missing") is None
    s.delete("kv", "a")
    assert s.get("kv", "a") is None
    s.close()
    # reopen: jobs table survived
    s2 = SqliteStoreClient(path)
    assert s2.all("jobs") == {"a": b"job-a"}
    s2.close()


def test_kv_server_restart_keeps_keys(tmp_path):
    from ray_tpu.parallel.distributed import KVClient, KVServer

    path = str(tmp_path / "kv.db")
    srv = KVServer(persist_path=path)
    cli = KVClient(f"127.0.0.1:{srv.port}")
    cli.put("weights/7", {"step": 7})
    cli.put("leader", "host-a")
    srv.shutdown()  # driver death

    srv2 = KVServer(persist_path=path)  # restarted coordinator
    cli2 = KVClient(f"127.0.0.1:{srv2.port}")
    assert cli2.get("weights/7") == {"step": 7}
    assert cli2.get("leader") == "host-a"
    # heartbeats are volatile by design: liveness re-proven, not loaded
    assert cli2.alive_nodes() == {}
    srv2.shutdown()


def test_job_table_survives_driver(tmp_path):
    """A finished driver's job record is readable by the next driver
    (the gcs_job_manager table role)."""
    path = str(tmp_path / "state.db")
    script = f"""
import ray_tpu.core.api as ray
ray.init(state_path={path!r})
@ray.remote
class Reg:
    def ping(self):
        return 1
a = Reg.options(name="survivor").remote()
assert ray.get(a.ping.remote()) == 1
ray.shutdown()
"""
    sub = subprocess.run(
        [sys.executable, "-c", script],
        cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert sub.returncode == 0, sub.stderr[-2000:]

    from ray_tpu.core.api import list_jobs
    from ray_tpu.core.store_client import SqliteStoreClient

    jobs = list_jobs(state_path=path)
    assert len(jobs) == 1 and jobs[0]["status"] == "FINISHED"
    store = SqliteStoreClient(path)
    actors = {
        k: json.loads(v.decode())
        for k, v in store.all("actors").items()
    }
    store.close()
    assert actors["survivor"]["class"] == "Reg"


_RESUME_DRIVER = """
import sys
import ray_tpu.tune.tune as tune
from ray_tpu.tune.trainable import Trainable

class Slow(Trainable):
    def setup(self, config):
        self.x = 0
    def step(self):
        import time
        time.sleep(0.4)
        self.x += 1
        # per-run step tally: lets the test prove the resumed run did
        # NOT redo the first run's iterations
        with open(sys.argv[2], "a") as f:
            f.write("S")
        return {"episode_reward_mean": float(self.x)}
    def save_checkpoint(self, d):
        import json, os
        with open(os.path.join(d, "x.json"), "w") as f:
            json.dump({"x": self.x}, f)
        return d
    def load_checkpoint(self, d):
        import json, os
        with open(os.path.join(d, "x.json")) as f:
            self.x = json.load(f)["x"]

ana = tune.run(
    Slow,
    config={},
    num_samples=2,
    max_iterations=12,
    checkpoint_freq=1,
    local_dir=sys.argv[1],
    name="resume_exp",
    parallel=False,
    resume=("--resume" in sys.argv),
    verbose=0,
)
for t in ana.trials:
    print("TRIAL", t.trial_id, t.status,
          t.last_result.get("training_iteration"))
"""


@pytest.mark.regression
@pytest.mark.slow  # PR-1 budget rule: 10 s; checkpoint auto-restore
# keeps tier-1 coverage via test_resilience.py (crash→restore
# roundtrip) and test_elastic.py (stream-tail restore bound)
def test_tune_driver_kill_and_resume(tmp_path):
    """Kill the driver mid-experiment (SIGKILL, no cleanup); a resumed
    driver finishes from the checkpoints instead of restarting at
    iteration 0 (reference trial_runner.py checkpoint()/resume() +
    test_gcs_fault_tolerance-style kill)."""
    local_dir = str(tmp_path)
    driver = str(tmp_path / "driver.py")
    with open(driver, "w") as f:
        f.write(_RESUME_DRIVER)
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        # the driver script lives in tmp_path: python puts the SCRIPT
        # dir (not cwd) on sys.path, so the repo must come via
        # PYTHONPATH (preserving the image's site entries)
        "PYTHONPATH": f"{REPO}:{os.environ.get('PYTHONPATH', '')}",
    }
    steps1 = str(tmp_path / "steps_run1")
    steps2 = str(tmp_path / "steps_run2")
    p = subprocess.Popen(
        [sys.executable, driver, local_dir, steps1],
        cwd=REPO,
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
    )
    # let it make progress past several checkpoints, then hard-kill
    state = pathlib.Path(local_dir) / "resume_exp" / "experiment_state.pkl"
    deadline = time.time() + 120
    while time.time() < deadline and not state.exists():
        time.sleep(0.5)
    assert state.exists(), "experiment never wrote durable state"
    time.sleep(3.0)
    p.send_signal(signal.SIGKILL)
    p.wait(timeout=30)

    out = subprocess.run(
        [sys.executable, driver, local_dir, steps2, "--resume"],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    lines = [
        ln for ln in out.stdout.splitlines() if ln.startswith("TRIAL")
    ]
    assert len(lines) == 2, out.stdout
    for ln in lines:
        _, tid, status, iters = ln.split()
        assert status == "TERMINATED", ln
        assert int(iters) == 12, ln
    # continuation proof: the killed run made progress, and the resumed
    # run did strictly fewer than the full 2 x 12 iterations — it
    # picked up from the checkpoints rather than restarting at 0
    done1 = len(pathlib.Path(steps1).read_text())
    done2 = len(pathlib.Path(steps2).read_text())
    assert done1 >= 2, f"first driver made no progress ({done1})"
    assert done2 < 24, (
        f"resumed driver redid everything ({done2} steps)"
    )
    import pickle

    saved = pickle.loads(state.read_bytes())
    assert all(s["status"] == "TERMINATED" for s in saved.values())


def test_kv_hmac_token_gate():
    """With a shared token configured, unauthenticated requests are
    rejected and token-bearing clients work (the cheap second wall for
    non-loopback KV deployments)."""
    from ray_tpu.parallel.distributed import KVClient, KVServer

    srv = KVServer(token="s3cret")
    try:
        good = KVClient(f"127.0.0.1:{srv.port}", token="s3cret")
        good.put("k", 1)
        assert good.get("k") == 1

        bad = KVClient(f"127.0.0.1:{srv.port}", token="wrong")
        with pytest.raises(Exception):
            bad.get("k", timeout=1.0)
        naked = KVClient(f"127.0.0.1:{srv.port}", token=None)
        naked.token = None
        with pytest.raises(Exception):
            naked.get("k", timeout=1.0)
    finally:
        srv.shutdown()
