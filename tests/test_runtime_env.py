"""runtime_env provisioning: env_vars, working_dir, py_modules
(reference ``python/ray/_private/runtime_env/`` plugins + URI cache)."""

import os
import pathlib
import subprocess
import sys

import pytest

import ray_tpu.core.api as ray
from ray_tpu.core.runtime_env import (
    _cache_root,
    pack_runtime_env,
)

REPO = pathlib.Path(__file__).resolve().parents[1]


def _make_working_dir(tmp_path):
    wd = tmp_path / "proj"
    wd.mkdir()
    (wd / "data.txt").write_text("hello from working_dir")
    (wd / "helper.py").write_text("VALUE = 41\n")
    return str(wd)


def test_pack_rejects_unknown_keys(tmp_path):
    with pytest.raises(ValueError, match="conda"):
        pack_runtime_env({"conda": {"deps": ["x"]}})
    assert pack_runtime_env(None) is None
    assert pack_runtime_env({}) is None


def test_actor_env_vars_and_working_dir(tmp_path):
    wd = _make_working_dir(tmp_path)

    @ray.remote
    class Probe:
        def read(self):
            # working_dir semantics: relative paths resolve there and
            # local modules import
            import helper

            with open("data.txt") as f:
                return (
                    f.read(),
                    helper.VALUE,
                    os.environ.get("MY_FLAG"),
                )

    a = Probe.options(
        runtime_env={
            "working_dir": wd,
            "env_vars": {"MY_FLAG": "on"},
        }
    ).remote()
    text, value, flag = ray.get(a.read.remote())
    assert text == "hello from working_dir"
    assert value == 41
    assert flag == "on"
    ray.kill(a)


def test_task_env_vars_restore_between_tasks(tmp_path):
    @ray.remote
    def get_flag():
        return os.environ.get("TASK_FLAG")

    with_env = get_flag.options(
        runtime_env={"env_vars": {"TASK_FLAG": "set"}}
    )
    assert ray.get(with_env.remote()) == "set"
    # pooled workers restore env vars after the task
    assert ray.get(get_flag.remote()) is None


def test_py_modules_importable(tmp_path):
    pkg = tmp_path / "mylib"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("def answer():\n    return 42\n")

    @ray.remote
    def use_pkg():
        import mylib

        return mylib.answer()

    out = ray.get(
        use_pkg.options(
            runtime_env={"py_modules": [str(pkg)]}
        ).remote()
    )
    assert out == 42


def test_archive_cache_is_content_addressed(tmp_path):
    wd = _make_working_dir(tmp_path)
    packed1 = pack_runtime_env({"working_dir": wd})
    packed2 = pack_runtime_env({"working_dir": wd})
    # second pack hits the zip cache: identical content hash
    h1 = packed1["archives"][0]["hash"]
    assert packed2["archives"][0]["hash"] == h1
    # changing content changes the hash
    with open(os.path.join(wd, "data.txt"), "a") as f:
        f.write("!")
    os.utime(wd)
    packed3 = pack_runtime_env({"working_dir": wd})
    assert packed3["archives"][0]["hash"] != h1


def test_job_level_runtime_env(tmp_path):
    """ray.init(runtime_env=...) reaches every worker (subprocess: the
    pytest session's runtime is already initialized)."""
    wd = _make_working_dir(tmp_path)
    script = f"""
import os
import ray_tpu.core.api as ray

if __name__ == "__main__":
    ray.init(num_cpus=2, runtime_env={{
        "working_dir": {wd!r},
        "env_vars": {{"JOB_FLAG": "yes"}},
    }})

    @ray.remote
    def probe():
        import helper
        with open("data.txt") as f:
            return f.read(), helper.VALUE, os.environ["JOB_FLAG"]

    text, value, flag = ray.get(probe.remote())
    assert text.startswith("hello"), text
    assert value == 41 and flag == "yes"
    print("JOB_ENV_OK")
    ray.shutdown()
"""
    driver = tmp_path / "driver.py"
    driver.write_text(script)
    out = subprocess.run(
        [sys.executable, str(driver)],
        cwd=REPO,
        env={
            **os.environ,
            "JAX_PLATFORMS": "cpu",
            "PYTHONPATH": f"{REPO}:{os.environ.get('PYTHONPATH', '')}",
        },
        capture_output=True,
        text=True,
        timeout=180,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "JOB_ENV_OK" in out.stdout
