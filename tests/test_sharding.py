"""ray_tpu.sharding runtime tests (ISSUE 2).

All run on the 8-device simulated CPU platform conftest.py forces
(``--xla_force_host_platform_device_count=8``): mesh construction and
caching, spec builders incl. the ragged-leading-dim fallback, donation,
compile-cache stats, and mesh/pmap backend parity on a fixed-seed PPO
learn step.
"""

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu import sharding as sl
from ray_tpu.data.sample_batch import SampleBatch


# ---------------------------------------------------------------------------
# mesh
# ---------------------------------------------------------------------------


def test_mesh_default_is_1d_batch_over_all_devices():
    mesh = sl.get_mesh()
    assert mesh.axis_names == ("batch",)
    assert sl.data_axis(mesh) == "batch"
    assert sl.num_shards(mesh) == len(jax.devices()) == 8


def test_mesh_is_cached_per_process():
    assert sl.get_mesh() is sl.get_mesh()
    sub = sl.get_mesh(devices=jax.devices()[:4])
    assert sub is sl.get_mesh(devices=jax.devices()[:4])
    assert sub is not sl.get_mesh()
    assert sl.num_shards(sub) == 4


def test_mesh_axis_shapes_and_oversubscription():
    mesh = sl.get_mesh(axis_shapes=[("batch", 4), ("model", 2)])
    assert mesh.axis_names == ("batch", "model")
    assert dict(mesh.shape) == {"batch": 4, "model": 2}
    with pytest.raises(ValueError):
        sl.get_mesh(axis_shapes=[("batch", 16)])


def test_legacy_parallel_adapter_keeps_data_axis():
    from ray_tpu.parallel import mesh as legacy

    mesh = legacy.make_mesh()
    assert mesh.axis_names == ("data",)
    # the adapter helpers derive the axis from the mesh, so they also
    # accept the runtime's ("batch",) meshes
    assert legacy.num_data_shards(sl.get_mesh()) == 8
    spec = legacy.data_sharding(sl.get_mesh()).spec
    assert tuple(spec) == ("batch",)


def test_resolve_mesh_backend_selection():
    assert sl.resolve_mesh({}).axis_names == ("batch",)
    assert sl.resolve_mesh(
        {"sharding_backend": "pmap"}
    ).axis_names == ("data",)
    injected = sl.get_mesh(devices=jax.devices()[:2])
    assert sl.resolve_mesh({"_mesh": injected}) is injected


# ---------------------------------------------------------------------------
# specs
# ---------------------------------------------------------------------------


def test_leaf_sharding_ragged_fallback():
    mesh = sl.get_mesh()
    even = np.zeros((16, 3), np.float32)
    ragged = np.zeros((13, 3), np.float32)  # 13 % 8 != 0
    scalar = np.float32(1.0)
    assert tuple(sl.leaf_sharding(even, mesh).spec) == ("batch",)
    assert tuple(sl.leaf_sharding(ragged, mesh).spec) == ()
    assert tuple(sl.leaf_sharding(scalar, mesh).spec) == ()


def test_sharding_tree_per_leaf_and_replicate_keys():
    mesh = sl.get_mesh()
    tree = {
        "rows": np.zeros((32, 4), np.float32),
        "ragged": np.zeros((9,), np.float32),
        "pool": np.zeros((16, 8), np.float32),
    }
    specs = sl.sharding_tree(tree, mesh, replicate_keys=("pool",))
    assert tuple(specs["rows"].spec) == ("batch",)
    assert tuple(specs["ragged"].spec) == ()
    # divisible but pinned replicated by key
    assert tuple(specs["pool"].spec) == ()


def test_shard_batch_places_rows_across_devices():
    mesh = sl.get_mesh()
    dev = sl.shard_batch(
        {"x": np.arange(64, dtype=np.float32)}, mesh, block=True
    )
    x = dev["x"]
    assert x.sharding.is_equivalent_to(sl.batch_sharded(mesh), x.ndim)
    assert len(x.addressable_shards) == 8
    assert x.addressable_shards[0].data.shape == (8,)


# ---------------------------------------------------------------------------
# compile (sharded_jit)
# ---------------------------------------------------------------------------


def test_sharded_jit_donation_releases_buffers():
    mesh = sl.get_mesh()
    rep = sl.replicated(mesh)
    fn = sl.sharded_jit(
        lambda x: x * 2.0,
        in_specs=(rep,),
        out_specs=rep,
        donate_argnums=(0,),
    )
    x = jax.device_put(jnp.ones((128,)), rep)
    y = fn(x)
    assert x.is_deleted()  # donated into the output
    assert not y.is_deleted()
    np.testing.assert_allclose(np.asarray(y), 2.0)


def test_sharded_jit_compile_cache_stats():
    mesh = sl.get_mesh()
    dat = sl.batch_sharded(mesh)
    fn = sl.sharded_jit(
        lambda x: x.sum(), in_specs=(dat,), label="sum"
    )
    a = jax.device_put(jnp.ones((16,)), dat)
    fn(a)
    assert fn.stats()["traces"] == 1
    fn(a)  # same shape: cache hit
    assert fn.traces == 1 and fn.recompiles == 0 and fn.calls == 2
    fn(jax.device_put(jnp.ones((32,)), dat))  # new shape: retrace
    assert fn.traces == 2 and fn.recompiles == 1
    agg = sl.compile_stats()
    assert agg["calls"] >= 3
    assert any(
        s["label"] == "sum" for s in agg["per_function"]
    )


# ---------------------------------------------------------------------------
# backend parity: fixed-seed PPO learn step, mesh vs pmap
# ---------------------------------------------------------------------------


def _ppo_policy(backend, n_dev):
    from ray_tpu.algorithms.ppo.ppo import PPOJaxPolicy
    from ray_tpu.parallel import mesh as legacy

    devs = jax.devices()[:n_dev]
    mesh = (
        sl.get_mesh(devices=devs)
        if backend == "mesh"
        else legacy.make_mesh(devices=devs)
    )
    return PPOJaxPolicy(
        gym.spaces.Box(-1.0, 1.0, (8,), np.float32),
        gym.spaces.Discrete(4),
        {
            "_mesh": mesh,
            "sharding_backend": backend,
            "model": {"fcnet_hiddens": [16]},
            "train_batch_size": 32,
            "sgd_minibatch_size": 16,
            "num_sgd_iter": 2,
            "lr": 1e-3,
            "seed": 0,
        },
    )


def _ppo_batch(b=32):
    rng = np.random.default_rng(42)
    return SampleBatch(
        {
            SampleBatch.OBS: rng.standard_normal((b, 8)).astype(
                np.float32
            ),
            SampleBatch.ACTIONS: rng.integers(0, 4, b).astype(
                np.int64
            ),
            SampleBatch.ACTION_LOGP: np.full(b, -1.4, np.float32),
            SampleBatch.ACTION_DIST_INPUTS: rng.standard_normal(
                (b, 4)
            ).astype(np.float32),
            SampleBatch.ADVANTAGES: rng.standard_normal(b).astype(
                np.float32
            ),
            SampleBatch.VALUE_TARGETS: rng.standard_normal(b).astype(
                np.float32
            ),
        }
    )


@pytest.mark.parametrize("n_dev", [1, 8])
def test_mesh_pmap_parity_fixed_seed_ppo(n_dev):
    """Acceptance: with sharding_backend="mesh" a fixed-seed PPO
    learn_on_batch is numerically identical to the pmap backend —
    bitwise, on 1 device AND on 8 simulated host devices — and the
    compiled program does not retrace across constant-shape steps."""
    results = {}
    for backend in ("mesh", "pmap"):
        pol = _ppo_policy(backend, n_dev)
        pol.learn_on_batch(_ppo_batch())
        stats = pol.learn_on_batch(_ppo_batch())
        fn = pol.learn_fn(32)
        assert fn.traces == 1 and fn.recompiles == 0, backend
        # mesh backend: batch really lands sharded over "batch"
        if backend == "mesh" and n_dev == 8:
            assert sl.data_axis(pol.mesh) == "batch"
            assert pol.n_shards == 8
        results[backend] = (stats, jax.device_get(pol.params))
    s_mesh, w_mesh = results["mesh"]
    s_pmap, w_pmap = results["pmap"]
    assert s_mesh["total_loss"] == s_pmap["total_loss"]
    for a, b in zip(
        jax.tree_util.tree_leaves(w_mesh),
        jax.tree_util.tree_leaves(w_pmap),
    ):
        np.testing.assert_array_equal(a, b)


def test_learn_timers_and_train_results(tmp_path):
    """Per-stage learner timers ride the policy and train() results;
    save_checkpoint survives (and is atomic — temp names never leak)."""
    import os

    from ray_tpu.algorithms.ppo import PPOConfig

    algo = (
        PPOConfig()
        .environment("CartPole-v1")
        .rollouts(num_rollout_workers=0, rollout_fragment_length=64)
        .training(
            train_batch_size=128,
            sgd_minibatch_size=64,
            num_sgd_iter=2,
            lr=3e-4,
        )
        .debugging(seed=0)
        .build()
    )
    result = algo.train()
    timers = result["info"]["timers"]["default_policy"]
    assert timers["learn_transfer_s"] >= 0.0
    assert timers["learn_step_s"] > 0.0
    assert timers["learn_compile_s"] > 0.0  # first step compiled
    assert timers["learn_recompiles"] == 1.0
    result = algo.train()
    timers = result["info"]["timers"]["default_policy"]
    assert timers["learn_compile_s"] == 0.0  # steady state: cache hit
    assert timers["learn_recompiles"] == 0.0
    # the same stages are exported as metrics series
    from ray_tpu.utils.metrics import get_metric

    for name in (
        "ray_tpu_learner_step_seconds",
        "ray_tpu_learner_transfer_seconds",
        "ray_tpu_learner_total_seconds",
    ):
        m = get_metric(name)
        assert m is not None and m.series(), name
    ckpt = str(tmp_path / "ckpt")
    os.makedirs(ckpt, exist_ok=True)
    algo.save_checkpoint(ckpt)
    names = sorted(os.listdir(ckpt))
    assert "algorithm_state.pkl" in names
    assert "rllib_checkpoint.json" in names
    assert not [n for n in names if ".tmp." in n]
    algo.cleanup()
