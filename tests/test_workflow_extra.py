"""Workflow step options, continuations, and management API
(reference ``python/ray/workflow/tests``: test_basic_workflows
retry/catch cases, test_dag continuation, management API tests)."""

import pytest

from ray_tpu import workflow


def test_step_retries_until_success(tmp_path):
    calls = {"n": 0}

    @workflow.step
    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise RuntimeError("transient")
        return "ok"

    out = workflow.run(
        flaky.options(max_retries=3, retry_delay_s=0.01).bind(),
        workflow_id="wf_retry",
        storage=str(tmp_path),
    )
    assert out == "ok" and calls["n"] == 3


def test_catch_exceptions_returns_pair(tmp_path):
    @workflow.step
    def boom():
        raise ValueError("nope")

    @workflow.step
    def fine():
        return 7

    v, err = workflow.run(
        boom.options(catch_exceptions=True).bind(),
        workflow_id="wf_catch1",
        storage=str(tmp_path),
    )
    assert v is None and isinstance(err, ValueError)
    v, err = workflow.run(
        fine.options(catch_exceptions=True).bind(),
        workflow_id="wf_catch2",
        storage=str(tmp_path),
    )
    assert v == 7 and err is None


def test_exhausted_retries_fail_workflow(tmp_path):
    @workflow.step
    def always():
        raise RuntimeError("permanent")

    with pytest.raises(RuntimeError, match="permanent"):
        workflow.run(
            always.options(max_retries=1, retry_delay_s=0.01).bind(),
            workflow_id="wf_fail",
            storage=str(tmp_path),
        )
    assert workflow.get_status("wf_fail", str(tmp_path)) == "FAILED"


def test_dynamic_continuation(tmp_path):
    """A step returning a StepNode continues the workflow (reference
    workflow.continuation); recursion checkpoints each hop."""

    @workflow.step
    def countdown(n):
        if n == 0:
            return "liftoff"
        return countdown.bind(n - 1)

    out = workflow.run(
        countdown.bind(3),
        workflow_id="wf_cont",
        storage=str(tmp_path),
    )
    assert out == "liftoff"
    # each recursion level checkpointed (4 ids: n=3..0)
    assert len(workflow.run.last_execution.steps_run) == 4


def test_management_api_and_resume_by_id(tmp_path):
    calls = {"n": 0}

    @workflow.step
    def work(x):
        calls["n"] += 1
        return x * 2

    out = workflow.run(
        work.bind(21), workflow_id="wf_mgmt", storage=str(tmp_path)
    )
    assert out == 42
    assert ("wf_mgmt", "SUCCEEDED") in workflow.list_all(str(tmp_path))
    assert workflow.get_status("wf_mgmt", str(tmp_path)) == "SUCCEEDED"
    assert workflow.get_output("wf_mgmt", str(tmp_path)) == 42
    # resume by id alone: stored DAG, cached steps -> no re-execution
    assert workflow.resume("wf_mgmt", str(tmp_path)) == 42
    assert calls["n"] == 1
    with pytest.raises(ValueError):
        workflow.resume("no_such_wf", str(tmp_path))


def test_cancel_stops_before_next_step(tmp_path):
    @workflow.step
    def first():
        # cancel mid-flight: the NEXT step must not start
        workflow.cancel("wf_cancel", str(tmp_path))
        return 1

    @workflow.step
    def second(x):
        raise AssertionError("must not run")

    with pytest.raises(workflow.WorkflowCanceledError):
        workflow.run(
            second.bind(first.bind()),
            workflow_id="wf_cancel",
            storage=str(tmp_path),
        )
    assert workflow.get_status("wf_cancel", str(tmp_path)) == "CANCELED"


def test_catch_exceptions_with_continuation(tmp_path):
    """A step with catch_exceptions=True returning a continuation must
    execute the continuation, not checkpoint the raw StepNode."""

    @workflow.step
    def tail(x):
        return x + 1

    @workflow.step
    def head():
        return tail.bind(10)  # dynamic continuation

    value, err = workflow.run(
        head.options(catch_exceptions=True).bind(),
        workflow_id="wf_catch_cont",
        storage=str(tmp_path),
    )
    assert err is None
    assert value == 11


def test_failing_continuation_under_catch_exceptions(tmp_path):
    """catch_exceptions covers the whole continuation chain: a failing
    continuation yields (None, err), it does not raise."""

    @workflow.step
    def bad_tail(x):
        raise RuntimeError("tail broke")

    @workflow.step
    def head():
        return bad_tail.bind(1)

    value, err = workflow.run(
        head.options(catch_exceptions=True).bind(),
        workflow_id="wf_catch_bad_cont",
        storage=str(tmp_path),
    )
    assert value is None
    assert isinstance(err, RuntimeError)


def test_cancel_unknown_workflow_raises(tmp_path):
    # canceling a never-run id would brick it (run refuses CANCELED,
    # resume has no DAG) — so cancel only accepts known workflows
    with pytest.raises(ValueError):
        workflow.cancel("wf_never_ran", str(tmp_path))


def test_canceled_workflow_needs_explicit_resume(tmp_path):
    calls = {"n": 0}

    @workflow.step
    def work():
        calls["n"] += 1
        return calls["n"]

    assert (
        workflow.run(
            work.bind(), workflow_id="wf_recancel", storage=str(tmp_path)
        )
        == 1
    )
    workflow.cancel("wf_recancel", str(tmp_path))
    # a fresh run() of a CANCELED id refuses...
    with pytest.raises(workflow.WorkflowCanceledError):
        workflow.run(
            work.bind(), workflow_id="wf_recancel", storage=str(tmp_path)
        )
    # ...but an explicit resume() may proceed (cached steps reload)
    assert workflow.resume("wf_recancel", str(tmp_path)) == 1
    assert calls["n"] == 1
