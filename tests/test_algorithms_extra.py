"""Tests for the PG / DDPG / TD3 / APPO algorithm families."""

import time

import numpy as np
import pytest

from ray_tpu.algorithms.appo import APPOConfig
from ray_tpu.algorithms.ddpg import DDPGConfig, TD3Config
from ray_tpu.algorithms.pg import PGConfig
from ray_tpu.algorithms.registry import get_algorithm_class


def test_registry_has_new_algos():
    for name in ("PG", "DDPG", "TD3", "APPO", "SimpleQ", "A3C"):
        assert get_algorithm_class(name) is not None


@pytest.mark.slow  # ~19 s on the tier-1 host: PG learning curve
# (moved out of tier-1 with PR 7, budget rule; the PG loss/algorithm
# surface stays covered by the registry + exploration tests)
def test_pg_cartpole_learns():
    algo = (
        PGConfig()
        .environment("CartPole-v1")
        .rollouts(num_rollout_workers=0, rollout_fragment_length=200)
        .training(train_batch_size=400, lr=4e-3)
        .debugging(seed=0)
        .build()
    )
    best = -np.inf
    deadline = time.time() + 240
    while time.time() < deadline:
        result = algo.train()
        r = result.get("episode_reward_mean", np.nan)
        if np.isfinite(r):
            best = max(best, r)
        if best >= 80.0:
            break
    algo.cleanup()
    assert best >= 80.0, f"PG failed to learn: best={best}"


def test_ddpg_pendulum_step_and_td_error():
    algo = (
        DDPGConfig()
        .environment("Pendulum-v1")
        .rollouts(num_rollout_workers=0, rollout_fragment_length=16)
        .training(
            train_batch_size=64,
            num_steps_sampled_before_learning_starts=64,
        )
        .debugging(seed=0)
        .build()
    )
    pol = algo.get_policy()
    from ray_tpu.utils.exploration import OrnsteinUhlenbeckNoise

    assert isinstance(pol.exploration, OrnsteinUhlenbeckNoise)
    for _ in range(6):
        result = algo.train()
    info = result["info"]["learner"]["default_policy"]
    assert np.isfinite(info["actor_loss"])
    assert np.isfinite(info["critic_loss"])
    # actions honor the space bounds even with exploration noise
    obs = np.zeros((16, 3), np.float32)
    acts, _, _ = pol.compute_actions(obs, explore=True)
    assert (acts >= -2.0 - 1e-5).all() and (acts <= 2.0 + 1e-5).all()
    algo.cleanup()


def test_td3_twin_q_and_delayed_updates():
    algo = (
        TD3Config()
        .environment("Pendulum-v1")
        .rollouts(num_rollout_workers=0, rollout_fragment_length=16)
        .training(
            train_batch_size=64,
            num_steps_sampled_before_learning_starts=32,
        )
        .debugging(seed=0)
        .build()
    )
    pol = algo.get_policy()
    from ray_tpu.utils.exploration import GaussianNoise

    assert isinstance(pol.exploration, GaussianNoise)
    assert pol.twin_q and pol.policy_delay == 2
    import jax

    actor_before = jax.device_get(pol.params["actor"])
    for _ in range(4):
        result = algo.train()
    info = result["info"]["learner"]["default_policy"]
    assert np.isfinite(info["critic_loss"])
    actor_after = jax.device_get(pol.params["actor"])
    # the delayed actor still updates across several steps
    leaves_b = jax.tree_util.tree_leaves(actor_before)
    leaves_a = jax.tree_util.tree_leaves(actor_after)
    assert any(
        not np.allclose(b, a) for b, a in zip(leaves_b, leaves_a)
    )
    algo.cleanup()


def test_ddpg_checkpoint_roundtrip():
    cfg = (
        DDPGConfig()
        .environment("Pendulum-v1")
        .rollouts(num_rollout_workers=0, rollout_fragment_length=8)
        .training(
            train_batch_size=32,
            num_steps_sampled_before_learning_starts=16,
        )
        .debugging(seed=0)
    )
    algo = cfg.build()
    for _ in range(3):
        algo.train()
    state = algo.get_policy().get_state()
    algo2 = cfg.build()
    algo2.get_policy().set_state(state)
    import jax

    w1 = jax.device_get(algo.get_policy().params)
    w2 = jax.device_get(algo2.get_policy().params)
    for a, b in zip(
        jax.tree_util.tree_leaves(w1), jax.tree_util.tree_leaves(w2)
    ):
        np.testing.assert_allclose(a, b)
    algo.cleanup()
    algo2.cleanup()


def test_appo_step_and_target_refresh():
    algo = (
        APPOConfig()
        .environment("CartPole-v1")
        .rollouts(num_rollout_workers=0, rollout_fragment_length=16)
        .training(
            train_batch_size=64,
            use_kl_loss=True,
            target_update_frequency=1,
        )
        .debugging(seed=0)
        .build()
    )
    # the learner thread compiles on its first batch; loop until it has
    # trained enough for a target refresh (bounded by a deadline)
    deadline = time.time() + 120
    result = algo.train()
    while (
        algo._counters["num_target_updates"] < 1
        and time.time() < deadline
    ):
        result = algo.train()
    assert algo._counters["num_target_updates"] >= 1
    info = result["info"]["learner"]["default_policy"]
    assert np.isfinite(info.get("policy_loss", np.nan))
    assert "mean_is_ratio" in info
    algo.cleanup()


@pytest.mark.slow  # ~30 s on the tier-1 host: APPO learning regression
def test_appo_cartpole_learns():
    algo = (
        APPOConfig()
        .environment("CartPole-v1")
        .rollouts(num_rollout_workers=0, rollout_fragment_length=50)
        .training(
            train_batch_size=200,
            lr=3e-3,
            entropy_coeff=0.01,
            clip_param=0.3,
        )
        .debugging(seed=0)
        .build()
    )
    best = -np.inf
    deadline = time.time() + 240
    while time.time() < deadline:
        result = algo.train()
        r = result.get("episode_reward_mean", np.nan)
        if np.isfinite(r):
            best = max(best, r)
        if best >= 100.0:
            break
    algo.cleanup()
    assert best >= 100.0, f"APPO failed to learn: best={best}"


@pytest.mark.slow  # ~10 s multi-worker e2e; moved out of tier-1 by
# the PR-1 budget rule — tier-1 keeps test_ddppo_requires_workers,
# with the full learning run already in the slow tier
def test_ddppo_decentralized_learning():
    from ray_tpu.algorithms.ddppo import DDPPOConfig

    algo = (
        DDPPOConfig()
        .environment("CartPole-v1")
        .rollouts(num_rollout_workers=2, rollout_fragment_length=64)
        .training(num_sgd_iter=4, lr=3e-4)
        .debugging(seed=0)
        .build()
    )
    result = algo.train()
    info = result["info"]["learner"]["default_policy"]
    assert np.isfinite(info["total_loss"])
    # after allreduced updates, all workers hold identical weights
    import jax

    w = [
        __import__("ray_tpu").get(rw.get_weights.remote())
        for rw in algo.workers.remote_workers()
    ]
    for a, b in zip(
        jax.tree_util.tree_leaves(w[0]),
        jax.tree_util.tree_leaves(w[1]),
    ):
        np.testing.assert_allclose(a["default_policy"] if isinstance(a, dict) else a,
                                   b["default_policy"] if isinstance(b, dict) else b,
                                   rtol=1e-5)
    # and the local worker was synced for checkpoint/eval parity
    lw = jax.tree_util.tree_leaves(
        algo.workers.local_worker().get_weights()
    )
    for a, b in zip(jax.tree_util.tree_leaves(w[0]), lw):
        np.testing.assert_allclose(
            a["default_policy"] if isinstance(a, dict) else a,
            b["default_policy"] if isinstance(b, dict) else b,
            rtol=1e-5,
        )
    algo.cleanup()


def test_ddppo_requires_workers():
    from ray_tpu.algorithms.ddppo import DDPPOConfig

    with pytest.raises(ValueError):
        (
            DDPPOConfig()
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=0)
            .build()
        )


@pytest.mark.slow  # ~38 s on the tier-1 host: full DD-PPO learning run
def test_ddppo_cartpole_learns():
    from ray_tpu.algorithms.ddppo import DDPPOConfig

    algo = (
        DDPPOConfig()
        .environment("CartPole-v1")
        .rollouts(num_rollout_workers=2, rollout_fragment_length=256,
                  num_envs_per_worker=2)
        .training(num_sgd_iter=6, lr=5e-4, entropy_coeff=0.01,
                  clip_param=0.2, kl_coeff=0.0)
        .debugging(seed=0)
        .build()
    )
    best = -np.inf
    deadline = time.time() + 300
    while time.time() < deadline:
        result = algo.train()
        r = result.get("episode_reward_mean", np.nan)
        if np.isfinite(r):
            best = max(best, r)
        if best >= 100.0:
            break
    algo.cleanup()
    assert best >= 100.0, f"DDPPO failed to learn: best={best}"
