"""Automatic ObjectRef reference counting.

Reference strategy: ``python/ray/tests/test_reference_counting.py``
(the local-handle half of ``core_worker/reference_count.h:61``) — an
object lives exactly as long as some driver-side handle can still
reach it: user variables, task records pinning argument refs for
retries, handles deserialized from results. Out-of-scope objects free
their store entry (shm or spilled) without ray.free().
"""

import gc
import time

import numpy as np
import pytest

import ray_tpu as ray
from ray_tpu.core import api


@pytest.fixture()
def rt():
    ray.init(num_cpus=2, ignore_reinit_error=True)
    yield api._require_runtime()


def _entry_count(rt, oid):
    return 1 if oid in rt.store._entries else 0


def test_put_freed_when_handle_dropped(rt):
    ref = ray.put(np.zeros(100_000, np.float32))
    oid = ref.id
    assert _entry_count(rt, oid) == 1
    del ref
    gc.collect()
    assert _entry_count(rt, oid) == 0


def test_copies_and_pickles_share_the_count(rt):
    import pickle

    ref = ray.put("v")
    oid = ref.id
    ref2 = pickle.loads(pickle.dumps(ref))
    del ref
    gc.collect()
    assert _entry_count(rt, oid) == 1  # ref2 still holds it
    assert ray.get(ref2) == "v"
    del ref2
    gc.collect()
    assert _entry_count(rt, oid) == 0


def test_task_arg_pinned_until_task_done(rt):
    @ray.remote
    def consume(x, delay):
        time.sleep(delay)
        return float(x.sum())

    big = ray.put(np.ones(50_000, np.float32))
    oid = big.id
    out = consume.remote(big, 0.5)
    del big  # user handle gone; the task record still pins it
    gc.collect()
    assert ray.get(out, timeout=60) == 50_000.0
    del out
    deadline = time.time() + 10
    while time.time() < deadline and (
        oid in rt.store._entries
    ):
        gc.collect()
        time.sleep(0.05)
    assert _entry_count(rt, oid) == 0  # released after completion


def test_fire_and_forget_result_freed_on_arrival(rt):
    @ray.remote
    def produce():
        return np.ones(10_000, np.float32)

    ref = produce.remote()
    oid = ref.id
    del ref  # dropped before the result lands
    gc.collect()
    deadline = time.time() + 30
    while time.time() < deadline:
        # entry may exist transiently while in flight; it must be
        # freed once the (unobservable) result arrives
        e = rt.store._entries.get(oid)
        if e is not None and e.event.is_set():
            time.sleep(0.1)
            gc.collect()
        if oid not in rt.store._entries:
            break
        time.sleep(0.05)
    assert _entry_count(rt, oid) == 0


def test_multi_return_refs_free_independently(rt):
    @ray.remote(num_returns=2)
    def pair():
        return np.ones(10_000), np.zeros(10_000)

    a, b = pair.remote()
    assert float(ray.get(a, timeout=60).sum()) == 10_000.0
    oa, ob = a.id, b.id
    del a
    gc.collect()
    assert _entry_count(rt, oa) == 0
    assert float(ray.get(b, timeout=60).sum()) == 0.0
    del b
    gc.collect()
    assert _entry_count(rt, ob) == 0


def test_explicit_free_then_drop_is_safe(rt):
    ref = ray.put("x")
    oid = ref.id
    ray.free([ref])
    del ref
    gc.collect()  # no error: decref on a freed id is a no-op
    # and no phantom entry resurrected by a deferred free
    assert oid not in rt.store._entries
    assert oid not in rt.store._refcounts
