"""Bandit (LinUCB/LinTS) and QMIX tests (reference
rllib/algorithms/bandit/tests, qmix/tests/test_qmix.py)."""

import time

import gymnasium as gym
import numpy as np
import pytest

from ray_tpu.algorithms.bandit import (
    BanditLinTSConfig,
    BanditLinUCBConfig,
)
from ray_tpu.env.registry import register_env


class LinearContextBandit(gym.Env):
    """Reward = theta_a . context for the chosen arm (+ noise); one-step
    episodes. Best arm varies with the context."""

    def __init__(self, config=None):
        config = config or {}
        self.dim = int(config.get("dim", 4))
        self.num_arms = int(config.get("num_arms", 3))
        rng = np.random.default_rng(config.get("seed", 7))
        self.theta = rng.standard_normal((self.num_arms, self.dim))
        self.observation_space = gym.spaces.Box(
            -1.0, 1.0, (self.dim,), np.float32
        )
        self.action_space = gym.spaces.Discrete(self.num_arms)
        self._rng = rng
        self._ctx = None

    def reset(self, *, seed=None, options=None):
        self._ctx = self._rng.uniform(-1, 1, self.dim).astype(
            np.float32
        )
        return self._ctx, {}

    def step(self, action):
        reward = float(
            self.theta[int(action)] @ self._ctx
            + 0.01 * self._rng.standard_normal()
        )
        regret = float(
            (self.theta @ self._ctx).max()
            - self.theta[int(action)] @ self._ctx
        )
        return self._ctx, reward, True, False, {"regret": regret}


def _bandit_env_register():
    register_env(
        "lin_bandit", lambda cfg: LinearContextBandit(cfg)
    )


@pytest.mark.parametrize(
    "config_cls", [BanditLinUCBConfig, BanditLinTSConfig]
)
def test_bandit_learns_linear_problem(config_cls):
    _bandit_env_register()
    algo = (
        config_cls()
        .environment("lin_bandit", env_config={"dim": 4, "num_arms": 3})
        .rollouts(num_rollout_workers=0, rollout_fragment_length=16)
        .training(train_batch_size=16)
        .debugging(seed=0)
        .build()
    )
    # early performance (mostly exploring)
    first = algo.train()
    early = first["episode_reward_mean"]
    for _ in range(25):
        result = algo.train()
    late = result["episode_reward_mean"]
    assert np.isfinite(late)
    assert late > early, (early, late)
    pol = algo.get_policy()
    # posterior actually updated away from the prior
    assert float(np.abs(np.asarray(pol.moment)).sum()) > 0
    algo.cleanup()


def test_bandit_weights_roundtrip():
    _bandit_env_register()
    algo = (
        BanditLinUCBConfig()
        .environment("lin_bandit")
        .rollouts(num_rollout_workers=0, rollout_fragment_length=8)
        .training(train_batch_size=8)
        .build()
    )
    algo.train()
    w = algo.get_policy().get_weights()
    algo2 = (
        BanditLinUCBConfig()
        .environment("lin_bandit")
        .rollouts(num_rollout_workers=0, rollout_fragment_length=8)
        .training(train_batch_size=8)
        .build()
    )
    algo2.get_policy().set_weights(w)
    np.testing.assert_allclose(
        np.asarray(algo2.get_policy().precision),
        w["precision"],
    )
    algo.cleanup()
    algo2.cleanup()


class TwoStepCoopEnv:
    """The QMIX paper's two-step cooperative matrix game (Rashid et al.
    2018, sec. 5): optimal play requires coordinated joint actions that
    a pure VDN-style sum cannot always represent."""

    def __init__(self, config=None):
        self.agents = ["a0", "a1"]
        self.observation_space = gym.spaces.Box(
            0.0, 1.0, (3,), np.float32
        )
        self.action_space = gym.spaces.Discrete(2)
        self._state = 0

    def _obs(self):
        o = np.zeros(3, np.float32)
        o[self._state] = 1.0
        return {a: o.copy() for a in self.agents}

    def reset(self, *, seed=None, options=None):
        self._state = 0
        return self._obs(), {a: {} for a in self.agents}

    def step(self, action_dict):
        a0 = action_dict["a0"]
        a1 = action_dict["a1"]
        if self._state == 0:
            # agent 0's action selects the second-stage game
            self._state = 1 if a0 == 0 else 2
            return (
                self._obs(),
                {a: 0.0 for a in self.agents},
                {"__all__": False},
                {"__all__": False},
                {},
            )
        if self._state == 1:
            reward = 7.0  # state 2A: constant
        else:  # state 2B payoff matrix: coordination matters
            matrix = np.array([[0.0, 1.0], [1.0, 8.0]])
            reward = float(matrix[a0, a1])
        return (
            self._obs(),
            {a: reward / 2.0 for a in self.agents},
            {"__all__": True},
            {"__all__": False},
            {},
        )


@pytest.mark.slow  # ~10s on this container; moved out of tier-1 with PR 14 (budget rule: suite at ~856 s vs the 870 s cap; tier-1 siblings: test_qmix_recurrent_agents_solve_memory_task + checkpoint roundtrip)
def test_qmix_learns_two_step_coordination():
    from ray_tpu.algorithms.qmix import QMIXConfig

    register_env("two_step", lambda cfg: TwoStepCoopEnv(cfg))
    algo = (
        QMIXConfig()
        .environment("two_step")
        .rollouts(rollout_fragment_length=16)
        .training(
            train_batch_size=32,
            lr=3e-3,
            buffer_size=2000,
            target_network_update_freq=64,
            num_steps_sampled_before_learning_starts=100,
            epsilon_timesteps=1500,
            final_epsilon=0.05,
            mixing_embed_dim=16,
        )
        .debugging(seed=0)
        .build()
    )
    assert algo.n_agents == 2
    best = -np.inf
    deadline = time.time() + 240
    while time.time() < deadline:
        result = algo.train()
        r = result.get("episode_reward_mean", np.nan)
        if np.isfinite(r):
            best = max(best, r)
        # optimal = 8 (team), i.e. both agents pick action 1 in 2B
        if best >= 7.5:
            break
    algo.cleanup()
    assert best >= 7.5, f"QMIX failed to coordinate: best={best}"


class RecallCoopEnv:
    """Memory probe: each agent sees its private cue bit ONLY at t=0;
    the team is rewarded at t=2 iff every agent's final action matches
    its own cue. Feedforward agents are blind at decision time (the
    final obs carries no cue), so only recurrent agents — the
    reference's RNN-over-episode training — can beat chance."""

    def __init__(self, config=None):
        config = config or {}
        self.agents = ["a0", "a1"]
        self.observation_space = gym.spaces.Box(
            0.0, 1.0, (3,), np.float32
        )
        self.action_space = gym.spaces.Discrete(2)
        self._rng = np.random.default_rng(config.get("seed", 0))

    def _obs(self, show_cue):
        out = {}
        for i, a in enumerate(self.agents):
            o = np.zeros(3, np.float32)
            o[0] = self._t / 2.0
            if show_cue:
                o[1 + self._cues[i]] = 1.0
            out[a] = o
        return out

    def reset(self, *, seed=None, options=None):
        self._cues = self._rng.integers(0, 2, size=2)
        self._t = 0
        return self._obs(True), {a: {} for a in self.agents}

    def step(self, action_dict):
        self._t += 1
        done = self._t >= 2
        reward = 0.0
        if done:
            reward = float(
                all(
                    int(action_dict[a]) == int(self._cues[i])
                    for i, a in enumerate(self.agents)
                )
            )
        return (
            self._obs(False),
            {a: reward / 2.0 for a in self.agents},
            {"__all__": done},
            {"__all__": False},
            {},
        )


@pytest.mark.regression
def test_qmix_recurrent_agents_solve_memory_task():
    """Chance is 0.25 (two independent coin cues); recurrent QMIX must
    carry the t=0 cues to the t=2 decision."""
    from ray_tpu.algorithms.qmix import QMIXConfig

    register_env("recall_coop", lambda cfg: RecallCoopEnv(cfg))
    algo = (
        QMIXConfig()
        .environment("recall_coop")
        .rollouts(rollout_fragment_length=16)
        .training(
            train_batch_size=32,
            lr=3e-3,
            buffer_size=2000,
            episode_limit=4,
            target_network_update_freq=64,
            num_steps_sampled_before_learning_starts=100,
            epsilon_timesteps=2500,
            final_epsilon=0.05,
            mixing_embed_dim=16,
        )
        .debugging(seed=0)
        .build()
    )
    best = -np.inf
    deadline = time.time() + 300
    while time.time() < deadline:
        result = algo.train()
        r = result.get("episode_reward_mean", np.nan)
        if np.isfinite(r):
            best = max(best, r)
        if best >= 0.8:
            break
    algo.cleanup()
    assert best >= 0.8, f"no memory: best={best} (chance ~0.25)"


def test_qmix_checkpoint_roundtrip(tmp_path):
    from ray_tpu.algorithms.qmix import QMIXConfig

    register_env("two_step", lambda cfg: TwoStepCoopEnv(cfg))
    cfg = (
        QMIXConfig()
        .environment("two_step")
        .rollouts(rollout_fragment_length=8)
        .training(
            train_batch_size=16,
            num_steps_sampled_before_learning_starts=16,
        )
        .debugging(seed=0)
    )
    algo = cfg.build()
    for _ in range(3):
        algo.train()
    path = algo.save(str(tmp_path))
    import jax

    w = jax.device_get(algo.params)
    algo.cleanup()
    algo2 = cfg.build()
    algo2.restore(path)
    w2 = jax.device_get(algo2.params)
    for a, b in zip(
        jax.tree_util.tree_leaves(w), jax.tree_util.tree_leaves(w2)
    ):
        np.testing.assert_allclose(a, b)
    algo2.cleanup()


class CoopSpreadEnv:
    """Tiny cooperative continuous env: two agents on a line must move
    toward each other (reward = -distance); tests MADDPG's centralized
    critic + decentralized actors."""

    def __init__(self, config=None):
        self.agents = ["a0", "a1"]
        self.observation_space = gym.spaces.Box(
            -5.0, 5.0, (2,), np.float32
        )
        self.action_space = gym.spaces.Box(-1.0, 1.0, (1,), np.float32)
        self._pos = None
        self._t = 0

    def _obs(self):
        return {
            "a0": np.array(
                [self._pos[0], self._pos[1]], np.float32
            ),
            "a1": np.array(
                [self._pos[1], self._pos[0]], np.float32
            ),
        }

    def reset(self, *, seed=None, options=None):
        rng = np.random.default_rng(seed)
        self._pos = rng.uniform(-3, 3, 2).astype(np.float32)
        self._t = 0
        return self._obs(), {a: {} for a in self.agents}

    def step(self, action_dict):
        self._pos[0] = np.clip(
            self._pos[0] + 0.3 * float(np.asarray(action_dict["a0"])[0]),
            -5, 5,
        )
        self._pos[1] = np.clip(
            self._pos[1] + 0.3 * float(np.asarray(action_dict["a1"])[0]),
            -5, 5,
        )
        self._t += 1
        dist = abs(self._pos[0] - self._pos[1])
        reward = -float(dist)
        done = self._t >= 25
        return (
            self._obs(),
            {a: reward / 2.0 for a in self.agents},
            {"__all__": done},
            {"__all__": False},
            {},
        )


def test_maddpg_learns_cooperation():
    from ray_tpu.algorithms.maddpg import MADDPGConfig

    register_env("coop_spread", lambda cfg: CoopSpreadEnv(cfg))
    algo = (
        MADDPGConfig()
        .environment("coop_spread")
        .rollouts(rollout_fragment_length=25)
        .training(
            train_batch_size=64,
            actor_lr=3e-3,
            critic_lr=3e-3,
            num_steps_sampled_before_learning_starts=200,
            exploration_stddev=0.2,
        )
        .debugging(seed=0)
        .build()
    )
    assert algo.n_agents == 2
    best = -np.inf
    deadline = time.time() + 240
    while time.time() < deadline:
        result = algo.train()
        r = result.get("episode_reward_mean", np.nan)
        if np.isfinite(r):
            best = max(best, r)
        # random play: ~ -2 per step * 25 steps ~ -40; coordinated
        # agents converge and hold distance ~0
        if best >= -15.0:
            break
    algo.cleanup()
    assert best >= -15.0, f"MADDPG failed to cooperate: best={best}"


def test_maddpg_checkpoint_roundtrip(tmp_path):
    from ray_tpu.algorithms.maddpg import MADDPGConfig

    register_env("coop_spread", lambda cfg: CoopSpreadEnv(cfg))
    cfg = (
        MADDPGConfig()
        .environment("coop_spread")
        .rollouts(rollout_fragment_length=8)
        .training(
            train_batch_size=16,
            num_steps_sampled_before_learning_starts=16,
        )
        .debugging(seed=0)
    )
    algo = cfg.build()
    for _ in range(3):
        algo.train()
    path = algo.save(str(tmp_path))
    import jax

    w = jax.device_get(algo.params)
    algo.cleanup()
    algo2 = cfg.build()
    algo2.restore(path)
    for a, b in zip(
        jax.tree_util.tree_leaves(w),
        jax.tree_util.tree_leaves(jax.device_get(algo2.params)),
    ):
        np.testing.assert_allclose(a, b)
    algo2.cleanup()
