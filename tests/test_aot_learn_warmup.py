"""Elastic-joiner AOT warmup of the learn program (ROADMAP item 2
leftover, wired at the ``JaxPolicy._build_learn_fn`` call sites):

- the FIRST policy to learn with ``aot_cache_dir`` set compiles ahead
  of time once (``aot_source == "aot_live"``) and seeds the
  fleet-shared cache;
- a freshly built second policy (the "joiner") warms its learn
  program from the cache with ZERO fresh compiles
  (``aot_source == "aot_cache"``, ``traces == 0``);
- the restored executable is the same program: fixed-seed params
  after one learn step are BITWISE identical across the seeder, the
  joiner, and a plain live-jit policy (1-shard mesh — the parity
  geometry);
- without ``aot_cache_dir`` the wiring is inert (no aot path, no
  cache directory touched).
"""

import numpy as np
import pytest

import jax

from ray_tpu import sharding as sharding_lib
from ray_tpu.data.sample_batch import SampleBatch as SB
from ray_tpu.sharding import aot as aot_lib

pytestmark = pytest.mark.skipif(
    not aot_lib.supported(),
    reason="this jax build cannot serialize compiled executables",
)

BS = 16


def _policy(aot_dir=None, seed=0):
    import gymnasium as gym

    from ray_tpu.algorithms.ppo.ppo import PPOJaxPolicy

    cfg = {
        "train_batch_size": BS,
        "sgd_minibatch_size": BS,
        "num_sgd_iter": 1,
        "lr": 1e-3,
        "seed": seed,
        # bitwise parity needs the 1-shard mesh (per-shard matmul
        # shapes differ on the 8-way virtual mesh)
        "_mesh": sharding_lib.get_mesh(devices=jax.devices()[:1]),
    }
    if aot_dir is not None:
        cfg["aot_cache_dir"] = str(aot_dir)
    return PPOJaxPolicy(
        gym.spaces.Box(-1, 1, (8,), np.float32),
        gym.spaces.Discrete(4),
        cfg,
    )


def _batch(n=BS):
    rng = np.random.default_rng(7)
    return {
        SB.OBS: rng.standard_normal((n, 8)).astype(np.float32),
        SB.ACTIONS: rng.integers(0, 4, n).astype(np.int64),
        SB.ACTION_LOGP: np.full(n, -1.3, np.float32),
        SB.ACTION_DIST_INPUTS: rng.standard_normal((n, 4)).astype(
            np.float32
        ),
        SB.ADVANTAGES: rng.standard_normal(n).astype(np.float32),
        SB.VALUE_TARGETS: rng.standard_normal(n).astype(np.float32),
    }


def _params(policy):
    return [
        np.asarray(x)
        for x in jax.tree_util.tree_leaves(
            jax.device_get(policy.params)
        )
    ]


def _learn_fn(policy):
    fns = list(policy._learn_fns.values())
    assert len(fns) == 1
    return fns[0]


def test_joiner_warms_with_zero_fresh_compiles(tmp_path):
    cache_dir = tmp_path / "aot"
    batch = _batch()

    # the seeder: compiles ahead of time ONCE and populates the cache
    seeder = _policy(cache_dir)
    seeder.learn_on_batch(dict(batch))
    fn1 = _learn_fn(seeder)
    assert fn1.aot_source == "aot_live"
    assert fn1.traces == 1  # the one AOT compile, honestly counted
    cache1 = seeder._learn_aot_cache()
    cache1.flush()
    assert cache1.stats()["saves"] == 1

    # the joiner: fresh policy, same config/topology — learn program
    # restores from disk, ZERO fresh compiles
    joiner = _policy(cache_dir)
    joiner.learn_on_batch(dict(batch))
    fn2 = _learn_fn(joiner)
    assert fn2.aot_source == "aot_cache"
    assert fn2.traces == 0, "joiner paid an XLA compile"
    assert joiner._learn_aot_cache().stats()["hits"] == 1

    # live-jit reference: no cache configured
    live = _policy(None)
    live.learn_on_batch(dict(batch))
    assert _learn_fn(live).aot_source is None

    # same program, bitwise: seeder ≡ joiner ≡ live after one step
    p1, p2, p3 = _params(seeder), _params(joiner), _params(live)
    for a, b in zip(p1, p2):
        assert np.array_equal(a, b)
    for a, b in zip(p1, p3):
        assert np.array_equal(a, b)


def test_unconfigured_policy_never_touches_aot(tmp_path):
    p = _policy(None)
    p.learn_on_batch(dict(_batch()))
    fn = _learn_fn(p)
    assert fn.aot_source is None
    assert p._learn_aot_cache() is None
