"""Resource-aware scheduling, placement groups, object spilling tests
(reference python/ray/tests/test_placement_group*.py,
test_scheduling*.py, test_object_spilling.py)."""

import time

import numpy as np
import pytest

import ray_tpu as ray
from ray_tpu.util import (
    PlacementGroupSchedulingStrategy,
    placement_group,
    remove_placement_group,
)


def setup_function(_):
    ray.shutdown()


def teardown_function(_):
    ray.shutdown()


def test_num_cpus_limits_concurrency():
    """Two 2-CPU tasks cannot run concurrently on a 3-CPU runtime even
    though enough worker processes exist."""
    ray.init(num_cpus=3)

    @ray.remote(num_cpus=2)
    def heavy():
        time.sleep(0.5)
        return 1

    @ray.remote(num_cpus=1)
    def light():
        time.sleep(0.5)
        return 1

    # warm the worker pool so spawn cost doesn't mask scheduling
    ray.get([light.remote() for _ in range(3)])

    t0 = time.time()
    assert sum(ray.get([heavy.remote() for _ in range(3)])) == 3
    heavy_elapsed = time.time() - t0
    # 2-CPU demand on 3 CPUs strictly serializes: >= 3 x 0.5s
    assert heavy_elapsed >= 1.4, heavy_elapsed

    t0 = time.time()
    assert sum(ray.get([light.remote() for _ in range(3)])) == 3
    light_elapsed = time.time() - t0
    # three 1-CPU tasks fit concurrently
    assert light_elapsed < 1.2, light_elapsed


def test_custom_resources_gate_dispatch():
    ray.init(num_cpus=4, resources={"accelerator": 1})

    @ray.remote(num_cpus=1, resources={"accelerator": 1})
    def uses_acc():
        time.sleep(0.3)
        return time.time()

    t0 = time.time()
    out = ray.get([uses_acc.remote() for _ in range(3)])
    # 3 tasks x 0.3s serialized on the single accelerator token
    assert time.time() - t0 >= 0.85
    assert ray.available_resources()["accelerator"] == 1.0


def test_placement_group_reserves_and_admits():
    ray.init(num_cpus=4)
    pg = placement_group([{"CPU": 2}], strategy="PACK")
    assert pg.ready(timeout=5)
    assert ray.available_resources()["CPU"] == 2.0

    @ray.remote(num_cpus=1)
    def inside():
        time.sleep(0.2)
        return 1

    strategy = PlacementGroupSchedulingStrategy(pg)
    refs = [
        inside.options(scheduling_strategy=strategy).remote()
        for _ in range(4)
    ]
    assert sum(ray.get(refs)) == 4
    # group resources return to the pool on removal
    remove_placement_group(pg)
    assert ray.available_resources()["CPU"] == 4.0


def test_placement_group_waits_for_capacity():
    ray.init(num_cpus=2)
    pg1 = placement_group([{"CPU": 2}])
    assert pg1.ready(timeout=5)
    pg2 = placement_group([{"CPU": 1}])
    assert not pg2.ready(timeout=0.3)  # no capacity yet
    remove_placement_group(pg1)
    assert pg2.ready(timeout=5)
    remove_placement_group(pg2)


def test_object_spilling_and_restore():
    # 3MB store budget; three ~1.2MB objects force a spill
    ray.init(num_cpus=1, object_store_memory=3 * 1024 * 1024)
    rt = ray.core.api._require_runtime()
    arrays = [
        np.full((300, 1024), i, np.float32) for i in range(3)
    ]
    refs = [ray.put(a) for a in arrays]
    assert rt.store._resident_bytes <= 3 * 1024 * 1024
    spilled = [
        oid
        for oid, e in rt.store._entries.items()
        if e.spill_path is not None
    ]
    assert spilled, "nothing was spilled despite exceeding the budget"
    # every object — spilled or resident — reads back exactly
    for ref, a in zip(refs, arrays):
        np.testing.assert_array_equal(ray.get(ref), a)
    # freeing a spilled object removes its disk file
    import os

    e = rt.store._entries[spilled[0]]
    path = e.spill_path
    ray.free([r for r in refs if r.id == spilled[0]])
    assert path is None or not os.path.exists(path)


def test_actor_calls_do_not_leak_cpu_accounting():
    """Actor methods run on the actor's dedicated process — completing
    calls must not inflate available CPUs."""
    ray.init(num_cpus=4)

    @ray.remote
    class A:
        def f(self):
            return 1

    a = A.remote()
    for _ in range(10):
        ray.get(a.f.remote())
    assert ray.available_resources()["CPU"] == 4.0


def test_placement_group_bundle_pinning():
    ray.init(num_cpus=4)
    pg = placement_group([{"CPU": 1}, {"CPU": 1}])
    assert pg.ready(timeout=5)

    @ray.remote(num_cpus=1)
    def slow():
        time.sleep(0.4)
        return 1

    pin0 = PlacementGroupSchedulingStrategy(
        pg, placement_group_bundle_index=0
    )
    # two tasks pinned to the SAME 1-CPU bundle must serialize even
    # though bundle 1 sits idle
    t0 = time.time()
    refs = [
        slow.options(scheduling_strategy=pin0).remote()
        for _ in range(2)
    ]
    assert sum(ray.get(refs)) == 2
    assert time.time() - t0 >= 0.75
    remove_placement_group(pg)
