"""Tier-1 smoke for the sharding runtime + its bench entry.

Runs the exact code path ``bench.py --sharding-ab`` drives (tiny
geometry) so signature drift in the public sharding API fails tests
instead of the driver run — the same contract test_bench_smoke.py
establishes for the headline bench.
"""

import json

import pytest

import bench

pytestmark = pytest.mark.smoke


def test_bench_sharding_ab_runs_and_reports(tmp_path):
    out = str(tmp_path / "sharding_ab.json")
    report = bench.bench_sharding_ab(
        b=64, mb=32, iters=1, rounds=2, out_path=out
    )
    assert set(report["backends"]) == {"mesh", "pmap"}
    for be in report["backends"].values():
        assert be["step_ms_median"] > 0
        assert be["recompiles"] == 0
    assert report["parity_bitwise"] is True
    with open(out) as f:
        assert json.load(f)["metric"] == (
            "sharding_backend_ab_learn_step"
        )


def test_sharding_public_api_surface():
    """The names documented in docs/sharding.md exist and compose."""
    import jax
    import numpy as np

    from ray_tpu import sharding as sl

    mesh = sl.get_mesh()
    assert sl.BATCH_AXIS == "batch"
    rep, dat = sl.replicated(mesh), sl.batch_sharded(mesh)
    fn = sl.sharded_jit(
        lambda p, x: (p, x.sum()),
        in_specs=(rep, dat),
        out_specs=(rep, rep),
        label="smoke",
    )
    p = jax.device_put(np.float32(2.0), rep)
    x = jax.device_put(np.ones(16, np.float32), dat)
    _, s = fn(p, x)
    assert float(s) == 16.0
    assert fn.stats()["recompiles"] == 0
    assert sl.compile_stats()["functions"] >= 1
