"""Fleet control-plane crash tolerance units (PR 19): the fenced
lease state machine, stale-term write rejection, monotonic liveness
under wall-clock steps, the retried KV transport riding out injected
drops, subscriber/heartbeat outage survival, standby coordinator
failover, and HostAgent partition self-fencing — all against real
in-process KV servers (killed and restarted on their own ports).

The cross-process version of the same story — coordinator killed
mid-epoch via ``kill_coordinator`` chaos, standby takes over, params
bitwise, zero fresh compiles — lives in the slow 2-process rung
(test_multihost.py) and ``bench.py --fleet-chaos``.
"""

import os
import time

import pytest

from ray_tpu import fleet
from ray_tpu.fleet.coordinator import (
    K_EPOCH_PTR,
    LEASE_NAME,
    epoch_key,
)
from ray_tpu.resilience.faults import FaultInjector


@pytest.fixture()
def server():
    srv = fleet.KVServer(host="127.0.0.1")
    yield srv
    srv.shutdown()


@pytest.fixture()
def kv(server):
    return fleet.KVClient(f"127.0.0.1:{server.port}")


def _restart(server, down_s: float = 0.0):
    """Kill the KV server and rebind a fresh one on the same port —
    the coordinator-host restart. ``down_s`` holds the port dark long
    enough to exhaust a client's retry schedule (a real outage, not a
    blip the transport hides)."""
    port = server.port
    server.shutdown()
    if down_s:
        time.sleep(down_s)
    return fleet.KVServer(host="127.0.0.1", port=port)


# ---------------------------------------------------------------------------
# lease state machine
# ---------------------------------------------------------------------------


def test_lease_acquire_renew_release(kv):
    r = kv.lease_acquire("fleet/test", "alice", ttl=5.0)
    assert r["granted"] and r["term"] == 1
    # same-holder re-acquire is a refresh: granted, SAME term
    r2 = kv.lease_acquire("fleet/test", "alice", ttl=5.0)
    assert r2["granted"] and r2["term"] == 1
    # a rival is refused and told who holds it and for how long
    r3 = kv.lease_acquire("fleet/test", "bob", ttl=5.0)
    assert not r3["granted"]
    assert r3["holder"] == "alice" and r3["expires_in"] > 0
    # renew works only for the live holder at the current term
    assert kv.lease_renew("fleet/test", "alice", 1, ttl=5.0)
    assert not kv.lease_renew("fleet/test", "alice", 0, ttl=5.0)
    assert not kv.lease_renew("fleet/test", "bob", 1, ttl=5.0)
    # release: the next acquire is granted immediately, term BUMPS
    kv.lease_release("fleet/test", "alice")
    r4 = kv.lease_acquire("fleet/test", "bob", ttl=5.0)
    assert r4["granted"] and r4["term"] == 2


def test_lease_expiry_hands_over_at_higher_term(kv):
    r = kv.lease_acquire("fleet/test", "alice", ttl=0.2)
    assert r["granted"] and r["term"] == 1
    time.sleep(0.35)
    # expired: the standby wins without a release, term bumps past
    # the dead leader so its writes are fenced from this instant
    r2 = kv.lease_acquire("fleet/test", "bob", ttl=5.0)
    assert r2["granted"] and r2["term"] == 2
    # the old leader's renew is refused — how it learns to stop
    assert not kv.lease_renew("fleet/test", "alice", 1, ttl=5.0)


def test_lease_terms_survive_kv_restart(tmp_path):
    persist = str(tmp_path / "kv.sqlite")
    srv = fleet.KVServer(host="127.0.0.1", persist_path=persist)
    kv = fleet.KVClient(f"127.0.0.1:{srv.port}")
    assert kv.lease_acquire(LEASE_NAME, "alice", ttl=60.0)["term"] == 1
    port = srv.port
    srv.shutdown()
    srv = fleet.KVServer(
        host="127.0.0.1", port=port, persist_path=persist
    )
    try:
        info = kv.lease_info(LEASE_NAME)
        # term durable, holder volatile: leadership is re-acquired,
        # never assumed, but fencing never regresses
        assert info["term"] == 1 and info["holder"] is None
        assert (
            kv.lease_acquire(LEASE_NAME, "bob", ttl=60.0)["term"] == 2
        )
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# fenced writes (the split-brain counter-proof, unit scale)
# ---------------------------------------------------------------------------


def test_stale_term_write_rejected_and_counted(kv):
    assert kv.lease_acquire(LEASE_NAME, "new-leader", ttl=60.0)[
        "term"
    ] == 1
    kv.put("fleet/members", {"a": {}}, term=1, holder="new-leader")
    assert kv.get("fleet/members") == {"a": {}}
    # zombie ex-coordinator at term 0: rejected AT THE STORE
    with pytest.raises(fleet.StaleTermError):
        kv.put("fleet/members", {"z": {}}, term=0, holder="zombie")
    assert kv.get("fleet/members") == {"a": {}}  # value untouched
    assert kv.lease_info(LEASE_NAME)["fenced_writes"] == 1
    # unfenced puts (no term) are unaffected — data-plane keys don't
    # carry leadership
    kv.put("scratch", 7)
    assert kv.get("scratch") == 7


def test_fenced_write_increments_metric(kv):
    from ray_tpu.telemetry import metrics as tm

    before = tm.counter_total(tm.FLEET_FENCED_WRITES_TOTAL)
    kv.lease_acquire(LEASE_NAME, "leader", ttl=60.0)
    with pytest.raises(fleet.StaleTermError):
        kv.put("fleet/epoch", 9, term=0, holder="zombie2")
    assert (
        tm.counter_total(tm.FLEET_FENCED_WRITES_TOTAL) == before + 1
    )


# ---------------------------------------------------------------------------
# monotonic liveness (the NTP-step regression)
# ---------------------------------------------------------------------------


def test_wall_clock_step_cannot_expire_liveness(server, kv):
    kv.heartbeat("host0")
    assert "host0" in kv.alive_nodes(horizon=30.0)
    # step the WALL clock forward an hour (NTP correction): liveness
    # must not notice — stamps and expiry run on time.monotonic
    server._wall = lambda: time.time() + 3600.0
    assert "host0" in kv.alive_nodes(horizon=30.0)
    # the skew handshake (clock op) DOES see the step — on purpose:
    # skew correction is about wall clocks
    assert kv.server_clock() - time.time() > 3000.0
    # leases run on the monotonic clock too
    r = kv.lease_acquire("fleet/test", "alice", ttl=60.0)
    assert r["granted"]
    assert not kv.lease_acquire("fleet/test", "bob", ttl=60.0)[
        "granted"
    ]


# ---------------------------------------------------------------------------
# retried transport + chaos
# ---------------------------------------------------------------------------


def test_retry_rides_through_injected_drop(kv):
    # first put attempt is dropped at the wire; the retry schedule
    # must absorb it invisibly
    kv._chaos = FaultInjector(
        {"kv_drop": [{"kv_op": "put", "on_call": 1}]}
    )
    kv.put("k", 41)
    assert kv.get("k") == 41


def test_unretried_client_dies_on_drop(server):
    # ray-tpu: allow[RTA013] proving the retry=False failure mode
    raw = fleet.KVClient(f"127.0.0.1:{server.port}", retry=False)
    raw._chaos = FaultInjector(
        {"kv_drop": [{"kv_op": "put", "on_call": 1}]}
    )
    with pytest.raises(ConnectionError):
        raw.put("k", 1)


def test_kv_delay_injects_latency(kv):
    kv._chaos = FaultInjector(
        {"kv_delay": [{"delay_ms": 120.0, "on_call": 1}]}
    )
    t0 = time.monotonic()
    kv.put("k", 1)
    assert time.monotonic() - t0 >= 0.1


def test_partition_host_blocks_matching_host_only(server):
    a = fleet.KVClient(f"127.0.0.1:{server.port}", node="hostA")
    b = fleet.KVClient(f"127.0.0.1:{server.port}", node="hostB")
    a._chaos = b._chaos = FaultInjector(
        {
            "partition_host": [
                {"host": "hostA", "on_call": 1, "heal_s": 0.4}
            ]
        }
    )
    a._retry = None  # observe the raw partition, not the retry
    with pytest.raises(ConnectionError):
        a.put("k", 1)
    b.put("k", 2)  # unpartitioned host sails through
    assert b.get("k") == 2
    time.sleep(0.5)
    a.put("k", 3)  # healed
    assert b.get("k") == 3


def test_retry_backs_off_through_kv_restart(server, kv):
    """The headline transport claim: a put launched into a dead KV
    window succeeds once the server is back, within the schedule."""
    import threading

    port = server.port
    server.shutdown()
    revived = {}

    def revive():
        time.sleep(0.25)
        revived["srv"] = fleet.KVServer(host="127.0.0.1", port=port)

    t = threading.Thread(target=revive)
    t.start()
    try:
        kv.put("after", "restart")  # retries until the server is back
        assert kv.get("after") == "restart"
    finally:
        t.join()
        revived["srv"].shutdown()


# ---------------------------------------------------------------------------
# subscriber / heartbeat outage survival
# ---------------------------------------------------------------------------


def test_subscriber_survives_kv_restart(server, kv):
    got = []
    sub = fleet.Subscriber(
        kv, ["chaos/*"], lambda ch, m: got.append(m), poll_timeout=0.5
    )
    try:
        kv.publish("chaos/x", 1)
        deadline = time.monotonic() + 5.0
        while not got and time.monotonic() < deadline:
            time.sleep(0.02)
        assert got == [1]
        server = _restart(server)  # registration lost with the server
        deadline = time.monotonic() + 10.0
        while sub.reconnects == 0 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert sub.reconnects >= 1
        kv.publish("chaos/x", 2)
        deadline = time.monotonic() + 10.0
        while len(got) < 2 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert got[-1] == 2  # the stream is live again
    finally:
        sub.stop()
        server.shutdown()


def test_heartbeat_reporter_tracks_outage(server, kv):
    hb = fleet.HeartbeatReporter(kv, "host0", interval=0.05)
    try:
        deadline = time.monotonic() + 5.0
        while hb.last_rtt_s is None and time.monotonic() < deadline:
            time.sleep(0.02)
        assert hb.seconds_since_ok() < 2.0
        server = _restart(server, down_s=0.8)
        # the loop survives the restart window and recovers
        deadline = time.monotonic() + 10.0
        while hb.reconnects == 0 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert hb.failures >= 1 and hb.reconnects >= 1
        assert hb.seconds_since_ok() < 5.0
    finally:
        hb.stop()
        server.shutdown()


# ---------------------------------------------------------------------------
# standby coordinator failover
# ---------------------------------------------------------------------------


def test_standby_failover_fences_the_dead_leader(kv):
    leader = fleet.FleetCoordinator(
        kv, subscribe=False, lease_ttl=0.4, holder="leader-A"
    )
    assert leader.is_leader and leader.term == 1
    leader.register_host("h0", rank_hint=0)
    leader.register_host("h1", rank_hint=1)
    epoch = leader.propose_epoch(reason="bootstrap")
    assert epoch.gen == 1 and epoch.hosts == ("h0", "h1")
    standby = fleet.FleetCoordinator(
        kv,
        subscribe=False,
        standby=True,
        lease_ttl=0.4,
        holder="standby-B",
    )
    assert not standby.is_leader
    # the leader dies WITHOUT releasing (crash): renewals stop, the
    # lease runs out, the standby must win within ~the TTL
    leader.stop(release_lease=False)
    t0 = time.monotonic()
    term = standby.acquire_leadership(timeout=5.0)
    failover_wall = time.monotonic() - t0
    assert term == 2
    assert failover_wall < 3 * 0.4 + 1.0
    # the standby rebuilt state from the durable KV table
    assert sorted(standby.members()) == ["h0", "h1"]
    assert standby.current_epoch().gen == 1
    # it leads for real: cuts the next epoch at its term
    e2 = standby.propose_epoch(reason="failover")
    assert e2.gen == 2
    # the revived ex-leader's write dies at the store — split-brain
    # counter-proof (term 1 < term 2)
    with pytest.raises(fleet.StaleTermError):
        leader._put("fleet/members", {"rogue": {}})
    assert not leader.is_leader
    assert sorted(standby.members()) == ["h0", "h1"]
    standby.stop()


def test_clean_stop_releases_lease_for_instant_takeover(kv):
    a = fleet.FleetCoordinator(
        kv, subscribe=False, lease_ttl=30.0, holder="A"
    )
    a.stop()  # releases: no 30s TTL wait for the successor
    t0 = time.monotonic()
    b = fleet.FleetCoordinator(
        kv, subscribe=False, lease_ttl=30.0, holder="B"
    )
    assert time.monotonic() - t0 < 5.0
    assert b.is_leader and b.term == 2
    b.stop()


def test_renewal_loss_flips_is_leader_off(kv):
    a = fleet.FleetCoordinator(
        kv, subscribe=False, lease_ttl=0.3, holder="A"
    )
    # a rival steals the lease after expiry (A's renew thread is
    # alive but we race it with a forced takeover: simulate by
    # releasing behind A's back, then acquiring as B at term+1)
    kv.lease_release(fleet.LEASE_NAME, "A")
    assert kv.lease_acquire(fleet.LEASE_NAME, "B", ttl=30.0)[
        "granted"
    ]
    deadline = time.monotonic() + 5.0
    while a.is_leader and time.monotonic() < deadline:
        time.sleep(0.05)
    assert not a.is_leader  # the renew loop noticed and stood down
    a.stop(release_lease=False)


# ---------------------------------------------------------------------------
# partition self-fencing
# ---------------------------------------------------------------------------


def test_host_agent_parks_and_resumes_in_epoch(server, kv):
    kv.put(K_EPOCH_PTR, 1)
    epoch = fleet.MeshEpoch(gen=1, hosts=("h0",), reason="bootstrap")
    kv.put(epoch_key(1), epoch.to_dict())
    agent = fleet.HostAgent(kv, "h0", heartbeat_interval=0.05)
    try:
        time.sleep(0.2)
        assert not agent.self_fenced(horizon=1.0)
        server = _restart(server)  # brief outage, fleet did NOT move
        kv.put(K_EPOCH_PTR, 1)
        kv.put(epoch_key(1), epoch.to_dict())
        resumed, in_epoch = agent.park_until_reconnected(
            epoch, timeout=10.0
        )
        assert in_epoch and resumed.gen == 1
    finally:
        agent.stop()
        server.shutdown()


def test_host_agent_rejoins_new_epoch_after_partition(server, kv):
    epoch1 = fleet.MeshEpoch(
        gen=1, hosts=("h0", "h1"), reason="bootstrap"
    )
    kv.put(epoch_key(1), epoch1.to_dict())
    kv.put(K_EPOCH_PTR, 1)
    agent = fleet.HostAgent(kv, "h1", heartbeat_interval=0.05)
    try:
        # while h1 was gone the fleet cut gen 2 without it
        epoch2 = fleet.MeshEpoch(
            gen=2, hosts=("h0",), reason="heartbeat-expired"
        )
        kv.put(epoch_key(2), epoch2.to_dict())
        kv.put(K_EPOCH_PTR, 2)
        resumed, in_epoch = agent.park_until_reconnected(
            epoch1, timeout=10.0
        )
        assert not in_epoch
        assert resumed.gen == 2 and resumed.hosts == ("h0",)
        # the self-fence was counted
        from ray_tpu.telemetry import metrics as tm

        assert tm.counter_total(tm.FLEET_SELF_FENCES_TOTAL) >= 1
    finally:
        agent.stop()


def test_self_fenced_detects_kv_outage(server, kv):
    agent = fleet.HostAgent(kv, "h0", heartbeat_interval=0.05)
    srv_down = False
    try:
        time.sleep(0.15)
        assert not agent.self_fenced(horizon=0.5)
        server.shutdown()
        srv_down = True
        deadline = time.monotonic() + 15.0
        while (
            not agent.self_fenced(horizon=0.5)
            and time.monotonic() < deadline
        ):
            time.sleep(0.1)
        assert agent.self_fenced(horizon=0.5)
        assert agent.kv_outage_s() > 0.5
    finally:
        agent.stop()
        if srv_down:
            server._thread.join(timeout=0.1)


def test_resync_epoch_follows_the_pointer(kv):
    e1 = fleet.MeshEpoch(gen=1, hosts=("a", "b"))
    e2 = fleet.MeshEpoch(gen=2, hosts=("a",), reason="shrink")
    kv.put(epoch_key(1), e1.to_dict())
    kv.put(epoch_key(2), e2.to_dict())
    kv.put(K_EPOCH_PTR, 2)
    got = fleet.resync_epoch(kv, current_gen=1, timeout=5.0)
    assert got.gen == 2 and got.hosts == ("a",)
    # a backwards pointer (fresh unpersisted KV) never downgrades us
    kv.put(K_EPOCH_PTR, 1)
    got = fleet.resync_epoch(kv, current_gen=2, timeout=5.0)
    assert got.gen == 2
