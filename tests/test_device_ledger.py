"""Device-plane observability: the compiled-program ledger
(``ray_tpu/telemetry/device.py``, docs/observability.md "device
ledger").

Covers the ISSUE-13 tentpole seams:
- ledger rows: cost_analysis FLOPs / bytes, memory_analysis HBM
  footprint, steady-state execution counts, device-busy time closed at
  drain points, MFU against the (configurable) peak-FLOPs table;
- recompile forensics: the ``jit:recompile`` event carries the
  abstract-signature diff (leaf path + shape/dtype delta) and
  ``compile_stats()["recompile_causes"]`` rolls it up;
- device lanes + the transfer lane render in the chrome trace (golden
  structure assertions);
- the flight-recorder report CLI reads a trace + ledger dump;
- fixed-seed BIT-parity: superstep PPO with ledger + profile_iters on
  is bitwise identical to telemetry-off, end to end through a real
  Algorithm.
"""

import json

import jax
import numpy as np
import pytest

from ray_tpu import sharding as sharding_lib
from ray_tpu.telemetry import device as device_ledger
from ray_tpu.util import tracing


def setup_function(_fn):
    device_ledger.disable()
    device_ledger.clear()
    tracing.disable()
    tracing.clear()


teardown_function = setup_function


# -- ledger rows -------------------------------------------------------


def test_ledger_records_cost_memory_and_executions():
    device_ledger.enable(analyze=True)
    fn = sharded_jit_matmul("ledger_probe")
    x = np.ones((64, 64), np.float32)
    fn(x)  # trace+compile (not a steady-state execution)
    for _ in range(3):
        fn(x)
    device_ledger.drain_point()
    snap = device_ledger.snapshot()
    (row,) = [
        p
        for p in snap["programs"]
        if p["label"] == "ledger_probe"
    ]
    assert row["traces"] == 1 and row["recompiles"] == 0
    assert row["executions"] == 3
    assert row["device_time_s"] > 0
    assert row["compile_time_s"] > 0
    # XLA cost/memory analysis captured (CPU PJRT supports both)
    assert row["flops"] and row["flops"] > 0
    assert row["bytes_accessed"] and row["bytes_accessed"] > 0
    assert row["memory"]["argument_bytes"] > 0
    # MFU is executed FLOPs over peak x busy — a real number in (0, 1]
    # territory on any sane peak table
    assert row["mfu"] is not None and row["mfu"] > 0
    assert snap["totals"]["executions"] == 3
    assert snap["totals"]["mfu"] is not None


def sharded_jit_matmul(label):
    from ray_tpu.sharding.compile import sharded_jit

    return sharded_jit(
        lambda x: (x @ x.T).sum(), label=label
    )


def test_ledger_disabled_is_inert_and_peak_flops_override():
    fn = sharded_jit_matmul("inert_probe")
    fn(np.ones((8, 8), np.float32))
    assert device_ledger.snapshot()["programs"] == []
    # peak override (the CPU-container MFU knob)
    device_ledger.set_peak_flops(123.0)
    try:
        assert device_ledger.peak_flops_per_device() == 123.0
    finally:
        device_ledger.set_peak_flops(None)


def test_traced_calls_do_not_count_as_executions():
    """Warmup/compile calls are excluded from executions and busy
    time, so steady-state MFU isn't diluted by compile wall."""
    device_ledger.enable(analyze=False)
    fn = sharded_jit_matmul("warm_probe")
    fn(np.ones((16, 16), np.float32))  # traces
    snap = device_ledger.snapshot()
    (row,) = [
        p for p in snap["programs"] if p["label"] == "warm_probe"
    ]
    assert row["executions"] == 0 and row["traces"] == 1


# -- recompile forensics -----------------------------------------------


def test_recompile_event_carries_cause_diff():
    from ray_tpu.sharding.compile import compile_stats

    device_ledger.enable(analyze=False)
    tracing.enable()
    fn = sharded_jit_matmul("forensics_probe")
    fn(np.ones((32, 8), np.float32))
    fn(np.ones((64, 8), np.float32))  # shape change → retrace
    fn(np.ones((64, 8), np.int32))  # dtype change → retrace
    events = [
        s
        for s in tracing.get_spans()
        if s["name"] == "jit:recompile"
    ]
    assert len(events) == 2
    shape_cause = events[0]["attributes"]["cause"]
    dtype_cause = events[1]["attributes"]["cause"]
    # leaf path + shape delta
    assert "float32[32,8]" in shape_cause
    assert "float32[64,8]" in shape_cause
    # dtype delta
    assert "float32[64,8]" in dtype_cause
    assert "int32[64,8]" in dtype_cause
    causes = compile_stats()["recompile_causes"]
    assert "forensics_probe" in causes
    assert sum(c["count"] for c in causes["forensics_probe"]) == 2


def test_signature_diff_reports_added_and_removed_leaves():
    sig_a = device_ledger.signature_of(
        ({"obs": np.zeros((4, 8), np.float32)},), {}
    )
    sig_b = device_ledger.signature_of(
        (
            {
                "obs": np.zeros((4, 8), np.float32),
                "extra": np.zeros((4,), np.float32),
            },
        ),
        {},
    )
    diff = device_ledger.diff_signatures(sig_a, sig_b)
    assert "added" in diff and len(diff["added"]) == 1
    assert "extra" in diff["added"][0]["path"]
    back = device_ledger.diff_signatures(sig_b, sig_a)
    assert "removed" in back
    assert device_ledger.cause_string(diff)


# -- timeline: device + transfer lanes (golden structure) ---------------


def test_chrome_trace_renders_device_and_transfer_lanes(tmp_path):
    """One exported trace shows a driver-thread span, the device
    program lane (synthetic tid + ``device:`` thread_name metadata),
    and the device_feed transfer lane — the perfetto merge the ISSUE
    tentpole names."""
    from ray_tpu.execution.device_feed import DeviceFeeder

    device_ledger.enable(analyze=False)
    tracing.enable()
    fn = sharded_jit_matmul("lane_probe")
    x = np.ones((16, 16), np.float32)
    with tracing.start_span("train:iteration"):
        fn(x)  # compile
        fn(x)
        device_ledger.drain_point()
        feeder = DeviceFeeder()
        try:
            feeder.put({"x": x}, meta=None)
            feeder.get(timeout=30)
        finally:
            feeder.stop()
    path = tracing.export_chrome_trace(str(tmp_path / "t.json"))
    events = json.load(open(path))["traceEvents"]
    x_ev = [e for e in events if e["ph"] == "X"]
    names = {e["name"] for e in x_ev}
    assert "device:lane_probe" in names
    assert "feeder:transfer" in names
    assert "train:iteration" in names
    dev = next(
        e for e in x_ev if e["name"] == "device:lane_probe"
    )
    drv = next(
        e for e in x_ev if e["name"] == "train:iteration"
    )
    # the device lane is synthetic — distinct from any host thread
    assert dev["tid"] != drv["tid"]
    assert dev["dur"] >= 0
    lanes = {
        e["args"]["name"]
        for e in events
        if e["ph"] == "M" and e["name"] == "thread_name"
    }
    assert "device:lane_probe" in lanes
    # transfer span carries its payload size for the report CLI
    feed = next(
        e for e in x_ev if e["name"] == "feeder:transfer"
    )
    assert feed["args"]["nbytes"] == x.nbytes


def test_report_cli_renders_trace_and_ledger(tmp_path, capsys):
    from ray_tpu.telemetry import report as report_mod

    device_ledger.enable(analyze=True)
    tracing.enable()
    fn = sharded_jit_matmul("report_probe")
    fn(np.ones((32, 32), np.float32))
    fn(np.ones((32, 32), np.float32))
    fn(np.ones((48, 32), np.float32))  # one recompile with cause
    device_ledger.drain_point()
    trace = tracing.export_chrome_trace(
        str(tmp_path / "trace.json")
    )
    ledger = device_ledger.dump(str(tmp_path / "ledger.json"))
    assert report_mod.main([trace, "--ledger", ledger]) == 0
    text = capsys.readouterr().out
    assert "report_probe" in text
    assert "top programs by device time" in text
    assert "recompiles" in text
    # forensics cause made it into the report
    assert "float32[32,32]" in text
    # JSON mode is machine-parseable
    assert (
        report_mod.main([trace, "--ledger", ledger, "--json"])
        == 0
    )
    rep = json.loads(capsys.readouterr().out)
    assert rep["programs_total"] >= 1
    assert rep["programs"][0]["label"] == "report_probe"
    assert rep["programs"][0]["flops"] > 0


# -- bit parity: ledger + tracing + profiler must not touch numerics ---


def test_policy_superstep_bit_parity_with_ledger(tmp_path):
    """Fixed-seed superstep PPO chain with the full ledger (AOT
    analysis) and span tracing running is BITWISE identical to the
    bare chain — the observers wrap the dispatch path, so this is
    where a numerics leak would show. The algorithm-level run with
    ``profile_iters`` on top is the slow-marked e2e below."""
    import gymnasium as gym

    from ray_tpu.algorithms.ppo.ppo import PPOJaxPolicy

    def make_policy():
        return PPOJaxPolicy(
            gym.spaces.Box(-1, 1, (8,), np.float32),
            gym.spaces.Discrete(4),
            {
                "train_batch_size": 32,
                "sgd_minibatch_size": 16,
                "num_sgd_iter": 1,
                "lr": 1e-3,
                "seed": 0,
            },
        )

    rng = np.random.default_rng(3)
    K = 2
    batches = [
        {
            "obs": rng.standard_normal((32, 8)).astype(np.float32),
            "actions": rng.integers(0, 4, 32).astype(np.int64),
            "action_logp": np.full(32, -1.3, np.float32),
            "action_dist_inputs": rng.standard_normal(
                (32, 4)
            ).astype(np.float32),
            "advantages": rng.standard_normal(32).astype(
                np.float32
            ),
            "value_targets": rng.standard_normal(32).astype(
                np.float32
            ),
        }
        for _ in range(K)
    ]
    stacked = {
        c: np.stack([b[c] for b in batches]) for c in batches[0]
    }

    def run(observed: bool):
        if observed:
            device_ledger.enable(analyze=True)
            tracing.enable()
        p = make_policy()
        for _ in range(2):
            p.learn_superstep(
                K, 32, stacked=dict(stacked), k_max=K
            )
        if observed:
            # the ledger really saw the chain it must not perturb
            assert any(
                r["label"].startswith("superstep[")
                for r in device_ledger.snapshot()["programs"]
            )
            tracing.disable()
            tracing.clear()
            device_ledger.disable()
        return jax.device_get(p.params)

    params_obs = run(True)
    params_bare = run(False)
    la = jax.tree_util.tree_leaves(params_obs)
    lb = jax.tree_util.tree_leaves(params_bare)
    assert len(la) == len(lb)
    for a, b in zip(la, lb):
        assert np.array_equal(np.asarray(a), np.asarray(b))


# -- end to end: superstep PPO ledger + bit parity ----------------------


def _ppo_cfg(telemetry: bool, tmp_str: str):
    from ray_tpu.algorithms.ppo import PPOConfig

    cfg = (
        PPOConfig()
        .environment("CartPole-v1")
        .rollouts(
            num_rollout_workers=1,
            rollout_fragment_length=32,
            sample_prefetch=1,
        )
        .training(
            train_batch_size=64,
            sgd_minibatch_size=32,
            num_sgd_iter=1,
            lr=3e-4,
            superstep=2,
        )
        .debugging(seed=0)
    )
    if telemetry:
        cfg = cfg.telemetry(
            trace=True, device_ledger=True, profile_iters=1
        )
    return cfg


@pytest.mark.slow  # two full PPO builds (~25 s on the 1-core box);
# the per-train()-result ledger surface is tier-1-covered by
# test_telemetry.test_ppo_telemetry_end_to_end and the numerics half
# by the policy-level parity test above
def test_superstep_ppo_ledger_e2e_and_bit_parity(tmp_path):
    """Acceptance: ``info/device_ledger`` on superstep PPO reports
    per-program FLOPs, HBM bytes, execution counts and MFU; the
    exported timeline contains device program lanes; and the ledger +
    ``profile_iters`` run is BITWISE identical to telemetry-off at a
    fixed seed (observability must never touch the numerics)."""
    algo = _ppo_cfg(True, str(tmp_path)).build()
    try:
        for _ in range(2):
            result = algo.train()
        ledger = result["info"]["device_ledger"]
        assert ledger["programs"], "ledger saw no programs"
        sup = next(
            p
            for p in ledger["programs"]
            if p["label"].startswith("superstep[")
        )
        assert sup["flops"] and sup["flops"] > 0
        assert sup["bytes_accessed"] and sup["bytes_accessed"] > 0
        assert sup["memory"]["temp_bytes"] >= 0
        assert sup["executions"] >= 1
        assert sup["mfu"] is not None and sup["mfu"] > 0
        assert ledger["totals"]["mfu"] is not None
        assert ledger["peak_flops_per_device"] > 0
        # Prometheus families fed
        from ray_tpu.utils.metrics import get_metric

        m = get_metric("ray_tpu_program_executions_total")
        assert m is not None and any(
            "superstep[" in dict(tags).get("program", "")
            for tags, _v in m.series()
        )
        # device lanes render in the unified timeline
        path = algo.export_timeline(
            str(tmp_path / "timeline.json")
        )
        events = json.load(open(path))["traceEvents"]
        dev_names = {
            e["name"]
            for e in events
            if e["ph"] == "X"
            and e["name"].startswith("device:")
        }
        assert any("superstep[" in n for n in dev_names)
        weights_on = algo.get_policy().get_weights()
    finally:
        algo.cleanup()
    tracing.disable()
    tracing.clear()
    device_ledger.disable()
    device_ledger.clear()

    algo_off = _ppo_cfg(False, str(tmp_path)).build()
    try:
        for _ in range(2):
            algo_off.train()
        weights_off = algo_off.get_policy().get_weights()
    finally:
        algo_off.cleanup()
    la = jax.tree_util.tree_leaves(weights_on)
    lb = jax.tree_util.tree_leaves(weights_off)
    assert len(la) == len(lb)
    for a, b in zip(la, lb):
        assert np.array_equal(np.asarray(a), np.asarray(b))
