"""Run telemetry: tracing/metrics plumbing + the training-loop layer.

Covers the ISSUE-3 satellites and tentpole seams:
- golden Prometheus exposition format (stable, fully-sorted series
  keys — counter/gauge/histogram with tags);
- Histogram.observe under concurrent writers (lock correctness);
- span propagation through AsyncRequestsManager (worker execution
  spans parent under the driver's iteration span);
- per-thread chrome-trace lanes (Span.tid);
- the iteration roll-up math (stage busy times, overlap fraction);
- config-driven activation end to end: PPO + .telemetry() →
  info/telemetry in train results, /metrics scrape, export_timeline.
"""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

import ray_tpu as ray
from ray_tpu.util import tracing


def setup_function(_fn):
    tracing.clear()


def teardown_function(_fn):
    tracing.disable()
    tracing.clear()


# -- Prometheus exposition golden -------------------------------------


def test_prometheus_exposition_golden():
    from ray_tpu.utils import metrics as m
    from ray_tpu.utils.metrics_exporter import format_prometheus

    m.clear_registry()
    c = m.Counter("gold_req", "requests", ("zone", "path"))
    # tags given in DIFFERENT insertion orders must render identically
    c.inc(2, {"zone": "a", "path": "/x"})
    c.inc(3, {"path": "/y", "zone": "b"})
    g = m.Gauge("gold_depth", "queue depth", ("queue",))
    g.set(4, {"queue": "in"})
    h = m.Histogram(
        "gold_lat", "latency", boundaries=[0.1, 1.0], tag_keys=("op",)
    )
    h.observe(0.05, {"op": "put"})
    h.observe(0.5, {"op": "put"})
    h.observe(5.0, {"op": "put"})
    text = format_prometheus()
    expected = """\
# HELP gold_req requests
# TYPE gold_req counter
gold_req{path="/x",zone="a"} 2.0
gold_req{path="/y",zone="b"} 3.0
# HELP gold_depth queue depth
# TYPE gold_depth gauge
gold_depth{queue="in"} 4.0
# HELP gold_lat latency
# TYPE gold_lat histogram
gold_lat_bucket{le="0.1",op="put"} 1.0
gold_lat_bucket{le="1.0",op="put"} 2.0
gold_lat_bucket{le="+Inf",op="put"} 3.0
gold_lat_sum{op="put"} 5.55
gold_lat_count{op="put"} 3
"""
    assert text == expected
    m.clear_registry()


def test_prometheus_series_keys_stable_across_scrapes():
    """_sum/_count must use the same (sorted) tag rendering as
    _bucket: series keys may not depend on tag insertion order."""
    from ray_tpu.utils import metrics as m
    from ray_tpu.utils.metrics_exporter import format_prometheus

    m.clear_registry()
    h = m.Histogram(
        "stab_lat", boundaries=[1.0], tag_keys=("b", "a")
    )
    h.observe(0.5, {"b": "2", "a": "1"})
    first = format_prometheus()
    h.observe(0.5, {"a": "1", "b": "2"})  # reversed insertion order
    second = format_prometheus()
    key_first = [
        ln.split(" ")[0]
        for ln in first.splitlines()
        if ln.startswith("stab_lat")
    ]
    key_second = [
        ln.split(" ")[0]
        for ln in second.splitlines()
        if ln.startswith("stab_lat")
    ]
    assert key_first == key_second
    assert 'stab_lat_sum{a="1",b="2"}' in second
    m.clear_registry()


# -- concurrency -------------------------------------------------------


def test_histogram_concurrent_observe_threadsafe():
    from ray_tpu.utils import metrics as m

    m.clear_registry()
    h = m.Histogram(
        "conc_lat", boundaries=[0.5], tag_keys=("t",)
    )
    n_threads, n_obs = 8, 500

    def pound(i):
        tags = {"t": str(i % 2)}
        for k in range(n_obs):
            h.observe(0.25 if k % 2 else 0.75, tags)

    threads = [
        threading.Thread(target=pound, args=(i,))
        for i in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    series = dict(h.series())
    total = sum(s["count"] for s in series.values())
    assert total == n_threads * n_obs
    for s in series.values():
        assert sum(s["buckets"]) == s["count"]
        assert s["sum"] == pytest.approx(s["count"] * 0.5)
    m.clear_registry()


# -- span propagation through the request manager ---------------------


@ray.remote
class _SpanWorker:
    def sample(self):
        from ray_tpu.util import tracing as wtracing

        with wtracing.start_span("rollout:sample", worker="w"):
            time.sleep(0.01)
        return 1


def test_async_requests_manager_propagates_spans():
    """Worker execution spans harvested through AsyncRequestsManager
    parent under the driver's iteration span (same trace, child of
    the actor-method span the submitted context opened)."""
    from ray_tpu.execution.parallel_requests import (
        AsyncRequestsManager,
    )

    if not ray.is_initialized():
        ray.init()
    tracing.enable()
    w = _SpanWorker.remote()
    mgr = AsyncRequestsManager(
        [w],
        max_remote_requests_in_flight_per_worker=1,
        name="span_test",
    )
    with tracing.start_span("train:iteration") as root:
        mgr.submit_available()
        got = {}
        deadline = time.time() + 30
        while not got and time.time() < deadline:
            got = mgr.get_ready(timeout=5.0)
    assert sum(len(v) for v in got.values()) == 1
    spans = {s["name"]: s for s in tracing.get_spans()}
    method = spans["actor:_SpanWorker.sample"]
    inner = spans["rollout:sample"]
    assert method["trace_id"] == root.trace_id
    assert method["parent_id"] == root.span_id
    assert inner["parent_id"] == method["span_id"]
    # the worker span really came from another process
    assert inner["pid"] != spans["train:iteration"]["pid"]
    # manager-side telemetry: submit/harvest spans on the driver
    assert "requests:submit" in spans
    assert "requests:harvest" in spans
    ray.kill(w)


# -- thread lanes ------------------------------------------------------


def test_spans_record_thread_ids_as_lanes(tmp_path):
    tracing.enable()

    def worker():
        with tracing.start_span("feeder:transfer"):
            time.sleep(0.001)

    t = threading.Thread(target=worker, name="lane_thread")
    with tracing.start_span("learn:nest"):
        t.start()
        t.join()
    path = tracing.export_chrome_trace(str(tmp_path / "t.json"))
    events = json.load(open(path))["traceEvents"]
    by_name = {
        e["name"]: e for e in events if e["ph"] == "X"
    }
    assert (
        by_name["learn:nest"]["tid"]
        != by_name["feeder:transfer"]["tid"]
    )
    lane_meta = [
        e
        for e in events
        if e["ph"] == "M" and e["name"] == "thread_name"
    ]
    assert any(
        e["args"]["name"] == "lane_thread" for e in lane_meta
    )


# -- roll-up math ------------------------------------------------------


def _span(name, start, end, pid=1, tid=1):
    return {
        "name": name,
        "start": start,
        "end": end,
        "pid": pid,
        "tid": tid,
        "trace_id": "t",
        "span_id": name,
        "parent_id": None,
        "attributes": {},
    }


def test_iteration_rollup_overlap_fraction():
    from ray_tpu import telemetry

    spans = [
        # sampling runs 0..6 on a worker (two overlapping fragments
        # must not double count: union is 0..6)
        _span("rollout:sample", 0.0, 4.0, pid=2),
        _span("rollout:sample", 3.0, 6.0, pid=2),
        # learn runs 2..5 → 2 of its 3 seconds overlap sampling... all
        # 3 do (sampling covers 0..6), so carve sampling down:
        _span("learn:nest", 5.0, 8.0),
        _span("feeder:transfer", 1.0, 1.5),
        _span("prefetch:assemble", 1.5, 2.0),
    ]
    r = telemetry.iteration_rollup(spans, 0.0, 10.0)
    assert r["sample_s"] == pytest.approx(6.0)
    assert r["learn_s"] == pytest.approx(3.0)
    assert r["transfer_s"] == pytest.approx(0.5)
    assert r["assemble_s"] == pytest.approx(0.5)
    # learn 5..8 ∩ sampling 0..6 = 5..6 → 1/3
    assert r["overlap_fraction"] == pytest.approx(1.0 / 3.0)
    # window clamping: a span straddling the window edge only counts
    # its inside part
    r2 = telemetry.iteration_rollup(spans, 5.5, 7.0)
    assert r2["learn_s"] == pytest.approx(1.5)
    assert r2["sample_s"] == pytest.approx(0.5)
    assert r2["overlap_fraction"] == pytest.approx(0.5 / 1.5)
    # no learn span in window → fraction pinned to 0, not NaN
    r3 = telemetry.iteration_rollup(spans, 0.0, 1.0)
    assert r3["overlap_fraction"] == 0.0


def test_iteration_rollup_same_iteration_vs_deferred_harvest():
    """The overlap roll-up is a pure function of (spans, window):
    worker spans harvested WITHIN the iteration and the same spans
    arriving an iteration late (the old deferred protocol) produce
    identical numbers for that window — which is what lets
    Algorithm.step roll up the CURRENT window instead of lagging one
    iteration (ISSUE-13 satellite)."""
    from ray_tpu import telemetry

    worker = [
        _span("rollout:sample", 1.0, 6.0, pid=2),
        _span("sampler:collect", 2.0, 5.0, pid=2),
    ]
    driver = [
        _span("learn:nest", 4.0, 8.0),
        _span("feeder:transfer", 3.0, 3.5),
    ]
    window = (0.0, 10.0)
    # harvested in-iteration (worker spans already present) vs
    # deferred (they arrive after the driver's, i.e. appended last) vs
    # interleaved: all the same
    orders = [
        worker + driver,
        driver + worker,
        [driver[0], worker[0], driver[1], worker[1]],
    ]
    results = [
        telemetry.iteration_rollup(o, *window) for o in orders
    ]
    for r in results[1:]:
        assert r == results[0]
    assert results[0]["sample_s"] == pytest.approx(5.0)
    # learn 4..8 ∩ sampling 1..6 = 4..6 → 2/4
    assert results[0]["overlap_fraction"] == pytest.approx(0.5)


def test_merge_and_intersect_primitives():
    from ray_tpu.telemetry import intersect, merge_intervals

    merged = merge_intervals(
        [(0, 2), (1, 3), (5, 6), (6, 7), (9, 9)]
    )
    assert merged == [(0, 3), (5, 7)]
    assert intersect([(0, 3), (5, 7)], [(2, 6)]) == [
        (2, 3),
        (5, 6),
    ]


# -- config-driven activation (tentpole e2e) ---------------------------


@pytest.mark.slow  # ~19 s on this container; moved out of tier-1 by
# the PR-1 budget rule — tier-1 keeps the roll-up/span/exposition
# units here plus the fixed-seed ledger+telemetry e2e in
# test_device_ledger.py
def test_ppo_telemetry_end_to_end(tmp_path):
    """AlgorithmConfig.telemetry() activates everything, on the
    superstep path: train() results carry info/telemetry (stage times
    + overlap fraction, with the fused ``learn:superstep`` span
    counting as the learn stage) AND info/device_ledger (per-program
    FLOPs / HBM bytes / executions / MFU — the ISSUE-13 acceptance
    surface), /metrics scrapes throughput + queue + program series,
    export_timeline writes one chrome trace with spans from >= 2
    processes, >= 2 driver threads, and the device program lanes."""
    from ray_tpu.algorithms.ppo import PPOConfig
    from ray_tpu.telemetry import device as device_ledger

    cfg = (
        PPOConfig()
        .environment("CartPole-v1")
        .rollouts(
            num_rollout_workers=1,
            rollout_fragment_length=64,
            sample_prefetch=1,
        )
        .training(
            train_batch_size=128,
            sgd_minibatch_size=64,
            num_sgd_iter=2,
            lr=3e-4,
            superstep=2,
        )
        .debugging(seed=0)
        .telemetry(metrics_port=0, trace=True)
    )
    algo = cfg.build()
    try:
        for _ in range(3):
            result = algo.train()
        tel = result["info"]["telemetry"]
        for key in (
            "sample_s",
            "assemble_s",
            "transfer_s",
            "learn_s",
            "overlap_fraction",
            "env_steps_per_s",
            "learn_steps_per_s",
        ):
            assert key in tel, key
        assert tel["learn_s"] > 0
        # satellite fix: the roll-up prefers the CURRENT iteration's
        # window (worker spans harvested within it included) and only
        # falls back one settled window when this window's sampling is
        # still in flight — never more
        assert tel["window_iterations_ago"] in (0, 1)
        assert tel["sample_s"] > 0
        assert 0.0 <= tel["overlap_fraction"] <= 1.0
        # the superstep path really ran (fused updates counted)
        assert tel["superstep"]["updates"] > 0

        # device ledger (acceptance): per-program FLOPs, HBM bytes,
        # executions, MFU on the superstep program
        ledger = result["info"]["device_ledger"]
        sup = next(
            p
            for p in ledger["programs"]
            if p["label"].startswith("superstep[")
        )
        assert sup["flops"] and sup["flops"] > 0
        assert sup["bytes_accessed"] and sup["bytes_accessed"] > 0
        assert sup["memory"]["temp_bytes"] >= 0
        assert sup["executions"] >= 1
        assert sup["mfu"] is not None and sup["mfu"] > 0
        assert ledger["totals"]["mfu"] is not None
        assert ledger["peak_flops_per_device"] > 0

        port = algo._telemetry.metrics_port
        blob = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10
        ).read().decode()
        assert "ray_tpu_env_steps_per_s" in blob
        assert "ray_tpu_learn_steps_per_s" in blob
        assert 'ray_tpu_queue_depth{queue="feeder_out"}' in blob
        assert (
            'ray_tpu_requests_in_flight{manager="sample_prefetcher"}'
            in blob
        )
        assert "ray_tpu_program_executions_total" in blob
        assert "ray_tpu_program_device_seconds_total" in blob
        assert "ray_tpu_program_flops" in blob

        path = algo.export_timeline(
            str(tmp_path / "iter.json"), last_n=2
        )
        events = json.load(open(path))["traceEvents"]
        x = [e for e in events if e["ph"] == "X"]
        names = {e["name"] for e in x}
        assert {
            "rollout:sample",
            "prefetch:assemble",
            "feeder:transfer",
            "learn:superstep",
        } <= names
        # device program lanes merged into the same file
        assert any(n.startswith("device:") for n in names)
        assert len({e["pid"] for e in x}) >= 2
        driver_pid = next(
            e["pid"] for e in x if e["name"] == "learn:superstep"
        )
        driver_tids = {
            e["tid"] for e in x if e["pid"] == driver_pid
        }
        assert len(driver_tids) >= 2
    finally:
        algo.cleanup()
        device_ledger.disable()
        device_ledger.clear()


def test_telemetry_off_by_default_records_nothing():
    """The default config leaves tracing off: start_span hands back
    the shared null span and the buffer stays empty."""
    from ray_tpu.algorithms.ppo import PPOConfig

    assert PPOConfig().telemetry_config == {}
    tracing.clear()
    assert not tracing.is_enabled()
    with tracing.start_span("learn:nest") as sp:
        sp.set_attribute("k", "v")  # must be a no-op, not a crash
    assert tracing.get_spans() == []
