"""Model + distribution tests (reference rllib/models/tests/)."""

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import (
    FCNet,
    VisionNet,
    LSTMWrapper,
    GTrXLNet,
    ModelCatalog,
)
from ray_tpu.models import distributions as dists


def test_fcnet_shapes():
    model = FCNet(num_outputs=6, hiddens=(32, 32))
    obs = jnp.zeros((4, 8))
    params = model.init(jax.random.PRNGKey(0), obs)
    logits, value, state = model.apply(params, obs)
    assert logits.shape == (4, 6)
    assert value.shape == (4,)
    assert state == ()


def test_fcnet_free_log_std():
    model = FCNet(num_outputs=8, hiddens=(16,), free_log_std=True)
    obs = jnp.zeros((2, 3))
    params = model.init(jax.random.PRNGKey(0), obs)
    logits, _, _ = model.apply(params, obs)
    assert logits.shape == (2, 8)
    # log-std half must be identical across batch (state-independent).
    np.testing.assert_array_equal(
        np.asarray(logits[0, 4:]), np.asarray(logits[1, 4:])
    )


def test_visionnet_shapes():
    model = VisionNet(num_outputs=4)
    obs = jnp.zeros((2, 84, 84, 4), jnp.uint8)
    params = model.init(jax.random.PRNGKey(0), obs)
    logits, value, _ = model.apply(params, obs)
    assert logits.shape == (2, 4)
    assert logits.dtype == jnp.float32
    assert value.shape == (2,)


def test_lstm_wrapper_step_vs_unroll():
    """Stepping T=1 twice must equal unrolling T=2 once."""
    model = LSTMWrapper(num_outputs=3, cell_size=16, hiddens=(8,))
    B, T, D = 2, 2, 5
    obs = jax.random.normal(jax.random.PRNGKey(1), (B, T, D))
    state0 = model.initial_state(B)
    params = model.init(jax.random.PRNGKey(0), obs, state0)

    logits_full, _, _ = model.apply(params, obs, state0)

    l0, _, s1 = model.apply(params, obs[:, :1], state0)
    l1, _, _ = model.apply(params, obs[:, 1:], s1)
    step_logits = jnp.concatenate(
        [l0.reshape(B, 1, -1), l1.reshape(B, 1, -1)], axis=1
    ).reshape(B * T, -1)
    np.testing.assert_allclose(
        np.asarray(logits_full), np.asarray(step_logits), rtol=1e-5, atol=1e-5
    )


def test_lstm_reset_mask_zeroes_state():
    """A reset at t must make output independent of pre-reset history."""
    model = LSTMWrapper(num_outputs=3, cell_size=16, hiddens=(8,))
    B, T, D = 1, 4, 5
    obs = jax.random.normal(jax.random.PRNGKey(1), (B, T, D))
    state0 = model.initial_state(B)
    params = model.init(jax.random.PRNGKey(0), obs, state0)

    resets = jnp.array([[0.0, 0.0, 1.0, 0.0]])
    logits_a, _, _ = model.apply(params, obs, state0, resets=resets)

    # Different history before the reset point
    obs_b = obs.at[:, :2].set(obs[:, :2] + 10.0)
    logits_b, _, _ = model.apply(params, obs_b, state0, resets=resets)
    la = np.asarray(logits_a).reshape(T, -1)
    lb = np.asarray(logits_b).reshape(T, -1)
    # post-reset outputs identical, pre-reset different
    assert not np.allclose(la[1], lb[1])
    np.testing.assert_allclose(la[2], lb[2], rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(la[3], lb[3], rtol=1e-5, atol=1e-5)


def test_gtrxl_shapes_and_memory():
    model = GTrXLNet(
        num_outputs=5, attention_dim=32, num_transformer_units=2,
        num_heads=2, head_dim=16, memory_len=8,
    )
    B, T, D = 3, 4, 6
    obs = jnp.zeros((B, T, D))
    state0 = model.initial_state(B)
    assert len(state0) == 2
    params = model.init(jax.random.PRNGKey(0), obs, state0)
    logits, value, state1 = model.apply(params, obs, state0)
    assert logits.shape == (B * T, 5)
    assert value.shape == (B * T,)
    assert state1[0].shape == (B, 8, 32)


def test_gtrxl_causality():
    """Output at t must not depend on inputs at t' > t."""
    model = GTrXLNet(
        num_outputs=2, attention_dim=16, num_transformer_units=1,
        num_heads=1, head_dim=16, memory_len=4,
    )
    B, T, D = 1, 5, 3
    obs = jax.random.normal(jax.random.PRNGKey(2), (B, T, D))
    state0 = model.initial_state(B)
    params = model.init(jax.random.PRNGKey(0), obs, state0)
    logits_a, _, _ = model.apply(params, obs, state0)
    obs_b = obs.at[:, -1].set(obs[:, -1] + 5.0)
    logits_b, _, _ = model.apply(params, obs_b, state0)
    la = np.asarray(logits_a).reshape(T, -1)
    lb = np.asarray(logits_b).reshape(T, -1)
    np.testing.assert_allclose(la[:-1], lb[:-1], rtol=1e-5, atol=1e-5)
    assert not np.allclose(la[-1], lb[-1])


# ---------------- catalog ----------------


def test_catalog_discrete():
    obs_space = gym.spaces.Box(-1, 1, (4,), np.float32)
    act_space = gym.spaces.Discrete(2)
    dist_cls, n = ModelCatalog.get_action_dist(act_space)
    assert dist_cls is dists.Categorical and n == 2
    model = ModelCatalog.get_model(obs_space, act_space, n, {})
    assert isinstance(model, FCNet)


def test_catalog_box_action():
    act_space = gym.spaces.Box(-2, 2, (3,), np.float32)
    dist_cls, n = ModelCatalog.get_action_dist(act_space)
    assert n == 6
    d = dist_cls(jnp.zeros((1, 6)))
    assert isinstance(d, dists.DiagGaussian)


def test_catalog_image_obs():
    obs_space = gym.spaces.Box(0, 255, (84, 84, 4), np.uint8)
    act_space = gym.spaces.Discrete(6)
    model = ModelCatalog.get_model(obs_space, act_space, 6, {})
    assert isinstance(model, VisionNet)


def test_catalog_lstm():
    obs_space = gym.spaces.Box(-1, 1, (4,), np.float32)
    act_space = gym.spaces.Discrete(2)
    model = ModelCatalog.get_model(
        obs_space, act_space, 2, {"use_lstm": True, "lstm_cell_size": 32}
    )
    assert isinstance(model, LSTMWrapper)
    assert model.cell_size == 32


def test_catalog_multidiscrete():
    act_space = gym.spaces.MultiDiscrete([3, 4])
    dist_cls, n = ModelCatalog.get_action_dist(act_space)
    assert n == 7
    d = dist_cls(jnp.zeros((2, 7)))
    a = d.sample(jax.random.PRNGKey(0))
    assert a.shape == (2, 2)


def test_custom_model_registration():
    class MyModel(FCNet):
        pass

    ModelCatalog.register_custom_model("my_model", MyModel)
    obs_space = gym.spaces.Box(-1, 1, (4,), np.float32)
    model = ModelCatalog.get_model(
        obs_space, gym.spaces.Discrete(2), 2,
        {"custom_model": "my_model",
         "custom_model_config": {"hiddens": (8,)}},
    )
    assert isinstance(model, MyModel)


# ---------------- distributions ----------------


def test_categorical_logp_entropy():
    logits = jnp.asarray([[2.0, 0.0, -1.0]])
    d = dists.Categorical(logits)
    p = jax.nn.softmax(logits)[0]
    want_entropy = -float(jnp.sum(p * jnp.log(p)))
    assert abs(float(d.entropy()[0]) - want_entropy) < 1e-5
    logp = d.logp(jnp.asarray([0]))
    assert abs(float(logp[0]) - float(jnp.log(p[0]))) < 1e-5


def test_categorical_kl_self_zero():
    logits = jax.random.normal(jax.random.PRNGKey(0), (4, 5))
    d = dists.Categorical(logits)
    np.testing.assert_allclose(
        np.asarray(d.kl(dists.Categorical(logits))), 0.0, atol=1e-6
    )


def test_diag_gaussian_logp_matches_scipy():
    from scipy import stats

    mean = np.array([[0.5, -0.3]], np.float32)
    log_std = np.array([[0.1, -0.2]], np.float32)
    inputs = jnp.asarray(np.concatenate([mean, log_std], -1))
    d = dists.DiagGaussian(inputs)
    x = np.array([[0.7, 0.1]], np.float32)
    want = stats.norm.logpdf(x, mean, np.exp(log_std)).sum(-1)
    np.testing.assert_allclose(
        np.asarray(d.logp(jnp.asarray(x))), want, rtol=1e-4
    )


def test_squashed_gaussian_bounds_and_logp_consistency():
    rng = jax.random.PRNGKey(0)
    inputs = jax.random.normal(rng, (100, 4))
    d = dists.SquashedGaussian(inputs, low=-2.0, high=2.0)
    a, logp = d.sampled_action_logp(jax.random.PRNGKey(1))
    a_np = np.asarray(a)
    assert a_np.min() >= -2.0 and a_np.max() <= 2.0
    # logp(sample) should match recomputing via d.logp — away from the
    # tanh-saturated boundary where unsquash(squash(x)) loses precision.
    logp2 = d.logp(a)
    interior = np.all(np.abs(a_np) < 1.8, axis=-1)
    np.testing.assert_allclose(
        np.asarray(logp)[interior], np.asarray(logp2)[interior],
        rtol=1e-2, atol=1e-2,
    )


def test_bernoulli():
    logits = jnp.asarray([[0.0, 3.0, -3.0]])
    d = dists.Bernoulli(logits)
    det = np.asarray(d.deterministic_sample())
    np.testing.assert_array_equal(det, [[0, 1, 0]])
    x = jnp.asarray([[1, 1, 0]])
    want = float(
        jnp.log(jax.nn.sigmoid(0.0))
        + jnp.log(jax.nn.sigmoid(3.0))
        + jnp.log(1 - jax.nn.sigmoid(-3.0))
    )
    assert abs(float(d.logp(x)[0]) - want) < 1e-4
