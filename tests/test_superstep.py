"""On-device training superstep: one dispatch per K updates.

The uniform learner contract of docs/data_plane.md
(``AlgorithmConfig.training(superstep=...)``,
``JaxPolicy.learn_superstep``, ``sharding/superstep.py``):

- fixed-seed BIT-parity of ``superstep=k`` vs k individual deferred
  learn calls (PPO stacked feed on the 8-shard mesh; SAC device-ring
  and DQN-prioritized host+device feeds on a single-shard mesh — on
  multi-shard meshes cross-program collective lowering rounds the last
  ulp differently, an XLA property, so there the asserted invariant is
  the program-level one: scan(K) ≡ scan(1)^K through ONE executable,
  plus allclose vs the classic path);
- deferred-stats stacking/drain semantics (per-update stats bitwise
  equal to the per-call deferred fetches);
- prioritized-replay refresh: ONE stacked (k, B) D2H, applied to the
  host sum tree in exact update order;
- one compiled program serves every k ≤ K (no per-K recompile,
  ``compile_stats``-asserted);
- the in-scan replay gather adds no collective to the program;
- the nan guard runs INSIDE the scan body (skip mask in the stats
  tree, masked updates are exact no-ops);
- checkpoint restore mid-superstep-cadence resumes fused training.
"""

import numpy as np
import pytest

import jax

from ray_tpu import sharding as sharding_lib
from ray_tpu.data.sample_batch import SampleBatch as SB


BS = 16


def _eq_trees(a, b):
    la = jax.tree_util.tree_leaves(jax.device_get(a))
    lb = jax.tree_util.tree_leaves(jax.device_get(b))
    assert len(la) == len(lb)
    return all(np.array_equal(x, y) for x, y in zip(la, lb))


def _mesh(n):
    return sharding_lib.get_mesh(devices=jax.devices()[:n])


def _ppo_policy(mesh=None, **over):
    import gymnasium as gym

    from ray_tpu.algorithms.ppo.ppo import PPOJaxPolicy

    cfg = {
        "train_batch_size": 4 * BS,
        "sgd_minibatch_size": 2 * BS,
        "num_sgd_iter": 2,
        "lr": 1e-3,
        "seed": 0,
    }
    if mesh is not None:
        cfg["_mesh"] = mesh
    cfg.update(over)
    return PPOJaxPolicy(
        gym.spaces.Box(-1, 1, (8,), np.float32),
        gym.spaces.Discrete(4),
        cfg,
    )


def _ppo_batch(rng, n=4 * BS):
    return {
        SB.OBS: rng.standard_normal((n, 8)).astype(np.float32),
        SB.ACTIONS: rng.integers(0, 4, n).astype(np.int64),
        SB.ACTION_LOGP: np.full(n, -1.3, np.float32),
        SB.ACTION_DIST_INPUTS: rng.standard_normal((n, 4)).astype(
            np.float32
        ),
        SB.ADVANTAGES: rng.standard_normal(n).astype(np.float32),
        SB.VALUE_TARGETS: rng.standard_normal(n).astype(np.float32),
    }


def _sac_policy(mesh=None, seed=0):
    import gymnasium as gym

    from ray_tpu.algorithms.sac.sac import SACJaxPolicy

    cfg = {"seed": seed, "gamma": 0.99, "tau": 0.005}
    if mesh is not None:
        cfg["_mesh"] = mesh
    return SACJaxPolicy(
        gym.spaces.Box(-1, 1, (6,), np.float32),
        gym.spaces.Box(-1, 1, (2,), np.float32),
        cfg,
    )


def _sac_rows(rng, n):
    return {
        SB.OBS: rng.standard_normal((n, 6)).astype(np.float32),
        SB.NEXT_OBS: rng.standard_normal((n, 6)).astype(np.float32),
        SB.ACTIONS: rng.uniform(-1, 1, (n, 2)).astype(np.float32),
        SB.REWARDS: rng.standard_normal(n).astype(np.float32),
        SB.TERMINATEDS: np.zeros(n, np.float32),
    }


def _dqn_policy(mesh=None, **over):
    import gymnasium as gym

    from ray_tpu.algorithms.dqn.dqn import DQNJaxPolicy

    cfg = {
        "seed": 0,
        "lr": 1e-3,
        "train_batch_size": BS,
        "dueling": False,
        "double_q": True,
    }
    if mesh is not None:
        cfg["_mesh"] = mesh
    cfg.update(over)
    return DQNJaxPolicy(
        gym.spaces.Box(-1, 1, (6,), np.float32),
        gym.spaces.Discrete(4),
        cfg,
    )


def _dqn_rows(rng, n):
    return {
        SB.OBS: rng.standard_normal((n, 6)).astype(np.float32),
        SB.NEXT_OBS: rng.standard_normal((n, 6)).astype(np.float32),
        SB.ACTIONS: rng.integers(0, 4, n).astype(np.int64),
        SB.REWARDS: rng.standard_normal(n).astype(np.float32),
        SB.TERMINATEDS: np.zeros(n, np.float32),
    }


# -- bit parity: superstep == k individual calls -----------------------


def test_ppo_superstep_bit_parity_and_stats_stacking():
    """superstep=k on the 8-shard mesh: params AND opt-state bitwise
    equal to k sequential deferred learn calls on the same (host
    stacked) batches, and the drained (k,)-stacked stats bitwise equal
    to the per-call deferred fetches, in update order. Afterwards the
    SAME compiled program serves k = 1, 2, 4 with zero recompiles
    (compile_stats-asserted: one executable for every K in a run)."""
    rng = np.random.default_rng(0)
    K, KMAX, n = 3, 4, 4 * BS
    batches = [_ppo_batch(rng, n) for _ in range(K)]

    p_seq = _ppo_policy()
    seq_stats = []
    for b in batches:
        dev = jax.device_put(b, p_seq.batch_shardings(b))
        seq_stats.append(
            jax.device_get(
                p_seq.learn_on_device_batch(dev, n, defer_stats=True)
            )
        )

    p_sup = _ppo_policy()
    stacked = {
        c: np.stack([b[c] for b in batches] + [batches[0][c]])
        for c in batches[0]
    }
    infos, pri, skipped = p_sup.learn_superstep(
        K, n, stacked=stacked, k_max=KMAX
    )
    assert pri is None and skipped == [False] * K
    assert _eq_trees(p_seq.params, p_sup.params)
    assert _eq_trees(p_seq.opt_state, p_sup.opt_state)
    assert len(infos) == K
    for i in range(K):
        for name, v in seq_stats[i].items():
            assert float(v) == infos[i][name], (i, name)
    # num_grad_updates advances like k calls would
    assert p_sup.num_grad_updates == p_seq.num_grad_updates

    # zero-recompile across chain lengths: every k ≤ K_MAX rides the
    # ONE compiled executable
    for k in (1, 2, 4):
        p_sup.learn_superstep(k, n, stacked=stacked, k_max=KMAX)
    (fn,) = p_sup._superstep_fns.values()
    assert fn.traces == 1 and fn.recompiles == 0 and fn.calls == 4
    per_fn = {
        s["label"]: s
        for s in sharding_lib.compile_stats()["per_function"]
    }
    label = f"superstep[PPOJaxPolicy:{n}x{KMAX}]"
    assert per_fn[label]["recompiles"] == 0


@pytest.mark.slow  # ~10 s; moved out of tier-1 by the PR-1 budget
# rule — tier-1 keeps the PPO superstep bit-parity + zero-recompile
# pin above, the DQN prioritized-superstep parity below, and the SAC
# device-vs-host bitwise pin in test_device_replay.py
def test_sac_superstep_device_rings_parity():
    """Device-resident replay rings consumed IN PLACE by the scan:
    bit-identical to k sequential sample+learn calls on a single-shard
    mesh (same host generator call order, same rng splits); on the
    8-shard mesh the chain is bit-identical THROUGH the superstep
    program (scan(K) == scan(1)^K, one executable); vs the classic
    path it agrees to collective-rounding (cross-program lowering
    rounds the last ulp differently — an XLA property, not a data-path
    one; docs/data_plane.md)."""
    from ray_tpu.execution.replay_buffer import DeviceReplayBuffer

    rng = np.random.default_rng(1)
    rows = _sac_rows(rng, 8 * BS)
    K = 3

    # single-shard mesh: exact parity vs the classic per-update path
    m1 = _mesh(1)
    p_seq, p_sup = _sac_policy(m1), _sac_policy(m1)
    b_seq = DeviceReplayBuffer(capacity=8 * BS, seed=7, mesh=m1)
    b_sup = DeviceReplayBuffer(capacity=8 * BS, seed=7, mesh=m1)
    b_seq.add_tree(dict(rows))
    b_sup.add_tree(dict(rows))
    lazy = []
    for _ in range(K):
        db = b_seq.sample(BS)
        lazy.append(
            p_seq.learn_on_device_batch(
                dict(db.tree), BS, defer_stats=True
            )
        )
    jax.device_get(lazy)
    idx = b_sup.draw_index_sets(K, BS)
    infos, _, _ = p_sup.learn_superstep(
        K, BS, rings=b_sup.superstep_feed(idx), k_max=K
    )
    assert _eq_trees(p_seq.params, p_sup.params)
    assert _eq_trees(p_seq.opt_state, p_sup.opt_state)
    assert _eq_trees(p_seq.aux_state, p_sup.aux_state)
    # the pre-drawn index matrix consumed the generator exactly like
    # k sequential draws
    assert (
        b_seq._rng.bit_generator.state == b_sup._rng.bit_generator.state
    )

    # 8-shard mesh: program-level exactness. One policy, one compiled
    # program: snapshot the initial state, run scan(K), restore, run
    # scan(1)^K through the SAME executable.
    p_a = _sac_policy()
    buf = DeviceReplayBuffer(capacity=8 * BS, seed=7)
    buf.add_tree(dict(rows))
    idx = buf.draw_index_sets(K, BS)
    snap = (
        jax.device_get(p_a.params),
        jax.device_get(p_a.opt_state),
        jax.device_get(p_a.aux_state),
        p_a._rng,
    )
    p_a.learn_superstep(
        K, BS, rings=buf.superstep_feed(idx), k_max=K
    )
    fused = (
        jax.device_get(p_a.params), jax.device_get(p_a.opt_state),
        jax.device_get(p_a.aux_state),
    )
    from ray_tpu.policy.jax_policy import _tree_to_device

    p_a.params = _tree_to_device(snap[0], p_a._param_sharding)
    p_a.opt_state = _tree_to_device(snap[1], p_a._param_sharding)
    p_a.aux_state = _tree_to_device(snap[2], p_a._param_sharding)
    p_a._rng = snap[3]
    for i in range(K):
        one = np.repeat(idx[i : i + 1], K, axis=0)
        p_a.learn_superstep(
            1, BS, rings=buf.superstep_feed(one), k_max=K
        )
    (fn,) = p_a._superstep_fns.values()
    assert fn.traces == 1  # literally the same executable
    assert _eq_trees(fused[0], p_a.params)
    assert _eq_trees(fused[1], p_a.opt_state)
    assert _eq_trees(fused[2], p_a.aux_state)


def test_dqn_prioritized_superstep_parity():
    """DQN + prioritized replay, host AND device buffers, single-shard
    mesh: superstep_train_replay is bit-identical — params, opt-state,
    sum-tree leaves, max-priority, generator state — to the per-update
    reference (pre-drawn index sets, learn → td → refresh per update,
    priorities applied in update order)."""
    from ray_tpu.execution.replay_buffer import (
        DevicePrioritizedReplayBuffer,
        PrioritizedReplayBuffer,
    )
    from ray_tpu.execution.train_ops import superstep_train_replay

    rng = np.random.default_rng(2)
    rows = _dqn_rows(rng, 8 * BS)
    K, beta = 3, 0.4
    m1 = _mesh(1)

    def fill(buf):
        if isinstance(buf, DevicePrioritizedReplayBuffer):
            buf.add_tree(dict(rows))
        else:
            buf.add(SB(dict(rows)))
        buf.update_priorities(
            np.arange(16), np.linspace(1.0, 5.0, 16)
        )
        return buf

    from ray_tpu.policy.jax_policy import _tree_to_device

    # one policy pair serves both buffer modes (compiled programs
    # reused; state + host rng rewound between modes)
    p_ref, p_sup = _dqn_policy(m1), _dqn_policy(m1)
    snaps = [
        (
            jax.device_get(p.params),
            jax.device_get(p.opt_state),
            jax.device_get(p.aux_state),
            p._rng,
        )
        for p in (p_ref, p_sup)
    ]

    for device_buf in (False, True):
        for p, snap in zip((p_ref, p_sup), snaps):
            p.params = _tree_to_device(snap[0], p._param_sharding)
            p.opt_state = _tree_to_device(snap[1], p._param_sharding)
            p.aux_state = _tree_to_device(snap[2], p._param_sharding)
            p._rng = snap[3]
        if device_buf:
            b_ref = fill(
                DevicePrioritizedReplayBuffer(
                    capacity=8 * BS, alpha=0.6, seed=9, mesh=m1
                )
            )
            b_sup = fill(
                DevicePrioritizedReplayBuffer(
                    capacity=8 * BS, alpha=0.6, seed=9, mesh=m1
                )
            )
        else:
            b_ref = fill(
                PrioritizedReplayBuffer(
                    capacity=8 * BS, alpha=0.6, seed=9
                )
            )
            b_sup = fill(
                PrioritizedReplayBuffer(
                    capacity=8 * BS, alpha=0.6, seed=9
                )
            )

        # reference: pre-drawn sets (the superstep's documented
        # within-chain priority staleness), then per-update
        # learn → td → in-order refresh
        idx, w = b_ref.draw_prioritized_sets(K, BS, beta)
        for i in range(K):
            if device_buf:
                db = b_ref.gather(idx[i])
                tree = dict(db.tree)
                tree["weights"] = jax.device_put(
                    w[i], sharding_lib.batch_sharded(m1)
                )
                td_src = b_ref.gather(idx[i])
            else:
                b = b_ref._make_batch(idx[i])
                b["weights"] = w[i]
                b["batch_indexes"] = idx[i].astype(np.int64)
                host, n = p_ref.prepare_batch(b)
                assert n == BS
                tree = jax.device_put(
                    host, p_ref.batch_shardings(host)
                )
                td_src = b_ref._make_batch(idx[i])
            jax.device_get(
                p_ref.learn_on_device_batch(
                    tree, BS, defer_stats=True
                )
            )
            td = p_ref.compute_td_error(td_src)
            b_ref.update_priorities(idx[i], td + 1e-6)

        info = superstep_train_replay(
            None, p_sup, b_sup, K, K, BS, prioritized=True, beta=beta
        )
        assert info and np.isfinite(info["mean_td_error"])
        assert _eq_trees(p_ref.params, p_sup.params), device_buf
        assert _eq_trees(p_ref.opt_state, p_sup.opt_state), device_buf
        i_all = np.arange(8 * BS)
        assert np.array_equal(
            np.asarray(b_ref._sum_tree[i_all]),
            np.asarray(b_sup._sum_tree[i_all]),
        ), device_buf
        assert b_ref._max_priority == b_sup._max_priority
        assert (
            b_ref._rng.bit_generator.state
            == b_sup._rng.bit_generator.state
        ), device_buf


def test_priority_refresh_update_order_exactness():
    """Overlapping index sets: the stacked refresh applied in update
    order produces exactly the per-update tree (last write wins per
    leaf); applying the same matrix in reverse does not."""
    from ray_tpu.execution.replay_buffer import PrioritizedReplayBuffer

    rng = np.random.default_rng(3)
    rows = _dqn_rows(rng, 64)

    def fresh():
        b = PrioritizedReplayBuffer(capacity=64, alpha=0.6, seed=0)
        b.add(SB(dict(rows)))
        return b

    idx = np.array([[1, 2, 3, 4], [3, 4, 5, 6], [1, 6, 7, 8]])
    pri = rng.uniform(0.1, 2.0, idx.shape)

    interleaved, ordered, reverse = fresh(), fresh(), fresh()
    for i in range(3):  # the per-update cadence
        interleaved.update_priorities(idx[i], pri[i])
    for i in range(3):  # the superstep's end-of-chain application
        ordered.update_priorities(idx[i], pri[i])
    for i in reversed(range(3)):
        reverse.update_priorities(idx[i], pri[i])
    leaves = np.arange(64)
    assert np.array_equal(
        np.asarray(interleaved._sum_tree[leaves]),
        np.asarray(ordered._sum_tree[leaves]),
    )
    assert not np.array_equal(
        np.asarray(interleaved._sum_tree[leaves]),
        np.asarray(reverse._sum_tree[leaves]),
    )


# -- layout-matched in-program gather ----------------------------------


def test_superstep_ring_gather_adds_no_collective():
    """Layout-matched in-program replay gather (8-shard mesh): the
    rings-fed superstep lowers with exactly the collectives of the
    stacked-fed program — the gather's explicit row-sharded
    out-shardings mean no resharding collective fires at the
    scan-body boundary, and no gather/all-to-all appears at all.
    (Lower-only: the programs are traced and inspected, not
    executed.)"""
    import re

    from ray_tpu.execution.replay_buffer import DeviceReplayBuffer
    from ray_tpu.sharding.superstep import build_superstep_fn

    rng = np.random.default_rng(5)
    rows = _sac_rows(rng, 8 * BS)
    K = 2
    p = _sac_policy()
    buf = DeviceReplayBuffer(capacity=8 * BS, seed=7)
    buf.add_tree(dict(rows))
    idx = buf.draw_index_sets(K, BS)
    feed = buf.superstep_feed(idx)
    common = dict(mesh=p.mesh, backend=p.sharding_backend, k=K)
    fn_rings = build_superstep_fn(
        p._device_update_fn(BS),
        label="rings",
        gather_fn=feed.gather_fn,
        store_shardings=feed.shardings,
        **common,
    )
    cols = tuple(sorted(feed.store))
    fn_stacked = build_superstep_fn(
        p._device_update_fn(BS),
        label="stacked",
        stacked_cols=cols,
        **common,
    )

    def collectives(fn, *args):
        txt = fn.lower(*args).as_text()
        return {
            name: len(re.findall(name, txt))
            for name in (
                "all_reduce", "all_gather", "all_to_all",
                "collective_permute",
            )
        }

    active = np.ones(K, np.float32)
    rngs = np.zeros((K, 2), np.uint32)
    c_rings = collectives(
        fn_rings,
        p.params, p.opt_state, p.aux_state,
        (feed.store, feed.idx, feed.extra), active, rngs, {},
    )
    stacked_shape = {
        c: jax.ShapeDtypeStruct(
            (K, BS) + tuple(rows[c].shape[1:]), rows[c].dtype
        )
        for c in cols
    }
    c_stacked = collectives(
        fn_stacked,
        p.params, p.opt_state, p.aux_state,
        stacked_shape, active, rngs, {},
    )
    assert c_rings == c_stacked, (c_rings, c_stacked)
    assert c_rings["all_to_all"] == 0
    assert c_rings["all_gather"] == 0


# -- nan guard inside the scan body ------------------------------------


def test_superstep_nan_guard_in_scan():
    """With ``nan_guard`` on, a non-finite batch inside the chain is
    detected ON DEVICE (device-resident batches never pass the host
    choke points): its update is an exact no-op (params bitwise equal
    to the chain without that slot active), the per-update skip flag
    lands in the stats tree."""
    from ray_tpu.policy.jax_policy import _tree_to_device

    rng = np.random.default_rng(6)
    n = 4 * BS
    m1 = _mesh(1)
    good = _ppo_batch(rng, n)
    bad = dict(good)
    bad[SB.ADVANTAGES] = good[SB.ADVANTAGES].copy()
    bad[SB.ADVANTAGES][3] = np.nan

    p = _ppo_policy(m1, nan_guard=True)
    snap = (
        jax.device_get(p.params), jax.device_get(p.opt_state), p._rng,
    )
    stacked_bad = {
        c: np.stack([good[c], bad[c]]) for c in good
    }
    infos, _, skipped = p.learn_superstep(
        2, n, stacked=stacked_bad, k_max=2
    )
    assert skipped == [False, True]
    guarded = (jax.device_get(p.params), jax.device_get(p.opt_state))
    # rewind and run only the finite slot through the SAME program
    p.params = _tree_to_device(snap[0], p._param_sharding)
    p.opt_state = _tree_to_device(snap[1], p._param_sharding)
    p._rng = snap[2]
    stacked_ok = {c: np.stack([good[c], good[c]]) for c in good}
    infos_ok, _, sk_ok = p.learn_superstep(
        1, n, stacked=stacked_ok, k_max=2
    )
    assert sk_ok == [False]
    # the poisoned slot was an exact no-op
    assert _eq_trees(guarded[0], p.params)
    assert _eq_trees(guarded[1], p.opt_state)

    # without the guard the NaN batch corrupts the params
    p_unguarded = _ppo_policy(m1)
    infos_u, _, sk_u = p_unguarded.learn_superstep(
        2, n, stacked=stacked_bad, k_max=2
    )
    assert sk_u == [False, False]
    assert not _eq_trees(guarded[0], p_unguarded.params)


# -- wiring: learner thread + chained updates + recovery ---------------


def test_learner_thread_superstep_fuses_queued_batches():
    """A LearnerThread whose policy enables ``superstep=2`` fuses
    queued batches into K-update dispatches: the compiled superstep
    program exists and num_steps counts every update. (The thread only
    fuses on its deferred path — policies with host-side
    ``after_learn_on_batch`` hooks keep per-update dispatch — so the
    policy here is hook-free, like the IMPALA family.)"""
    import time

    import gymnasium as gym

    from ray_tpu.algorithms.ppo.ppo import PPOJaxPolicy
    from ray_tpu.execution.learner_thread import LearnerThread
    from ray_tpu.policy.jax_policy import JaxPolicy

    class _HookFreePolicy(PPOJaxPolicy):
        # no host-side per-update stat reaction (IMPALA-style): the
        # thread's deferred/superstep path applies
        after_learn_on_batch = JaxPolicy.after_learn_on_batch

    rng = np.random.default_rng(7)
    n = 4 * BS
    p = _HookFreePolicy(
        gym.spaces.Box(-1, 1, (8,), np.float32),
        gym.spaces.Discrete(4),
        {
            "train_batch_size": n,
            "sgd_minibatch_size": 2 * BS,
            "num_sgd_iter": 2,
            "lr": 1e-3,
            "seed": 0,
            "superstep": 2,
        },
    )
    assert p.supports_superstep
    lt = LearnerThread(p, inqueue_size=16)
    assert lt._superstep_k == 2
    for _ in range(4):
        lt.add_batch(SB(_ppo_batch(rng, n)))
    lt.start()
    deadline = time.time() + 60
    while lt.num_steps < 4 and time.time() < deadline:
        assert lt.healthy(), lt.error
        time.sleep(0.05)
    lt.stop()
    assert lt.num_steps == 4
    assert p._superstep_fns, "no fused dispatch happened"
    infos = []
    while not lt.outqueue.empty():
        infos.append(lt.outqueue.get_nowait())
    assert infos and all(np.isfinite(i[1]["total_loss"]) for i in infos)


def test_dqn_chained_updates_superstep_and_recovery(tmp_path):
    """DQN end-to-end with ``superstep=2`` + training_intensity: the
    chained path runs fused windows (superstep counter moves), a
    checkpoint saved mid-cadence restores into a fresh algorithm, and
    fused training resumes after the restore."""
    from ray_tpu.algorithms.dqn.dqn import DQNConfig
    from ray_tpu.telemetry import metrics as telemetry_metrics

    def counter():
        return telemetry_metrics.counter_total(
            telemetry_metrics.SUPERSTEP_UPDATES_TOTAL
        )

    cfg = (
        DQNConfig()
        .environment("CartPole-v1")
        .rollouts(num_rollout_workers=0, rollout_fragment_length=16)
        .training(
            train_batch_size=32,
            lr=1e-3,
            superstep=2,
            replay_buffer_config={"capacity": 2000},
            num_steps_sampled_before_learning_starts=32,
        )
        .reporting(min_time_s_per_iteration=0)
        .debugging(seed=0)
    )
    cfg.training_intensity = 8.0
    algo = cfg.build()
    try:
        before = counter()
        for _ in range(2):
            algo.train()
        assert counter() > before, "no fused superstep ran"
        trained = algo._counters["num_env_steps_trained"]
        assert trained > 0
        ckpt = str(tmp_path / "ckpt")
        import os

        os.makedirs(ckpt, exist_ok=True)
        algo.save_checkpoint(ckpt)
    finally:
        algo.cleanup()

    algo2 = cfg.build()
    try:
        algo2.load_checkpoint(ckpt)
        mid = counter()
        algo2.train()
        assert counter() > mid, "superstep did not resume post-restore"
        assert (
            algo2._counters["num_env_steps_trained"] >= trained
        )
    finally:
        algo2.cleanup()
