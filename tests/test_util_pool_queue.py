"""ray.util.ActorPool + ray.util.queue.Queue (reference
``ray/util/actor_pool.py`` + ``ray/util/queue.py`` and their
tests)."""

import pytest

import ray_tpu as ray
from ray_tpu.util.actor_pool import ActorPool
from ray_tpu.util.queue import Empty, Full, Queue


@pytest.fixture(autouse=True)
def _init():
    ray.init(num_cpus=4, ignore_reinit_error=True)


@ray.remote
class Doubler:
    def double(self, x):
        return 2 * x


def test_actor_pool_map_ordered():
    pool = ActorPool([Doubler.remote() for _ in range(2)])
    out = list(
        pool.map(lambda a, v: a.double.remote(v), range(6))
    )
    assert out == [0, 2, 4, 6, 8, 10]  # submission order, 2 actors


def test_actor_pool_map_unordered_and_queueing():
    pool = ActorPool([Doubler.remote()])  # 1 actor, 5 jobs queue
    out = sorted(
        pool.map_unordered(lambda a, v: a.double.remote(v), range(5))
    )
    assert out == [0, 2, 4, 6, 8]


def test_actor_pool_submit_get_next():
    # ordered semantics: results come back in SUBMISSION order
    pool = ActorPool([Doubler.remote() for _ in range(2)])
    pool.submit(lambda a, v: a.double.remote(v), 1)
    pool.submit(lambda a, v: a.double.remote(v), 2)
    assert pool.get_next(timeout=60) == 2 * 1
    assert pool.get_next(timeout=60) == 2 * 2
    assert not pool.has_next()
    with pytest.raises(StopIteration):
        pool.get_next()


def test_queue_fifo_across_workers():
    q = Queue()
    q.put("a")
    q.put("b")

    @ray.remote
    def consume_and_produce(queue):
        first = queue.get(timeout=30)
        queue.put(first + "_seen")
        return first

    assert ray.get(consume_and_produce.remote(q), timeout=120) == "a"
    assert q.get(timeout=30) == "b"
    assert q.get(timeout=30) == "a_seen"
    assert q.empty()


def test_queue_maxsize_and_nowait():
    q = Queue(maxsize=2)
    q.put(1)
    q.put(2)
    assert q.full()
    with pytest.raises(Full):
        q.put(3, block=False)
    with pytest.raises(Full):
        q.put(3, timeout=0.2)
    assert q.get_nowait() == 1
    q.put(3)  # room again
    assert q.get_batch(5) == [2, 3]
    with pytest.raises(Empty):
        q.get_nowait()
    q.shutdown()
